"""Static lock-order cross-check: observed sanitizer dump vs declared
hierarchy.

The runtime half (surrealdb_tpu/utils/locks.py, SURREAL_SANITIZE=1)
records which lock-order edges ACTUALLY happen; utils/locks.HIERARCHY
declares which orders are ALLOWED. This module closes the loop in CI:
`python -m scripts.graftlint --lock-order <dump.json>` fails when the
observed run contains

- an acquisition cycle (potential deadlock, even if it didn't fire),
- a guarded-state violation (mutation without the declared lock),
- an edge that inverts the declared hierarchy, or nests two same-level
  locks.

Edges touching lock names outside the declared hierarchy are warnings
only (test-local locks constructed outside `locks.isolated()` blocks).
"""

from __future__ import annotations

import json
from typing import List, Tuple


def check_dump(path: str) -> Tuple[List[str], List[str]]:
    """(errors, warnings) for one SURREAL_SANITIZE_OUT dump."""
    from surrealdb_tpu.utils import locks

    with open(path) as f:
        doc = json.load(f)
    errors: List[str] = []
    warnings: List[str] = []
    if not doc.get("enabled"):
        warnings.append(
            "dump was recorded with the sanitizer DISABLED — no edges to check"
        )
    for cyc in doc.get("cycles", []):
        errors.append(f"lock-order cycle (potential deadlock): {' -> '.join(cyc)}")
    for v in doc.get("violations", []):
        errors.append(
            f"guarded-state violation: {v.get('state')} mutated without "
            f"{v.get('lock')} (thread {v.get('thread')})"
        )
    edges = {(e["from"], e["to"]) for e in doc.get("edges", [])}
    errs, warns = locks.check_hierarchy(edges)
    errors.extend(errs)
    warnings.extend(warns)
    return errors, warnings
