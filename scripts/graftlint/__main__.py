"""graftlint CLI — `python -m scripts.graftlint [paths...]`.

Exit codes: 0 = clean (every finding baselined), 1 = new findings or
lock-order check failure, 2 = bad usage.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="engine-specific static analysis for surrealdb_tpu",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: surrealdb_tpu/ at the repo root)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default scripts/graftlint/baseline.json)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--lock-order", metavar="DUMP",
        help="check a SURREAL_SANITIZE_OUT dump against the declared "
        "hierarchy instead of (or in addition to) linting",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--no-lint", action="store_true",
        help="with --lock-order: skip the static lint pass",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules as rules_mod

        for rid, (_fn, doc) in sorted(rules_mod.RULES.items()):
            print(f"{rid}  {doc}")
        return 0

    rc = 0
    if not args.no_lint:
        paths = args.paths or [os.path.join(engine.repo_root(), "surrealdb_tpu")]
        rules = (
            [r.strip().upper() for r in args.rules.split(",") if r.strip()]
            if args.rules
            else None
        )
        findings = engine.lint_paths(paths, rules=rules)
        if args.update_baseline:
            if args.paths or args.rules:
                # a restricted run sees a SUBSET of findings; writing it
                # would silently drop every other grandfathered entry and
                # break the next full-scope gate run
                print(
                    "error: --update-baseline requires the default full "
                    "scope (no path arguments, no --rules)",
                    file=sys.stderr,
                )
                return 2
            path = engine.write_baseline(findings, args.baseline)
            print(f"baseline written: {path} ({len(findings)} findings)")
            return 0
        baseline = engine.load_baseline(args.baseline)
        new, stale = engine.apply_baseline(findings, baseline)
        for f in new:
            print(f.render())
        for k in stale:
            print(f"warning: stale baseline entry (finding fixed — remove it): {k}")
        grandfathered = len(findings) - len(new)
        print(
            f"graftlint: {len(findings)} finding(s), {grandfathered} "
            f"baselined, {len(new)} new"
        )
        if new:
            rc = 1

    if args.lock_order:
        from . import lockorder

        errors, warnings = lockorder.check_dump(args.lock_order)
        for w in warnings:
            print(f"lock-order warning: {w}")
        for e in errors:
            print(f"lock-order ERROR: {e}")
        print(
            f"lock-order: {len(errors)} error(s), {len(warnings)} warning(s) "
            f"({args.lock_order})"
        )
        if errors:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
