"""graftlint core: module loading, suppression comments, baseline, runner.

Rules live in rules.py and register themselves in RULES; each rule is a
callable `rule(modules) -> List[Finding]` over the WHOLE module set (the
telemetry label-consistency rule is inherently cross-module; per-module
rules just loop).

Baselines: findings carry a STABLE key (rule + path + a rule-specific
symbol like the env-var name or enclosing function — never a line number,
so unrelated edits don't churn the file). The committed baseline
(scripts/graftlint/baseline.json) grandfathers pre-existing findings;
anything new fails the run. `--update-baseline` rewrites it from the
current findings — review the diff like any other code change.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable(-file)?=([A-Za-z0-9_,]+)")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    key: str  # stable baseline key (no line numbers)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Module:
    """One parsed source file + the comment-level suppression map."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> {rule ids}; a standalone suppression comment covers the
        # NEXT line, a trailing one covers its own
        self.suppressed: Dict[int, set] = {}
        self.file_suppressed: set = set()
        for i, ln in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(ln)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(2).split(",") if r.strip()}
            if m.group(1):  # disable-file
                self.file_suppressed |= rules
            elif ln.lstrip().startswith("#"):
                self.suppressed.setdefault(i + 1, set()).update(rules)
            else:
                self.suppressed.setdefault(i, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressed:
            return True
        return rule in self.suppressed.get(line, ())

    def enclosing_def(self, node: ast.AST) -> str:
        """Dotted name of the innermost function/class containing `node`
        (stable symbol for baseline keys)."""
        target = (node.lineno, getattr(node, "col_offset", 0))
        best: List[str] = []

        def walk(n: ast.AST, stack: List[str]) -> None:
            for child in ast.iter_child_nodes(n):
                name = getattr(child, "name", None)
                is_scope = isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                )
                lo = getattr(child, "lineno", None)
                hi = getattr(child, "end_lineno", None)
                if is_scope and lo is not None and hi is not None:
                    if lo <= target[0] <= hi:
                        stack.append(name)
                        best[:] = list(stack)
                        walk(child, stack)
                        stack.pop()
                else:
                    walk(child, stack)

        walk(self.tree, [])
        return ".".join(best) if best else "<module>"


def collect_modules(paths: List[str], root: Optional[str] = None) -> List[Module]:
    """Parse every .py under `paths` (files or directories). `root` anchors
    the repo-relative names used by allowlists and baseline keys."""
    if root is None:
        root = repo_root()
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(
                os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
            )
    out: List[Module] = []
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            out.append(Module(f, rel, src))
        except SyntaxError as e:  # surfaced as a finding, not a crash
            m = Module.__new__(Module)
            m.path, m.rel, m.source = f, rel.replace(os.sep, "/"), src
            m.lines, m.tree = src.splitlines(), ast.Module(body=[], type_ignores=[])
            m.suppressed, m.file_suppressed = {}, set()
            m.syntax_error = e  # type: ignore[attr-defined]
            out.append(m)
    return out


def repo_root() -> str:
    """The directory containing scripts/ (…/scripts/graftlint/engine.py)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


# ------------------------------------------------------------------ baseline
# IO shared with graftcheck (scripts/baselines.py); only the default path
# and the file comment are graftlint's own
_BASELINE_COMMENT = (
    "graftlint grandfathered findings: entries here do not fail the "
    "run. Keys are line-number-free so edits elsewhere don't churn "
    "this file. Shrink it; never grow it without a review."
)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, dict]:
    from scripts.baselines import load_baseline as _load

    return _load(path or default_baseline_path())


def write_baseline(findings: List[Finding], path: Optional[str] = None) -> str:
    from scripts.baselines import write_baseline as _write

    return _write(findings, path or default_baseline_path(), _BASELINE_COMMENT)


# ------------------------------------------------------------------ runner
def lint_paths(
    paths: List[str],
    rules: Optional[List[str]] = None,
    root: Optional[str] = None,
) -> List[Finding]:
    """Run (a subset of) the rules over `paths`; returns ALL findings —
    the caller applies the baseline."""
    from . import rules as rules_mod

    modules = collect_modules(paths, root=root)
    findings: List[Finding] = []
    for m in modules:
        err = getattr(m, "syntax_error", None)
        if err is not None:
            findings.append(
                Finding(
                    "GL000", m.rel, err.lineno or 1, 0,
                    f"syntax error: {err.msg}", f"GL000:{m.rel}",
                )
            )
    for rule_id, (fn, _doc) in rules_mod.RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        for f in fn(modules):
            mod = next((m for m in modules if m.rel == f.path), None)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def apply_baseline(
    findings: List[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[str]]:
    """Split into (new findings, stale baseline keys)."""
    from scripts.baselines import apply_baseline as _apply

    return _apply(findings, baseline)
