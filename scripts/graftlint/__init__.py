"""graftlint — engine-specific static analysis for surrealdb_tpu.

The reference codebase leans on TLA+ specs and Rust's borrow checker for
its concurrency/resource invariants (doc/tla/); a Python engine gets the
equivalent only by building it. graftlint is the static half of that
tooling (utils/locks.py is the runtime half): an AST-based rule engine
whose rules encode THIS codebase's invariants — the things reviewers used
to enforce from memory:

  GL001  raw threading.Thread/Timer outside bg.py — flight-recorder
         blind spots (unattributable threads in stack dumps, watchdog
         can't see them)
  GL002  jax.jit call sites in modules that never touch compile_log —
         phantom unattributed XLA compiles (the classic latency-swing
         mystery the compile log exists to kill)
  GL003  os.environ / os.getenv outside cnf.py — configuration entering
         the engine outside the sanctioned knob surface
  GL004  ds.transaction() whose handle can leak without commit()/cancel()
         on all paths — txn leaks the runtime detector only catches
         after the fact
  GL005  blocking host sync (np.asarray, .block_until_ready, device_get)
         inside dispatch hot-path files — a hidden serialization point in
         the coalescing pipeline
  GL006  telemetry metric hygiene — dynamic metric names (unbounded
         series), inconsistent label-key sets across call sites (broken
         Prometheus aggregation), high-cardinality label keys
  GL007  manual span names (tracing.pop / record_span_into) drifting
         from the telemetry.observe() family recorded in the same
         function — a drifted name breaks the trace<->metric join
  GL008  fault-handling hygiene (the failpoint engine's static twin):
         `while True` retry loops whose handler continues with no
         sleep/backoff (a CPU-speed hammer on a failing dependency),
         and broad `except Exception: pass` swallows that erase the
         evidence every recovery path needs
  GL009  event-timeline hygiene (events.py's static twin): emissions
         must go through events.emit(kind, ...) with a kind from the
         declared KINDS registry — dynamic/unregistered kinds and
         ad-hoc appends to the ring are un-filterable, un-alertable
         timeline entries
  GL010  `except BaseException` that terminates the exception outside
         the sanctioned supervisor sites (bg.py service loops,
         faults.py) — it swallows KeyboardInterrupt/SystemExit and the
         sanitizer's control exceptions; cleanup-then-re-raise is the
         allowed shape everywhere else

Workflow:

  python -m scripts.graftlint                    # lint surrealdb_tpu/
  python -m scripts.graftlint --update-baseline  # grandfather findings
  python -m scripts.graftlint --lock-order F     # check a sanitizer dump

Findings not in scripts/graftlint/baseline.json fail the run (exit 1).
Intentional exceptions are annotated in source with
`# graftlint: disable=GL00X` (same line or the line above) or
`# graftlint: disable-file=GL00X` anywhere in the file.
"""

from .engine import Finding, lint_paths, load_baseline  # noqa: F401
