"""graftlint rules GL001–GL010 (see package docstring for the catalog).

Each rule is `fn(modules: List[Module]) -> List[Finding]`. Rules are
deliberately HEURISTIC — they encode this codebase's conventions, not a
soundness proof — and every rule supports `# graftlint: disable=GL00X`
for the rare intentional exception (the suppression is visible in review,
which is the point).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Finding, Module

# rule id -> (fn, one-line doc); populated by @_rule below
RULES: Dict[str, Tuple] = {}


def _rule(rule_id: str, doc: str):
    def deco(fn):
        RULES[rule_id] = (fn, doc)
        return fn

    return deco


def _call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(receiver, attr) for `recv.attr(...)`, (None, name) for `name(...)`."""
    f = node.func
    if isinstance(f, ast.Attribute):
        recv = f.value.id if isinstance(f.value, ast.Name) else None
        return recv, f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, ""


def _imports_of(m: Module) -> Set[str]:
    """Every module path this file imports (absolute, dotted)."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            out.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module:
            out.add(node.module)
            out.update(f"{node.module}.{a.name}" for a in node.names)
    return out


def _from_imports(m: Module, module: str) -> Set[str]:
    """Names imported via `from <module> import ...` in this file."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            out.update(a.asname or a.name for a in node.names)
    return out


# ------------------------------------------------------------------ GL001
# Threads the flight recorder cannot see: bg.py owns ALL thread/timer
# creation (spawn/spawn_service/start_thread/timer) so every thread has a
# registry entry, a deterministic name, and watchdog coverage.
GL001_ALLOWED_FILES = frozenset({"surrealdb_tpu/bg.py"})


@_rule("GL001", "raw threading.Thread/Timer outside bg.py")
def gl001(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL001_ALLOWED_FILES:
            continue
        direct = _from_imports(m, "threading")
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            hit = (
                attr in ("Thread", "Timer")
                and (
                    (recv is not None and "threading" in recv)
                    or (recv is None and attr in direct)
                )
            )
            if hit:
                out.append(
                    Finding(
                        "GL001", m.rel, node.lineno, node.col_offset,
                        f"raw threading.{attr} — spawn via surrealdb_tpu.bg "
                        "(spawn/spawn_service/start_thread/timer) so the "
                        "flight recorder sees it",
                        f"GL001:{m.rel}:{m.enclosing_def(node)}:{attr}",
                    )
                )
    return out


# ------------------------------------------------------------------ GL002
# Kernel-definition-only modules: their jitted functions are invoked (and
# compile_log-wrapped) by callers, never launched here.
GL002_KERNEL_DEF_MODULES = frozenset(
    {
        "surrealdb_tpu/ops/bm25.py",
        "surrealdb_tpu/ops/distances.py",
        "surrealdb_tpu/parallel/mesh.py",
    }
)


@_rule("GL002", "jax.jit site in a module that never touches compile_log")
def gl002(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL002_KERNEL_DEF_MODULES:
            continue
        if "compile_log" in m.source and (
            "surrealdb_tpu.compile_log" in _imports_of(m)
            or "compile_log" in _from_imports(m, "surrealdb_tpu")
        ):
            continue
        for node in ast.walk(m.tree):
            jit_site: Optional[ast.AST] = None
            if isinstance(node, ast.Call):
                recv, attr = _call_name(node)
                if attr == "jit" and recv == "jax":
                    jit_site = node
                # functools.partial(jax.jit, ...)
                elif attr == "partial" and node.args:
                    a0 = node.args[0]
                    if (
                        isinstance(a0, ast.Attribute)
                        and a0.attr == "jit"
                        and isinstance(a0.value, ast.Name)
                        and a0.value.id == "jax"
                    ):
                        jit_site = node
            elif isinstance(node, ast.Attribute) and node.attr == "jit":
                # bare @jax.jit decorator (no call parens)
                if isinstance(node.value, ast.Name) and node.value.id == "jax":
                    jit_site = node
            if jit_site is not None:
                out.append(
                    Finding(
                        "GL002", m.rel, node.lineno, node.col_offset,
                        "jax.jit in a module with no compile_log wiring — "
                        "first-call XLA compiles here are phantom "
                        "(unattributed) latency; wrap launch sites with "
                        "compile_log.tracked(...)",
                        f"GL002:{m.rel}:{m.enclosing_def(node)}",
                    )
                )
                break  # one finding per scope is enough; key is per-def
    # de-dup same-key findings (break above only stops the walk early)
    seen: Set[str] = set()
    uniq = []
    for f in out:
        if f.key not in seen:
            seen.add(f.key)
            uniq.append(f)
    return uniq


# ------------------------------------------------------------------ GL003
GL003_ALLOWED_FILES = frozenset({"surrealdb_tpu/cnf.py"})


@_rule("GL003", "os.environ/os.getenv outside cnf.py")
def gl003(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL003_ALLOWED_FILES:
            continue
        direct = _from_imports(m, "os")
        for node in ast.walk(m.tree):
            name = None
            if isinstance(node, ast.Attribute) and node.attr in (
                "environ", "getenv",
            ):
                if isinstance(node.value, ast.Name) and node.value.id in (
                    "os", "_os",
                ):
                    name = node.attr
            elif isinstance(node, ast.Name) and node.id in direct and node.id in (
                "environ", "getenv",
            ):
                name = node.id
            if name is None:
                continue
            env_var = _nearest_env_literal(m, node)
            detail = env_var or m.enclosing_def(node)
            out.append(
                Finding(
                    "GL003", m.rel, node.lineno, node.col_offset,
                    f"os.{name} outside cnf.py — route through a cnf knob "
                    "or cnf.env_* helper"
                    + (f" (variable {env_var})" if env_var else ""),
                    f"GL003:{m.rel}:{detail}",
                )
            )
    return out


def _nearest_env_literal(m: Module, node: ast.AST) -> Optional[str]:
    """The env-var string literal on the same source line, if any (stable
    baseline detail)."""
    try:
        line = m.lines[node.lineno - 1]
    except IndexError:
        return None
    import re as _re

    lits = _re.findall(r"[\"']([A-Z][A-Z0-9_]{2,})[\"']", line)
    return lits[0] if lits else None


# ------------------------------------------------------------------ GL004
@_rule("GL004", "transaction handle without commit/cancel on any path")
def gl004(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        for fn in ast.walk(m.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.extend(_gl004_check_fn(m, fn))
    return out


def _gl004_check_fn(m: Module, fn: ast.AST) -> List[Finding]:
    # local names assigned from `<expr>.transaction(...)`
    tx_names: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "transaction"
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            tx_names[node.targets[0].id] = node
    if not tx_names:
        return []
    finished: Set[str] = set()
    escaped: Set[str] = set()
    for node in ast.walk(fn):
        # txn.commit() / txn.cancel() finishes it
        if isinstance(node, ast.Attribute) and node.attr in ("commit", "cancel"):
            if isinstance(node.value, ast.Name) and node.value.id in tx_names:
                finished.add(node.value.id)
        # escapes: returned / yielded / passed to a call / stored on an
        # object / re-assigned to something else — ownership moved, the
        # callee or holder is responsible
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = node.value
            if v is not None:
                for n in ast.walk(v):
                    if isinstance(n, ast.Name) and n.id in tx_names:
                        escaped.add(n.id)
        elif isinstance(node, ast.Call):
            for n in list(node.args) + [kw.value for kw in node.keywords]:
                for nn in ast.walk(n):
                    if isinstance(nn, ast.Name) and nn.id in tx_names:
                        escaped.add(nn.id)
        elif isinstance(node, ast.Assign):
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id in tx_names:
                    if not (
                        isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "transaction"
                    ):
                        escaped.add(n.id)
    out: List[Finding] = []
    for name, site in tx_names.items():
        if name in finished or name in escaped:
            continue
        out.append(
            Finding(
                "GL004", m.rel, site.lineno, site.col_offset,
                f"transaction `{name}` has no commit()/cancel() in "
                f"{m.enclosing_def(site)} and never escapes — leaks its "
                "snapshot until GC (the runtime detector fires after the "
                "fact; fix the path)",
                f"GL004:{m.rel}:{m.enclosing_def(site)}:{name}",
            )
        )
    return out


# ------------------------------------------------------------------ GL005
# Files whose functions are dispatch hot path: a blocking host sync here
# serializes the whole coalescing pipeline. Other files opt in with a
# `# graftlint: hot-path` comment anywhere in the file.
GL005_HOT_FILES = frozenset({"surrealdb_tpu/dbs/dispatch.py"})
GL005_BLOCKING_ATTRS = frozenset({"block_until_ready", "device_get", "tolist"})
GL005_NP_SYNC = frozenset({"asarray", "array"})
GL005_NP_NAMES = frozenset({"np", "numpy", "onp", "jnp"})


@_rule("GL005", "blocking host sync inside dispatch hot-path files")
def gl005(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        hot = m.rel in GL005_HOT_FILES or any(
            "graftlint: hot-path" in ln for ln in m.lines[:50]
        )
        if not hot:
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            hit = attr in GL005_BLOCKING_ATTRS or (
                attr in GL005_NP_SYNC and recv in GL005_NP_NAMES
            )
            if hit:
                out.append(
                    Finding(
                        "GL005", m.rel, node.lineno, node.col_offset,
                        f"blocking host sync `.{attr}(...)` on the dispatch "
                        "hot path — this serializes every rider of the "
                        "coalesced batch; move it to a collect phase / the "
                        "runner closure",
                        f"GL005:{m.rel}:{m.enclosing_def(node)}:{attr}",
                    )
                )
    return out


# ------------------------------------------------------------------ GL006
GL006_WRITERS = frozenset(
    {"inc", "observe", "observe_hist", "gauge_set", "gauge_add", "span"}
)
# positional/config kwargs that are NOT metric labels
GL006_NON_LABEL_KWARGS = frozenset({"by", "buckets"})
GL006_FORBIDDEN_LABELS = frozenset({"id", "trace_id", "sql", "query", "path"})
GL006_NAME_RE = r"^[a-z][a-z0-9_]*$"


@_rule("GL006", "telemetry metric-name / label-cardinality hygiene")
def gl006(modules: List[Module]) -> List[Finding]:
    import re as _re

    out: List[Finding] = []
    # metric -> {frozenset(label keys) -> [(module, node), ...] all sites}
    label_sets: Dict[str, Dict[frozenset, List[Tuple[Module, ast.Call]]]] = {}
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            if recv != "telemetry" or attr not in GL006_WRITERS:
                continue
            if not node.args:
                continue
            name_node = node.args[0]
            if not (
                isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)
            ):
                out.append(
                    Finding(
                        "GL006", m.rel, node.lineno, node.col_offset,
                        f"telemetry.{attr} with a DYNAMIC metric name — "
                        "unbounded series cardinality; use a static name "
                        "and put the variable part in a label",
                        f"GL006:{m.rel}:{m.enclosing_def(node)}:dynamic-name",
                    )
                )
                continue
            metric = name_node.value
            if not _re.match(GL006_NAME_RE, metric):
                out.append(
                    Finding(
                        "GL006", m.rel, node.lineno, node.col_offset,
                        f"metric name {metric!r} is not a valid Prometheus "
                        "base name ([a-z][a-z0-9_]*)",
                        f"GL006:{metric}:name",
                    )
                )
            keys = []
            for kw in node.keywords:
                if kw.arg is None:
                    out.append(
                        Finding(
                            "GL006", m.rel, node.lineno, node.col_offset,
                            f"telemetry.{attr}({metric!r}, **dynamic) — "
                            "label KEYS must be static keywords",
                            f"GL006:{metric}:dynamic-labels",
                        )
                    )
                    continue
                if kw.arg in GL006_NON_LABEL_KWARGS:
                    continue
                keys.append(kw.arg)
                if kw.arg in GL006_FORBIDDEN_LABELS:
                    out.append(
                        Finding(
                            "GL006", m.rel, node.lineno, node.col_offset,
                            f"label key {kw.arg!r} on {metric!r} is "
                            "high-cardinality by construction (per-request "
                            "values) — join via the slow/error rings or "
                            "traces instead",
                            f"GL006:{metric}:label:{kw.arg}",
                        )
                    )
            label_sets.setdefault(metric, {}).setdefault(
                frozenset(keys), []
            ).append((m, node))
    # cross-site consistency: one metric, one label-key set (Prometheus
    # aggregation breaks silently otherwise). Canonical = the set used at
    # the MOST call sites (an outlier new site must not out-vote five
    # established ones just by carrying more keys); ties break to the
    # larger set.
    for metric, sets in sorted(label_sets.items()):
        if len(sets) <= 1:
            continue
        majority = max(sets, key=lambda s: (len(sets[s]), len(s), sorted(s)))
        for keyset, sites in sorted(
            sets.items(), key=lambda kv: (kv[1][0][0].rel, kv[1][0][1].lineno)
        ):
            if keyset == majority:
                continue
            m, node = sites[0]
            out.append(
                Finding(
                    "GL006", m.rel, node.lineno, node.col_offset,
                    f"metric {metric!r} emitted with label keys "
                    f"{sorted(keyset) or '[]'} here ({len(sites)} site(s)) "
                    f"but {sorted(majority)} at {len(sets[majority])} "
                    "other site(s) — inconsistent label sets break "
                    "aggregation",
                    f"GL006:{metric}:labelset:{','.join(sorted(keyset))}",
                )
            )
    return out


# ------------------------------------------------------------------ GL007
# Manual span-record calls that hand-name their span: tracing.pop's 2nd
# positional arg, tracing.record_span_into's 2nd positional arg.
GL007_SPAN_RECORDERS = {"pop": 1, "record_span_into": 1}


@_rule("GL007", "manual span name drifting from its observe() metric family")
def gl007(modules: List[Module]) -> List[Finding]:
    """Sites that record a span by hand (tracing.pop / record_span_into)
    AND feed a duration histogram (telemetry.observe) in the same function
    must use ONE name for both — the span tree and the metric family are
    two views of the same instrument, and a drifted name breaks the
    trace<->metric join (`knn_search` spans with an `ivf_probe` histogram
    would never correlate). telemetry.span() is exempt: it feeds both from
    one name by construction."""
    out: List[Finding] = []
    for m in modules:
        for fn in ast.walk(m.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            observes: Set[str] = set()
            spans: List[Tuple[str, ast.Call]] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                recv, attr = _call_name(node)
                if recv == "telemetry" and attr == "observe" and node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                        observes.add(a0.value)
                elif recv == "tracing" and attr in GL007_SPAN_RECORDERS:
                    idx = GL007_SPAN_RECORDERS[attr]
                    if len(node.args) > idx:
                        a = node.args[idx]
                        if isinstance(a, ast.Constant) and isinstance(a.value, str):
                            spans.append((a.value, node))
            if not observes or not spans:
                continue
            for name, node in spans:
                if name in observes:
                    continue
                out.append(
                    Finding(
                        "GL007", m.rel, node.lineno, node.col_offset,
                        f"manual span {name!r} recorded in a function whose "
                        f"observe() families are {sorted(observes)} — span "
                        "name and metric family must match for the "
                        "trace<->metric join; rename one (or move the span "
                        "to telemetry.span())",
                        f"GL007:{m.rel}:{m.enclosing_def(node)}:{name}",
                    )
                )
    return out


# ------------------------------------------------------------------ GL008
# Fault-handling hygiene (the failpoint engine's static companion): a retry
# loop with no backoff hammers whatever just failed, and a bare
# `except Exception: pass` erases the evidence every recovery path needs.
GL008_BROAD_TYPES = frozenset({"Exception", "BaseException"})
GL008_PACING_CALLS = frozenset({"sleep", "wait"})


def _gl008_is_const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and test.value is True


def _gl008_has_pacing(node: ast.AST) -> bool:
    """A sleep()/Event.wait()-class call anywhere in the loop body — the
    minimum evidence of backoff between retry attempts."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            _, attr = _call_name(sub)
            if attr in GL008_PACING_CALLS:
                return True
    return False


@_rule("GL009", "event emitted outside events.emit / with an unregistered kind")
def gl009(modules: List[Module]) -> List[Finding]:
    """The event timeline (surrealdb_tpu/events.py) is a CLOSED registry:
    every emission goes through `events.emit(kind, ...)` with a kind
    declared in events.KINDS — a dynamic or unregistered kind is a
    timeline entry nobody can filter, alert on, or document, and an
    ad-hoc append to the ring (`events._ring`) bypasses the trace link,
    the counter, and the runtime registry check."""
    kinds = _gl009_registry()
    out: List[Finding] = []
    for m in modules:
        if m.rel == "surrealdb_tpu/events.py":
            continue
        # direct-import aliases: `from surrealdb_tpu.events import emit`
        # (or `emit as e`) must not bypass the rule, and importing the
        # ring itself is flagged at the import site
        emit_names: Set[str] = set()
        for imp in ast.walk(m.tree):
            if not (
                isinstance(imp, ast.ImportFrom)
                and imp.module == "surrealdb_tpu.events"
            ):
                continue
            for a in imp.names:
                if a.name == "emit":
                    emit_names.add(a.asname or a.name)
                elif a.name == "_ring":
                    out.append(
                        Finding(
                            "GL009", m.rel, imp.lineno, imp.col_offset,
                            "importing events._ring — the timeline is "
                            "written only through events.emit(kind, ...) "
                            "(trace link + counter + registry check)",
                            f"GL009:{m.rel}:import:_ring",
                        )
                    )
        for node in ast.walk(m.tree):
            # (a) ad-hoc ring access: events._ring.<anything> outside the
            # module that owns it
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "_ring"
                and isinstance(node.value, ast.Name)
                and node.value.id in ("events", "_events")
            ):
                out.append(
                    Finding(
                        "GL009", m.rel, node.lineno, node.col_offset,
                        "direct events._ring access — the timeline is "
                        "written only through events.emit(kind, ...) "
                        "(trace link + counter + registry check)",
                        f"GL009:{m.rel}:{m.enclosing_def(node)}:ring",
                    )
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            is_emit = (attr == "emit" and recv in ("events", "_events")) or (
                recv is None and attr in emit_names
            )
            if not is_emit:
                continue
            if not node.args:
                continue
            names = _gl009_kind_strings(node.args[0])
            if names is None:
                out.append(
                    Finding(
                        "GL009", m.rel, node.lineno, node.col_offset,
                        "events.emit with a DYNAMIC kind — kinds are a "
                        "closed registry (events.KINDS); use a static "
                        "registered string and put the variable part in "
                        "a field",
                        f"GL009:{m.rel}:{m.enclosing_def(node)}:dynamic-kind",
                    )
                )
                continue
            for name in names:
                if kinds is not None and name not in kinds:
                    out.append(
                        Finding(
                            "GL009", m.rel, node.lineno, node.col_offset,
                            f"events.emit kind {name!r} is not in the "
                            "events.KINDS registry — register it (with a "
                            "description) before emitting",
                            f"GL009:{m.rel}:kind:{name}",
                        )
                    )
    return out


def _gl009_kind_strings(a0: ast.AST) -> Optional[List[str]]:
    """Static kind candidates of an emit's first arg: a string constant,
    or a conditional expression whose branches both resolve statically
    (`"a.up" if up else "a.down"` names two registered kinds). None means
    the kind is dynamic."""
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return [a0.value]
    if isinstance(a0, ast.IfExp):
        body = _gl009_kind_strings(a0.body)
        orelse = _gl009_kind_strings(a0.orelse)
        if body is not None and orelse is not None:
            return body + orelse
    return None


def _gl009_registry() -> Optional[Set[str]]:
    """The declared kind registry. Imported from the real module (linting
    runs from the repo root) so the rule and the runtime check can never
    drift; None (skip the kind check) if the engine is unimportable."""
    try:
        from surrealdb_tpu.events import KINDS

        return set(KINDS)
    except Exception:  # noqa: BLE001 — lint must not require a working engine
        return None


# ------------------------------------------------------------------ GL010
# BaseException catches KeyboardInterrupt/SystemExit and the sanitizer's
# own control exceptions: outside the supervisor sites that deliberately
# firewall service loops (bg.py) and the fault-injection engine
# (faults.py), a handler may only catch BaseException to CLEAN UP AND
# RE-RAISE. A handler that terminates the exception converts a process
# shutdown into a half-alive engine.
GL010_ALLOWED_FILES = frozenset(
    {"surrealdb_tpu/bg.py", "surrealdb_tpu/faults.py"}
)


@_rule("GL010", "except BaseException without re-raise outside bg.py/faults.py")
def gl010(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL010_ALLOWED_FILES:
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            names = []
            if isinstance(t, ast.Name):
                names = [t.id]
            elif isinstance(t, ast.Tuple):
                names = [e.id for e in t.elts if isinstance(e, ast.Name)]
            # a bare `except:` IS `except BaseException:` — same hazard
            if t is not None and "BaseException" not in names:
                continue
            # cleanup-then-propagate is the sanctioned shape: any raise
            # inside the handler body keeps the exception alive
            if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
                continue
            out.append(
                Finding(
                    "GL010", m.rel, node.lineno, node.col_offset,
                    "`except BaseException` that terminates the exception "
                    "— this swallows KeyboardInterrupt/SystemExit too; "
                    "narrow to Exception, or re-raise after cleanup "
                    "(supervisor firewalls live only in bg.py/faults.py)",
                    f"GL010:{m.rel}:{m.enclosing_def(node)}",
                )
            )
    return out


# ------------------------------------------------------------------ GL011
# Every named engine lock must be declared in utils/locks.HIERARCHY: the
# runtime sanitizer can only prove an order for levels it knows, and the
# graftflow static lock-order proof (GF001) skips undeclared names
# entirely — an undeclared lock is a lock with NO deadlock coverage.
# Today an unhierarchied name is only caught when a sanitized test run
# happens to nest it; this rule fails it at lint time, before any test.
GL011_ALLOWED_FILES = frozenset({"surrealdb_tpu/utils/locks.py"})
GL011_LOCK_RECEIVERS = frozenset({"locks", "_locks"})
GL011_LOCKS_MODULE = "surrealdb_tpu.utils.locks"


def _gl011_lock_aliases(m: Module) -> Set[str]:
    """Every local alias the locks module is importable under in this
    file — `import surrealdb_tpu.utils.locks as lk` must not dodge the
    rule just by not being named 'locks'/'_locks'."""
    out = set(GL011_LOCK_RECEIVERS)
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == GL011_LOCKS_MODULE and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                full = f"{node.module}.{a.name}"
                if full == GL011_LOCKS_MODULE or (
                    a.name == "locks" and node.module.endswith("utils")
                ):
                    out.add(a.asname or a.name)
    return out


def _gl011_hierarchy():
    """Imported from the REAL module (linting runs from the repo root) so
    the rule and the runtime check can never drift; None skips the check."""
    try:
        from surrealdb_tpu.utils.locks import HIERARCHY

        return set(HIERARCHY)
    except Exception:  # noqa: BLE001 — lint must not require a working engine
        return None


@_rule("GL011", "locks.Lock/RLock name missing from the declared HIERARCHY")
def gl011(modules: List[Module]) -> List[Finding]:
    declared = _gl011_hierarchy()
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL011_ALLOWED_FILES:
            continue
        aliases = _gl011_lock_aliases(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            if attr not in ("Lock", "RLock") or recv not in aliases:
                continue
            a0 = node.args[0] if node.args else None
            if a0 is None:  # locks.Lock(name="...") is legal too
                a0 = next(
                    (kw.value for kw in node.keywords if kw.arg == "name"), None
                )
            if not (isinstance(a0, ast.Constant) and isinstance(a0.value, str)):
                out.append(
                    Finding(
                        "GL011", m.rel, node.lineno, node.col_offset,
                        f"locks.{attr} with a DYNAMIC (or missing) name — "
                        "lock names are the unit of the declared order; use "
                        "a static string registered in locks.HIERARCHY",
                        f"GL011:{m.rel}:{m.enclosing_def(node)}:dynamic-name",
                    )
                )
                continue
            name = a0.value
            if declared is not None and name not in declared:
                out.append(
                    Finding(
                        "GL011", m.rel, node.lineno, node.col_offset,
                        f"lock name {name!r} is not declared in "
                        "utils/locks.HIERARCHY — it has no level, so neither "
                        "the runtime sanitizer nor graftflow GF001 can prove "
                        "any ordering against it; declare it (with a level) "
                        "before acquiring it",
                        f"GL011:{m.rel}:name:{name}",
                    )
                )
    return out


@_rule("GL008", "retry loop without backoff/attempt cap; bare except-swallow")
def gl008(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        for node in ast.walk(m.tree):
            # (a) swallow: a broad handler whose whole body is `pass` —
            # the failure is erased, not handled (narrow the type, log it,
            # or record it somewhere a human can find)
            if isinstance(node, ast.ExceptHandler):
                t = node.type
                broad = t is None or (
                    isinstance(t, ast.Name) and t.id in GL008_BROAD_TYPES
                )
                if broad and all(isinstance(b, ast.Pass) for b in node.body):
                    out.append(
                        Finding(
                            "GL008", m.rel, node.lineno, node.col_offset,
                            "bare `except Exception: pass` swallows the "
                            "failure with no trace — narrow the type, or "
                            "record it (telemetry/bg record/log) before "
                            "continuing",
                            f"GL008:{m.rel}:{m.enclosing_def(node)}:swallow",
                        )
                    )
            # (b) unbounded retry loop with no pacing: `while True` whose
            # exception handler `continue`s straight back into the attempt
            # with no sleep/wait anywhere in the loop — a tight hammer on
            # whatever just failed
            elif isinstance(node, ast.While) and _gl008_is_const_true(node.test):
                retries = any(
                    isinstance(sub, ast.Try)
                    and any(
                        any(isinstance(x, ast.Continue) for x in ast.walk(h))
                        for h in sub.handlers
                    )
                    for sub in ast.walk(node)
                )
                if retries and not _gl008_has_pacing(node):
                    out.append(
                        Finding(
                            "GL008", m.rel, node.lineno, node.col_offset,
                            "`while True` retry loop with no backoff — a "
                            "failing dependency gets hammered at CPU speed; "
                            "add exponential backoff (and an attempt cap) "
                            "or bound the loop",
                            f"GL008:{m.rel}:{m.enclosing_def(node)}:retry",
                        )
                    )
    return out


# ------------------------------------------------------------------ GL012
# The statement-statistics store (surrealdb_tpu/stats.py) has ONE write
# door: stats.record(). It owns the lock discipline (mutate under
# stats.store, emit events/counters only after release) and the plan-flip
# detection; an ad-hoc writer reaching into the private store, the
# activation table, or the entry class would bypass both. Outside
# stats.py, touching any private member of the stats module is a finding.
GL012_ALLOWED_FILES = frozenset({"surrealdb_tpu/stats.py"})
GL012_STATS_MODULE = "surrealdb_tpu.stats"
GL012_PRIVATE = frozenset(
    {"_store", "_lock", "_active_by_thread", "_Entry", "_evicted",
     "_note_evictions"}
)


def _gl012_stats_aliases(m: Module) -> Set[str]:
    """Every local NAME the stats module is bound to in this file
    (`from surrealdb_tpu import stats [as _stats]`,
    `import surrealdb_tpu.stats as x`). A plain
    `import surrealdb_tpu.stats` binds only `surrealdb_tpu` — that access
    path is matched as the dotted chain in gl012(), not as an alias."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == GL012_STATS_MODULE and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if (
                    f"{node.module}.{a.name}" == GL012_STATS_MODULE
                    or (a.name == "stats" and node.module == "surrealdb_tpu")
                ):
                    out.add(a.asname or a.name)
    return out


def _gl012_dotted(node) -> Optional[str]:
    """`a.b.c` rendered as a dotted name, None when the chain's root is
    not a plain Name (a call/subscript can't be the module)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@_rule("GL012", "ad-hoc access to the statement-stats store outside stats.record()")
def gl012(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL012_ALLOWED_FILES:
            continue
        aliases = _gl012_stats_aliases(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in GL012_PRIVATE:
                continue
            via_alias = (
                isinstance(node.value, ast.Name) and node.value.id in aliases
            )
            # the dotted form a plain `import surrealdb_tpu.stats` enables
            via_dotted = _gl012_dotted(node.value) == GL012_STATS_MODULE
            if not (via_alias or via_dotted):
                continue
            out.append(
                Finding(
                    "GL012", m.rel, node.lineno, node.col_offset,
                    f"stats.{node.attr} accessed outside stats.py — "
                    "statement-stats recording must go through "
                    "stats.record() (the one door that keeps the lock "
                    "discipline and the plan-flip detection honest)",
                    f"GL012:{m.rel}:{m.enclosing_def(node)}:{node.attr}",
                )
            )
    return out


# ------------------------------------------------------------------ GL013
# The tenant cost-attribution store (surrealdb_tpu/accounting.py) has ONE
# write door: accounting.charge(). It owns the lock discipline (mutate
# under accounting.store, emit breach events/counters only after release),
# the budget crossing detection and the store/fp-cap eviction; an ad-hoc
# writer reaching into the private store, the activation/tally tables, or
# the entry class would bypass all three — and break the conservation
# property the bench validator enforces. Outside accounting.py, touching
# any private member of the accounting module is a finding.
GL013_ALLOWED_FILES = frozenset({"surrealdb_tpu/accounting.py"})
GL013_ACCT_MODULE = "surrealdb_tpu.accounting"
GL013_PRIVATE = frozenset(
    {"_store", "_lock", "_global", "_evicted", "_Entry",
     "_active_by_thread", "_tally_by_thread", "_tenant_ctx",
     "_budget_cache"}
)


def _gl013_acct_aliases(m: Module) -> Set[str]:
    """Every local NAME the accounting module is bound to in this file
    (mirrors _gl012_stats_aliases; a plain `import surrealdb_tpu.accounting`
    is matched as the dotted chain in gl013())."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == GL013_ACCT_MODULE and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if (
                    f"{node.module}.{a.name}" == GL013_ACCT_MODULE
                    or (a.name == "accounting" and node.module == "surrealdb_tpu")
                ):
                    out.add(a.asname or a.name)
    return out


@_rule("GL013", "ad-hoc access to the tenant-accounting store outside accounting.charge()")
def gl013(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL013_ALLOWED_FILES:
            continue
        aliases = _gl013_acct_aliases(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in GL013_PRIVATE:
                continue
            via_alias = (
                isinstance(node.value, ast.Name) and node.value.id in aliases
            )
            via_dotted = _gl012_dotted(node.value) == GL013_ACCT_MODULE
            if not (via_alias or via_dotted):
                continue
            out.append(
                Finding(
                    "GL013", m.rel, node.lineno, node.col_offset,
                    f"accounting.{node.attr} accessed outside accounting.py "
                    "— tenant-meter mutation must go through "
                    "accounting.charge() (the one door that keeps the lock "
                    "discipline, budget detection and conservation honest)",
                    f"GL013:{m.rel}:{m.enclosing_def(node)}:{node.attr}",
                )
            )
    return out


# ------------------------------------------------------------------ GL014
# The advisor proposal store (surrealdb_tpu/advisor.py) has ONE
# construction door: advisor.propose(). It owns the stable-id derivation,
# the re-arm-vs-duplicate lifecycle, the kind/evidence validation and the
# lock discipline (mutate under advisor.store, emit proposal events only
# after release). The rule has two halves, mirroring GL012/GL013 on the
# store side and GL009 on the call side: (a) outside advisor.py, touching
# any private member of the advisor module is a finding; (b) every
# propose() call site must name a STATIC kind that is registered in
# advisor.KINDS (imported from the real module so the static and runtime
# checks can never drift) and must pass a non-empty `evidence=` argument
# — a proposal without a resolvable evidence chain is an opinion.
GL014_ALLOWED_FILES = frozenset({"surrealdb_tpu/advisor.py"})
GL014_ADVISOR_MODULE = "surrealdb_tpu.advisor"
GL014_PRIVATE = frozenset(
    {"_store", "_lock", "_expired_ring", "_evicted", "_sweeps",
     "_last_sweep", "_counter_base", "_digest", "_expire_missing"}
)


def _gl014_advisor_aliases(m: Module) -> Set[str]:
    """Every local NAME the advisor module is bound to in this file
    (mirrors _gl013_acct_aliases)."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == GL014_ADVISOR_MODULE and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if (
                    f"{node.module}.{a.name}" == GL014_ADVISOR_MODULE
                    or (a.name == "advisor" and node.module == "surrealdb_tpu")
                ):
                    out.add(a.asname or a.name)
    return out


def _gl014_propose_aliases(m: Module) -> Set[str]:
    """Direct-import aliases of the door itself:
    `from surrealdb_tpu.advisor import propose (as p)`."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module == GL014_ADVISOR_MODULE
        ):
            for a in node.names:
                if a.name == "propose":
                    out.add(a.asname or a.name)
    return out


def _gl014_registry() -> Optional[Set[str]]:
    """The declared proposal-kind registry, imported from the real module
    (the GL009 pattern); None skips the kind check if the engine is
    unimportable — lint must not require a working engine."""
    try:
        from surrealdb_tpu.advisor import KINDS

        return set(KINDS)
    except Exception:  # noqa: BLE001
        return None


@_rule("GL014", "advisor proposals constructed outside advisor.propose() "
                "or with an unregistered kind / missing evidence")
def gl014(modules: List[Module]) -> List[Finding]:
    kinds = _gl014_registry()
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL014_ALLOWED_FILES:
            continue
        aliases = _gl014_advisor_aliases(m)
        propose_names = _gl014_propose_aliases(m)
        for node in ast.walk(m.tree):
            # (a) private store access outside advisor.py
            if isinstance(node, ast.Attribute):
                if node.attr not in GL014_PRIVATE:
                    continue
                via_alias = (
                    isinstance(node.value, ast.Name)
                    and node.value.id in aliases
                )
                via_dotted = _gl012_dotted(node.value) == GL014_ADVISOR_MODULE
                if not (via_alias or via_dotted):
                    continue
                out.append(
                    Finding(
                        "GL014", m.rel, node.lineno, node.col_offset,
                        f"advisor.{node.attr} accessed outside advisor.py — "
                        "proposals must go through advisor.propose() (the "
                        "one door that keeps the stable-id lifecycle, the "
                        "kind/evidence validation and the lock discipline "
                        "honest)",
                        f"GL014:{m.rel}:{m.enclosing_def(node)}:{node.attr}",
                    )
                )
                continue
            # (b) propose() call-site hygiene
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            is_propose = (
                attr == "propose" and recv in aliases
            ) or (recv is None and attr in propose_names)
            if not is_propose:
                continue
            kind_arg = node.args[0] if node.args else None
            if kind_arg is None:
                for kw in node.keywords:
                    if kw.arg == "kind":
                        kind_arg = kw.value
            if not (
                isinstance(kind_arg, ast.Constant)
                and isinstance(kind_arg.value, str)
            ):
                out.append(
                    Finding(
                        "GL014", m.rel, node.lineno, node.col_offset,
                        "advisor.propose with a DYNAMIC kind — proposal "
                        "kinds are a closed registry (advisor.KINDS); use "
                        "a static registered string and put the variable "
                        "part in the subject",
                        f"GL014:{m.rel}:{m.enclosing_def(node)}:dynamic-kind",
                    )
                )
            elif kinds is not None and kind_arg.value not in kinds:
                out.append(
                    Finding(
                        "GL014", m.rel, node.lineno, node.col_offset,
                        f"advisor.propose kind {kind_arg.value!r} is not in "
                        "the advisor.KINDS registry — register it (with a "
                        "description) before proposing",
                        f"GL014:{m.rel}:kind:{kind_arg.value}",
                    )
                )
            evidence = None
            for kw in node.keywords:
                if kw.arg == "evidence":
                    evidence = kw.value
                if kw.arg is None:
                    evidence = evidence or True  # **kwargs: can't see inside
            if evidence is None:
                out.append(
                    Finding(
                        "GL014", m.rel, node.lineno, node.col_offset,
                        "advisor.propose without an evidence= argument — a "
                        "proposal without a resolvable evidence chain is "
                        "an opinion, not a proposal",
                        f"GL014:{m.rel}:{m.enclosing_def(node)}:no-evidence",
                    )
                )
            elif (
                isinstance(evidence, (ast.List, ast.Tuple))
                and not evidence.elts
            ):
                out.append(
                    Finding(
                        "GL014", m.rel, node.lineno, node.col_offset,
                        "advisor.propose with an EMPTY evidence list — at "
                        "least one {plane, metric, window, value, "
                        "threshold} entry is required",
                        f"GL014:{m.rel}:{m.enclosing_def(node)}:empty-evidence",
                    )
                )
    return out


# ------------------------------------------------------------------ GL015
# The plan/pipeline cache (surrealdb_tpu/dbs/plan_cache.py) has ONE write
# door: the PlanCache methods themselves (fetch/observe/install_*/
# bump_generation/ddl_begin/ddl_end/on_plan_flip/note_epoch/clear). They
# own the lock discipline (mutate under plan_cache.store, emit eviction
# events/counters only after release) and the validation-on-serve
# contract — generation/epoch/scope stamps checked on every serve. An
# ad-hoc writer reaching into the private tables (`_entries`, `_gen`,
# route maps, the timing windows) would bypass both and could serve a
# stale plan, the one failure mode the cache is built to make impossible.
# Outside plan_cache.py, touching any private member of the module OR of
# a PlanCache INSTANCE (any attribute chain ending in `.plan_cache`, the
# datastore's handle) is a finding.
GL015_ALLOWED_FILES = frozenset({"surrealdb_tpu/dbs/plan_cache.py"})
GL015_PC_MODULE = "surrealdb_tpu.dbs.plan_cache"
GL015_PRIVATE = frozenset(
    {"_entries", "_warm", "_by_stmt", "_index_defs", "_gen", "_inflight",
     "_epoch", "_timing", "_hits", "_misses", "_invalidations", "_verifies",
     "_evlog", "_lock", "_caches", "_serve_digest", "_serve_lexed",
     "_route_for", "_emit_evict", "_note_timing"}
)


def _gl015_pc_aliases(m: Module) -> Set[str]:
    """Every local NAME the plan_cache module is bound to in this file
    (mirrors _gl012_stats_aliases)."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == GL015_PC_MODULE and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if (
                    f"{node.module}.{a.name}" == GL015_PC_MODULE
                    or (a.name == "plan_cache"
                        and node.module == "surrealdb_tpu.dbs")
                ):
                    out.add(a.asname or a.name)
    return out


@_rule("GL015", "plan-cache state mutated outside the cache's write door")
def gl015(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if m.rel in GL015_ALLOWED_FILES:
            continue
        aliases = _gl015_pc_aliases(m)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in GL015_PRIVATE:
                continue
            # module-level access: plan_cache._caches via alias or the
            # dotted form a plain `import surrealdb_tpu.dbs.plan_cache`
            # enables
            via_alias = (
                isinstance(node.value, ast.Name) and node.value.id in aliases
            )
            via_dotted = _gl012_dotted(node.value) == GL015_PC_MODULE
            # instance access: any chain ENDING in `.plan_cache` is the
            # datastore's cache handle (ds.plan_cache._entries,
            # self.ds.plan_cache._lock, ctx.executor.ds.plan_cache._gen…)
            via_instance = (
                isinstance(node.value, ast.Attribute)
                and node.value.attr == "plan_cache"
            )
            if not (via_alias or via_dotted or via_instance):
                continue
            out.append(
                Finding(
                    "GL015", m.rel, node.lineno, node.col_offset,
                    f"plan_cache.{node.attr} accessed outside "
                    "dbs/plan_cache.py — plan-cache state must go through "
                    "the PlanCache write door (the methods that keep the "
                    "lock discipline and the validation-on-serve stamps "
                    "honest; a bypass can serve a stale plan)",
                    f"GL015:{m.rel}:{m.enclosing_def(node)}:{node.attr}",
                )
            )
    return out


# ------------------------------------------------------------------ GL016
# Event-loop-marked modules (module-level `EVENT_LOOP_MODULE = True`, e.g.
# surrealdb_tpu/net/loop.py) multiplex 100k+ sockets on a handful of
# threads: ONE blocking call stalls every connection the thread owns. Two
# classes of finding inside a marked module:
#   - blocking socket calls — `.recv()`, `.sendall()`, `.accept()` (and
#     recv variants) anywhere except inside a `_nb_`-prefixed nonblocking
#     wrapper function, which is where EAGAIN is actually handled;
#   - `time.sleep` ANYWHERE — loop pacing belongs to selector timeouts
#     and `Event.wait`, which a shutdown can interrupt; a sleep can't be.
GL016_MARKER = "EVENT_LOOP_MODULE"
GL016_BLOCKING = frozenset({"recv", "recv_into", "recvfrom", "sendall", "accept"})


def _gl016_marked(m: Module) -> bool:
    """True for modules declaring `EVENT_LOOP_MODULE = True` at top level."""
    for node in m.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id == GL016_MARKER
                    and isinstance(node.value, ast.Constant)
                    and bool(node.value.value)
                ):
                    return True
    return False


@_rule("GL016", "blocking socket call / time.sleep in an event-loop module")
def gl016(modules: List[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        if not _gl016_marked(m):
            continue
        sleep_direct = "sleep" in _from_imports(m, "time")
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            recv, attr = _call_name(node)
            fn = m.enclosing_def(node) or ""
            if attr in GL016_BLOCKING and not fn.split(".")[-1].startswith("_nb_"):
                out.append(
                    Finding(
                        "GL016", m.rel, node.lineno, node.col_offset,
                        f"blocking socket .{attr}() on an event-loop thread "
                        "— one blocked call stalls every connection this "
                        "loop owns; go through a `_nb_*` nonblocking "
                        "wrapper that handles EAGAIN",
                        f"GL016:{m.rel}:{fn}:{attr}",
                    )
                )
            is_sleep = attr == "sleep" and (
                (recv is not None and "time" in recv)
                or (recv is None and sleep_direct)
            )
            if is_sleep:
                out.append(
                    Finding(
                        "GL016", m.rel, node.lineno, node.col_offset,
                        "time.sleep in an event-loop module — pace with "
                        "selector timeouts or Event.wait (interruptible at "
                        "shutdown); a sleeping loop thread is a stalled "
                        "ingress",
                        f"GL016:{m.rel}:{fn}:sleep",
                    )
                )
    return out
