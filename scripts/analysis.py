"""Unified static-analysis entry point — `python -m scripts.analysis`.

Runs the repo's three analysis layers in order, each as its own process
(graftcheck MUST be: it pins JAX_PLATFORMS/XLA_FLAGS before jax loads):

  1. graftlint  — file-local source AST rules GL001–GL011
  2. graftcheck — compiled-IR kernel audit GC001–GC004 (jaxpr/StableHLO
                  under the simulated 8-device mesh)
  3. graftflow  — whole-program interprocedural flow rules GF001–GF004
                  (+ the flow_audit report bundle.py embeds)

scripts/tier1.sh calls THIS module, so the three tools cannot drift in
invocation: a new layer added here is a new tier-1 gate everywhere.

Exit code is a bitmask naming every failed layer (so CI output alone
says which): 1 = graftlint, 2 = graftcheck, 4 = graftflow; 0 = all
clean; 64 = usage error (reserved OUTSIDE the bitmask range so a typo'd
--skip can never read as "graftcheck failed"). One summary line always
prints last.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

LAYERS = (
    # (name, exit-code bit, argv tail, timeout seconds)
    ("graftlint", 1, ["-m", "scripts.graftlint"], 300),
    ("graftcheck", 2, ["-m", "scripts.graftcheck"], 600),
    ("graftflow", 4, ["-m", "scripts.graftflow"], 300),
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="analysis",
        description="run graftlint + graftcheck + graftflow as one gate",
    )
    ap.add_argument(
        "--skip", default="",
        help="comma-separated layer names to skip (e.g. graftcheck — the "
        "kernel audit needs jax and ~a minute; the AST layers are seconds)",
    )
    args = ap.parse_args(argv)
    skip = {s.strip() for s in args.skip.split(",") if s.strip()}
    unknown = skip - {name for name, _b, _a, _t in LAYERS}
    if unknown:
        print(f"error: unknown layer(s) in --skip: {sorted(unknown)}",
              file=sys.stderr)
        return 64  # usage error — outside the 1/2/4 layer bitmask

    rc = 0
    statuses = []
    for name, bit, tail, timeout in LAYERS:
        if name in skip:
            statuses.append(f"{name}=SKIPPED")
            continue
        try:
            proc = subprocess.run([sys.executable, *tail], timeout=timeout)
            code = proc.returncode
        except subprocess.TimeoutExpired:
            code = 124
        if code != 0:
            rc |= bit
            statuses.append(f"{name}=FAIL(rc={code})")
        else:
            statuses.append(f"{name}=OK")
    print(f"analysis: {' '.join(statuses)} (exit {rc})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
