#!/usr/bin/env python
"""Compare two bench artifacts and flag per-config / per-phase regressions.

The config-4 (hybrid) vs_baseline number swings round-to-round; since
schema /4 the hybrid line carries per-phase knn/filter/expand p50s, and
since /5 every line carries structural background-task overlap + compile
attribution. This tool turns two artifacts into a culprit list:

    python scripts/bench_diff.py bench_results_r08.json bench_results_r09.json
    python scripts/bench_diff.py OLD NEW --threshold 0.3

For every config present in both artifacts it reports the headline value
delta, the latency percentile deltas, and (hybrid) the per-phase deltas —
naming the phase that moved most. Deltas beyond --threshold (default 0.25
= 25%) are FLAGGED; when the newer artifact is schema /5 each flagged
config also cites the background tasks and on-demand compiles that ran in
its window (the usual suspects). Exit code 1 when anything was flagged,
0 otherwise (pipe-friendly: use `|| true` where the diff is informational).

`--bundles` compares the two runs' EMBEDDED debug bundles instead (or two
standalone surrealdb-tpu-bundle/1 files from GET /debug/bundle): column-
mirror staleness flips, tables that appeared/vanished, compile-cache drift
(shapes compiled in one round but not the other, on-demand compile counts),
ANN quantizer state changes, dispatch counter ratios, on bundle/5 the
graftflow flow_audit drift (call-graph coverage shrink, new static
lock-order edges, GF-rule pass->fail flips), and — on bundle/4 —
graftcheck kernel_audit drift (per-kernel HLO-digest changes, declared- or
lowered-collective changes, rule failures) — the round-over-round
engine-state attribution the per-config metric deltas can't show.

FEDERATED bundles (GET /debug/bundle?cluster=1, or a schema-/9 artifact's
cluster_obs embed) are diffed per node: each member's sections compare
pairwise against its previous-round self, plus a PEER-DRIFT pass over the
new bundle — one node's compile cache missing shapes its peers compiled,
a breaker open toward a member the rest consider alive, a column mirror
stale on one node but fresh on the others (the one-node-p99 signatures).

`--statements` compares the two runs' per-statement-FINGERPRINT stats
(schema /12 `statements.top` embeds, stats.py): per-shape qps and p99
regressions beyond the threshold, and PLAN-MIX FLIPS — the dominant scan
decision changing between runs (columnar-pipeline -> row after a mirror
decline or a degraded-write stand-down), the regression EXPLAIN can't
show because nobody re-ran EXPLAIN. Each flagged fingerprint prints its
normalized SQL, both mix vectors, and the in-window flip log. Since
schema /14 each entry also carries the planner cost hook's accumulated
chosen/declined margin: the diff prints the per-call margin both sides
and flags a THINNING margin (the decision getting marginal is the
leading indicator of the next plan-mix flip).

`--advisor` compares the two runs' advisor-plane embeds (schema /14
config-12 `advisor` objects): proposals that APPEARED (new advice this
round), RESOLVED (advice whose evidence decayed away — taken or moot),
and FLAPPED (expired then re-armed — oscillating evidence the operator
should tune thresholds for, not act on). Severity escalations between
rounds are flagged too.

`--plan-cache` compares the two runs' plan-cache parity objects (schema
/15 configs 2/6/9): a parity regression (a warm serve diverging from
its cold parse) flags unconditionally; warm hit-rate drops, warm
pre-kernel cost growth and serve-vs-reparse speedup losses flag beyond
the threshold.

Also importable: `diff(old_art, new_art, threshold) -> list[dict]`,
`diff_bundles(old_bundle, new_bundle) -> dict`,
`diff_statements(old_art, new_art, threshold) -> list[dict]`,
`diff_advisor(old_art, new_art) -> dict`,
`diff_federated(old, new) -> dict` and `peer_drift(bundle) -> list[str]`.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def _per_config(art: dict) -> Dict[str, dict]:
    """First metric line per config (the headline line of its window)."""
    out: Dict[str, dict] = {}
    for r in art.get("results") or []:
        cfg = r.get("config")
        if cfg is not None and str(cfg) not in out and r.get("value") is not None:
            out[str(cfg)] = r
    return out


def _rel(old: Optional[float], new: Optional[float]) -> Optional[float]:
    """Relative delta (new-old)/old, None when not comparable."""
    try:
        if old is None or new is None or float(old) == 0.0:
            return None
        return (float(new) - float(old)) / abs(float(old))
    except (TypeError, ValueError):
        return None


def _suspects(line: dict) -> List[str]:
    """Schema-/5 window evidence for a flagged config: overlapping
    background tasks and on-demand compiles."""
    out: List[str] = []
    bt = line.get("bg_tasks") or {}
    for kind, agg in (bt.get("kinds") or {}).items():
        note = f"bg:{kind} x{agg.get('count')} ({agg.get('overlap_s')}s overlap)"
        if agg.get("stalled"):
            note += f" [{agg['stalled']} STALLED]"
        out.append(note)
    comp = line.get("compiles") or {}
    if comp.get("on_demand"):
        out.append(f"{comp['on_demand']} on-demand XLA compile(s) in window")
    return out


def diff(old: dict, new: dict, threshold: float = 0.25) -> List[dict]:
    """Per-config comparison records; entry["flags"] non-empty = regression
    beyond threshold. `value` deltas are signed so a qps DROP is negative
    (durations/latencies flag on increase instead)."""
    rows: List[dict] = []
    oc, nc = _per_config(old), _per_config(new)
    for cfg in sorted(oc.keys() & nc.keys()):
        o, n = oc[cfg], nc[cfg]
        entry: Dict[str, Any] = {
            "config": cfg,
            "metric": n.get("metric"),
            "old_value": o.get("value"),
            "new_value": n.get("value"),
            "unit": n.get("unit"),
            "flags": [],
            "deltas": {},
        }
        dv = _rel(o.get("value"), n.get("value"))
        entry["deltas"]["value"] = dv
        # higher is better for every headline unit bench emits
        # (qps / edges/s / rows/s): flag drops
        if dv is not None and dv < -threshold:
            entry["flags"].append(f"value dropped {dv * 100:.1f}%")
        lo, ln = o.get("latency_ms") or {}, n.get("latency_ms") or {}
        for p in ("p50", "p95", "p99"):
            dp = _rel(lo.get(p), ln.get(p))
            if dp is None:
                continue
            entry["deltas"][f"latency_{p}"] = dp
            if dp > threshold:
                entry["flags"].append(f"latency {p} grew {dp * 100:.1f}%")
        # per-phase attribution (hybrid): name the culprit phase
        po, pn = o.get("phases") or {}, n.get("phases") or {}
        worst: Optional[tuple] = None
        for ph in ("knn_ms", "filter_ms", "expand_ms"):
            dp = _rel(po.get(ph), pn.get(ph))
            if dp is None:
                continue
            entry["deltas"][f"phase_{ph}"] = dp
            if worst is None or dp > worst[1]:
                worst = (ph, dp)
            if dp > threshold:
                entry["flags"].append(f"phase {ph} grew {dp * 100:.1f}%")
        if worst is not None:
            entry["culprit_phase"] = worst[0]
        for counter in ("errors", "retries", "splits"):
            ov, nv = o.get(counter), n.get(counter)
            ot = sum(ov.values()) if isinstance(ov, dict) else ov
            nt = sum(nv.values()) if isinstance(nv, dict) else nv
            if isinstance(ot, (int, float)) and isinstance(nt, (int, float)) and nt > ot:
                entry["flags"].append(f"{counter} rose {int(ot)} -> {int(nt)}")
        if entry["flags"]:
            entry["suspects"] = _suspects(n)
        rows.append(entry)
    return rows


# ------------------------------------------------------------------ bundles
def _as_bundle(doc: dict) -> Optional[dict]:
    """Accept a standalone bundle (GET /debug/bundle), a FEDERATED cluster
    bundle (GET /debug/bundle?cluster=1 — has a `nodes` map), or a bench
    artifact embedding either."""
    if not isinstance(doc, dict):
        return None
    if str(doc.get("schema", "")).startswith("surrealdb-tpu-bundle/"):
        return doc
    b = doc.get("bundle")
    if isinstance(b, dict):
        return b
    # schema/9 cluster lines embed the federated bundle under cluster_obs
    co = doc.get("cluster_obs")
    if isinstance(co, dict) and isinstance(co.get("bundle"), dict):
        return co["bundle"]
    return None


def _is_federated(bundle: Optional[dict]) -> bool:
    return isinstance(bundle, dict) and isinstance(bundle.get("nodes"), dict)


def diff_bundles(old: dict, new: dict) -> dict:
    """Engine-state drift between two debug bundles: mirror staleness and
    compile-cache movement — what changed under the numbers between rounds."""
    out: Dict[str, Any] = {"flags": [], "columns": {}, "compiles": {}, "ann": {}}

    # ---- column-mirror staleness drift
    oc = (old.get("engine") or {}).get("column_mirrors") or {}
    nc = (new.get("engine") or {}).get("column_mirrors") or {}
    for tb in sorted(set(oc) | set(nc)):
        o, n = oc.get(tb), nc.get(tb)
        if o is None:
            out["columns"][tb] = {"change": "appeared", "stale": bool(n.get("stale"))}
            continue
        if n is None:
            out["columns"][tb] = {"change": "vanished"}
            continue
        entry = {
            "rows": [o.get("rows"), n.get("rows")],
            "stale": [bool(o.get("stale")), bool(n.get("stale"))],
            "rebuild_armed": [bool(o.get("rebuild_armed")), bool(n.get("rebuild_armed"))],
        }
        out["columns"][tb] = entry
        if not o.get("stale") and n.get("stale"):
            out["flags"].append(
                f"column mirror {tb} went STALE between rounds "
                "(queries fall back to the row path until it rebuilds)"
            )

    # ---- compile-cache drift
    ocm = old.get("compiles") or {}
    ncm = new.get("compiles") or {}

    def shapes(c):
        return {
            f"{e.get('subsystem')}:{e.get('shape')}"
            for e in (c.get("events") or [])
        }

    os_, ns_ = shapes(ocm), shapes(ncm)
    out["compiles"] = {
        "on_demand": [ocm.get("on_demand"), ncm.get("on_demand")],
        "prewarmed": [ocm.get("prewarmed"), ncm.get("prewarmed")],
        "only_in_new": sorted(ns_ - os_),
        "only_in_old": sorted(os_ - ns_),
    }
    new_od = int(ncm.get("on_demand") or 0)
    old_od = int(ocm.get("on_demand") or 0)
    if new_od > old_od:
        out["flags"].append(
            f"on-demand XLA compiles rose {old_od} -> {new_od} — a shape the "
            "warmers used to cover is compiling inside requests"
        )
    if ns_ - os_:
        out["flags"].append(
            f"{len(ns_ - os_)} kernel shape(s) compiled this round that the "
            "old round never saw (shape drift — check dispatch widths/knobs)"
        )

    # ---- ANN quantizer drift
    ov = (old.get("engine") or {}).get("vector_indexes") or {}
    nv = (new.get("engine") or {}).get("vector_indexes") or {}
    for ix in sorted(set(ov) | set(nv)):
        o_state = ((ov.get(ix) or {}).get("ann") or {}).get("state")
        n_state = ((nv.get(ix) or {}).get("ann") or {}).get("state")
        out["ann"][ix] = [o_state, n_state]
        if o_state == "ready" and n_state in ("stale", "training", "none"):
            out["flags"].append(
                f"ANN quantizer {ix}: {o_state} -> {n_state} — kNN may be "
                "serving the exact fallback path this round"
            )

    # ---- kernel_audit drift (graftcheck compiled-IR report, bundle/4+):
    # a changed HLO digest means the kernel LOWERS differently this round
    # (toolchain bump or code change — either way, re-bench before
    # trusting deltas); a changed declared-collective set means someone
    # widened a mesh kernel's allowlist between rounds
    out["kernel_audit"] = _diff_kernel_audit(
        old.get("kernel_audit"), new.get("kernel_audit"), out["flags"]
    )

    # ---- flow_audit drift (graftflow whole-program report, bundle/5+):
    # shrinking call-graph stats mean the analyzer lost coverage (a
    # resolution regression silently exempts paths from the GF001 proof);
    # a rule flipping pass -> fail means a new interprocedural violation
    out["flow_audit"] = _diff_flow_audit(
        old.get("flow_audit"), new.get("flow_audit"), out["flags"]
    )

    # ---- dispatch counter ratios (retry/split pressure)
    od = ((old.get("engine") or {}).get("dispatch") or {}).get("stats") or {}
    nd = ((new.get("engine") or {}).get("dispatch") or {}).get("stats") or {}
    out["dispatch"] = {k: [od.get(k), nd.get(k)] for k in sorted(set(od) | set(nd))}
    for counter in ("retries", "splits", "failures"):
        o_n, n_n = od.get(counter) or 0, nd.get(counter) or 0
        o_d, n_d = max(od.get("dispatches") or 1, 1), max(nd.get("dispatches") or 1, 1)
        if n_n / n_d > (o_n / o_d) * 2 and n_n > o_n:
            out["flags"].append(
                f"dispatch {counter} rate doubled between rounds "
                f"({o_n}/{o_d} -> {n_n}/{n_d})"
            )
    return out


def _diff_kernel_audit(
    old: Optional[dict], new: Optional[dict], flags: List[str]
) -> dict:
    """Per-kernel HLO-digest / declared-collective / rule-result drift
    between two kernel_audit sections. Appends to `flags` in place."""
    o_av = bool(isinstance(old, dict) and old.get("available"))
    n_av = bool(isinstance(new, dict) and new.get("available"))
    out: Dict[str, Any] = {"available": [o_av, n_av], "kernels": {}}
    if o_av and not n_av:
        flags.append(
            "kernel_audit available in the old round but missing now — "
            "the graftcheck gate did not run before this bench"
        )
    if not (o_av and n_av):
        return out
    ok, nk = old.get("kernels") or {}, new.get("kernels") or {}
    for name in sorted(set(ok) | set(nk)):
        o, n = ok.get(name), nk.get(name)
        if o is None or n is None:
            change = "appeared" if o is None else "vanished"
            out["kernels"][name] = {"change": change}
            if change == "vanished":
                flags.append(
                    f"kernel {name}: VANISHED from the audit between rounds "
                    "— it left graftcheck coverage (site deregistered?)"
                )
            continue
        entry: Dict[str, Any] = {}
        oc = list(o.get("declared_collectives") or [])
        nc = list(n.get("declared_collectives") or [])
        if oc != nc:
            entry["declared_collectives"] = [oc, nc]
            flags.append(
                f"kernel {name}: declared collectives changed {oc} -> {nc} "
                "— the mesh allowlist was widened/narrowed between rounds"
            )
        os_, ns_ = o.get("shapes") or {}, n.get("shapes") or {}
        drifted = []
        for label in sorted(set(os_) & set(ns_)):
            oh = (os_[label] or {}).get("hlo_sha256")
            nh = (ns_[label] or {}).get("hlo_sha256")
            if oh and nh and oh != nh:
                drifted.append(label)
            ocol = (os_[label] or {}).get("collectives") or {}
            ncol = (ns_[label] or {}).get("collectives") or {}
            if ocol != ncol:
                flags.append(
                    f"kernel {name}[{label}]: lowered collectives changed "
                    f"{ocol} -> {ncol} — XLA inserts different communication "
                    "this round"
                )
        if drifted:
            entry["hlo_drift"] = drifted
            flags.append(
                f"kernel {name}: HLO digest drifted for shape(s) {drifted} "
                "— the kernel lowers differently this round (re-validate "
                "perf deltas against the new lowering)"
            )
        failed = sorted(
            f"{label}:{rid}"
            for label, s in ns_.items()
            for rid, res in (s.get("rules") or {}).items()
            if res != "pass"
        )
        if failed:
            entry["rule_failures"] = failed
            flags.append(
                f"kernel {name}: graftcheck rule failure(s) in this round's "
                f"audit: {failed}"
            )
        if entry:
            out["kernels"][name] = entry
    return out


def _diff_flow_audit(
    old: Optional[dict], new: Optional[dict], flags: List[str]
) -> dict:
    """Call-graph-stat / lock-graph / per-rule drift between two
    flow_audit sections. Appends to `flags` in place."""
    o_av = bool(isinstance(old, dict) and old.get("available"))
    n_av = bool(isinstance(new, dict) and new.get("available"))
    out: Dict[str, Any] = {"available": [o_av, n_av]}
    if o_av and not n_av:
        flags.append(
            "flow_audit available in the old round but missing now — "
            "the graftflow gate did not run before this bench"
        )
    if not (o_av and n_av):
        return out
    ocg, ncg = old.get("callgraph") or {}, new.get("callgraph") or {}
    out["callgraph"] = {
        k: [ocg.get(k), ncg.get(k)]
        for k in ("nodes", "edges", "lock_sites", "unresolved_calls")
    }
    for stat in ("nodes", "edges", "lock_sites"):
        o_n, n_n = int(ocg.get(stat) or 0), int(ncg.get(stat) or 0)
        if o_n and n_n < o_n * 0.7:
            flags.append(
                f"flow_audit {stat} shrank {o_n} -> {n_n} — the call-graph "
                "lost coverage; paths may have silently left the GF001 proof"
            )
    oe = {(e.get("from"), e.get("to")) for e in (old.get("lock_graph") or {}).get("edges") or []}
    ne = {(e.get("from"), e.get("to")) for e in (new.get("lock_graph") or {}).get("edges") or []}
    out["lock_graph"] = {
        "edges": [len(oe), len(ne)],
        "only_in_new": sorted(f"{a}->{b}" for a, b in ne - oe),
        "only_in_old": sorted(f"{a}->{b}" for a, b in oe - ne),
    }
    if ne - oe:
        flags.append(
            f"{len(ne - oe)} new static lock-order edge(s) this round "
            "(new acquires-while-holding paths — check them against the "
            "declared hierarchy)"
        )
    orl, nrl = old.get("rules") or {}, new.get("rules") or {}
    regressed = sorted(
        rid for rid in set(orl) | set(nrl)
        if str(orl.get(rid, "pass")) == "pass" and str(nrl.get(rid, "pass")) != "pass"
    )
    if regressed:
        out["rule_regressions"] = regressed
        flags.append(
            f"flow_audit rule(s) flipped pass -> fail between rounds: "
            f"{regressed}"
        )
    return out


def peer_drift(bundle: dict) -> List[str]:
    """Per-node drift WITHIN one federated bundle: the flags that say one
    member's engine state has diverged from its peers — the node a p99
    regression on a 2-8 node bench run should be read against."""
    flags: List[str] = []
    nodes = bundle.get("nodes") or {}
    reachable = {
        nid: b for nid, b in nodes.items()
        if isinstance(b, dict) and not b.get("unreachable")
    }
    for nid, b in sorted(nodes.items()):
        if not isinstance(b, dict) or b.get("unreachable"):
            flags.append(f"node {nid}: UNREACHABLE in this bundle")
    if len(reachable) < 2:
        return flags

    # compile-cache drift: a member compiling shapes its peers never saw
    # (or missing shapes every peer has) pays per-request XLA compiles the
    # others don't — the classic one-node-p99 signature
    shape_sets = {
        nid: {
            f"{e.get('subsystem')}:{e.get('shape')}"
            for e in ((b.get("compiles") or {}).get("events") or [])
        }
        for nid, b in reachable.items()
    }
    union = set().union(*shape_sets.values())
    for nid, shapes in sorted(shape_sets.items()):
        missing = union - shapes
        # only flag when a PEERED shape (seen on >= half the other nodes)
        # is absent here; node-local tables legitimately differ
        peered = {
            s for s in missing
            if sum(s in o for n2, o in shape_sets.items() if n2 != nid)
            >= max((len(shape_sets) - 1 + 1) // 2, 1)
        }
        if peered:
            flags.append(
                f"node {nid}: compile cache diverged from peers — missing "
                f"{len(peered)} shape(s) most peers compiled "
                f"(e.g. {sorted(peered)[0]})"
            )

    # membership-epoch drift: a member still routing under an old ring
    # version after a join/leave/replace cutover — its responsibility
    # filters and replica sets disagree with the fleet's (ISSUE 14)
    epochs = {
        nid: ((b.get("engine") or {}).get("cluster") or {}).get("epoch")
        for nid, b in reachable.items()
    }
    known = {nid: e for nid, e in epochs.items() if isinstance(e, int)}
    if known and len(set(known.values())) > 1:
        newest = max(known.values())
        for nid, e in sorted(known.items()):
            if e < newest:
                flags.append(
                    f"node {nid}: membership epoch {e} behind the fleet's "
                    f"{newest} — it routes under a stale ring version "
                    "(missed cutover?)"
                )

    # breaker/liveness drift: a member whose view of the cluster disagrees
    # with its peers (open breakers, down marks) while the others are calm
    for nid, b in sorted(reachable.items()):
        cl = ((b.get("engine") or {}).get("cluster") or {})
        for peer, st in sorted((cl.get("nodes") or {}).items()):
            breaker = (st or {}).get("breaker")
            if breaker and breaker != "closed":
                flags.append(
                    f"node {nid}: breaker {breaker.upper()} toward {peer} "
                    "(its peers may be serving around a node this member "
                    "considers dead)"
                )

    # column-mirror staleness drift: the same table stale on one member but
    # fresh on its peers serves the row path only there
    stale_by_tb: Dict[str, List[str]] = {}
    fresh_by_tb: Dict[str, List[str]] = {}
    for nid, b in reachable.items():
        for tb, st in (((b.get("engine") or {}).get("column_mirrors")) or {}).items():
            (stale_by_tb if st.get("stale") else fresh_by_tb).setdefault(
                tb, []
            ).append(nid)
    for tb in sorted(stale_by_tb):
        if tb in fresh_by_tb:
            flags.append(
                f"column mirror {tb}: STALE on {sorted(stale_by_tb[tb])} "
                f"but fresh on {sorted(fresh_by_tb[tb])} — those members "
                "serve the row path for the same statements"
            )
    return flags


def diff_federated(old: dict, new: dict) -> dict:
    """Two federated bundles: pairwise per-node section diffs (the
    round-over-round view) plus the NEW bundle's peer-drift flags (the
    within-round view)."""
    out: Dict[str, Any] = {"per_node": {}, "flags": []}
    onodes, nnodes = old.get("nodes") or {}, new.get("nodes") or {}
    for nid in sorted(set(onodes) | set(nnodes)):
        ob, nb = onodes.get(nid), nnodes.get(nid)
        o_dead = not isinstance(ob, dict) or ob.get("unreachable")
        n_dead = not isinstance(nb, dict) or nb.get("unreachable")
        if o_dead and n_dead:
            out["per_node"][nid] = {"unreachable": True}
            continue
        if n_dead:
            out["per_node"][nid] = {"unreachable": True}
            out["flags"].append(f"node {nid}: reachable before, UNREACHABLE now")
            continue
        if o_dead:
            out["per_node"][nid] = {"appeared": True}
            continue
        rep = diff_bundles(ob, nb)
        out["per_node"][nid] = rep
        out["flags"].extend(f"node {nid}: {fl}" for fl in rep["flags"])
    out["peer_drift"] = peer_drift(new)
    out["flags"].extend(out["peer_drift"])
    return out


# ------------------------------------------------------------------ statements
def _statements_by_fp(art: dict) -> Dict[str, dict]:
    """Every statement-fingerprint entry embedded in an artifact's config
    lines (schema /12 `statements.top`), keyed by fingerprint. An entry
    appearing in several config windows keeps the one with more calls
    (bench resets the store per window, so windows never double-count)."""
    out: Dict[str, dict] = {}
    for r in art.get("results") or []:
        st = r.get("statements")
        if not isinstance(st, dict):
            continue
        for ent in st.get("top") or []:
            if not isinstance(ent, dict) or not ent.get("fingerprint"):
                continue
            fp = str(ent["fingerprint"])
            cur = out.get(fp)
            if cur is None or (ent.get("calls") or 0) > (cur.get("calls") or 0):
                out[fp] = dict(ent, config=r.get("config"))
    return out


def _dominant_mix(ent: dict) -> Optional[str]:
    mix = ent.get("plan_mix") or {}
    scan = {
        k: v
        for k, v in mix.items()
        if isinstance(v, (int, float))
        and (str(k).startswith(("columnar", "knn-")) or k in ("row", "index"))
    }
    if not scan:
        return None
    return max(sorted(scan), key=lambda k: scan[k])


def diff_statements(
    old: dict, new: dict, threshold: float = 0.25
) -> List[dict]:
    """Per-fingerprint comparison of two artifacts' statement stats: the
    culprit list the re-measure checklist reads. Flags
    - qps regressions (calls/total_s throughput down beyond threshold),
    - p99 latency regressions beyond threshold,
    - PLAN-MIX FLIPS: the dominant scan decision changed between the two
      runs (columnar-pipeline -> row is the silent regression EXPLAIN
      can't show), or the entry's own flip counter went up."""
    o_by, n_by = _statements_by_fp(old), _statements_by_fp(new)
    rows: List[dict] = []
    for fp in sorted(set(o_by) & set(n_by)):
        oe, ne = o_by[fp], n_by[fp]
        flags: List[str] = []
        o_qps = (oe.get("calls") or 0) / (oe.get("total_s") or 1e-9)
        n_qps = (ne.get("calls") or 0) / (ne.get("total_s") or 1e-9)
        d_qps = _rel(o_qps, n_qps)
        if d_qps is not None and d_qps < -threshold:
            flags.append(f"qps {o_qps:.1f} -> {n_qps:.1f} ({d_qps * 100:+.0f}%)")
        d_p99 = _rel(oe.get("p99_ms"), ne.get("p99_ms"))
        if d_p99 is not None and d_p99 > threshold:
            flags.append(
                f"p99 {oe.get('p99_ms')}ms -> {ne.get('p99_ms')}ms "
                f"({d_p99 * 100:+.0f}%)"
            )
        o_dom, n_dom = _dominant_mix(oe), _dominant_mix(ne)
        if o_dom is not None and n_dom is not None and o_dom != n_dom:
            flags.append(f"plan-mix flip: {o_dom} -> {n_dom}")
        if (ne.get("plan_flips") or 0) > (oe.get("plan_flips") or 0):
            flags.append(
                f"in-window plan flips: {oe.get('plan_flips') or 0} -> "
                f"{ne.get('plan_flips') or 0} (flip_log: "
                f"{json.dumps(ne.get('flip_log') or [])})"
            )
        # planner cost-hook margin (schema /14): a thinning per-call margin
        # between the chosen and declined strategies is the leading
        # indicator of the next plan-mix flip — flag it before it happens
        o_margin = ((oe.get("cost") or {}).get("margin_per_call"))
        n_margin = ((ne.get("cost") or {}).get("margin_per_call"))
        d_margin = _rel(o_margin, n_margin)
        if d_margin is not None and d_margin < -threshold:
            flags.append(
                f"cost margin/call thinned {o_margin} -> {n_margin} "
                f"row-visits ({d_margin * 100:+.0f}%) — the plan decision "
                "is getting marginal"
            )
        rows.append(
            {
                "fingerprint": fp,
                "sql": ne.get("sql"),
                "config": ne.get("config"),
                "old": {"qps": round(o_qps, 2), "p99_ms": oe.get("p99_ms"),
                        "mix": oe.get("plan_mix"), "dominant": o_dom,
                        "margin_per_call": o_margin},
                "new": {"qps": round(n_qps, 2), "p99_ms": ne.get("p99_ms"),
                        "mix": ne.get("plan_mix"), "dominant": n_dom,
                        "margin_per_call": n_margin},
                "flags": flags,
            }
        )
    return rows


def _main_statements(old: dict, new: dict, threshold: float) -> int:
    rows = diff_statements(old, new, threshold)
    if not rows:
        print(
            "no shared statement fingerprints between the two artifacts "
            "(schema /12 embeds required)",
            file=sys.stderr,
        )
        return 2
    flagged = 0
    for r in rows:
        head = (
            f"{r['fingerprint']} (config {r['config']}): "
            f"{r['old']['qps']} -> {r['new']['qps']} qps, "
            f"p99 {r['old']['p99_ms']} -> {r['new']['p99_ms']} ms"
        )
        if r["old"].get("margin_per_call") is not None or r["new"].get(
            "margin_per_call"
        ) is not None:
            head += (
                f", margin/call {r['old'].get('margin_per_call')} -> "
                f"{r['new'].get('margin_per_call')}"
            )
        print(("FLAG  " if r["flags"] else "ok    ") + head)
        if r["flags"]:
            print(f"      sql: {str(r['sql'])[:120]}")
        for fl in r["flags"]:
            print(f"      - {fl}")
        flagged += bool(r["flags"])
    print(
        f"{flagged}/{len(rows)} fingerprint(s) flagged "
        f"(threshold {threshold * 100:.0f}%)"
    )
    return 1 if flagged else 0


# ------------------------------------------------------------------ advisor
def _advisor_state(art: dict) -> dict:
    """One artifact's advisor plane, collapsed to {live, expired}: `live`
    keys every proposal id seen in any config-12 phase snapshot to its
    LATEST record (the lifecycle's end state for the round), `expired`
    the ids the round's decay ring recorded. A proposal present in both
    flapped within the round."""
    live: Dict[str, dict] = {}
    expired: Dict[str, dict] = {}
    for r in art.get("results") or []:
        adv = r.get("advisor")
        if not isinstance(adv, dict):
            continue
        for ph in adv.get("phases") or []:
            for p in (ph or {}).get("proposals") or []:
                if not isinstance(p, dict) or not p.get("id"):
                    continue
                cur = live.get(p["id"])
                if cur is None or (p.get("last_seen_ts") or 0) >= (
                    cur.get("last_seen_ts") or 0
                ):
                    live[p["id"]] = p
        for p in adv.get("expired") or []:
            if isinstance(p, dict) and p.get("id"):
                expired[p["id"]] = p
    # an id that expired and never re-armed is not live at round end
    for pid in list(live):
        if pid in expired and (
            (expired[pid].get("last_seen_ts") or 0)
            >= (live[pid].get("last_seen_ts") or 0)
        ):
            del live[pid]
    return {"live": live, "expired": expired}


def _brief(p: dict) -> str:
    return f"{p.get('kind')} {p.get('subject')} [{p.get('severity')}]"


def diff_advisor(old: dict, new: dict) -> dict:
    """Round-over-round advisor drift: which advice appeared, which
    resolved (evidence decayed — taken or moot), which flapped (expired
    then re-armed inside the new round: oscillating evidence means tune
    the thresholds, don't act), and which escalated in severity."""
    o, n = _advisor_state(old), _advisor_state(new)
    out: Dict[str, Any] = {
        "appeared": [], "resolved": [], "flapped": [], "escalated": [],
        "flags": [],
    }
    rank = {"info": 0, "warn": 1, "critical": 2}
    for pid in sorted(set(n["live"]) - set(o["live"])):
        out["appeared"].append(n["live"][pid])
        out["flags"].append(f"appeared: {_brief(n['live'][pid])}")
    for pid in sorted(set(o["live"]) - set(n["live"]) - set(n["expired"])):
        out["resolved"].append(o["live"][pid])
    for pid in sorted(set(o["live"]) & set(n["expired"])):
        out["resolved"].append(o["live"][pid])
    for pid in sorted(set(n["live"]) & set(n["expired"])):
        out["flapped"].append(n["live"][pid])
        out["flags"].append(
            f"flapped: {_brief(n['live'][pid])} — expired then re-armed "
            "within the round (oscillating evidence; tune thresholds)"
        )
    for pid in sorted(set(o["live"]) & set(n["live"])):
        op, np_ = o["live"][pid], n["live"][pid]
        if rank.get(np_.get("severity"), 0) > rank.get(op.get("severity"), 0):
            out["escalated"].append(np_)
            out["flags"].append(
                f"escalated: {_brief(np_)} (was {op.get('severity')})"
            )
    return out


def _main_advisor(old: dict, new: dict) -> int:
    if not any(
        isinstance(r.get("advisor"), dict) for r in new.get("results") or []
    ):
        print(
            "no advisor embeds in the new artifact "
            "(schema /14 config-12 required)",
            file=sys.stderr,
        )
        return 2
    rep = diff_advisor(old, new)
    for label in ("appeared", "resolved", "flapped", "escalated"):
        for p in rep[label]:
            print(f"{label:<9} {_brief(p)}  id={p.get('id')}")
    print(
        f"{len(rep['appeared'])} appeared, {len(rep['resolved'])} resolved, "
        f"{len(rep['flapped'])} flapped, {len(rep['escalated'])} escalated"
    )
    return 1 if rep["flags"] else 0


# ------------------------------------------------------------------ plan cache
def _plan_cache_by_config(art: dict) -> Dict[str, dict]:
    """Every plan_cache_parity proof object embedded in an artifact's
    config lines (schema /15, configs 2/6/9), keyed by config."""
    out: Dict[str, dict] = {}
    for r in art.get("results") or []:
        pp = r.get("plan_cache_parity")
        if isinstance(pp, dict) and r.get("config") is not None:
            out[str(r["config"])] = dict(pp, metric=r.get("metric"))
    return out


def diff_plan_cache(old: dict, new: dict, threshold: float = 0.25) -> List[dict]:
    """Per-config comparison of two artifacts' plan-cache parity objects:
    parity regressions are flagged unconditionally (a warm serve that
    started diverging is a correctness event, not a perf delta); hit-rate
    drops and warm pre-kernel cost growth flag beyond the threshold."""
    o_by, n_by = _plan_cache_by_config(old), _plan_cache_by_config(new)
    rows: List[dict] = []
    for cfg in sorted(set(o_by) & set(n_by)):
        op, np_ = o_by[cfg], n_by[cfg]
        flags: List[str] = []
        if op.get("parity") is True and np_.get("parity") is not True:
            flags.append(
                f"PARITY REGRESSED: {np_.get('mismatches')} warm serve(s) "
                "diverged from the cold parse"
            )
        d_hit = _rel(op.get("warm_hit_rate"), np_.get("warm_hit_rate"))
        if d_hit is not None and d_hit < -threshold:
            flags.append(
                f"warm hit rate {op.get('warm_hit_rate')} -> "
                f"{np_.get('warm_hit_rate')} ({d_hit * 100:+.0f}%)"
            )
        d_warm = _rel(op.get("prekernel_warm_us"), np_.get("prekernel_warm_us"))
        if d_warm is not None and d_warm > threshold:
            flags.append(
                f"warm pre-kernel {op.get('prekernel_warm_us')}us -> "
                f"{np_.get('prekernel_warm_us')}us ({d_warm * 100:+.0f}%) — "
                "serving is getting slower"
            )
        d_sp = _rel(op.get("speedup"), np_.get("speedup"))
        if d_sp is not None and d_sp < -threshold:
            flags.append(
                f"serve-vs-reparse speedup {op.get('speedup')}x -> "
                f"{np_.get('speedup')}x ({d_sp * 100:+.0f}%)"
            )
        rows.append(
            {
                "config": cfg,
                "metric": np_.get("metric"),
                "old": op,
                "new": np_,
                "flags": flags,
            }
        )
    return rows


def _main_plan_cache(old: dict, new: dict, threshold: float) -> int:
    rows = diff_plan_cache(old, new, threshold)
    if not rows:
        print(
            "no shared plan_cache_parity configs between the two artifacts "
            "(schema /15 configs 2/6/9 required)",
            file=sys.stderr,
        )
        return 2
    flagged = 0
    for r in rows:
        head = (
            f"config {r['config']} ({r['metric']}): hit "
            f"{r['old'].get('warm_hit_rate')} -> {r['new'].get('warm_hit_rate')}, "
            f"warm {r['old'].get('prekernel_warm_us')} -> "
            f"{r['new'].get('prekernel_warm_us')}us, speedup "
            f"{r['old'].get('speedup')} -> {r['new'].get('speedup')}x"
        )
        print(("FLAG  " if r["flags"] else "ok    ") + head)
        for fl in r["flags"]:
            print(f"      - {fl}")
        flagged += bool(r["flags"])
    print(f"{flagged}/{len(rows)} config(s) flagged (threshold {threshold * 100:.0f}%)")
    return 1 if flagged else 0


# ------------------------------------------------------------------ tenants
def _tenants_by_key(art: dict) -> Dict[str, dict]:
    """Every per-tenant meter entry embedded in an artifact's config lines
    (schema /13 `tenants.per_tenant`), keyed `ns/db`. An entry appearing
    in several config windows keeps the one with more statements (bench
    resets the accounting store per window, so windows never
    double-count)."""
    out: Dict[str, dict] = {}
    for r in art.get("results") or []:
        tn = r.get("tenants")
        if not isinstance(tn, dict):
            continue
        for ent in tn.get("per_tenant") or []:
            if not isinstance(ent, dict) or not ent.get("ns"):
                continue
            key = f"{ent['ns']}/{ent.get('db') or ''}"
            cur = out.get(key)
            if cur is None or (ent.get("statements") or 0) > (
                cur.get("statements") or 0
            ):
                out[key] = dict(ent, config=r.get("config"))
    return out


def diff_tenants(old: dict, new: dict, threshold: float = 0.25) -> List[dict]:
    """Per-tenant comparison of two artifacts' cost-attribution embeds:
    which (ns, db) got more expensive between two runs, and on which
    meter. Flags
    - cost-share shifts: a tenant's share of the window's total exec time
      moved beyond threshold (the noisy-neighbour drift signal — absolute
      times move with the machine, shares shouldn't),
    - per-meter regressions (cpu_s, dispatch_s, rows_scanned per
      statement) beyond threshold,
    - budget breaches appearing in the new run that the old didn't have."""
    o_by, n_by = _tenants_by_key(old), _tenants_by_key(new)
    o_total = sum((e.get("exec_s") or 0) for e in o_by.values()) or 1e-9
    n_total = sum((e.get("exec_s") or 0) for e in n_by.values()) or 1e-9
    rows: List[dict] = []
    for key in sorted(set(o_by) & set(n_by)):
        oe, ne = o_by[key], n_by[key]
        flags: List[str] = []
        o_share = (oe.get("exec_s") or 0) / o_total
        n_share = (ne.get("exec_s") or 0) / n_total
        if abs(n_share - o_share) > threshold:
            flags.append(
                f"exec-time share {o_share * 100:.0f}% -> {n_share * 100:.0f}%"
            )
        o_calls = max(oe.get("statements") or 0, 1)
        n_calls = max(ne.get("statements") or 0, 1)
        for meter in ("cpu_s", "dispatch_s", "rows_scanned"):
            d = _rel(
                (oe.get(meter) or 0) / o_calls, (ne.get(meter) or 0) / n_calls
            )
            if d is not None and d > threshold:
                flags.append(f"{meter}/stmt ({d * 100:+.0f}%)")
        o_breach = sum((oe.get("breaches") or {}).values())
        n_breach = sum((ne.get("breaches") or {}).values())
        if n_breach > o_breach:
            flags.append(f"budget breaches: {o_breach} -> {n_breach}")
        rows.append(
            {
                "tenant": key,
                "config": ne.get("config"),
                "old": {"share": round(o_share, 4),
                        "exec_s": oe.get("exec_s"), "cpu_s": oe.get("cpu_s"),
                        "statements": oe.get("statements")},
                "new": {"share": round(n_share, 4),
                        "exec_s": ne.get("exec_s"), "cpu_s": ne.get("cpu_s"),
                        "statements": ne.get("statements")},
                "flags": flags,
            }
        )
    return rows


def _main_tenants(old: dict, new: dict, threshold: float) -> int:
    rows = diff_tenants(old, new, threshold)
    if not rows:
        print(
            "no shared tenants between the two artifacts "
            "(schema /13 embeds required)",
            file=sys.stderr,
        )
        return 2
    flagged = 0
    for r in rows:
        head = (
            f"{r['tenant']} (config {r['config']}): "
            f"share {r['old']['share'] * 100:.0f}% -> "
            f"{r['new']['share'] * 100:.0f}%, "
            f"exec {r['old']['exec_s']} -> {r['new']['exec_s']} s"
        )
        print(("FLAG  " if r["flags"] else "ok    ") + head)
        for fl in r["flags"]:
            print(f"      - {fl}")
        flagged += bool(r["flags"])
    print(
        f"{flagged}/{len(rows)} tenant(s) flagged "
        f"(threshold {threshold * 100:.0f}%)"
    )
    return 1 if flagged else 0


def _main_bundles(old_doc: dict, new_doc: dict) -> int:
    ob, nb = _as_bundle(old_doc), _as_bundle(new_doc)
    if ob is None or nb is None:
        print(
            "not a bundle: inputs must be surrealdb-tpu-bundle/1 files or "
            "artifacts embedding one (schema /5+)",
            file=sys.stderr,
        )
        return 2
    if _is_federated(ob) or _is_federated(nb):
        if not (_is_federated(ob) and _is_federated(nb)):
            print(
                "cannot diff a federated (cluster=1) bundle against a "
                "single-node one — capture both from the coordinator",
                file=sys.stderr,
            )
            return 2
        rep = diff_federated(ob, nb)
        for nid, sub in sorted(rep["per_node"].items()):
            head = "unreachable" if sub.get("unreachable") else (
                "appeared" if sub.get("appeared") else f"{len(sub.get('flags') or [])} flag(s)"
            )
            print(f"node {nid}: {head}")
        for fl in rep["flags"]:
            print(f"FLAG  {fl}")
        print(f"{len(rep['flags'])} drift flag(s)")
        return 1 if rep["flags"] else 0
    rep = diff_bundles(ob, nb)
    for tb, entry in sorted(rep["columns"].items()):
        print(f"column {tb}: {json.dumps(entry)}")
    print(f"compiles: {json.dumps(rep['compiles'])}")
    for ix, states in sorted(rep["ann"].items()):
        print(f"ann {ix}: {states[0]} -> {states[1]}")
    for fl in rep["flags"]:
        print(f"FLAG  {fl}")
    print(f"{len(rep['flags'])} drift flag(s)")
    return 1 if rep["flags"] else 0


def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench_diff.py",
        description="Compare two bench artifacts; flag per-config/per-phase regressions.",
    )
    ap.add_argument("old", help="baseline bench_results_*.json")
    ap.add_argument("new", help="candidate bench_results_*.json")
    ap.add_argument(
        "--threshold", type=float, default=0.25,
        help="relative delta that flags (default 0.25 = 25%%)",
    )
    ap.add_argument(
        "--bundles", action="store_true",
        help="diff the two runs' debug bundles (mirror staleness, "
        "compile-cache drift) instead of the metric lines",
    )
    ap.add_argument(
        "--statements", action="store_true",
        help="diff the two runs' per-statement-fingerprint stats (schema "
        "/12): qps/p99 regressions and plan-mix flips, named per shape",
    )
    ap.add_argument(
        "--tenants", action="store_true",
        help="diff the two runs' per-tenant cost-attribution embeds "
        "(schema /13): exec-share shifts, per-meter regressions and new "
        "budget breaches, named per (ns, db)",
    )
    ap.add_argument(
        "--advisor", action="store_true",
        help="diff the two runs' advisor-plane embeds (schema /14): "
        "proposals appeared / resolved / flapped / escalated between "
        "rounds",
    )
    ap.add_argument(
        "--plan-cache", action="store_true", dest="plan_cache",
        help="diff the two runs' plan-cache parity objects (schema /15): "
        "parity regressions, warm hit-rate drops, warm pre-kernel cost "
        "growth, per config",
    )
    try:
        ns = ap.parse_args(argv)
    except SystemExit:
        return 2
    threshold = ns.threshold
    try:
        with open(ns.old) as f:
            old = json.load(f)
        with open(ns.new) as f:
            new = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable artifact: {e}", file=sys.stderr)
        return 2
    if ns.bundles:
        return _main_bundles(old, new)
    if ns.statements:
        return _main_statements(old, new, threshold)
    if ns.tenants:
        return _main_tenants(old, new, threshold)
    if ns.advisor:
        return _main_advisor(old, new)
    if ns.plan_cache:
        return _main_plan_cache(old, new, threshold)
    rows = diff(old, new, threshold)
    if not rows:
        print("no comparable configs between the two artifacts", file=sys.stderr)
        return 2
    flagged = 0
    for r in rows:
        head = (
            f"config {r['config']} ({r['metric']}): "
            f"{r['old_value']} -> {r['new_value']} {r['unit']}"
        )
        dv = r["deltas"].get("value")
        if dv is not None:
            head += f" ({dv * 100:+.1f}%)"
        if r.get("culprit_phase"):
            head += f"  culprit phase: {r['culprit_phase']}"
        print(("FLAG  " if r["flags"] else "ok    ") + head)
        for fl in r["flags"]:
            print(f"      - {fl}")
        for s in r.get("suspects", []):
            print(f"      suspect: {s}")
        flagged += bool(r["flags"])
    print(f"{flagged}/{len(rows)} config(s) flagged (threshold {threshold * 100:.0f}%)")
    return 1 if flagged else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
