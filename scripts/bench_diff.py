#!/usr/bin/env python
"""Compare two bench artifacts and flag per-config / per-phase regressions.

The config-4 (hybrid) vs_baseline number swings round-to-round; since
schema /4 the hybrid line carries per-phase knn/filter/expand p50s, and
since /5 every line carries structural background-task overlap + compile
attribution. This tool turns two artifacts into a culprit list:

    python scripts/bench_diff.py bench_results_r08.json bench_results_r09.json
    python scripts/bench_diff.py OLD NEW --threshold 0.3

For every config present in both artifacts it reports the headline value
delta, the latency percentile deltas, and (hybrid) the per-phase deltas —
naming the phase that moved most. Deltas beyond --threshold (default 0.25
= 25%) are FLAGGED; when the newer artifact is schema /5 each flagged
config also cites the background tasks and on-demand compiles that ran in
its window (the usual suspects). Exit code 1 when anything was flagged,
0 otherwise (pipe-friendly: use `|| true` where the diff is informational).

Also importable: `diff(old_art, new_art, threshold) -> list[dict]`.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def _per_config(art: dict) -> Dict[str, dict]:
    """First metric line per config (the headline line of its window)."""
    out: Dict[str, dict] = {}
    for r in art.get("results") or []:
        cfg = r.get("config")
        if cfg is not None and str(cfg) not in out and r.get("value") is not None:
            out[str(cfg)] = r
    return out


def _rel(old: Optional[float], new: Optional[float]) -> Optional[float]:
    """Relative delta (new-old)/old, None when not comparable."""
    try:
        if old is None or new is None or float(old) == 0.0:
            return None
        return (float(new) - float(old)) / abs(float(old))
    except (TypeError, ValueError):
        return None


def _suspects(line: dict) -> List[str]:
    """Schema-/5 window evidence for a flagged config: overlapping
    background tasks and on-demand compiles."""
    out: List[str] = []
    bt = line.get("bg_tasks") or {}
    for kind, agg in (bt.get("kinds") or {}).items():
        note = f"bg:{kind} x{agg.get('count')} ({agg.get('overlap_s')}s overlap)"
        if agg.get("stalled"):
            note += f" [{agg['stalled']} STALLED]"
        out.append(note)
    comp = line.get("compiles") or {}
    if comp.get("on_demand"):
        out.append(f"{comp['on_demand']} on-demand XLA compile(s) in window")
    return out


def diff(old: dict, new: dict, threshold: float = 0.25) -> List[dict]:
    """Per-config comparison records; entry["flags"] non-empty = regression
    beyond threshold. `value` deltas are signed so a qps DROP is negative
    (durations/latencies flag on increase instead)."""
    rows: List[dict] = []
    oc, nc = _per_config(old), _per_config(new)
    for cfg in sorted(oc.keys() & nc.keys()):
        o, n = oc[cfg], nc[cfg]
        entry: Dict[str, Any] = {
            "config": cfg,
            "metric": n.get("metric"),
            "old_value": o.get("value"),
            "new_value": n.get("value"),
            "unit": n.get("unit"),
            "flags": [],
            "deltas": {},
        }
        dv = _rel(o.get("value"), n.get("value"))
        entry["deltas"]["value"] = dv
        # higher is better for every headline unit bench emits
        # (qps / edges/s / rows/s): flag drops
        if dv is not None and dv < -threshold:
            entry["flags"].append(f"value dropped {dv * 100:.1f}%")
        lo, ln = o.get("latency_ms") or {}, n.get("latency_ms") or {}
        for p in ("p50", "p95", "p99"):
            dp = _rel(lo.get(p), ln.get(p))
            if dp is None:
                continue
            entry["deltas"][f"latency_{p}"] = dp
            if dp > threshold:
                entry["flags"].append(f"latency {p} grew {dp * 100:.1f}%")
        # per-phase attribution (hybrid): name the culprit phase
        po, pn = o.get("phases") or {}, n.get("phases") or {}
        worst: Optional[tuple] = None
        for ph in ("knn_ms", "filter_ms", "expand_ms"):
            dp = _rel(po.get(ph), pn.get(ph))
            if dp is None:
                continue
            entry["deltas"][f"phase_{ph}"] = dp
            if worst is None or dp > worst[1]:
                worst = (ph, dp)
            if dp > threshold:
                entry["flags"].append(f"phase {ph} grew {dp * 100:.1f}%")
        if worst is not None:
            entry["culprit_phase"] = worst[0]
        for counter in ("errors", "retries", "splits"):
            ov, nv = o.get(counter), n.get(counter)
            ot = sum(ov.values()) if isinstance(ov, dict) else ov
            nt = sum(nv.values()) if isinstance(nv, dict) else nv
            if isinstance(ot, (int, float)) and isinstance(nt, (int, float)) and nt > ot:
                entry["flags"].append(f"{counter} rose {int(ot)} -> {int(nt)}")
        if entry["flags"]:
            entry["suspects"] = _suspects(n)
        rows.append(entry)
    return rows


def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench_diff.py",
        description="Compare two bench artifacts; flag per-config/per-phase regressions.",
    )
    ap.add_argument("old", help="baseline bench_results_*.json")
    ap.add_argument("new", help="candidate bench_results_*.json")
    ap.add_argument(
        "--threshold", type=float, default=0.25,
        help="relative delta that flags (default 0.25 = 25%%)",
    )
    try:
        ns = ap.parse_args(argv)
    except SystemExit:
        return 2
    threshold = ns.threshold
    try:
        with open(ns.old) as f:
            old = json.load(f)
        with open(ns.new) as f:
            new = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable artifact: {e}", file=sys.stderr)
        return 2
    rows = diff(old, new, threshold)
    if not rows:
        print("no comparable configs between the two artifacts", file=sys.stderr)
        return 2
    flagged = 0
    for r in rows:
        head = (
            f"config {r['config']} ({r['metric']}): "
            f"{r['old_value']} -> {r['new_value']} {r['unit']}"
        )
        dv = r["deltas"].get("value")
        if dv is not None:
            head += f" ({dv * 100:+.1f}%)"
        if r.get("culprit_phase"):
            head += f"  culprit phase: {r['culprit_phase']}"
        print(("FLAG  " if r["flags"] else "ok    ") + head)
        for fl in r["flags"]:
            print(f"      - {fl}")
        for s in r.get("suspects", []):
            print(f"      suspect: {s}")
        flagged += bool(r["flags"])
    print(f"{flagged}/{len(rows)} config(s) flagged (threshold {threshold * 100:.0f}%)")
    return 1 if flagged else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
