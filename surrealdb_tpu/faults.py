"""Deterministic failpoint fault-injection engine.

Role of the reference's `fail::fail_point!` sites (the TiKV/FoundationDB
stacks underneath the reference earn their recovery claims from failpoint
chaos suites): every engine layer that has a RECOVERY STORY carries a named
injection site, and this module decides — deterministically, from a seeded
RNG — whether that site misbehaves on a given pass. A failure path that can
be triggered on demand is a failure path that can be TESTED; everything
else is a comment.

Activation:

- environment: ``SURREAL_FAILPOINTS="site=action[:prob][:count],..."``
  parsed once at first use (the spec string comes through cnf.FAILPOINTS);
- test API: :func:`enable` / :func:`disable` / :func:`reset` /
  :func:`seed` (reproducible chaos schedules).

Actions:

- ``error`` / ``error-<class>`` — raise an injected exception.  Classes:
  ``fault`` (FaultError, a SurrealError — the default), ``transient``
  (message carries ``UNAVAILABLE`` so dispatch classifies it transient and
  split-retries), ``oserror`` (a ConnectionError — the cluster RPC layer
  wraps it into NodeUnavailableError like any network failure), ``kvs``
  (KvsError), ``runtime`` (RuntimeError).
- ``latency-<ms>`` — sleep that long, then continue normally.
- ``corrupt`` — return a corrupted version of the payload the site passed
  to :func:`fire` (bytes are truncated + bit-flipped: the
  peer-died-mid-response shape).
- ``panic`` — raise :class:`FaultPanic`, a BaseException that escapes
  ``except Exception`` guards and kills the executing thread (the
  panic-thread action; bg service supervision is what catches it).

Site catalog (the layers with recovery stories; `bg.<kind>` is a family):

====================== ====================================================
``kvs.commit``          Transaction.commit_direct, before the backend commit
``kvs.group_commit.flush``  GroupCommit._flush, before the drain
``column.delta_apply``  ColumnMirrors.apply_bulk (decline-to-rebuild path)
``vector.delta_apply``  vector-mirror bulk delta application at commit
``dispatch.launch``     the coalesced kernel launch (bisect-retry path)
``cluster.rpc.send``    client request, before the socket write
``cluster.rpc.recv``    client response body (corrupt = truncated CBOR)
``cluster.rpc.handle``  server-side op execution
``cluster.hlc.stamp``   the write-path HLC stamp mint (pre-commit failure)
``cluster.migrate.stream``  one shard-migration batch, before its RPC
``cluster.migrate.cutover`` a member's ring cutover (epoch commit)
``cluster.repair.sweep``    one anti-entropy peer leg, before the digests
``bg.<kind>``           any background task body (bg.run lifecycle)
``cf.gc``               the changefeed GC sweep
====================== ====================================================

Trip counters export as ``failpoint_trips{site,action}`` on /metrics and as
the debug bundle's eighth section (``faults``, bundle.py). The internal
lock is ``faults`` in locks.HIERARCHY — a leaf above the telemetry leaves,
because sites fire while holding commit/dispatch locks.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, Optional

from surrealdb_tpu import cnf
from surrealdb_tpu.err import KvsError, SurrealError
from surrealdb_tpu.utils import locks as _locks


class FaultError(SurrealError):
    """The default injected failure (a plain engine error)."""


class TransientFaultError(SurrealError):
    """Injected failure whose message carries UNAVAILABLE — the dispatch
    layer classifies it transient and exercises its split-retry path."""


class FaultPanic(BaseException):
    """panic-thread action: deliberately NOT an Exception subclass, so the
    ubiquitous `except Exception` guards cannot swallow it — it kills the
    thread it fires on, the way a Rust panic would."""


def _mk_fault(site: str) -> BaseException:
    return FaultError(f"failpoint {site!r} injected error")


def _mk_transient(site: str) -> BaseException:
    return TransientFaultError(
        f"failpoint {site!r} injected transient fault (UNAVAILABLE)"
    )


def _mk_oserror(site: str) -> BaseException:
    return ConnectionError(f"failpoint {site!r} injected connection error")


def _mk_kvs(site: str) -> BaseException:
    return KvsError(f"failpoint {site!r} injected kvs error")


def _mk_runtime(site: str) -> BaseException:
    return RuntimeError(f"failpoint {site!r} injected runtime error")


ERROR_CLASSES = {
    "fault": _mk_fault,
    "transient": _mk_transient,
    "oserror": _mk_oserror,
    "kvs": _mk_kvs,
    "runtime": _mk_runtime,
}


class Failpoint:
    """One armed site's state (guarded by the module lock)."""

    __slots__ = ("site", "action", "arg", "prob", "remaining", "trips")

    def __init__(self, site, action, arg, prob, count):
        self.site = site
        self.action = action  # error | latency | corrupt | panic
        self.arg = arg  # error class key / latency seconds
        self.prob = prob
        self.remaining: Optional[int] = count  # None = unlimited
        self.trips = 0

    def to_dict(self) -> dict:
        return {
            "action": self.action
            + (f"-{self.arg}" if isinstance(self.arg, str) else ""),
            "arg": self.arg,
            "prob": self.prob,
            "remaining": self.remaining,
            "trips": self.trips,
        }


_lock = _locks.Lock("faults")
_sites: Dict[str, Failpoint] = {}
_rng = random.Random()
_seed: Optional[int] = None
_armed = False  # lock-free fast path: no site armed -> fire() is a no-op
_env_loaded = False


class CORRUPT:
    """Sentinel returned by the corrupt action for payloads with no natural
    corruption (None, numbers): unmistakably not a valid value."""


def _parse_action(text: str):
    """'error', 'error-transient', 'latency-50', 'corrupt', 'panic' ->
    (action, arg)."""
    head, _, arg = text.partition("-")
    head = head.strip().lower()
    if head == "error":
        key = (arg or "fault").strip().lower()
        if key not in ERROR_CLASSES:
            raise ValueError(
                f"unknown failpoint error class {key!r} "
                f"(one of {sorted(ERROR_CLASSES)})"
            )
        return "error", key
    if head == "latency":
        try:
            ms = float(arg or 10.0)
        except ValueError as e:
            raise ValueError(f"bad failpoint latency {arg!r}") from e
        return "latency", max(ms, 0.0) / 1000.0
    if head == "corrupt":
        return "corrupt", None
    if head == "panic":
        return "panic", None
    raise ValueError(f"unknown failpoint action {text!r}")


def configure(spec: str) -> None:
    """Arm sites from a spec string: ``site=action[:prob][:count]``,
    comma-separated. Raises ValueError on a malformed spec (a silently
    ignored chaos schedule is worse than none)."""
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        site, sep, rest = part.partition("=")
        if not sep or not site.strip():
            raise ValueError(f"bad failpoint spec {part!r} (want site=action)")
        bits = rest.split(":")
        action, arg = _parse_action(bits[0])
        prob = float(bits[1]) if len(bits) > 1 and bits[1] != "" else 1.0
        count = int(bits[2]) if len(bits) > 2 and bits[2] != "" else None
        enable(site.strip(), bits[0].strip(), prob=prob, count=count,
               _parsed=(action, arg))


def _ensure_env() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    if cnf.FAILPOINTS:
        configure(cnf.FAILPOINTS)
    if cnf.FAULTS_SEED is not None:
        seed(cnf.FAULTS_SEED)


def enable(
    site: str,
    action: str = "error",
    prob: float = 1.0,
    count: Optional[int] = None,
    _parsed=None,
) -> None:
    """Arm one site (test API). `action` uses the spec grammar
    ('error-transient', 'latency-25', ...)."""
    global _armed
    act, arg = _parsed if _parsed is not None else _parse_action(action)
    with _lock:
        fp = Failpoint(site, act, arg, min(max(prob, 0.0), 1.0), count)
        old = _sites.get(site)
        if old is not None:
            fp.trips = old.trips  # survived trip count stays attributable
        _sites[site] = fp
        _armed = True


def disable(site: str) -> None:
    """Disarm a site; its trip count stays visible in snapshots."""
    with _lock:
        fp = _sites.get(site)
        if fp is not None:
            fp.remaining = 0


def reset() -> None:
    """Drop every site and reseed from nothing (tests)."""
    global _armed, _seed
    with _lock:
        _sites.clear()
        _armed = False
        _seed = None
        _rng.seed()


def seed(n: int) -> None:
    """Seed the trip RNG — the same schedule over the same op sequence
    trips the same sites (reproducible chaos runs)."""
    global _seed
    with _lock:
        _seed = int(n)
        _rng.seed(int(n))


def _corrupt(payload: Any) -> Any:
    if isinstance(payload, (bytes, bytearray)):
        if len(payload) <= 1:
            return b"\xff"
        cut = bytearray(payload[: max(len(payload) // 2, 1)])
        cut[0] ^= 0xFF  # truncated AND mangled: the died-mid-write shape
        return bytes(cut)
    if isinstance(payload, str):
        return payload[: len(payload) // 2] + "\x00"
    if isinstance(payload, list):
        return payload[: len(payload) // 2]
    if isinstance(payload, dict):
        out = dict(payload)
        out["__corrupt__"] = True
        return out
    return CORRUPT


def fire(site: str, payload: Any = None) -> Any:
    """The injection hook every site calls. Unarmed sites cost one module
    attribute read. Armed sites roll the seeded RNG under the `faults`
    lock, then act: raise (error/panic), sleep (latency), or return a
    corrupted payload (corrupt). Returns `payload` untouched otherwise."""
    if not _armed and _env_loaded:
        return payload
    _ensure_env()
    if not _armed:
        return payload
    with _lock:
        fp = _sites.get(site)
        if fp is None or fp.remaining == 0:
            return payload
        if fp.prob < 1.0 and _rng.random() >= fp.prob:
            return payload
        if fp.remaining is not None:
            fp.remaining -= 1
        fp.trips += 1
        action, arg = fp.action, fp.arg
    from surrealdb_tpu import events, telemetry

    telemetry.inc("failpoint_trips", site=site, action=action)
    # timeline entry: a trip observed while serving a statement joins that
    # statement's trace — chaos runs read injected faults next to their
    # victims instead of diffing counters
    events.emit("fault.trip", site=site, action=action)
    if action == "error":
        raise ERROR_CLASSES[arg](site)
    if action == "latency":
        time.sleep(arg)
        return payload
    if action == "corrupt":
        return _corrupt(payload)
    if action == "panic":
        raise FaultPanic(f"failpoint {site!r} panic")
    return payload


def snapshot() -> dict:
    """The engine's failpoint state — the debug bundle's eighth section:
    armed sites, per-site trip counters, the seed that produced them."""
    _ensure_env()
    with _lock:
        return {
            "enabled": _armed,
            "seed": _seed,
            "sites": {name: fp.to_dict() for name, fp in sorted(_sites.items())},
            "trips_total": sum(fp.trips for fp in _sites.values()),
        }
