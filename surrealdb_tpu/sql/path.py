"""Idiom (path) evaluation: `a.b[3]->likes->person[WHERE age > 2].name`.

Role of the reference's idiom machinery (reference: core/src/sql/idiom.rs,
part.rs, graph.rs, and the 33 value-operation files in sql/value/ — get.rs,
set.rs, del.rs...). A Part::Graph hop scans the graph-pointer keyspace written
by RELATE (see doc/edges: endpoint --Out--> edge, edge --In/Out--> endpoints),
so `->knows->person` is: OUT-scan from the current ids over edge-table
`knows`, then OUT-scan from those edge ids restricted to table `person`.

The batched TPU frontier path (idx/graph) plugs in underneath `graph_hop` for
large frontiers; the semantics here are the per-record reference behavior.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import TypeError_
from .value import (
    NONE,
    Null,
    Range,
    Thing,
    escape_ident,
    is_none,
    is_nullish,
    truthy,
    value_eq,
)
from .ast import Expr


# ------------------------------------------------------------------- parts
class Part:
    __slots__ = ()


class PStart(Part):
    __slots__ = ("expr",)

    def __init__(self, expr: Expr):
        self.expr = expr

    def __repr__(self):
        return repr(self.expr)


class PField(Part):
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f".{escape_ident(self.name)}"


class PIndex(Part):
    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __repr__(self):
        return f"[{self.i}]"


class PAll(Part):
    def __repr__(self):
        return "[*]"


class PLast(Part):
    def __repr__(self):
        return "[$]"


class PFlatten(Part):
    def __repr__(self):
        return "…"


class POptional(Part):
    def __repr__(self):
        return "?"


class PWhere(Part):
    __slots__ = ("cond",)

    def __init__(self, cond: Expr):
        self.cond = cond

    def __repr__(self):
        return f"[WHERE {self.cond!r}]"


class PValue(Part):
    __slots__ = ("expr",)

    def __init__(self, expr: Expr):
        self.expr = expr

    def __repr__(self):
        return f"[{self.expr!r}]"


class PMethod(Part):
    """.method(args) — value method / closure-field call."""

    __slots__ = ("name", "args")

    def __init__(self, name: str, args: List[Expr]):
        self.name = name
        self.args = args

    def __repr__(self):
        return f".{self.name}(" + ", ".join(repr(a) for a in self.args) + ")"


class PDestructure(Part):
    """.{ a, b: b.c } — object destructuring projection."""

    __slots__ = ("fields",)

    def __init__(self, fields: List[Tuple[str, Optional[List[Part]]]]):
        self.fields = fields

    def __repr__(self):
        inner = ", ".join(k for k, _ in self.fields)
        return ".{" + inner + "}"


class PGraph(Part):
    """->table / <-table / <->table, with optional (tables.. WHERE cond AS alias)."""

    __slots__ = ("dir", "what", "cond", "alias", "expr_fields")

    def __init__(self, dir_: str, what: List[str], cond: Optional[Expr] = None, alias=None):
        self.dir = dir_  # 'out' | 'in' | 'both'
        self.what = what  # table names; empty = ? (any)
        self.cond = cond
        self.alias = alias

    def __repr__(self):
        arrow = {"out": "->", "in": "<-", "both": "<->"}[self.dir]
        what = "?" if not self.what else ",".join(self.what)
        if self.cond is not None:
            return f"{arrow}({what} WHERE {self.cond!r})"
        return f"{arrow}{what}"


class PRecurse(Part):
    """Recursion bounds `{min..max}` applied to the following path segment
    (reference IDIOM_RECURSION_LIMIT cnf/mod.rs:97)."""

    __slots__ = ("min", "max", "parts")

    def __init__(self, min_: int, max_: Optional[int], parts: List[Part]):
        self.min = min_
        self.max = max_
        self.parts = parts

    def __repr__(self):
        rng = f"{self.min}..{self.max if self.max is not None else ''}"
        return "{" + rng + "}" + "".join(repr(p) for p in self.parts)


# ------------------------------------------------------------------- idiom
class Idiom(Expr):
    __slots__ = ("parts",)

    def __init__(self, parts: List[Part]):
        self.parts = parts

    def compute(self, ctx):
        parts = self.parts
        if not parts:
            return NONE
        first = parts[0]
        if isinstance(first, PStart):
            start = first.expr.compute(ctx)
            return get_path(ctx, start, parts[1:])
        if isinstance(first, PGraph):
            start = ctx.doc_value()
            return get_path(ctx, start, parts)
        if isinstance(first, PField):
            if ctx.doc is not None:
                return get_path(ctx, ctx.doc_value(), parts)
            # no doc: a bare identifier denotes a table reference
            if len(parts) == 1:
                from .value import Table

                return Table(first.name)
            return NONE
        return get_path(ctx, ctx.doc_value(), parts)

    def writeable(self):
        return any(
            isinstance(p, PStart) and p.expr.writeable() for p in self.parts
        )

    def simple_name(self) -> Optional[str]:
        """If this is a single plain field (`name`), return it."""
        if len(self.parts) == 1 and isinstance(self.parts[0], PField):
            return self.parts[0].name
        return None

    def field_path(self) -> Optional[List[str]]:
        """If purely nested fields (`a.b.c`), return the name list."""
        out = []
        for p in self.parts:
            if isinstance(p, PField):
                out.append(p.name)
            else:
                return None
        return out or None

    def __repr__(self):
        out = []
        for i, p in enumerate(self.parts):
            if i == 0 and isinstance(p, PField):
                out.append(escape_ident(p.name))
            else:
                out.append(repr(p))
        return "".join(out)

    def __eq__(self, other):
        return isinstance(other, Idiom) and repr(self) == repr(other)

    def __hash__(self):
        return hash(repr(self))


# ------------------------------------------------------------------- get
def _fetch_record(ctx, thing: Thing):
    ns, db = ctx.ns_db()
    doc = ctx.txn().get_record(ns, db, thing.tb, thing.id)
    return doc if doc is not None else NONE


def get_path(ctx, value, parts: List[Part]):
    """Apply path parts to a value, fetching records / walking edges."""
    if not parts:
        return value
    p, rest = parts[0], parts[1:]

    # record pointer: fetch before applying a field-ish part
    if isinstance(value, Thing) and not isinstance(p, (POptional,)):
        if isinstance(p, PGraph):
            return _graph_part(ctx, [value], p, rest)
        if isinstance(p, PMethod):
            # record methods dispatch on the POINTER, not the fetched doc
            # (reference record-type method table: exists/id/tb/table)
            return _method_call(ctx, value, p, rest)
        value = _fetch_record(ctx, value)

    if isinstance(p, PStart):
        return get_path(ctx, p.expr.compute(ctx), rest)

    if isinstance(p, POptional):
        if is_nullish(value):
            return NONE
        return get_path(ctx, value, rest)

    if isinstance(p, PGraph):
        things = value if isinstance(value, list) else [value]
        things = [_as_thing(t) for t in things]
        things = [t for t in things if t is not None]
        return _graph_part(ctx, things, p, rest)

    if isinstance(p, PRecurse):
        return _recurse_part(ctx, value, p, rest)

    if isinstance(value, list):
        if isinstance(p, PIndex):
            v = value[p.i] if -len(value) <= p.i < len(value) else NONE
            return get_path(ctx, v, rest)
        if isinstance(p, PLast):
            return get_path(ctx, value[-1] if value else NONE, rest)
        if isinstance(p, PAll):
            return [get_path(ctx, v, rest) for v in value]
        if isinstance(p, PWhere):
            kept = []
            for v in value:
                dv = _fetch_record(ctx, v) if isinstance(v, Thing) else v
                with ctx.with_doc_value(dv, rid=v if isinstance(v, Thing) else None) as c:
                    if truthy(p.cond.compute(c)):
                        kept.append(v)
            return get_path(ctx, kept, rest)
        if isinstance(p, PValue):
            idx = p.expr.compute(ctx)
            if isinstance(idx, int) and not isinstance(idx, bool):
                v = value[idx] if -len(value) <= idx < len(value) else NONE
                return get_path(ctx, v, rest)
            if isinstance(idx, Range):
                lo = idx.beg if not is_none(idx.beg) else 0
                hi = idx.end if not is_none(idx.end) else len(value)
                if not idx.beg_incl:
                    lo += 1
                if idx.end_incl:
                    hi += 1
                return get_path(ctx, value[int(lo) : int(hi)], rest)
            return get_path(ctx, NONE, rest)
        if isinstance(p, PFlatten):
            flat = []
            for v in value:
                if isinstance(v, list):
                    flat.extend(v)
                else:
                    flat.append(v)
            return get_path(ctx, flat, rest)
        if isinstance(p, PMethod):
            return _method_call(ctx, value, p, rest)
        # field access distributes over arrays
        out = [get_path(ctx, v, [p]) for v in value]
        return get_path(ctx, out, rest)

    if isinstance(value, dict):
        if isinstance(p, PField):
            return get_path(ctx, value.get(p.name, NONE), rest)
        if isinstance(p, PAll):
            return get_path(ctx, value, rest) if not rest else {
                k: get_path(ctx, v, rest) for k, v in value.items()
            }
        if isinstance(p, PValue):
            k = p.expr.compute(ctx)
            if isinstance(k, str):
                return get_path(ctx, value.get(k, NONE), rest)
            return get_path(ctx, NONE, rest)
        if isinstance(p, PDestructure):
            out = {}
            for name, sub in p.fields:
                if sub is None:
                    out[name] = value.get(name, NONE)
                else:
                    out[name] = get_path(ctx, value, sub)
            return get_path(ctx, out, rest)
        if isinstance(p, PMethod):
            return _method_call(ctx, value, p, rest)
        if isinstance(p, PWhere):
            with ctx.with_doc_value(value) as c:
                ok = truthy(p.cond.compute(c))
            return get_path(ctx, value if ok else NONE, rest)
        return get_path(ctx, NONE, rest)

    if isinstance(p, PMethod):
        return _method_call(ctx, value, p, rest)

    if is_nullish(value):
        return NONE

    # scalar with remaining non-applicable parts
    return NONE


def _method_call(ctx, value, p: PMethod, rest):
    """`.method(args)`: closure field first, else builtin whose first arg is
    the receiver (reference: "value methods")."""
    from surrealdb_tpu import fnc
    from surrealdb_tpu.fnc.custom import run_closure
    from .value import Closure as ClosureV

    if isinstance(value, dict) and isinstance(value.get(p.name), ClosureV):
        args = [a.compute(ctx) for a in p.args]
        return get_path(ctx, run_closure(ctx, value[p.name], args), rest)
    args = [a.compute(ctx) for a in p.args]
    out = fnc.run_method(ctx, p.name, value, args)
    return get_path(ctx, out, rest)


def _as_thing(v) -> Optional[Thing]:
    """A record pointer: a Thing itself or a fetched document's id."""
    if isinstance(v, Thing):
        return v
    if isinstance(v, dict) and isinstance(v.get("id"), Thing):
        return v["id"]
    return None


# ------------------------------------------------------------------- graph
def graph_hop(ctx, things: List[Thing], dir_: str, what: List[str]) -> List[Thing]:
    """One edge hop: scan graph-pointer keys for each source id.

    Reference behavior: processor.rs:610-701 collect_edges. The TPU CSR path
    (idx/graph.py) accelerates multi-hop frontiers; this is the exact KV walk.
    """
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    dirs = {"out": [keys.DIR_OUT], "in": [keys.DIR_IN], "both": [keys.DIR_IN, keys.DIR_OUT]}[
        dir_
    ]
    out: List[Thing] = []
    for t in things:
        for d in dirs:
            if what:
                for ft in what:
                    pre = keys.graph_prefix(ns, db, t.tb, t.id, d, ft)
                    for k in txn.keys(pre, _prefix_end(pre)):
                        _, _, _, fk = keys.decode_graph(k, ns, db, t.tb)
                        out.append(fk)
            else:
                pre = keys.graph_prefix(ns, db, t.tb, t.id, d)
                for k in txn.keys(pre, _prefix_end(pre)):
                    _, _, _, fk = keys.decode_graph(k, ns, db, t.tb)
                    out.append(fk)
    return out


def _prefix_end(p: bytes) -> bytes:
    from surrealdb_tpu.key.encode import prefix_end

    return prefix_end(p)


def graph_chain_count(ctx, expr) -> "int | None":
    """count(->a->b->c) fast path: when the argument is a pure cond-free
    graph-chain idiom over the current record, sum the path counts on the
    CSR frontier without expanding (idx/graph_csr.py chain_count). Returns
    None when ineligible — the caller falls back to normal evaluation, so
    this is purely an execution strategy, never a semantics change."""
    if not isinstance(expr, Idiom) or not expr.parts:
        return None
    if not all(isinstance(p, PGraph) for p in expr.parts):
        return None
    doc = ctx.doc
    rid = doc.rid if doc is not None else None
    if not isinstance(rid, Thing):
        return None
    for p in expr.parts:
        if not _mirror_eligible(ctx, p):
            return None
    # no exception guard: deadline/internal errors must propagate, not
    # silently re-run the whole traversal on the slow path
    return ctx.ds().graph_mirrors.chain_count(ctx, [rid], list(expr.parts))


def _mirror_eligible(ctx, p: PGraph) -> bool:
    """A hop can ride the CSR mirrors when its edge tables are named, it has
    no per-record WHERE, and this transaction has no uncommitted edge writes
    (those are only visible to the exact KV walk)."""
    if p.cond is not None or not p.what:
        return False
    try:
        return ctx.ds() is not None and not ctx.txn().graph_deltas
    except Exception:
        return False


def _graph_part(ctx, things: List[Thing], p: PGraph, rest: List[Part]):
    # batched frontier path: a maximal run of eligible graph parts becomes a
    # chain of CSR gather hops (device above TPU_GRAPH_ONDEVICE_THRESHOLD)
    # instead of per-record `~` prefix scans (reference processor.rs:610-701)
    if things and _mirror_eligible(ctx, p):
        chain = [p]
        i = 0
        while (
            i < len(rest)
            and isinstance(rest[i], PGraph)
            and _mirror_eligible(ctx, rest[i])
        ):
            chain.append(rest[i])
            i += 1
        found = ctx.ds().graph_mirrors.chain(ctx, things, chain)
        return get_path(ctx, found, rest[i:])
    found = graph_hop(ctx, things, p.dir, p.what)
    if p.cond is not None:
        kept = []
        for t in found:
            doc = _fetch_record(ctx, t)
            with ctx.with_doc_value(doc, rid=t) as c:
                if truthy(p.cond.compute(c)):
                    kept.append(t)
        found = kept
    # no dedup: the reference flattens hop results without deduplication
    # (sql/value/get.rs:404-446), so parallel edges / converging paths
    # yield duplicate records — multiplicity is part of the result
    return get_path(ctx, found, rest)


def _recurse_part(ctx, value, p: PRecurse, rest: List[Part]):
    from surrealdb_tpu import cnf

    max_depth = p.max if p.max is not None else cnf.IDIOM_RECURSION_LIMIT
    if max_depth > cnf.IDIOM_RECURSION_LIMIT:
        raise TypeError_("Recursion depth exceeds the allowed limit")
    cur = value
    depth = 0
    while depth < max_depth:
        nxt = get_path(ctx, cur, p.parts)
        if isinstance(nxt, list) and not nxt:
            break
        if is_nullish(nxt):
            break
        cur = nxt
        depth += 1
        if depth >= p.min and p.max is None:
            # unbounded: iterate to fixpoint-ish; stop when result repeats
            continue
    if depth < p.min:
        return NONE
    return get_path(ctx, cur, rest)


# ------------------------------------------------------------------- set/del
def set_path(ctx, value, parts: List[Part], new) -> Any:
    """Set a nested path inside a document value (mutates dicts/lists)."""
    if not parts:
        return new
    p, rest = parts[0], parts[1:]
    if isinstance(p, PField):
        if isinstance(value, dict):
            if not rest:
                value[p.name] = new
            else:
                cur = value.get(p.name, NONE)
                if is_nullish(cur) or not isinstance(cur, (dict, list)):
                    cur = {} if not isinstance(
                        rest[0], (PIndex, PAll, PLast)
                    ) else []
                    value[p.name] = cur
                set_path(ctx, cur, rest, new)
        elif isinstance(value, list):
            for item in value:
                set_path(ctx, item, parts, new)
        return value
    if isinstance(p, PIndex):
        if isinstance(value, list) and -len(value) <= p.i < len(value):
            if not rest:
                value[p.i] = new
            else:
                set_path(ctx, value[p.i], rest, new)
        return value
    if isinstance(p, PLast):
        if isinstance(value, list) and value:
            if not rest:
                value[-1] = new
            else:
                set_path(ctx, value[-1], rest, new)
        return value
    if isinstance(p, PAll):
        if isinstance(value, list):
            if not rest:
                value[:] = [new for _ in value]
            else:
                for item in value:
                    set_path(ctx, item, rest, new)
        elif isinstance(value, dict):
            if not rest:
                for k in value:
                    value[k] = new
            else:
                for k in value:
                    set_path(ctx, value[k], rest, new)
        return value
    if isinstance(p, PWhere):
        if isinstance(value, list):
            for item in value:
                dv = item
                with ctx.with_doc_value(dv) as c:
                    if truthy(p.cond.compute(c)):
                        set_path(ctx, item, rest, new) if rest else None
        return value
    if isinstance(p, PValue):
        k = p.expr.compute(ctx)
        if isinstance(value, dict) and isinstance(k, str):
            if not rest:
                value[k] = new
            else:
                cur = value.get(k)
                if not isinstance(cur, (dict, list)):
                    cur = {}
                    value[k] = cur
                set_path(ctx, cur, rest, new)
        elif isinstance(value, list) and isinstance(k, int):
            if -len(value) <= k < len(value):
                if not rest:
                    value[k] = new
                else:
                    set_path(ctx, value[k], rest, new)
        return value
    return value


def del_path(ctx, value, parts: List[Part]) -> Any:
    if not parts:
        return value
    p, rest = parts[0], parts[1:]
    if isinstance(p, PField):
        if isinstance(value, dict):
            if not rest:
                value.pop(p.name, None)
            elif p.name in value:
                del_path(ctx, value[p.name], rest)
        elif isinstance(value, list):
            for item in value:
                del_path(ctx, item, parts)
        return value
    if isinstance(p, PIndex):
        if isinstance(value, list) and -len(value) <= p.i < len(value):
            if not rest:
                del value[p.i]
            else:
                del_path(ctx, value[p.i], rest)
        return value
    if isinstance(p, PAll):
        if isinstance(value, list):
            if not rest:
                value.clear()
            else:
                for item in value:
                    del_path(ctx, item, rest)
        return value
    if isinstance(p, PWhere):
        if isinstance(value, list):
            if not rest:
                keep = []
                for item in value:
                    with ctx.with_doc_value(item) as c:
                        if not truthy(p.cond.compute(c)):
                            keep.append(item)
                value[:] = keep
            else:
                for item in value:
                    with ctx.with_doc_value(item) as c:
                        if truthy(p.cond.compute(c)):
                            del_path(ctx, item, rest)
        return value
    if isinstance(p, PValue):
        k = p.expr.compute(ctx)
        if isinstance(value, dict) and isinstance(k, str):
            if not rest:
                value.pop(k, None)
            elif k in value:
                del_path(ctx, value[k], rest)
        return value
    return value
