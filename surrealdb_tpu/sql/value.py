"""The Value domain.

Role of the reference's 25-variant `Value` enum (reference:
core/src/sql/value/value.rs:91-131). Python natives carry the common cases
(bool/int/float/str/list/dict/bytes); distinguished singletons carry
NONE/NULL; wrapper classes carry the SurrealQL-specific types (Thing,
Duration, Datetime, Uuid, Range, Geometry, Closure, Future...).

Total ordering across types (for ORDER BY / index keys) follows the type
ordinal order: None < Null < Bool < Number < Strand < Duration < Datetime <
Uuid < Array < Object < Geometry < Bytes < Thing.
"""

from __future__ import annotations

import decimal as _decimal
import math
import os as _os
import random
import string as _string
import uuid as _uuid
from datetime import datetime as _pydt, timezone as _tz
from typing import Any, Dict, Iterable, List, Optional, Tuple


# ----------------------------------------------------------------- singletons
class _ValueNone:
    """SurrealQL NONE — absence of a value (distinct from NULL)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "NONE"

    def __bool__(self):
        return False

    def __eq__(self, other):
        return other is self or isinstance(other, _ValueNone)

    def __hash__(self):
        return hash("__surreal_none__")


class _ValueNull:
    """SurrealQL NULL — an explicitly set null."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "NULL"

    def __bool__(self):
        return False

    def __eq__(self, other):
        return other is self or isinstance(other, _ValueNull) or other is None

    def __hash__(self):
        return hash("__surreal_null__")


NONE = _ValueNone()
Null = _ValueNull()


def is_none(v) -> bool:
    return v is NONE or isinstance(v, _ValueNone)


def is_null(v) -> bool:
    return v is Null or v is None or isinstance(v, _ValueNull)


def is_nullish(v) -> bool:
    return is_none(v) or is_null(v)


# ----------------------------------------------------------------- Thing (record id)
_ID_CHARS = _string.ascii_lowercase + _string.digits
# byte -> id-char translation table; one urandom + translate per id is ~10x
# cheaper than 20 random.choices draws (hot in bulk RELATE ingest)
_ID_TABLE = bytes(ord(_ID_CHARS[b % 36]) for b in range(256))

# per-thread entropy buffer: on this kernel a getrandom syscall costs ~100µs,
# which made per-id urandom(20) calls 40% of bulk RELATE ingest. One 80KB
# read amortizes the syscall over 4096 ids; thread-local so two threads can
# never be handed the same slice (a shared cursor would mint duplicate ids).
_ID_BUF_IDS = 4096
import threading as _threading

_id_tls = _threading.local()


def generate_record_id() -> str:
    """20-char random id, same shape the reference generates for `CREATE tb`."""
    buf = getattr(_id_tls, "buf", None)
    pos = getattr(_id_tls, "pos", 0)
    if buf is None or pos + 20 > len(buf):
        buf = _id_tls.buf = _os.urandom(20 * _ID_BUF_IDS).translate(_ID_TABLE)
        pos = 0
    _id_tls.pos = pos + 20
    return buf[pos : pos + 20].decode("ascii")


class Thing:
    """A record pointer `tb:id`. Id may be int/str/Uuid/array/object/Range."""

    __slots__ = ("tb", "id")

    def __init__(self, tb: str, id_: Any = None):
        if id_ is None:
            id_ = generate_record_id()
        self.tb = tb
        self.id = id_

    @staticmethod
    def parse(text: str) -> "Thing":
        from surrealdb_tpu.syn import parse_thing

        return parse_thing(text)

    def __repr__(self):
        return f"{escape_ident(self.tb)}:{format_id(self.id)}"

    def __eq__(self, other):
        return (
            isinstance(other, Thing)
            and self.tb == other.tb
            and _id_eq(self.id, other.id)
        )

    def __hash__(self):
        try:
            return hash((self.tb, _hashable(self.id)))
        except TypeError:
            return hash((self.tb, repr(self.id)))

    def __lt__(self, other):
        if not isinstance(other, Thing):
            return NotImplemented
        return (self.tb, _cmp_key(self.id)) < (other.tb, _cmp_key(other.id))


def _id_eq(a, b):
    return a == b


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


# ----------------------------------------------------------------- Duration
_DUR_UNITS = [
    ("y", 365 * 24 * 3600 * 1_000_000_000),
    ("w", 7 * 24 * 3600 * 1_000_000_000),
    ("d", 24 * 3600 * 1_000_000_000),
    ("h", 3600 * 1_000_000_000),
    ("m", 60 * 1_000_000_000),
    ("s", 1_000_000_000),
    ("ms", 1_000_000),
    ("us", 1_000),
    ("ns", 1),
]
_DUR_UNIT_MAP = {u: n for u, n in _DUR_UNITS}
_DUR_UNIT_MAP["µs"] = 1_000


class Duration:
    __slots__ = ("nanos",)

    def __init__(self, nanos: int = 0):
        self.nanos = int(nanos)

    @staticmethod
    def parse(text: str) -> "Duration":
        total = 0
        i, n = 0, len(text)
        while i < n:
            j = i
            while j < n and (text[j].isdigit() or text[j] == "."):
                j += 1
            if j == i:
                raise ValueError(f"invalid duration {text!r}")
            num = float(text[i:j]) if "." in text[i:j] else int(text[i:j])
            k = j
            while k < n and not (text[k].isdigit() or text[k] == "."):
                k += 1
            unit = text[j:k]
            if unit not in _DUR_UNIT_MAP:
                raise ValueError(f"invalid duration unit {unit!r}")
            total += int(num * _DUR_UNIT_MAP[unit])
            i = k
        return Duration(total)

    @property
    def seconds(self) -> float:
        return self.nanos / 1e9

    def __repr__(self):
        if self.nanos == 0:
            return "0ns"
        if self.nanos < 0:
            return "-" + repr(Duration(-self.nanos))
        out = []
        rest = self.nanos
        for unit, size in _DUR_UNITS:
            if unit == "w":  # reference formats years then days (no weeks)
                continue
            q, rest = divmod(rest, size)
            if q:
                out.append(f"{q}{unit}")
        return "".join(out)

    def __eq__(self, other):
        return isinstance(other, Duration) and self.nanos == other.nanos

    def __hash__(self):
        return hash(("dur", self.nanos))

    def __lt__(self, other):
        if not isinstance(other, Duration):
            return NotImplemented
        return self.nanos < other.nanos

    def __add__(self, other):
        if isinstance(other, Duration):
            return Duration(self.nanos + other.nanos)
        if isinstance(other, Datetime):
            return Datetime(other.nanos + self.nanos)
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, Duration):
            return Duration(self.nanos - other.nanos)
        return NotImplemented


# ----------------------------------------------------------------- Datetime
class Datetime:
    """UTC datetime held as integer nanoseconds since the Unix epoch."""

    __slots__ = ("nanos",)

    def __init__(self, nanos: int = 0):
        self.nanos = int(nanos)

    @staticmethod
    def parse(text: str) -> "Datetime":
        t = text.strip()
        if t.endswith("Z"):
            t = t[:-1] + "+00:00"
        # Fractional seconds beyond microseconds: keep nanos manually
        extra_nanos = 0
        if "." in t:
            head, _, tail = t.partition(".")
            frac = ""
            idx = 0
            while idx < len(tail) and tail[idx].isdigit():
                frac += tail[idx]
                idx += 1
            rest = tail[idx:]
            if len(frac) > 6:
                extra_nanos = int(frac[6:].ljust(3, "0")[:3])
                frac = frac[:6]
            t = head + ("." + frac if frac else "") + rest
        if "T" not in t and " " not in t:
            t = t + "T00:00:00+00:00"
        elif "+" not in t and not t.endswith("00:00") and "Z" not in text:
            # naive datetime -> UTC
            try:
                _pydt.fromisoformat(t)
                if _pydt.fromisoformat(t).tzinfo is None:
                    t = t + "+00:00"
            except ValueError:
                pass
        dt = _pydt.fromisoformat(t)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_tz.utc)
        return Datetime(int(dt.timestamp() * 1_000_000) * 1000 + extra_nanos)

    @staticmethod
    def now() -> "Datetime":
        import time

        return Datetime(time.time_ns())

    @property
    def seconds(self) -> float:
        return self.nanos / 1e9

    def to_py(self) -> _pydt:
        return _pydt.fromtimestamp(self.nanos / 1e9, tz=_tz.utc)

    def __repr__(self):
        micros, nrem = divmod(self.nanos, 1000)
        secs, urem = divmod(micros, 1_000_000)
        dt = _pydt.fromtimestamp(secs, tz=_tz.utc)
        base = dt.strftime("%Y-%m-%dT%H:%M:%S")
        frac_ns = urem * 1000 + nrem
        if frac_ns:
            frac = f"{frac_ns:09d}".rstrip("0")
            return f"d'{base}.{frac}Z'"
        return f"d'{base}Z'"

    def __eq__(self, other):
        return isinstance(other, Datetime) and self.nanos == other.nanos

    def __hash__(self):
        return hash(("dt", self.nanos))

    def __lt__(self, other):
        if not isinstance(other, Datetime):
            return NotImplemented
        return self.nanos < other.nanos

    def __sub__(self, other):
        if isinstance(other, Datetime):
            return Duration(self.nanos - other.nanos)
        if isinstance(other, Duration):
            return Datetime(self.nanos - other.nanos)
        return NotImplemented

    def __add__(self, other):
        if isinstance(other, Duration):
            return Datetime(self.nanos + other.nanos)
        return NotImplemented


# ----------------------------------------------------------------- Uuid
class Uuid:
    __slots__ = ("value",)

    def __init__(self, value: Optional[_uuid.UUID] = None):
        if value is None:
            value = _uuid.uuid4()
        elif isinstance(value, str):
            value = _uuid.UUID(value)
        self.value = value

    @staticmethod
    def v4() -> "Uuid":
        return Uuid(_uuid.uuid4())

    @staticmethod
    def v7() -> "Uuid":
        import time

        ts = time.time_ns() // 1_000_000
        rand_a = random.getrandbits(12)
        rand_b = random.getrandbits(62)
        val = (ts & ((1 << 48) - 1)) << 80
        val |= 0x7 << 76
        val |= rand_a << 64
        val |= 0b10 << 62
        val |= rand_b
        return Uuid(_uuid.UUID(int=val))

    def __repr__(self):
        return f"u'{self.value}'"

    def __eq__(self, other):
        if isinstance(other, Uuid):
            return self.value == other.value
        if isinstance(other, _uuid.UUID):
            return self.value == other
        return False

    def __hash__(self):
        return hash(self.value)

    def __lt__(self, other):
        if isinstance(other, Uuid):
            return self.value < other.value
        return NotImplemented


# ----------------------------------------------------------------- Range
class Range:
    """`beg..end`, `beg..=end`, `beg>..end` — used in ids and WHERE."""

    __slots__ = ("beg", "end", "beg_incl", "end_incl")

    def __init__(self, beg=NONE, end=NONE, beg_incl=True, end_incl=False):
        self.beg, self.end = beg, end
        self.beg_incl, self.end_incl = beg_incl, end_incl

    def __repr__(self):
        b = "" if is_none(self.beg) else format_value(self.beg)
        e = "" if is_none(self.end) else format_value(self.end)
        pre = ">" if not self.beg_incl and not is_none(self.beg) else ""
        eq = "=" if self.end_incl else ""
        return f"{b}{pre}..{eq}{e}"

    def __eq__(self, other):
        return (
            isinstance(other, Range)
            and self.beg == other.beg
            and self.end == other.end
            and self.beg_incl == other.beg_incl
            and self.end_incl == other.end_incl
        )

    def __hash__(self):
        return hash(("range", _hashable(self.beg), _hashable(self.end), self.beg_incl, self.end_incl))

    def contains(self, v) -> bool:
        if not is_none(self.beg):
            c = value_cmp(v, self.beg)
            if c < 0 or (c == 0 and not self.beg_incl):
                return False
        if not is_none(self.end):
            c = value_cmp(v, self.end)
            if c > 0 or (c == 0 and not self.end_incl):
                return False
        return True


# ----------------------------------------------------------------- Geometry
class Geometry:
    """GeoJSON-style geometry. kind: Point/LineString/Polygon/MultiPoint/
    MultiLineString/MultiPolygon/GeometryCollection; coords: nested lists."""

    __slots__ = ("kind", "coords")

    def __init__(self, kind: str, coords: Any):
        self.kind = kind
        self.coords = coords

    def to_json(self) -> dict:
        if self.kind == "GeometryCollection":
            return {
                "type": self.kind,
                "geometries": [g.to_json() for g in self.coords],
            }
        return {"type": self.kind, "coordinates": self.coords}

    def __repr__(self):
        if self.kind == "Point":
            return f"({self.coords[0]}, {self.coords[1]})"
        import json

        return json.dumps(self.to_json())

    def __eq__(self, other):
        return (
            isinstance(other, Geometry)
            and self.kind == other.kind
            and self.coords == other.coords
        )

    def __hash__(self):
        return hash((self.kind, repr(self.coords)))


# ----------------------------------------------------------------- Table ref
class Table(str):
    """A bare table name used as a value (FROM person)."""

    def __repr__(self):
        return escape_ident(str(self))


# ----------------------------------------------------------------- Closure
class Closure:
    """`|$a: int| $a + 1` — anonymous function value."""

    __slots__ = ("params", "returns", "body")

    def __init__(self, params, returns, body):
        self.params = params  # list[(name, kind|None)]
        self.returns = returns
        self.body = body  # AST expression/block

    def __repr__(self):
        ps = ", ".join(f"${p}" for p, _ in self.params)
        return f"|{ps}| ..."

    def __eq__(self, other):
        return self is other

    def __hash__(self):
        return id(self)


# ----------------------------------------------------------------- ordering
_ORDINAL = {
    "none": 0,
    "null": 1,
    "bool": 2,
    "number": 3,
    "strand": 4,
    "duration": 5,
    "datetime": 6,
    "uuid": 7,
    "array": 8,
    "object": 9,
    "geometry": 10,
    "bytes": 11,
    "thing": 12,
    "table": 13,
    "range": 14,
    "closure": 15,
}


def type_ordinal(v) -> int:
    if is_none(v):
        return _ORDINAL["none"]
    if is_null(v):
        return _ORDINAL["null"]
    if isinstance(v, bool):
        return _ORDINAL["bool"]
    if isinstance(v, (int, float, _decimal.Decimal)):
        return _ORDINAL["number"]
    if isinstance(v, Table):
        return _ORDINAL["table"]
    if isinstance(v, str):
        return _ORDINAL["strand"]
    if isinstance(v, Duration):
        return _ORDINAL["duration"]
    if isinstance(v, Datetime):
        return _ORDINAL["datetime"]
    if isinstance(v, (Uuid, _uuid.UUID)):
        return _ORDINAL["uuid"]
    if isinstance(v, (list, tuple)):
        return _ORDINAL["array"]
    if isinstance(v, dict):
        return _ORDINAL["object"]
    if isinstance(v, Geometry):
        return _ORDINAL["geometry"]
    if isinstance(v, bytes):
        return _ORDINAL["bytes"]
    if isinstance(v, Thing):
        return _ORDINAL["thing"]
    if isinstance(v, Range):
        return _ORDINAL["range"]
    if isinstance(v, Closure):
        return _ORDINAL["closure"]
    return 99


def value_cmp(a, b) -> int:
    """Total order over the Value domain; -1/0/1."""
    ta, tb = type_ordinal(a), type_ordinal(b)
    if ta != tb:
        return -1 if ta < tb else 1
    if ta == 0 or ta == 1:
        return 0
    if ta == _ORDINAL["bool"]:
        return (a > b) - (a < b)
    if ta == _ORDINAL["number"]:
        if math.isnan(a) if isinstance(a, float) else False:
            return 0 if (isinstance(b, float) and math.isnan(b)) else -1
        if math.isnan(b) if isinstance(b, float) else False:
            return 1
        return (a > b) - (a < b)
    if ta == _ORDINAL["strand"] or ta == _ORDINAL["table"]:
        return (a > b) - (a < b)
    if ta == _ORDINAL["duration"]:
        return (a.nanos > b.nanos) - (a.nanos < b.nanos)
    if ta == _ORDINAL["datetime"]:
        return (a.nanos > b.nanos) - (a.nanos < b.nanos)
    if ta == _ORDINAL["uuid"]:
        ua = a.value if isinstance(a, Uuid) else a
        ub = b.value if isinstance(b, Uuid) else b
        return (ua > ub) - (ua < ub)
    if ta == _ORDINAL["array"]:
        for x, y in zip(a, b):
            c = value_cmp(x, y)
            if c != 0:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    if ta == _ORDINAL["object"]:
        ka, kb = sorted(a.keys()), sorted(b.keys())
        for x, y in zip(ka, kb):
            if x != y:
                return -1 if x < y else 1
            c = value_cmp(a[x], b[y])
            if c != 0:
                return c
        return (len(ka) > len(kb)) - (len(ka) < len(kb))
    if ta == _ORDINAL["bytes"]:
        return (a > b) - (a < b)
    if ta == _ORDINAL["thing"]:
        if a.tb != b.tb:
            return -1 if a.tb < b.tb else 1
        return value_cmp(a.id, b.id)
    ra, rb = repr(a), repr(b)
    return (ra > rb) - (ra < rb)


class _CmpKey:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return value_cmp(self.v, other.v) < 0

    def __eq__(self, other):
        return value_cmp(self.v, other.v) == 0


def _cmp_key(v):
    return _CmpKey(v)


def sort_key(v):
    """Key function usable with sorted() over mixed Values."""
    return _CmpKey(v)


def value_eq(a, b) -> bool:
    """SurrealQL `=` semantics (NONE = NONE true, NULL = NULL true...)."""
    if is_none(a) or is_none(b):
        return is_none(a) and is_none(b)
    if is_null(a) or is_null(b):
        return is_null(a) and is_null(b)
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if type_ordinal(a) != type_ordinal(b):
        # Thing vs string coercion: person:1 == "person:1"
        if isinstance(a, Thing) and isinstance(b, str):
            return repr(a) == b
        if isinstance(b, Thing) and isinstance(a, str):
            return repr(b) == a
        return False
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(value_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(value_eq(a[k], b[k]) for k in a)
    return a == b


def truthy(v) -> bool:
    """SurrealQL truthiness."""
    if is_nullish(v):
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v != 0
    if isinstance(v, str):
        return len(v) > 0
    if isinstance(v, (list, dict, bytes)):
        return len(v) > 0
    if isinstance(v, Duration):
        return v.nanos != 0
    if isinstance(v, (Thing, Datetime, Uuid, Geometry, Range, Closure)):
        return True
    return bool(v)


# ----------------------------------------------------------------- formatting
_IDENT_OK = set(_string.ascii_letters + _string.digits + "_")


def escape_ident(name: str) -> str:
    if name and all(c in _IDENT_OK for c in name) and not name.isdigit():
        return name
    return "⟨" + name.replace("⟩", "\\⟩") + "⟩"


def format_id(id_: Any) -> str:
    if isinstance(id_, int):
        return str(id_)
    if isinstance(id_, str):
        return escape_ident(id_)
    if isinstance(id_, Range):
        return repr(id_)
    return format_value(id_)


def format_value(v: Any, pretty: bool = False, _ind: int = 0) -> str:
    """Render a Value as SurrealQL text (the canonical output format)."""
    if is_none(v):
        return "NONE"
    if is_null(v):
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "inf" if v > 0 else "-inf"
        if v == int(v) and abs(v) < 1e15:
            return f"{int(v)}f"
        return repr(v) + "f"
    if isinstance(v, _decimal.Decimal):
        return format(v, "f") + "dec"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, Table):
        return repr(v)
    if isinstance(v, str):
        return "'" + v.replace("\\", "\\\\").replace("'", "\\'") + "'"
    if isinstance(v, (list, tuple)):
        inner = ", ".join(format_value(x, pretty, _ind + 1) for x in v)
        return f"[{inner}]"
    if type(v).__name__ == "ndarray":  # packed vector formats like its array
        return format_value(v.tolist(), pretty, _ind)
    if isinstance(v, dict):
        items = ", ".join(
            f"{escape_ident(k)}: {format_value(x, pretty, _ind + 1)}" for k, x in v.items()
        )
        return "{ " + items + " }" if items else "{  }"
    if isinstance(v, bytes):
        return 'b"' + v.hex().upper() + '"'
    if isinstance(v, _uuid.UUID):
        return f"u'{v}'"
    return repr(v)


def to_json_value(v: Any) -> Any:
    """Convert a Value to plain JSON-able Python."""
    if is_none(v) or is_null(v):
        return None
    if isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v
    if isinstance(v, _decimal.Decimal):
        # decimals render as JSON numbers (reference serde impl); exact
        # values survive in the storage/wire ext codecs, not json
        return int(v) if v == int(v) else float(v)
    if isinstance(v, (list, tuple)):
        return [to_json_value(x) for x in v]
    if type(v).__name__ == "ndarray":  # packed vector -> plain JSON array
        return v.tolist()
    if isinstance(v, dict):
        return {k: to_json_value(x) for k, x in v.items()}
    if isinstance(v, Thing):
        return repr(v)
    if isinstance(v, Duration):
        return repr(v)
    if isinstance(v, Datetime):
        return repr(v)[2:-1]  # strip d'...'
    if isinstance(v, Uuid):
        return str(v.value)
    if isinstance(v, Geometry):
        return v.to_json()
    if isinstance(v, bytes):
        import base64

        return base64.b64encode(v).decode()
    if isinstance(v, Range):
        return repr(v)
    return repr(v)


def copy_value(v: Any) -> Any:
    """Deep-copy the mutable parts of a Value tree."""
    if isinstance(v, list):
        return [copy_value(x) for x in v]
    if isinstance(v, dict):
        return {k: copy_value(x) for k, x in v.items()}
    return v
