"""Type kinds: casts and field-type coercion.

Role of the reference's Kind enum + Value::coerce_to/convert_to
(reference: core/src/sql/kind.rs, sql/value/coerce.rs, convert.rs).
Kind syntax: any | null | bool | bytes | datetime | duration | float | int |
number | decimal | object | point | string | uuid | regex | record<a|b> |
geometry<kind> | option<K> | array<K, n> | set<K, n> | either `A | B`.
"""

from __future__ import annotations

import math
import uuid as _uuid
from typing import Any, List, Optional

from surrealdb_tpu.err import TypeError_
from .value import (
    NONE,
    Datetime,
    Duration,
    Geometry,
    Null,
    Range,
    Table,
    Thing,
    Uuid,
    format_value,
    is_none,
    is_null,
    is_nullish,
    truthy,
    value_eq,
)


class Kind:
    """Parsed type kind."""

    __slots__ = ("name", "args", "size")

    def __init__(self, name: str, args: Optional[List] = None, size: Optional[int] = None):
        self.name = name  # lowercase base name, or 'either'
        self.args = args or []  # inner kinds / record tables / literal values
        self.size = size

    def __repr__(self):
        if self.name == "either":
            return " | ".join(repr(a) for a in self.args)
        if self.name == "record" and self.args:
            return f"record<{' | '.join(self.args)}>"
        if self.name in ("array", "set") and self.args:
            inner = repr(self.args[0])
            if self.size is not None:
                return f"{self.name}<{inner}, {self.size}>"
            return f"{self.name}<{inner}>"
        if self.name == "option" and self.args:
            return f"option<{self.args[0]!r}>"
        if self.name == "geometry" and self.args:
            return f"geometry<{'|'.join(self.args)}>"
        if self.name == "literal":
            return format_value(self.args[0])
        return self.name

    def __eq__(self, other):
        return isinstance(other, Kind) and repr(self) == repr(other)


def _err(v, kind) -> TypeError_:
    return TypeError_(
        f"Expected a {kind} but found {format_value(v)}"
    )


def coerce(kind: Kind, v: Any, strict: bool = True) -> Any:
    """Coerce value to kind (field TYPE checking). strict=False = cast mode
    (more lenient conversions, e.g. string->int)."""
    name = kind.name
    if name == "any":
        return v
    if name == "option":
        if is_nullish(v):
            return v
        return coerce(kind.args[0], v, strict)
    if name == "either":
        last = None
        for k in kind.args:
            try:
                return coerce(k, v, strict)
            except TypeError_ as e:
                last = e
        raise last or _err(v, kind)
    if name == "literal":
        if value_eq(v, kind.args[0]):
            return v
        raise _err(v, kind)
    if name == "null":
        if is_null(v):
            return Null
        raise _err(v, "null")
    if name == "bool":
        if isinstance(v, bool):
            return v
        if not strict:
            if isinstance(v, str):
                if v.lower() == "true":
                    return True
                if v.lower() == "false":
                    return False
            return truthy(v)
        raise _err(v, "bool")
    if name == "int":
        if isinstance(v, bool):
            raise _err(v, "int")
        if isinstance(v, int):
            return v
        if isinstance(v, float) and v == int(v):
            return int(v)
        if not strict:
            if isinstance(v, str):
                try:
                    return int(float(v)) if "." in v or "e" in v.lower() else int(v)
                except ValueError:
                    raise _err(v, "int")
            if isinstance(v, float):
                return int(v)
        raise _err(v, "int")
    if name == "float":
        if isinstance(v, bool):
            raise _err(v, "float")
        if isinstance(v, float):
            return v
        if isinstance(v, int):
            return float(v)
        if not strict and isinstance(v, str):
            try:
                return float(v)
            except ValueError:
                raise _err(v, "float")
        raise _err(v, "float")
    if name == "decimal":
        import decimal as _dec

        if isinstance(v, bool):
            raise _err(v, name)
        if isinstance(v, _dec.Decimal):
            return v
        if isinstance(v, int):
            return _dec.Decimal(v)
        if isinstance(v, float):
            return _dec.Decimal(repr(v))
        if not strict and isinstance(v, str):
            try:
                return _dec.Decimal(v)
            except _dec.InvalidOperation:
                raise _err(v, "decimal")
        raise _err(v, "decimal")
    if name == "number":
        import decimal as _dec

        if isinstance(v, bool):
            raise _err(v, name)
        if isinstance(v, (int, float, _dec.Decimal)):
            return v
        if not strict and isinstance(v, str):
            try:
                return int(v)
            except ValueError:
                try:
                    return float(v)
                except ValueError:
                    raise _err(v, name)
        raise _err(v, name)
    if name == "string":
        if isinstance(v, str) and not isinstance(v, Table):
            return v
        if not strict:
            if isinstance(v, bytes):
                return v.decode("utf-8", "replace")
            if is_nullish(v):
                raise _err(v, "string")
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, (int,)):
                return str(v)
            if isinstance(v, float):
                return repr(v) if v != int(v) else str(v)
            if isinstance(v, (Thing, Duration)):
                return repr(v)
            if isinstance(v, Datetime):
                return repr(v)[2:-1]
            if isinstance(v, Uuid):
                return str(v.value)
            if isinstance(v, Table):
                return str(v)
        raise _err(v, "string")
    if name == "bytes":
        if isinstance(v, bytes):
            return v
        if not strict and isinstance(v, str):
            return v.encode()
        raise _err(v, "bytes")
    if name == "datetime":
        if isinstance(v, Datetime):
            return v
        if not strict and isinstance(v, str):
            try:
                return Datetime.parse(v)
            except ValueError:
                raise _err(v, "datetime")
        raise _err(v, "datetime")
    if name == "duration":
        if isinstance(v, Duration):
            return v
        if not strict and isinstance(v, str):
            try:
                return Duration.parse(v)
            except ValueError:
                raise _err(v, "duration")
        raise _err(v, "duration")
    if name == "uuid":
        if isinstance(v, Uuid):
            return v
        if isinstance(v, _uuid.UUID):
            return Uuid(v)
        if not strict and isinstance(v, str):
            try:
                return Uuid(_uuid.UUID(v))
            except ValueError:
                raise _err(v, "uuid")
        raise _err(v, "uuid")
    if name == "record":
        if isinstance(v, Thing):
            if kind.args and v.tb not in kind.args:
                raise _err(v, f"record<{'|'.join(kind.args)}>")
            return v
        if not strict and isinstance(v, str):
            from surrealdb_tpu.syn import parse_thing

            t = parse_thing(v)
            if kind.args and t.tb not in kind.args:
                raise _err(v, f"record<{'|'.join(kind.args)}>")
            return t
        raise _err(v, "record")
    if name == "object":
        if isinstance(v, dict):
            return v
        raise _err(v, "object")
    if name in ("array", "set"):
        if not isinstance(v, (list, tuple)):
            if strict:
                raise _err(v, name)
            v = [v]
        out = list(v)
        if kind.args:
            out = [coerce(kind.args[0], x, strict) for x in out]
        if name == "set":
            dedup = []
            for x in out:
                if not any(value_eq(x, y) for y in dedup):
                    dedup.append(x)
            out = dedup
        if kind.size is not None and len(out) > kind.size:
            raise TypeError_(
                f"Expected a {kind!r} but found an array of length {len(out)}"
            )
        return out
    if name == "geometry":
        if isinstance(v, Geometry):
            if kind.args and v.kind.lower() not in [a.lower() for a in kind.args]:
                raise _err(v, f"geometry<{'|'.join(kind.args)}>")
            return v
        if isinstance(v, dict) and "type" in v and ("coordinates" in v or "geometries" in v):
            g = Geometry(v["type"], v.get("coordinates", v.get("geometries")))
            return coerce(kind, g, strict)
        raise _err(v, "geometry")
    if name == "point":
        if isinstance(v, Geometry) and v.kind == "Point":
            return v
        if isinstance(v, (list, tuple)) and len(v) == 2:
            return Geometry("Point", list(v))
        raise _err(v, "point")
    if name in ("function", "closure"):
        from .value import Closure

        if isinstance(v, Closure):
            return v
        raise _err(v, "function")
    if name == "range":
        if isinstance(v, Range):
            return v
        raise _err(v, "range")
    if name == "regex":
        import re

        if isinstance(v, re.Pattern):
            return v
        if not strict and isinstance(v, str):
            return re.compile(v)
        raise _err(v, "regex")
    raise TypeError_(f"unknown kind {name}")


def coerce_cast(kind_text, v: Any) -> Any:
    """<int> style cast — lenient conversions."""
    kind = kind_text if isinstance(kind_text, Kind) else parse_kind_text(kind_text)
    return coerce(kind, v, strict=False)


def parse_kind_text(text: str) -> Kind:
    from surrealdb_tpu.syn import parse_kind

    return parse_kind(text)
