"""Statement AST nodes.

Role of the reference's 29 statement kinds (reference:
core/src/sql/statement.rs:62-100, statements/). Execution of the data
statements (SELECT/CREATE/...) is delegated to the iterator machinery in
surrealdb_tpu.dbs; control-flow statements compute inline.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from surrealdb_tpu.err import (
    BreakError,
    ContinueError,
    ReturnError,
    ThrownError,
    TypeError_,
)
from .value import NONE, Duration, Thing, escape_ident, format_value, is_nullish, truthy
from .ast import Expr


class Statement:
    __slots__ = ()

    def compute(self, ctx):
        raise NotImplementedError(type(self).__name__)

    def writeable(self) -> bool:
        return False


class Query:
    # `sources` (parallel to `statements`) carries each statement's original
    # source text when parsed from a string — the cluster executor ships
    # THAT to peer nodes, because not every statement repr round-trips
    # (DDL reprs are summaries)
    __slots__ = ("statements", "sources")

    def __init__(self, statements: List[Statement], sources=None):
        self.statements = statements
        self.sources = sources

    def __repr__(self):
        return ";\n".join(repr(s) for s in self.statements) + ";"


# ------------------------------------------------------------------ clauses
class Field:
    """One projection in SELECT: expr [AS alias], or *."""

    __slots__ = ("expr", "alias", "all")

    def __init__(self, expr: Optional[Expr], alias=None, all_: bool = False):
        self.expr = expr
        self.alias = alias  # Idiom or None
        self.all = all_

    def __repr__(self):
        if self.all:
            return "*"
        if self.alias is not None:
            return f"{self.expr!r} AS {self.alias!r}"
        return repr(self.expr)


class Data:
    """SET/UNSET/CONTENT/MERGE/PATCH/REPLACE payload."""

    __slots__ = ("kind", "items")

    def __init__(self, kind: str, items):
        self.kind = kind  # set | unset | content | merge | patch | replace | values
        self.items = items

    def __repr__(self):
        if self.kind == "set":
            inner = ", ".join(f"{i!r} {op} {v!r}" for i, op, v in self.items)
            return f"SET {inner}"
        if self.kind == "unset":
            return "UNSET " + ", ".join(repr(i) for i in self.items)
        return f"{self.kind.upper()} {self.items!r}"


class Output:
    __slots__ = ("kind", "fields")

    def __init__(self, kind: str, fields=None):
        self.kind = kind  # none | null | diff | before | after | fields
        self.fields = fields

    def __repr__(self):
        if self.kind == "fields":
            return "RETURN " + ", ".join(repr(f) for f in self.fields)
        return f"RETURN {self.kind.upper()}"


class OrderItem:
    __slots__ = ("idiom", "asc", "collate", "numeric", "rand")

    def __init__(self, idiom, asc=True, collate=False, numeric=False, rand=False):
        self.idiom = idiom
        self.asc = asc
        self.collate = collate
        self.numeric = numeric
        self.rand = rand

    def __repr__(self):
        if self.rand:
            return "RAND()"
        out = repr(self.idiom)
        if self.collate:
            out += " COLLATE"
        if self.numeric:
            out += " NUMERIC"
        out += " ASC" if self.asc else " DESC"
        return out


class With:
    __slots__ = ("noindex", "indexes")

    def __init__(self, noindex: bool, indexes: Optional[List[str]] = None):
        self.noindex = noindex
        self.indexes = indexes or []

    def __repr__(self):
        return "WITH NOINDEX" if self.noindex else "WITH INDEX " + ", ".join(self.indexes)


# ------------------------------------------------------------------ control
class UseStatement(Statement):
    __slots__ = ("ns", "db")

    def __init__(self, ns: Optional[str], db: Optional[str]):
        self.ns = ns
        self.db = db

    def compute(self, ctx):
        if self.ns:
            ctx.session.ns = self.ns
        if self.db:
            ctx.session.db = self.db
        return NONE

    def __repr__(self):
        out = "USE"
        if self.ns:
            out += f" NS {self.ns}"
        if self.db:
            out += f" DB {self.db}"
        return out


class LetStatement(Statement):
    __slots__ = ("name", "what", "kind")

    def __init__(self, name: str, what: Expr, kind=None):
        self.name = name
        self.what = what
        self.kind = kind

    def compute(self, ctx):
        v = self.what.compute(ctx)
        if self.kind is not None:
            from .kind import coerce

            v = coerce(self.kind, v)
        ctx.set_param(self.name, v)
        return NONE

    def writeable(self):
        return self.what.writeable()

    def __repr__(self):
        return f"LET ${self.name} = {self.what!r}"


class ReturnStatement(Statement):
    __slots__ = ("what", "fetch")

    def __init__(self, what: Expr, fetch=None):
        self.what = what
        self.fetch = fetch

    def compute(self, ctx):
        v = self.what.compute(ctx)
        if self.fetch:
            from surrealdb_tpu.dbs.fetch import apply_fetch

            v = apply_fetch(ctx, v, self.fetch)
        raise ReturnError(v)

    def writeable(self):
        return self.what.writeable()

    def __repr__(self):
        return f"RETURN {self.what!r}"


class IfStatement(Statement):
    __slots__ = ("branches", "else_")

    def __init__(self, branches: List[Tuple[Expr, Expr]], else_: Optional[Expr]):
        self.branches = branches
        self.else_ = else_

    def compute(self, ctx):
        for cond, then in self.branches:
            if truthy(cond.compute(ctx)):
                return then.compute(ctx)
        if self.else_ is not None:
            return self.else_.compute(ctx)
        return NONE

    def writeable(self):
        return any(
            c.writeable() or t.writeable() for c, t in self.branches
        ) or (self.else_ is not None and self.else_.writeable())

    def __repr__(self):
        out = []
        for i, (c, t) in enumerate(self.branches):
            kw = "IF" if i == 0 else "ELSE IF"
            out.append(f"{kw} {c!r} {t!r}")
        if self.else_ is not None:
            out.append(f"ELSE {self.else_!r}")
        return " ".join(out)


class ForStatement(Statement):
    __slots__ = ("param", "what", "block")

    def __init__(self, param: str, what: Expr, block):
        self.param = param
        self.what = what
        self.block = block

    def compute(self, ctx):
        from .value import Range

        vals = self.what.compute(ctx)
        if isinstance(vals, Range):
            beg = vals.beg if not is_nullish(vals.beg) else 0
            end = vals.end
            if not vals.beg_incl:
                beg += 1
            if vals.end_incl:
                end += 1
            vals = range(int(beg), int(end))
        elif not isinstance(vals, (list, tuple, range)):
            raise TypeError_(
                f"Can not iterate over {format_value(vals)} in a FOR statement"
            )
        for v in vals:
            ctx.set_param(self.param, v)
            try:
                self.block.compute(ctx)
            except BreakError:
                break
            except ContinueError:
                continue
        return NONE

    def writeable(self):
        return self.block.writeable()

    def __repr__(self):
        return f"FOR ${self.param} IN {self.what!r} {self.block!r}"


class BreakStatement(Statement):
    def compute(self, ctx):
        raise BreakError()

    def __repr__(self):
        return "BREAK"


class ContinueStatement(Statement):
    def compute(self, ctx):
        raise ContinueError()

    def __repr__(self):
        return "CONTINUE"


class ThrowStatement(Statement):
    __slots__ = ("what",)

    def __init__(self, what: Expr):
        self.what = what

    def compute(self, ctx):
        raise ThrownError(format_value(self.what.compute(ctx)))

    def __repr__(self):
        return f"THROW {self.what!r}"


class SleepStatement(Statement):
    __slots__ = ("duration",)

    def __init__(self, duration: Duration):
        self.duration = duration

    def compute(self, ctx):
        import time

        time.sleep(self.duration.seconds)
        return NONE

    def __repr__(self):
        return f"SLEEP {self.duration!r}"


class OptionStatement(Statement):
    __slots__ = ("name", "value")

    def __init__(self, name: str, value: bool):
        self.name = name
        self.value = value

    def compute(self, ctx):
        ctx.set_option(self.name, self.value)
        return NONE

    def __repr__(self):
        return f"OPTION {self.name} = {'true' if self.value else 'false'}"


class BeginStatement(Statement):
    def compute(self, ctx):
        return NONE

    def __repr__(self):
        return "BEGIN TRANSACTION"


class CommitStatement(Statement):
    def compute(self, ctx):
        return NONE

    def __repr__(self):
        return "COMMIT TRANSACTION"


class CancelStatement(Statement):
    def compute(self, ctx):
        return NONE

    def __repr__(self):
        return "CANCEL TRANSACTION"


# ------------------------------------------------------------------ data
class SelectStatement(Statement):
    __slots__ = (
        "fields",
        "omit",
        "only",
        "what",
        "with_",
        "cond",
        "split",
        "group",
        "group_all",
        "order",
        "limit",
        "start",
        "fetch",
        "version",
        "timeout",
        "parallel",
        "explain",
        "explain_full",
        "explain_analyze",
        "value_mode",
    )

    def __init__(self, fields, what, **kw):
        self.fields = fields
        self.what = what
        self.omit = kw.get("omit")
        self.only = kw.get("only", False)
        self.with_ = kw.get("with_")
        self.cond = kw.get("cond")
        self.split = kw.get("split")
        self.group = kw.get("group")
        self.group_all = kw.get("group_all", False)
        self.order = kw.get("order")
        self.limit = kw.get("limit")
        self.start = kw.get("start")
        self.fetch = kw.get("fetch")
        self.version = kw.get("version")
        self.timeout = kw.get("timeout")
        self.parallel = kw.get("parallel", False)
        self.explain = kw.get("explain", False)
        self.explain_full = kw.get("explain_full", False)
        self.explain_analyze = kw.get("explain_analyze", False)
        self.value_mode = kw.get("value_mode", False)

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import select_compute

        return select_compute(ctx, self)

    def writeable(self):
        return False

    def __repr__(self):
        out = "SELECT "
        if self.value_mode:
            out += "VALUE "
        out += ", ".join(repr(f) for f in self.fields)
        out += " FROM "
        if self.only:
            out += "ONLY "
        out += ", ".join(repr(w) for w in self.what)
        if self.with_ is not None:
            out += f" {self.with_!r}"
        if self.cond is not None:
            out += f" WHERE {self.cond!r}"
        if self.split:
            out += " SPLIT " + ", ".join(repr(s) for s in self.split)
        if self.group:
            out += " GROUP BY " + ", ".join(repr(g) for g in self.group)
        elif self.group_all:
            out += " GROUP ALL"
        if self.order:
            out += " ORDER BY " + ", ".join(repr(o) for o in self.order)
        if self.limit is not None:
            out += f" LIMIT {self.limit!r}"
        if self.start is not None:
            out += f" START {self.start!r}"
        if self.fetch:
            out += " FETCH " + ", ".join(repr(f) for f in self.fetch)
        if self.parallel:
            out += " PARALLEL"
        if self.explain:
            out += " EXPLAIN"
            if self.explain_full:
                out += " FULL"
            if self.explain_analyze:
                out += " ANALYZE"
        return out


class CreateStatement(Statement):
    __slots__ = ("only", "what", "data", "output", "timeout", "parallel", "version")

    def __init__(self, what, **kw):
        self.what = what
        self.only = kw.get("only", False)
        self.data = kw.get("data")
        self.output = kw.get("output")
        self.timeout = kw.get("timeout")
        self.parallel = kw.get("parallel", False)
        self.version = kw.get("version")

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import create_compute

        return create_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = "CREATE " + ("ONLY " if self.only else "")
        out += ", ".join(repr(w) for w in self.what)
        if self.data is not None:
            out += f" {self.data!r}"
        if self.output is not None:
            out += f" {self.output!r}"
        return out


class UpdateStatement(Statement):
    __slots__ = ("only", "what", "data", "cond", "output", "timeout", "parallel")

    def __init__(self, what, **kw):
        self.what = what
        self.only = kw.get("only", False)
        self.data = kw.get("data")
        self.cond = kw.get("cond")
        self.output = kw.get("output")
        self.timeout = kw.get("timeout")
        self.parallel = kw.get("parallel", False)

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import update_compute

        return update_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = "UPDATE " + ("ONLY " if self.only else "")
        out += ", ".join(repr(w) for w in self.what)
        if self.data is not None:
            out += f" {self.data!r}"
        if self.cond is not None:
            out += f" WHERE {self.cond!r}"
        if self.output is not None:
            out += f" {self.output!r}"
        return out


class UpsertStatement(UpdateStatement):
    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import upsert_compute

        return upsert_compute(ctx, self)

    def __repr__(self):
        return "UPSERT" + super().__repr__()[6:]


class DeleteStatement(Statement):
    __slots__ = ("only", "what", "cond", "output", "timeout", "parallel")

    def __init__(self, what, **kw):
        self.what = what
        self.only = kw.get("only", False)
        self.cond = kw.get("cond")
        self.output = kw.get("output")
        self.timeout = kw.get("timeout")
        self.parallel = kw.get("parallel", False)

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import delete_compute

        return delete_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = "DELETE " + ("ONLY " if self.only else "")
        out += ", ".join(repr(w) for w in self.what)
        if self.cond is not None:
            out += f" WHERE {self.cond!r}"
        if self.output is not None:
            out += f" {self.output!r}"
        return out


class InsertStatement(Statement):
    __slots__ = ("into", "data", "ignore", "update", "output", "relation", "version")

    def __init__(self, into, data, **kw):
        self.into = into  # Expr or None (data carries ids)
        self.data = data  # Data('values', (fields, tuples)) | Data('content', expr)
        self.ignore = kw.get("ignore", False)
        self.update = kw.get("update")  # ON DUPLICATE KEY UPDATE set-items
        self.output = kw.get("output")
        self.relation = kw.get("relation", False)
        self.version = kw.get("version")

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import insert_compute

        return insert_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = "INSERT "
        if self.relation:
            out += "RELATION "
        if self.ignore:
            out += "IGNORE "
        if self.into is not None:
            out += f"INTO {self.into!r} "
        out += repr(self.data)
        return out


class RelateStatement(Statement):
    __slots__ = ("only", "kind", "from_", "with_", "uniq", "data", "output", "timeout", "parallel")

    def __init__(self, kind, from_, with_, **kw):
        self.kind = kind  # edge-table expr
        self.from_ = from_
        self.with_ = with_
        self.only = kw.get("only", False)
        self.uniq = kw.get("uniq", False)
        self.data = kw.get("data")
        self.output = kw.get("output")
        self.timeout = kw.get("timeout")
        self.parallel = kw.get("parallel", False)

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import relate_compute

        return relate_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = "RELATE " + ("ONLY " if self.only else "")
        out += f"{self.from_!r} -> {self.kind!r} -> {self.with_!r}"
        if self.uniq:
            out += " UNIQUE"
        if self.data is not None:
            out += f" {self.data!r}"
        if self.output is not None:
            # the cluster executor routes RELATE by repr — dropping the
            # RETURN clause would change what the owner node answers
            out += f" {self.output!r}"
        return out


# ------------------------------------------------------------------ live
class LiveStatement(Statement):
    __slots__ = ("fields", "what", "cond", "fetch", "diff")

    def __init__(self, fields, what, cond=None, fetch=None, diff=False):
        self.fields = fields
        self.what = what
        self.cond = cond
        self.fetch = fetch
        self.diff = diff

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import live_compute

        return live_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        body = "DIFF" if self.diff else ", ".join(repr(f) for f in self.fields)
        out = f"LIVE SELECT {body} FROM {self.what!r}"
        if self.cond is not None:
            out += f" WHERE {self.cond!r}"
        return out


class KillStatement(Statement):
    __slots__ = ("id",)

    def __init__(self, id_):
        self.id = id_

    def compute(self, ctx):
        from surrealdb_tpu.dbs.stmt_exec import kill_compute

        return kill_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        return f"KILL {self.id!r}"


class ShowStatement(Statement):
    """SHOW CHANGES FOR TABLE tb SINCE ts [LIMIT n]."""

    __slots__ = ("table", "since", "limit")

    def __init__(self, table, since, limit=None):
        self.table = table
        self.since = since
        self.limit = limit

    def compute(self, ctx):
        from surrealdb_tpu.cf.reader import show_changes

        return show_changes(ctx, self)

    def __repr__(self):
        out = f"SHOW CHANGES FOR TABLE {self.table}"
        if self.since is not None:
            out += f" SINCE {self.since!r}"
        if self.limit is not None:
            out += f" LIMIT {self.limit}"
        return out


# ------------------------------------------------------------------ info
class InfoStatement(Statement):
    __slots__ = ("level", "target", "structure")

    def __init__(self, level: str, target: Optional[str] = None, structure=False):
        self.level = level  # root | ns | db | table | user | index
        self.target = target
        self.structure = structure

    def compute(self, ctx):
        from surrealdb_tpu.dbs.info import info_compute

        return info_compute(ctx, self)

    def __repr__(self):
        lvl = {"root": "ROOT", "ns": "NAMESPACE", "db": "DATABASE", "table": "TABLE", "index": "INDEX", "user": "USER"}[
            self.level
        ]
        out = f"INFO FOR {lvl}"
        if self.target:
            out += f" {self.target}"
        return out


# ------------------------------------------------------------------ define
class DefineStatement(Statement):
    """One node for all DEFINE kinds; `kind` selects the handler.

    kinds: namespace database table field index event analyzer function param
    user access model config
    """

    __slots__ = ("kind", "args")

    def __init__(self, defkind: str, **args):
        self.kind = defkind
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu.dbs.define import define_compute

        return define_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        name = self.args.get("name", "")
        return f"DEFINE {self.kind.upper()} {name}"


class RemoveStatement(Statement):
    __slots__ = ("kind", "name", "table", "if_exists", "level")

    def __init__(self, kind: str, name: str, table=None, if_exists=False, level=None):
        self.kind = kind
        self.name = name
        self.table = table
        self.if_exists = if_exists
        self.level = level

    def compute(self, ctx):
        from surrealdb_tpu.dbs.define import remove_compute

        return remove_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        out = f"REMOVE {self.kind.upper()} {self.name}"
        if self.table:
            out += f" ON {self.table}"
        return out


class AlterStatement(Statement):
    __slots__ = ("kind", "name", "if_exists", "args")

    def __init__(self, kind: str, name: str, if_exists=False, **args):
        self.kind = kind
        self.name = name
        self.if_exists = if_exists
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu.dbs.define import alter_compute

        return alter_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        return f"ALTER {self.kind.upper()} {self.name}"


class RebuildStatement(Statement):
    __slots__ = ("name", "table", "if_exists")

    def __init__(self, name: str, table: str, if_exists=False):
        self.name = name
        self.table = table
        self.if_exists = if_exists

    def compute(self, ctx):
        from surrealdb_tpu.dbs.define import rebuild_compute

        return rebuild_compute(ctx, self)

    def writeable(self):
        return True

    def __repr__(self):
        return f"REBUILD INDEX {self.name} ON {self.table}"


class AccessStatement(Statement):
    """ACCESS ... GRANT/SHOW/REVOKE/PURGE (token/grant management)."""

    __slots__ = ("name", "base", "op", "args")

    def __init__(self, name: str, base, op: str, **args):
        self.name = name
        self.base = base
        self.op = op
        self.args = args

    def writeable(self) -> bool:
        return self.op in ("grant", "revoke", "purge")

    def compute(self, ctx):
        from surrealdb_tpu.iam.access import access_compute

        return access_compute(ctx, self)

    def __repr__(self):
        return f"ACCESS {self.name} {self.op.upper()}"
