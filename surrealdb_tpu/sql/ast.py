"""Expression AST.

Role of the reference's `sql::Value` expression variants and idiom machinery
(reference: core/src/sql/value/value.rs, sql/idiom.rs, sql/part.rs,
sql/graph.rs, sql/operator.rs). Every node computes against a Context
(surrealdb_tpu.dbs.context) carrying the transaction, session, options,
current document and parameters.

Path (idiom) evaluation including graph hops lives in sql/path.py; statement
nodes live in sql/statements.py.
"""

from __future__ import annotations

import decimal as _dec
import math
import re as _re
from typing import Any, List, Optional, Tuple

from surrealdb_tpu.err import ComputationDepthError, TypeError_
from surrealdb_tpu import cnf
from .value import (
    NONE,
    Closure,
    Datetime,
    Duration,
    Geometry,
    Null,
    Range,
    Table,
    Thing,
    Uuid,
    is_none,
    is_nullish,
    is_null,
    format_value,
    truthy,
    value_cmp,
    value_eq,
    type_ordinal,
    format_id,
    escape_ident,
)


class Expr:
    """Base expression node."""

    __slots__ = ()

    def compute(self, ctx) -> Any:
        raise NotImplementedError(type(self).__name__)

    def writeable(self) -> bool:
        """Does evaluating this expression potentially write?"""
        return False


# ------------------------------------------------------------------ literals
class Literal(Expr):
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def compute(self, ctx):
        return self.value

    def __repr__(self):
        return format_value(self.value)

    def __eq__(self, other):
        return isinstance(other, Literal) and value_eq(self.value, other.value)


class SlotLiteral(Literal):
    """A literal parameterized by the plan cache (dbs/plan_cache.py): slot
    `i` of the statement shape's literal-token sequence. A cached template
    AST is SHARED across executions of every same-fingerprint text, so the
    active execution's values ride the per-query Executor (set by the
    datastore before process()), never this node — `value` keeps the
    first-seen text's literal as the unbound default (repr/explain)."""

    __slots__ = ("slot",)

    def __init__(self, slot: int, value):
        super().__init__(value)
        self.slot = slot

    def compute(self, ctx):
        sv = getattr(ctx.executor, "slot_values", None)
        if sv is not None and self.slot < len(sv):
            return sv[self.slot]
        return self.value


class ArrayLit(Expr):
    __slots__ = ("items",)

    def __init__(self, items: List[Expr]):
        self.items = items

    def compute(self, ctx):
        return [compute_or_flatten(it, ctx) for it in self.items]

    def writeable(self):
        return any(i.writeable() for i in self.items)

    def __repr__(self):
        return "[" + ", ".join(repr(i) for i in self.items) + "]"


class ObjectLit(Expr):
    __slots__ = ("pairs",)

    def __init__(self, pairs: List[Tuple[str, Expr]]):
        self.pairs = pairs

    def compute(self, ctx):
        return {k: compute_or_flatten(v, ctx) for k, v in self.pairs}

    def writeable(self):
        return any(v.writeable() for _, v in self.pairs)

    def __repr__(self):
        inner = ", ".join(f"{escape_ident(k)}: {v!r}" for k, v in self.pairs)
        return "{ " + inner + " }"


class ThingLit(Expr):
    """`person:1`, `person:⟨x⟩`, `person:[1,2]`, `person:uuid()` ..."""

    __slots__ = ("tb", "id")

    def __init__(self, tb: str, id_expr):
        self.tb = tb
        self.id = id_expr  # Expr or literal value

    def compute(self, ctx):
        id_ = self.id.compute(ctx) if isinstance(self.id, Expr) else self.id
        if isinstance(id_, Range):
            return ThingRange(self.tb, id_)
        return Thing(self.tb, id_)

    def __repr__(self):
        if isinstance(self.id, Expr):
            return f"{escape_ident(self.tb)}:{self.id!r}"
        return repr(Thing(self.tb, self.id))


class ThingRange:
    """A range of record ids `person:1..100` (value-level, from ThingLit)."""

    __slots__ = ("tb", "rng")

    def __init__(self, tb: str, rng: Range):
        self.tb = tb
        self.rng = rng

    def __repr__(self):
        return f"{escape_ident(self.tb)}:{self.rng!r}"

    def __eq__(self, other):
        return (
            isinstance(other, ThingRange)
            and self.tb == other.tb
            and self.rng == other.rng
        )

    def __hash__(self):
        return hash((self.tb, self.rng))


class RangeLit(Expr):
    __slots__ = ("beg", "end", "beg_incl", "end_incl")

    def __init__(self, beg, end, beg_incl=True, end_incl=False):
        self.beg, self.end = beg, end
        self.beg_incl, self.end_incl = beg_incl, end_incl

    def compute(self, ctx):
        beg = self.beg.compute(ctx) if isinstance(self.beg, Expr) else self.beg
        end = self.end.compute(ctx) if isinstance(self.end, Expr) else self.end
        return Range(beg, end, self.beg_incl, self.end_incl)

    def __repr__(self):
        b = "" if self.beg is NONE else repr(self.beg)
        e = "" if self.end is NONE else repr(self.end)
        return f"{b}{'' if self.beg_incl else '>'}..{'=' if self.end_incl else ''}{e}"


class MockExpr(Expr):
    """`|person:1000|` / `|person:1..1000|` — generate test records."""

    __slots__ = ("tb", "count", "range")

    def __init__(self, tb: str, count: Optional[int], range_: Optional[Tuple[int, int]]):
        self.tb = tb
        self.count = count
        self.range = range_

    def compute(self, ctx):
        if self.range:
            return [Thing(self.tb, i) for i in range(self.range[0], self.range[1] + 1)]
        return [Thing(self.tb) for _ in range(self.count or 0)]

    def __repr__(self):
        if self.range:
            return f"|{self.tb}:{self.range[0]}..{self.range[1]}|"
        return f"|{self.tb}:{self.count}|"


class RegexLit(Expr):
    __slots__ = ("pattern", "compiled")

    def __init__(self, pattern: str):
        self.pattern = pattern
        try:
            self.compiled = _re.compile(pattern)
        except _re.error as e:
            from surrealdb_tpu.err import ParseError

            raise ParseError(f"invalid regex literal: {e}")

    def compute(self, ctx):
        return self.compiled

    def __repr__(self):
        return f"/{self.pattern}/"


class Param(Expr):
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def compute(self, ctx):
        return ctx.get_param(self.name)

    def __repr__(self):
        return f"${self.name}"


class TableExpr(Expr):
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def compute(self, ctx):
        return Table(self.name)

    def __repr__(self):
        return escape_ident(self.name)


class Constant(Expr):
    """math::pi and friends (reference core/src/sql/constant.rs)."""

    _VALUES = {
        "math::e": math.e,
        "math::frac_1_pi": 1 / math.pi,
        "math::frac_1_sqrt_2": 1 / math.sqrt(2),
        "math::frac_2_pi": 2 / math.pi,
        "math::frac_2_sqrt_pi": 2 / math.sqrt(math.pi),
        "math::frac_pi_2": math.pi / 2,
        "math::frac_pi_3": math.pi / 3,
        "math::frac_pi_4": math.pi / 4,
        "math::frac_pi_6": math.pi / 6,
        "math::frac_pi_8": math.pi / 8,
        "math::inf": math.inf,
        "math::neg_inf": -math.inf,
        "math::ln_10": math.log(10),
        "math::ln_2": math.log(2),
        "math::log10_2": math.log10(2),
        "math::log10_e": math.log10(math.e),
        "math::log2_10": math.log2(10),
        "math::log2_e": math.log2(math.e),
        "math::pi": math.pi,
        "math::sqrt_2": math.sqrt(2),
        "math::tau": math.tau,
        "math::nan": math.nan,
        "time::epoch": Datetime(0),
        "time::minimum": Datetime(-(2**62)),
        "time::maximum": Datetime(2**62),
        "duration::max": Duration(2**63 - 1),
    }

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def compute(self, ctx):
        return self._VALUES[self.name.lower()]

    def __repr__(self):
        return self.name


# ------------------------------------------------------------------ operators
class UnaryOp(Expr):
    __slots__ = ("op", "expr")

    def __init__(self, op: str, expr: Expr):
        self.op = op
        self.expr = expr

    def compute(self, ctx):
        v = self.expr.compute(ctx)
        if self.op == "-":
            if isinstance(v, bool) or not isinstance(v, (int, float, _dec.Decimal)):
                raise TypeError_(f"Can not negate {format_value(v)}")
            return -v
        if self.op == "+":
            return v
        if self.op in ("!", "NOT"):
            return not truthy(v)
        if self.op == "!!":
            return truthy(v)
        raise TypeError_(f"unknown unary operator {self.op}")

    def writeable(self):
        return self.expr.writeable()

    def __repr__(self):
        return f"{self.op}{self.expr!r}"


def _numeric(v, op: str):
    if isinstance(v, bool) or not isinstance(v, (int, float, _dec.Decimal)):
        raise TypeError_(
            f"Cannot perform arithmetic '{op}' on {format_value(v)}"
        )
    return v


def _num_pair(l, r, op: str):
    """Numeric operand pair with decimal promotion: mixing a decimal with a
    float promotes the float (reference Number arithmetic, sql/number.rs —
    decimal wins); int/Decimal interoperate natively."""
    ln, rn = _numeric(l, op), _numeric(r, op)
    if isinstance(ln, _dec.Decimal) and isinstance(rn, float):
        rn = _dec.Decimal(repr(rn))
    elif isinstance(rn, _dec.Decimal) and isinstance(ln, float):
        ln = _dec.Decimal(repr(ln))
    return ln, rn


def _sum2(l, r, op: str):
    ln, rn = _num_pair(l, r, op)
    return ln + rn


def _fuzzy_match(a: str, b: str) -> bool:
    """`~` operator: case/diacritic-insensitive containment."""
    return b.lower() in a.lower()


def _regex_match(val, rx) -> bool:
    if not isinstance(val, str):
        val = format_value(val)
    return rx.search(val) is not None


def _contains(container, item) -> bool:
    if isinstance(container, (list, tuple)):
        return any(value_eq(x, item) for x in container)
    if isinstance(container, str):
        return isinstance(item, str) and item in container
    if isinstance(container, dict):
        return isinstance(item, str) and item in container
    if isinstance(container, Range):
        return container.contains(item)
    if isinstance(container, Geometry):
        return _geo_contains(container, item)
    return False


def _geo_contains(poly: Geometry, item) -> bool:
    pt = None
    if isinstance(item, Geometry) and item.kind == "Point":
        pt = item.coords
    elif isinstance(item, (list, tuple)) and len(item) == 2:
        pt = item
    if pt is None or poly.kind != "Polygon":
        return False
    return _point_in_ring(pt, poly.coords[0]) and not any(
        _point_in_ring(pt, hole) for hole in poly.coords[1:]
    )


def _point_in_ring(pt, ring) -> bool:
    x, y = pt
    inside = False
    j = len(ring) - 1
    for i in range(len(ring)):
        xi, yi = ring[i][0], ring[i][1]
        xj, yj = ring[j][0], ring[j][1]
        if (yi > y) != (yj > y) and x < (xj - xi) * (y - yi) / (yj - yi) + xi:
            inside = not inside
        j = i
    return inside


class BinaryOp(Expr):
    __slots__ = ("op", "l", "r")

    def __init__(self, op: str, l: Expr, r: Expr):
        self.op = op
        self.l = l
        self.r = r

    def writeable(self):
        return self.l.writeable() or self.r.writeable()

    def compute(self, ctx):
        op = self.op
        # short-circuiting forms first
        if op in ("||", "OR"):
            l = self.l.compute(ctx)
            return l if truthy(l) else self.r.compute(ctx)
        if op in ("&&", "AND"):
            l = self.l.compute(ctx)
            return l if not truthy(l) else self.r.compute(ctx)
        if op == "??":
            l = self.l.compute(ctx)
            return self.r.compute(ctx) if is_nullish(l) else l
        if op == "?:":
            l = self.l.compute(ctx)
            return l if truthy(l) else self.r.compute(ctx)

        l = self.l.compute(ctx)
        r = self.r.compute(ctx)
        return apply_operator(op, l, r, ctx)

    def __repr__(self):
        return f"{self.l!r} {self.op} {self.r!r}"


def apply_operator(op: str, l, r, ctx=None):
    if op == "=":
        if isinstance(r, _re.Pattern):
            return _regex_match(l, r)
        return value_eq(l, r)
    if op in ("!=",):
        if isinstance(r, _re.Pattern):
            return not _regex_match(l, r)
        return not value_eq(l, r)
    if op == "==":
        return type_ordinal(l) == type_ordinal(r) and value_eq(l, r)
    if op == "?=":
        return isinstance(l, (list, tuple)) and any(value_eq(x, r) for x in l)
    if op == "*=":
        return isinstance(l, (list, tuple)) and all(value_eq(x, r) for x in l)
    if op == "~":
        if isinstance(r, _re.Pattern):
            return _regex_match(l, r)
        return isinstance(l, str) and isinstance(r, str) and _fuzzy_match(l, r)
    if op == "!~":
        return not apply_operator("~", l, r, ctx)
    if op == "?~":
        return isinstance(l, (list, tuple)) and any(
            apply_operator("~", x, r, ctx) for x in l
        )
    if op == "*~":
        return isinstance(l, (list, tuple)) and all(
            apply_operator("~", x, r, ctx) for x in l
        )
    if op == "<":
        return value_cmp(l, r) < 0
    if op == "<=":
        return value_cmp(l, r) <= 0
    if op == ">":
        return value_cmp(l, r) > 0
    if op == ">=":
        return value_cmp(l, r) >= 0
    if op == "+":
        if isinstance(l, str) and isinstance(r, str):
            return l + r
        if isinstance(l, (Datetime, Duration)) or isinstance(r, (Datetime, Duration)):
            try:
                return l + r
            except TypeError:
                raise TypeError_(
                    f"Cannot add {format_value(l)} and {format_value(r)}"
                )
        if isinstance(l, (list, tuple)) and isinstance(r, (list, tuple)):
            return list(l) + list(r)
        if isinstance(l, (list, tuple)):
            return list(l) + [r]
        return _sum2(l, r, op)
    if op == "-":
        if isinstance(l, (Datetime, Duration)) and isinstance(r, (Datetime, Duration)):
            try:
                return l - r
            except TypeError:
                raise TypeError_(
                    f"Cannot subtract {format_value(r)} from {format_value(l)}"
                )
        if isinstance(l, (list, tuple)):
            return [x for x in l if not value_eq(x, r)]
        ln, rn = _num_pair(l, r, op)
        return ln - rn
    if op in ("*", "×"):
        ln, rn = _num_pair(l, r, op)
        return ln * rn
    if op in ("/", "÷"):
        ln, rn = _num_pair(l, r, op)
        if rn == 0:
            if isinstance(ln, float) or isinstance(rn, float):
                return math.nan if ln == 0 else math.copysign(math.inf, ln)
            raise TypeError_("Cannot divide by zero")
        if isinstance(ln, int) and isinstance(rn, int):
            q = ln // rn
            return q if q * rn == ln else ln / rn
        return ln / rn
    if op == "%":
        ln, rn = _num_pair(l, r, op)
        if rn == 0:
            raise TypeError_("Cannot divide by zero")
        if isinstance(ln, _dec.Decimal) or isinstance(rn, _dec.Decimal):
            return _dec.Decimal(ln) % _dec.Decimal(rn)
        return math.fmod(ln, rn) if isinstance(ln, float) or isinstance(rn, float) else ln - rn * int(ln / rn)
    if op == "**":
        ln, rn = _num_pair(l, r, op)
        try:
            return ln**rn
        except _dec.InvalidOperation:
            raise TypeError_("Cannot raise to this power as a decimal")
    if op in ("IN", "INSIDE", "∈"):
        return _contains(r, l)
    if op in ("NOT IN", "NOTINSIDE", "∉"):
        return not _contains(r, l)
    if op in ("CONTAINS", "∋"):
        return _contains(l, r)
    if op in ("CONTAINSNOT", "∌"):
        return not _contains(l, r)
    if op in ("CONTAINSALL", "⊇"):
        return isinstance(r, (list, tuple)) and all(_contains(l, x) for x in r)
    if op in ("CONTAINSANY", "⊃"):
        return isinstance(r, (list, tuple)) and any(_contains(l, x) for x in r)
    if op in ("CONTAINSNONE", "⊅"):
        return isinstance(r, (list, tuple)) and not any(_contains(l, x) for x in r)
    if op in ("ALLINSIDE", "⊆"):
        return isinstance(l, (list, tuple)) and all(_contains(r, x) for x in l)
    if op in ("ANYINSIDE", "⊂"):
        return isinstance(l, (list, tuple)) and any(_contains(r, x) for x in l)
    if op in ("NONEINSIDE", "⊄"):
        return isinstance(l, (list, tuple)) and not any(_contains(r, x) for x in l)
    if op == "OUTSIDE":
        return not _contains(r, l)
    if op == "INTERSECTS":
        return _geo_intersects(l, r)
    raise TypeError_(f"unknown operator {op}")


def _geo_intersects(l, r) -> bool:
    if isinstance(l, Geometry) and isinstance(r, Geometry):
        if l.kind == "Point":
            return _geo_contains(r, l)
        if r.kind == "Point":
            return _geo_contains(l, r)
        if l.kind == "Polygon" and r.kind == "Polygon":
            return any(_point_in_ring(p, r.coords[0]) for p in l.coords[0]) or any(
                _point_in_ring(p, l.coords[0]) for p in r.coords[0]
            )
    return False


class MatchesOp(Expr):
    """`field @ref@ 'terms'` full-text matches operator
    (reference: sql/operator.rs:42)."""

    __slots__ = ("l", "r", "ref")

    def __init__(self, l: Expr, r: Expr, ref: Optional[int]):
        self.l = l
        self.r = r
        self.ref = ref

    def compute(self, ctx):
        exe = ctx.query_executor()
        if exe is not None and ctx.doc is not None:
            return exe.matches(ctx, ctx.doc, self)
        # fallback: naive containment over the raw text
        l = self.l.compute(ctx)
        r = self.r.compute(ctx)
        if isinstance(l, str) and isinstance(r, str):
            hay = l.lower().split()
            return all(t in hay for t in r.lower().split())
        return False

    def __repr__(self):
        at = f"@{self.ref}@" if self.ref is not None else "@@"
        return f"{self.l!r} {at} {self.r!r}"


class KnnOp(Expr):
    """`field <|k|> $vec`, `<|k,ef|>` (HNSW), `<|k,DIST|>` (brute/MTree)
    (reference: sql/operator.rs:63-65)."""

    __slots__ = ("l", "r", "k", "ef", "dist")

    def __init__(self, l: Expr, r: Expr, k: int, ef: Optional[int], dist: Optional[str]):
        self.l = l
        self.r = r
        self.k = k
        self.ef = ef
        self.dist = dist

    def compute(self, ctx):
        exe = ctx.query_executor()
        if exe is not None and ctx.doc is not None:
            return exe.knn(ctx, ctx.doc, self)
        return False

    def __repr__(self):
        if self.ef is not None:
            mid = f"{self.k},{self.ef}"
        elif self.dist is not None:
            mid = f"{self.k},{self.dist}"
        else:
            mid = f"{self.k}"
        return f"{self.l!r} <|{mid}|> {self.r!r}"


# ------------------------------------------------------------------ calls
class FunctionCall(Expr):
    __slots__ = ("name", "args")

    def __init__(self, name: str, args: List[Expr]):
        self.name = name
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu import fnc

        # count(->graph->chain) sums path counts on the mirror frontier
        # instead of materializing millions of expanded Things just to
        # len() them (the 3-hop north-star metric's hot path)
        if self.name == "count" and len(self.args) == 1:
            from surrealdb_tpu.sql.path import graph_chain_count

            n = graph_chain_count(ctx, self.args[0])
            if n is not None:
                return n
        args = [a.compute(ctx) for a in self.args]
        return fnc.run(ctx, self.name, args, exprs=self.args)

    def writeable(self):
        return any(a.writeable() for a in self.args)

    def __repr__(self):
        return f"{self.name}(" + ", ".join(repr(a) for a in self.args) + ")"


class ScriptCall(Expr):
    """`function(args) { js }` — embedded script block (reference:
    core/src/sql/function.rs:31 Function::Script; executed with `this` =
    current document and `arguments` = computed args, fnc/script/main.rs)."""

    __slots__ = ("src", "args")

    def __init__(self, src: str, args: List[Expr]):
        self.src = src
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu.fnc.script import run_script

        args = [a.compute(ctx) for a in self.args]
        doc = ctx.doc.current if ctx.doc is not None else None
        return run_script(ctx, self.src, args, doc)

    def writeable(self):
        return any(a.writeable() for a in self.args)

    def __repr__(self):
        return f"function({', '.join(repr(a) for a in self.args)}) {{{self.src}}}"


class CustomFunctionCall(Expr):
    """fn::name(args) — DEFINE FUNCTION lookup."""

    __slots__ = ("name", "args")

    def __init__(self, name: str, args: List[Expr]):
        self.name = name
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu.fnc.custom import run_custom

        args = [a.compute(ctx) for a in self.args]
        return run_custom(ctx, self.name, args)

    def writeable(self):
        return True

    def __repr__(self):
        return f"fn::{self.name}(" + ", ".join(repr(a) for a in self.args) + ")"


class ModelCall(Expr):
    """ml::name<version>(args) (reference: core/src/sql/model.rs:37)."""

    __slots__ = ("name", "version", "args")

    def __init__(self, name: str, version: str, args: List[Expr]):
        self.name = name
        self.version = version
        self.args = args

    def compute(self, ctx):
        # batched-SELECT override: the iterator pre-computes this call for
        # every scanned row in ONE device dispatch (dbs/iterator.py
        # _batched_projection) and parks the per-row result here
        ov = getattr(ctx.executor, "_ml_overrides", None)
        if ov is not None and id(self) in ov:
            return ov[id(self)]
        from surrealdb_tpu.ml.exec import run_model

        args = [a.compute(ctx) for a in self.args]
        return run_model(ctx, self.name, self.version, args)

    def __repr__(self):
        return (
            f"ml::{self.name}<{self.version}>("
            + ", ".join(repr(a) for a in self.args)
            + ")"
        )


class ClosureLit(Expr):
    __slots__ = ("params", "returns", "body")

    def __init__(self, params, returns, body):
        self.params = params
        self.returns = returns
        self.body = body

    def compute(self, ctx):
        return Closure(self.params, self.returns, self.body)

    def __repr__(self):
        ps = ", ".join(f"${p}" for p, _ in self.params)
        return f"|{ps}| {self.body!r}"


class ClosureCall(Expr):
    """Invoke a closure-valued expression: $fn(args) or <expr>(args)."""

    __slots__ = ("target", "args")

    def __init__(self, target: Expr, args: List[Expr]):
        self.target = target
        self.args = args

    def compute(self, ctx):
        from surrealdb_tpu.fnc.custom import run_closure

        f = self.target.compute(ctx)
        args = [a.compute(ctx) for a in self.args]
        return run_closure(ctx, f, args)

    def __repr__(self):
        return f"{self.target!r}(" + ", ".join(repr(a) for a in self.args) + ")"


# ------------------------------------------------------------------ structure
class Cast(Expr):
    __slots__ = ("kind", "expr")

    def __init__(self, kind: str, expr: Expr):
        self.kind = kind
        self.expr = expr

    def compute(self, ctx):
        from .kind import coerce_cast

        return coerce_cast(self.kind, self.expr.compute(ctx))

    def writeable(self):
        return self.expr.writeable()

    def __repr__(self):
        return f"<{self.kind}> {self.expr!r}"


class FutureLit(Expr):
    """`<future> { expr }` — lazily evaluated value."""

    __slots__ = ("expr",)

    def __init__(self, expr: Expr):
        self.expr = expr

    def compute(self, ctx):
        if ctx.opt_futures:
            return self.expr.compute(ctx)
        return self

    def __repr__(self):
        return f"<future> {{ {self.expr!r} }}"


class Subquery(Expr):
    __slots__ = ("stmt",)

    def __init__(self, stmt):
        self.stmt = stmt

    def compute(self, ctx):
        with ctx.descend() as c:
            return self.stmt.compute(c)

    def writeable(self):
        return self.stmt.writeable()

    def __repr__(self):
        return f"({self.stmt!r})"


class Block(Expr):
    """{ stmt; stmt; ... } — scoped statements, evaluates to last value."""

    __slots__ = ("stmts",)

    def __init__(self, stmts: List[Any]):
        self.stmts = stmts

    def compute(self, ctx):
        from surrealdb_tpu.err import ReturnError

        with ctx.child_scope() as c:
            out = NONE
            for s in self.stmts:
                try:
                    out = s.compute(c)
                except ReturnError as r:
                    return r.value
            return out

    def writeable(self):
        return any(s.writeable() for s in self.stmts)

    def __repr__(self):
        return "{ " + "; ".join(repr(s) for s in self.stmts) + " }"


def compute_or_flatten(e: Expr, ctx):
    v = e.compute(ctx)
    return v


# ------------------------------------------------------------------ walking
# Scope boundaries: nodes whose interior evaluates against a DIFFERENT
# document binding than the enclosing projection (so a walk looking for
# batchable work must not cross into them).
_SCOPE_BOUNDARIES = ("Subquery", "Block", "ClosureLit", "FutureLit")


def walk_exprs(node, visit, _depth: int = 0) -> None:
    """Generic pre-order walk over an AST fragment (exprs, idiom parts,
    field lists). `visit` is called for every surrealdb_tpu node; descent
    stops at subquery-like scope boundaries."""
    if node is None or _depth > 80:
        return
    if isinstance(node, (list, tuple)):
        for x in node:
            walk_exprs(x, visit, _depth + 1)
        return
    if isinstance(node, dict):
        for x in node.values():
            walk_exprs(x, visit, _depth + 1)
        return
    cls = type(node)
    if not cls.__module__.startswith("surrealdb_tpu"):
        return
    visit(node)
    if cls.__name__ in _SCOPE_BOUNDARIES:
        return
    seen = set()
    for klass in cls.__mro__:
        for slot in getattr(klass, "__slots__", ()) or ():
            if slot in seen:
                continue
            seen.add(slot)
            try:
                v = getattr(node, slot)
            except AttributeError:
                continue
            walk_exprs(v, visit, _depth + 1)
    for v in getattr(node, "__dict__", {}).values():
        walk_exprs(v, visit, _depth + 1)
