"""Background-task registry + watchdog (the flight recorder's task layer).

PRs 3-4 moved the engine's heaviest work into debounced background threads:
column-mirror rebuilds (idx/column_mirror.py), graph-CSR prewarm
(idx/graph_csr.py), IVF training (idx/knn.py), shape warming
(idx/knn.py / idx/ivf.py), changefeed GC (cf/gc.py). A wedged rebuild or a
surprise on-demand compile used to show up only as an unexplained latency
swing. This module makes every asynchronous engine activity a first-class,
attributable, exportable object (the Dapper posture: always on,
attribute everything):

- every job registers with a lifecycle `scheduled -> running -> done |
  failed | stalled`, carrying start/duration/retry/error fields and a
  parent trace link when a query triggered it;
- a single lazy watchdog thread flips tasks to `stalled` once they run
  past a per-kind deadline and bumps the `bg_task_stalled` counter — a
  wedged rebuild is now a metric + a registry entry, not a mystery;
- threads get deterministic names (`bg:<kind>:<target>`) so stack dumps
  and the txn leak detector's reports are attributable;
- `shutdown(owner)` joins an owner's pending tasks on `Datastore.close()`
  (no daemon-thread leaks under pytest), and parks the watchdog once the
  whole registry is idle.

The registry is process-global (like telemetry/tracing): tasks carry an
`owner` token (id of the owning Datastore) so per-datastore teardown only
joins its own work. Finished tasks are kept in a bounded ring
(cnf.BG_REGISTRY_CAP) for the debug bundle and bench overlap accounting.

Knobs: SURREAL_BG_WATCHDOG, SURREAL_BG_WATCHDOG_INTERVAL,
SURREAL_BG_WATCHDOG_DEADLINE (per-task override at register time).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

from surrealdb_tpu.utils import locks as _locks

# default per-kind watchdog deadlines (seconds) — how long a RUNNING task
# of this kind may take before it is presumed wedged. Callers may override
# per task; the global default (cnf.BG_WATCHDOG_DEADLINE_SECS) covers the
# rest. IVF training and graph prewarm legitimately run minutes at scale.
KIND_DEADLINES: Dict[str, float] = {
    "column_mirror": 120.0,
    "graph_prewarm": 600.0,
    "ivf_train": 900.0,
    "shape_warm": 300.0,
    "changefeed_gc": 60.0,
    "index_build": 900.0,
    "cluster_read_repair": 60.0,
    "cluster_tombstone_gc": 120.0,
    "advisor": 60.0,
}

_STATES = ("scheduled", "running", "done", "failed", "stalled")


class Task:
    """One background job's registry record."""

    __slots__ = (
        "id", "kind", "target", "state", "owner", "trace_id", "deadline_s",
        "scheduled_ts", "start_ts", "end_ts", "duration_s", "error",
        "retries", "stalled", "thread", "service", "stack", "tenant",
    )

    def __init__(self, tid, kind, target, owner, trace_id, deadline_s):
        self.id = tid
        self.kind = kind
        self.target = target
        self.state = "scheduled"
        self.owner = owner
        self.trace_id = trace_id
        self.deadline_s = deadline_s
        self.scheduled_ts = time.time()
        self.start_ts: Optional[float] = None
        self.end_ts: Optional[float] = None
        self.duration_s: Optional[float] = None
        self.error: Optional[str] = None
        self.retries = 0
        self.stalled = False  # sticky: set once the watchdog flagged it
        self.thread: Optional[threading.Thread] = None
        # long-lived worker loop (WS pump/pool, SDK reader, server tick):
        # lives as long as its connection, exempt from deadlines and joins
        self.service = False
        # stack sample captured by the watchdog when it flagged the stall
        self.stack: Optional[List[str]] = None
        # the (ns, db) whose statement ARMED this task — the same parent
        # link trace_id rides; the task's run time is charged to it
        self.tenant: Optional[tuple] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "kind": self.kind,
            "target": self.target,
            "state": self.state,
            "trace_id": self.trace_id,
            "scheduled_ts": round(self.scheduled_ts, 3),
            "start_ts": round(self.start_ts, 3) if self.start_ts else None,
            "end_ts": round(self.end_ts, 3) if self.end_ts else None,
            "duration_s": round(self.duration_s, 4)
            if self.duration_s is not None
            else None,
            "error": self.error,
            "retries": self.retries,
            "stalled": self.stalled,
            "service": self.service,
            "stack": self.stack,
            "thread": self.thread.name if self.thread is not None else None,
            "tenant": list(self.tenant) if self.tenant is not None else None,
        }


_lock = _locks.Lock("bg.registry")
_tasks: Dict[int, Task] = {}  # id -> Task (bounded: finished tasks trimmed)
_next_id = 0
_watchdog: Optional[threading.Thread] = None
_watchdog_stop = threading.Event()


def _trim_locked() -> None:
    """Drop the oldest FINISHED tasks past the registry cap (caller holds
    _lock). Live (scheduled/running/stalled-running) tasks are never
    evicted — the watchdog and teardown must always see them."""
    from surrealdb_tpu import cnf

    _locks.assert_held(_lock, "bg._tasks")
    cap = max(cnf.BG_REGISTRY_CAP, 16)
    if len(_tasks) <= cap:
        return
    for tid in sorted(_tasks):
        if len(_tasks) <= cap:
            break
        if _tasks[tid].state in ("done", "failed"):
            del _tasks[tid]


# ------------------------------------------------------------------ lifecycle
def register(
    kind: str,
    target: str = "",
    owner: Optional[int] = None,
    deadline: Optional[float] = None,
    trace_id: Any = "auto",
) -> int:
    """Create a `scheduled` task record; returns its id. `trace_id`
    defaults to the active request's trace (the parent link that turns
    "a rebuild ran" into "THIS query's commit armed it")."""
    global _next_id
    from surrealdb_tpu import cnf

    if trace_id == "auto":
        from surrealdb_tpu import tracing

        trace_id = tracing.current_trace_id()
    # the ARMING statement's tenant (registration happens on its thread /
    # context, exactly like the trace link above) — run() charges the
    # task's duration to it, however much later the body executes
    from surrealdb_tpu import accounting

    tenant = accounting.current_tenant()
    if deadline is None:
        deadline = KIND_DEADLINES.get(kind, cnf.BG_WATCHDOG_DEADLINE_SECS)
    with _lock:
        _next_id += 1
        tid = _next_id
        t = _tasks[tid] = Task(tid, kind, target, owner, trace_id, deadline)
        t.tenant = tenant
        _trim_locked()
    _ensure_watchdog()
    return tid


def touch(task_id: int) -> None:
    """Refresh a scheduled task's timestamp (debounce deadline advanced)."""
    with _lock:
        t = _tasks.get(task_id)
        if t is not None and t.state == "scheduled":
            t.scheduled_ts = time.time()


def retried(task_id: int) -> None:
    with _lock:
        t = _tasks.get(task_id)
        if t is not None:
            t.retries += 1


def forget(task_id: int) -> None:
    """Drop a FINISHED task's record entirely. For high-frequency periodic
    jobs (the 10s changefeed-GC tick) whose uneventful sweeps would
    otherwise flood the bounded finished ring and evict the diagnostically
    useful records; the task was still watchdog-covered while running."""
    with _lock:
        t = _tasks.get(task_id)
        if t is not None and t.state in ("done", "failed"):
            del _tasks[task_id]


def cancel(task_id: int, reason: str = "cancelled") -> None:
    """Resolve a scheduled task that will never run (timer cancelled)."""
    with _lock:
        t = _tasks.get(task_id)
        if t is not None and t.state == "scheduled":
            t.state = "done"
            t.error = reason
            t.end_ts = time.time()
            t.duration_s = 0.0


@contextmanager
def run(task_id: int, rename_thread: bool = True):
    """Execute a task's body: flips it to `running` (naming the current
    thread `bg:<kind>:<target>`), then to `done`/`failed`. A task the
    watchdog flagged keeps its sticky `stalled` field either way."""
    from surrealdb_tpu import telemetry

    # a prior Datastore.close() may have parked the watchdog while this
    # task was still timer-armed ('scheduled'); its actual run must be
    # stall-covered, so re-ensure the watchdog here, not only at register
    _ensure_watchdog()
    cur = threading.current_thread()
    with _lock:
        t = _tasks.get(task_id)
        if t is not None:
            t.state = "running"
            t.start_ts = time.time()
            t.thread = cur
            if rename_thread:
                cur.name = f"bg:{t.kind}:{t.target}" if t.target else f"bg:{t.kind}"
    err: Optional[BaseException] = None
    try:
        if t is not None:
            # chaos hook: every background-task body is an injection site
            # (`bg.<kind>` family) — an injected error/panic resolves the
            # record as failed exactly like a real body crash would
            from surrealdb_tpu import faults

            faults.fire(f"bg.{t.kind}")
        yield t
    except BaseException as e:
        err = e
        raise
    finally:
        now = time.time()
        with _lock:
            t = _tasks.get(task_id)
            if t is not None:
                t.end_ts = now
                t.duration_s = now - (t.start_ts or now)
                t.state = "failed" if err is not None else "done"
                if err is not None:
                    t.error = f"{type(err).__name__}: {err}"[:300]
                if t.stalled:
                    # it finished after all — count the recovery so a
                    # stalled counter spike can be read against it
                    telemetry.inc("bg_task_recovered", kind=t.kind)
                    from surrealdb_tpu import events

                    events.emit(
                        "bg.recovered", trace_id=t.trace_id,
                        task=t.kind, target=t.target, task_id=t.id,
                        duration_s=round(t.duration_s, 3),
                    )
                kind = t.kind
            else:
                kind = None
        if kind is not None:
            telemetry.inc(
                "bg_tasks", kind=kind, state="failed" if err else "done"
            )
            if t.duration_s is not None:
                telemetry.observe("bg_task", t.duration_s, kind=kind)
                # tenant accounting (AFTER _lock release — the store lock
                # must never nest inside bg.registry): the task's run time
                # lands on whoever armed it, mirrored into the global
                # counter the conservation check reads
                from surrealdb_tpu import accounting

                tenant = t.tenant or (None, None)
                telemetry.inc("bg_task_seconds", by=t.duration_s)
                accounting.charge(
                    tenant[0], tenant[1], bg_kind=kind,
                    bg_s=t.duration_s, bg_tasks=1,
                )


def spawn(
    kind: str,
    target: str,
    fn: Callable,
    *args,
    owner: Optional[int] = None,
    deadline: Optional[float] = None,
) -> int:
    """Register + start a named daemon thread running `fn(*args)` under the
    task lifecycle. Returns the task id (thread joinable via shutdown)."""
    tid = register(kind, target, owner=owner, deadline=deadline)

    def body():
        try:
            with run(tid):
                fn(*args)
        except Exception:
            # best-effort background work; run() already resolved the task
            # record as failed with the error text — count the escape so a
            # spike of dying spawn bodies is a metric, not a silent pass
            from surrealdb_tpu import telemetry

            telemetry.inc("bg_spawn_body_errors", kind=kind)

    t = threading.Thread(
        target=body,
        name=f"bg:{kind}:{target}" if target else f"bg:{kind}",
        daemon=True,
    )
    with _lock:
        rec = _tasks.get(tid)
        if rec is not None:
            rec.thread = t
    t.start()
    return tid


def spawn_service(
    kind: str,
    target: str,
    fn: Callable,
    *args,
    owner: Optional[int] = None,
    restart: bool = False,
) -> threading.Thread:
    """Register + start a long-lived WORKER LOOP (WS notification pump,
    WS request-pool worker, SDK reader, server tick loop): a daemon thread
    that lives as long as its connection/server, so it is exempt from the
    per-kind stall deadline and from shutdown() joins — its registry entry
    exists for ATTRIBUTION (deterministic `bg:<kind>:<target>` thread name,
    flight-recorder visibility, stack-dump identification). The entry
    flips to done/failed when the loop exits. Returns the Thread (callers
    that join on their own teardown need it).

    `restart=True` supervises the loop: a body that dies on an UNCAUGHT
    exception (including a panic-class BaseException) is re-run on the
    same thread after an exponential backoff (cnf.BG_SERVICE_BACKOFF_*,
    capped; reset after a healthy run) with `bg_service_restarts{kind}`
    counting each revival — a crashed pump degrades to a hiccup instead of
    dying silently. A NORMAL return (connection closed, stop flag) always
    ends the loop; supervisable services must encode shutdown as a return,
    not an exception."""
    tid = register(kind, target, owner=owner, deadline=float("inf"))
    with _lock:
        rec = _tasks.get(tid)
        if rec is not None:
            rec.service = True

    def body():
        from surrealdb_tpu import cnf, telemetry

        backoff = max(cnf.BG_SERVICE_BACKOFF_BASE_SECS, 0.01)
        while True:
            started = time.monotonic()
            try:
                with run(tid):
                    fn(*args)
                return  # normal exit: the service is done for good
            except BaseException:
                # the registry record carries the error either way
                if not restart:
                    return
                with _lock:
                    rec = _tasks.get(tid)
                    if rec is not None:
                        rec.retries += 1
                        err = rec.error
                    else:
                        err = None
                telemetry.inc("bg_service_restarts", kind=kind)
                from surrealdb_tpu import events

                events.emit(
                    "bg.service_restart", task=kind, target=target,
                    **({"error": err} if err else {}),
                )
                if time.monotonic() - started >= max(
                    cnf.BG_SERVICE_HEALTHY_RESET_SECS, 1.0
                ):
                    backoff = max(cnf.BG_SERVICE_BACKOFF_BASE_SECS, 0.01)
                time.sleep(min(backoff, max(cnf.BG_SERVICE_BACKOFF_MAX_SECS, 0.01)))
                backoff = min(
                    backoff * 2, max(cnf.BG_SERVICE_BACKOFF_MAX_SECS, 0.01)
                )

    t = threading.Thread(
        target=body,
        name=f"bg:{kind}:{target}" if target else f"bg:{kind}",
        daemon=True,
    )
    with _lock:
        rec = _tasks.get(tid)
        if rec is not None:
            rec.thread = t
    t.start()
    return t


def start_thread(task_id: int, fn: Callable, *args) -> threading.Thread:
    """Start the daemon thread for an ALREADY-REGISTERED task whose body
    enters `bg.run(task_id)` itself (the IVF-train / index-build pattern:
    registration happens under the caller's lock, the heavy body later).
    Centralizes raw thread creation in this module (graftlint GL001)."""
    with _lock:
        rec = _tasks.get(task_id)
        kind = rec.kind if rec is not None else "task"
        target = rec.target if rec is not None else ""
    t = threading.Thread(
        target=fn,
        args=args,
        name=f"bg:{kind}:{target}" if target else f"bg:{kind}",
        daemon=True,
    )
    with _lock:
        rec = _tasks.get(task_id)
        if rec is not None:
            rec.thread = t
    t.start()
    return t


def timer(
    delay: float, fn: Callable, *args, task_id: Optional[int] = None,
    name: Optional[str] = None, start: bool = True,
) -> threading.Timer:
    """Create a named daemon Timer attributed to a registered task (the
    debounced column-mirror / graph-prewarm arm sites). The caller keeps
    the Timer for cancel(); the registry keeps the attribution. Pass
    `start=False` when the callback must learn its own Timer object first
    (the self-identifying debounce pattern) — then call .start() yourself."""
    t = threading.Timer(delay, fn, args=args)
    t.daemon = True
    if task_id is not None:
        with _lock:
            rec = _tasks.get(task_id)
            if rec is not None:
                rec.thread = t
                if name is None:
                    name = (
                        f"bg:{rec.kind}:{rec.target}"
                        if rec.target
                        else f"bg:{rec.kind}"
                    )
    if name:
        t.name = name
    if start:
        t.start()
    return t


# ------------------------------------------------------------------ watchdog
def _ensure_watchdog() -> None:
    global _watchdog
    from surrealdb_tpu import cnf

    if not cnf.BG_WATCHDOG:
        return
    with _lock:
        if _watchdog is not None and _watchdog.is_alive():
            return
        _watchdog_stop.clear()
        _watchdog = threading.Thread(
            target=_watchdog_loop, name="bg:watchdog", daemon=True
        )
        _watchdog.start()


def _watchdog_loop() -> None:
    from surrealdb_tpu import cnf, telemetry

    while not _watchdog_stop.wait(max(cnf.BG_WATCHDOG_INTERVAL_SECS, 0.05)):
        now = time.time()
        flagged: List[Task] = []
        with _lock:
            for t in _tasks.values():
                if (
                    t.state == "running"
                    and not t.stalled
                    and t.start_ts is not None
                    and now - t.start_ts > t.deadline_s
                ):
                    t.state = "stalled"
                    t.stalled = True
                    flagged.append(t)
        for t in flagged:
            # counter first: observers poll state->counter in lockstep and
            # must not see a stalled task without its metric
            telemetry.inc("bg_task_stalled", kind=t.kind)
            from surrealdb_tpu import events

            # the watchdog runs outside any request — cite the task's own
            # arming trace so the timeline entry still joins a statement
            events.emit(
                "bg.stall", trace_id=t.trace_id,
                task=t.kind, target=t.target, task_id=t.id,
            )
        if flagged:
            # sample the wedged threads' stacks (sys._current_frames — the
            # faulthandler view, but attributable per task) so the bundle's
            # task-registry section says WHERE a stalled rebuild is stuck,
            # not just that it is
            stacks = _sample_stacks([t.thread for t in flagged])
            with _lock:
                for t in flagged:
                    if t.thread is not None and t.thread.ident in stacks:
                        t.stack = stacks[t.thread.ident]


def _sample_stacks(threads) -> Dict[int, List[str]]:
    """{thread ident: formatted stack tail} for live threads, via
    sys._current_frames(). Best-effort: a thread that exits between the
    flag and the sample simply yields no entry."""
    import sys
    import traceback

    idents = {t.ident for t in threads if t is not None and t.ident is not None}
    out: Dict[int, List[str]] = {}
    if not idents:
        return out
    try:
        frames = sys._current_frames()  # noqa: SLF001 — the documented API
    except Exception:  # noqa: BLE001
        return out
    for ident, frame in frames.items():
        if ident in idents:
            out[ident] = [
                ln.rstrip()
                for ln in traceback.format_stack(frame, limit=12)
            ][-12:]
    return out


def watchdog_alive() -> bool:
    with _lock:
        return _watchdog is not None and _watchdog.is_alive()


# ------------------------------------------------------------------ teardown
def shutdown(owner: Optional[int] = None, timeout: float = 10.0) -> bool:
    """Join the owner's pending tasks (all owners when None); then, if the
    registry is globally idle, stop + join the watchdog. Returns True when
    everything joined inside the timeout. Called by Datastore.close()."""
    global _watchdog
    deadline = time.monotonic() + timeout
    while True:
        with _lock:
            # services (WS pumps/pools, SDK readers) live as long as their
            # CONNECTION, not the datastore — they are never joined here;
            # their run() lifecycle resolves them when the loop exits
            pending = [
                t
                for t in _tasks.values()
                if t.state in ("running", "stalled")
                and not t.service
                and (owner is None or t.owner == owner)
            ]
        if not pending:
            break
        for t in pending:
            th = t.thread
            if th is not None and th.is_alive() and th is not threading.current_thread():
                # join in SHORT increments and re-check task state: a task
                # running on a persistent thread (changefeed GC on the
                # server tick loop) finishes in milliseconds while its
                # thread never exits — waiting on thread liveness for the
                # full deadline would stall close() for nothing
                th.join(min(0.1, max(deadline - time.monotonic(), 0.05)))
        if time.monotonic() >= deadline:
            break
    with _lock:
        # owner's never-ran scheduled tasks resolve as cancelled
        for t in _tasks.values():
            if t.state == "scheduled" and (owner is None or t.owner == owner):
                t.state = "done"
                t.error = "cancelled: datastore closed"
                t.end_ts = time.time()
                t.duration_s = 0.0
        idle = not any(
            t.state in ("running", "stalled") and not t.service
            for t in _tasks.values()
        )
        wd = _watchdog if idle else None
        if idle:
            _watchdog = None
    joined = True
    if wd is not None:
        _watchdog_stop.set()
        if wd is not threading.current_thread():
            wd.join(max(deadline - time.monotonic(), 0.1))
            joined = not wd.is_alive()
    with _lock:
        still = [
            t
            for t in _tasks.values()
            if t.state in ("running", "stalled")
            and not t.service
            and (owner is None or t.owner == owner)
        ]
    return joined and not still


def wait_idle(timeout: float = 30.0, owner: Optional[int] = None) -> bool:
    """Block until no scheduled/running task (of `owner`, or any) remains —
    test/bench determinism helper, never used on the query path."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with _lock:
            # 'stalled' is still EXECUTING (the watchdog only re-labeled
            # it) — reporting idle while a flagged rebuild keeps mutating
            # mirrors would race exactly the slow tasks this helper gates
            busy = any(
                t.state in ("scheduled", "running", "stalled")
                and not t.service  # worker loops never go idle by design
                and (owner is None or t.owner == owner)
                for t in _tasks.values()
            )
        if not busy:
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------------ views
def get(task_id: int) -> Optional[dict]:
    with _lock:
        t = _tasks.get(task_id)
        return t.to_dict() if t is not None else None


def snapshot() -> dict:
    """Registry state for the debug bundle: live tasks in full, finished
    ones newest-first, plus per-kind/state counts."""
    with _lock:
        tasks = [t.to_dict() for t in _tasks.values()]
    live = [t for t in tasks if t["state"] in ("scheduled", "running", "stalled")]
    recent = sorted(
        (t for t in tasks if t["state"] in ("done", "failed")),
        key=lambda t: t["end_ts"] or 0,
        reverse=True,
    )
    counts: Dict[str, int] = {}
    for t in tasks:
        key = f"{t['kind']}:{t['state']}"
        counts[key] = counts.get(key, 0) + 1
    return {
        "live": live,
        "recent": recent[:100],
        "counts": counts,
        "stalled_total": sum(1 for t in tasks if t["stalled"]),
        "watchdog_alive": _watchdog is not None and _watchdog.is_alive(),
    }


def window(t0: float, t1: Optional[float] = None) -> List[dict]:
    """Tasks whose RUN overlapped [t0, t1] wall-clock (t1 = now): the
    bench's structural overlap accounting — which background work ran
    inside a measurement window, and for how long."""
    if t1 is None:
        t1 = time.time()
    out = []
    with _lock:
        tasks = [t.to_dict() for t in _tasks.values()]
    for t in tasks:
        start = t["start_ts"]
        if start is None:
            continue
        end = t["end_ts"] if t["end_ts"] is not None else t1
        if start < t1 and end > t0:
            t["overlap_s"] = round(min(end, t1) - max(start, t0), 4)
            out.append(t)
    return out


def export_gauges() -> None:
    """Refresh bg_tasks_live{kind,state} gauges (called by the /metrics
    scrape path right before rendering)."""
    from surrealdb_tpu import telemetry

    with _lock:
        live: Dict[tuple, int] = {}
        for t in _tasks.values():
            if t.state in ("scheduled", "running", "stalled"):
                live[(t.kind, t.state)] = live.get((t.kind, t.state), 0) + 1
    seen = set()
    for (kind, state), n in live.items():
        telemetry.gauge_set("bg_tasks_live", n, kind=kind, state=state)
        seen.add((kind, state))
    # zero out series whose tasks all finished since the last scrape
    for lbls in telemetry.gauges_matching("bg_tasks_live"):
        key = (dict(lbls).get("kind"), dict(lbls).get("state"))
        if key not in seen:
            telemetry.gauge_set("bg_tasks_live", 0, kind=key[0], state=key[1])


def reset() -> None:
    """Drop every record (tests). Does not touch running threads."""
    with _lock:
        _tasks.clear()
