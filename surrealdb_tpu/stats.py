"""Workload statistics plane: statement fingerprints + per-shape stats.

The pg_stat_statements analog for an engine whose hot paths are jitted
kernels. Every executed statement is normalized at the ingress choke
points (dbs/executor.py for local execution, cluster/executor.py for
coordinated statements) into a literal-and-parameter-erased FINGERPRINT,
and cumulative per-fingerprint statistics accumulate in a bounded LRU
store:

- calls / errors / slow count, a fixed log-bucket latency histogram
  (telemetry.DURATION_BUCKETS, so p50/p99 are derivable per shape),
  rows in/out;
- the **plan-mix vector**: how many executions took each plan decision
  (columnar-pipeline vs columnar-scan vs index vs knn-<strategy> vs row,
  plus scatter/degraded/agg-pushdown in cluster mode and dispatch
  split/retry counts) — pulled from the existing plan-note machinery
  (`telemetry.note_plan`), NOT re-derived;
- **plan flips**: when a fingerprint's primary scan decision changes
  between consecutive executions (columnar-pipeline one call, row the
  next — the signature of a mirror decline or a cluster pushdown
  stand-down), the flip is counted, logged into a bounded per-entry
  flip ring, and emitted as a `stats.plan_flip` event joined to the
  statement's trace. This is the regression signal EXPLAIN cannot show,
  because nobody re-ran EXPLAIN after the plan silently changed.

Fingerprinting reuses the SurrealQL lexer: literals (NUMBER / STRING /
DURATION / DATETIME / UUID / BYTES / REGEX / SCRIPT) erase to `?`,
parameters to `$?`, comments and whitespace vanish with tokenization,
and literal-list runs collapse (`[?, ?, ?]` -> `[?..]`) so batch size
does not mint new shapes. Identifiers are kept verbatim — `person` and
`Person` are different tables, and shape-distinct statements must never
collide. The mapping is memoized (statement TEXT -> fingerprint), so the
steady-state cost per executed statement is one dict hit.

GL012 (scripts/graftlint): recording MUST go through `record()` — no
call site reaches into the private store, so the lock discipline and the
flip detection cannot be bypassed by an ad-hoc writer.

Surfaces: `GET /statements` (system-gated; `?cluster=1` federates
node-tagged per-member stores through cluster/federation.py),
`INFO FOR ROOT` (`system.statements`), debug-bundle section 12
(bundle.py), per-config embeds in bench artifacts (schema /12) and
`scripts/bench_diff.py --statements` regression naming.
"""

from __future__ import annotations

import functools
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from surrealdb_tpu.utils import locks as _locks

# token kinds that erase to `?` (value-carrying literals)
_LITERAL_KINDS = frozenset(
    {"NUMBER", "STRING", "DURATION", "DATETIME", "UUID", "BYTES", "REGEX",
     "SCRIPT"}
)
# collapse literal-list runs: `? , ?` repeats fold to one `?..` so
# `IN [1,2,3]` and `IN [4,5]` are the same statement shape; a bracketed
# single literal folds too (`IN [4]` is the same shape at length 1)
_LIST_RUN = re.compile(r"(\?|\$\?)( , (\?|\$\?))+")
_LIST_ONE = re.compile(r"\[ \? \]")

# SurrealQL keywords are case-insensitive (the parser matches IDENTs
# contextually), so keyword-cased variants of one statement must collapse.
# Identifiers that HAPPEN to spell a keyword fold too — grammatically they
# can't occupy the same token position as the keyword, so no two
# shape-distinct statements collide through this fold.
_KEYWORDS = frozenset(
    """
    select create update upsert delete insert relate define remove info
    use let begin commit cancel return if else then end for in from where
    group by order asc desc collate numeric limit start fetch timeout
    parallel explain analyze full set unset content merge patch replace
    values on duplicate key only with noindex index split at version
    and or not is contains containsall containsany containsnone inside
    notinside outside intersects knn live kill show changes since table
    database namespace ns db field type schemafull schemaless permissions
    when event function param analyzer access user password passhash
    roles token relation into ignore after before diff wait concurrently
    unique search mtree hnsw dimension dist efc bm25 highlights as true
    false null none break continue throw sleep option value flexible
    readonly default assert comment drop changefeed out what
    """.split()
)
# fallback normalizer pieces for text the lexer rejects (fingerprinting
# must never fail a statement that somehow reached execution)
_FB_STRING = re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_FB_NUMBER = re.compile(r"\b\d[\d_]*(?:\.\d+)?(?:[eE][+-]?\d+)?\b")
_FB_PARAM = re.compile(r"\$\w+")
_FB_WS = re.compile(r"\s+")

# plan-mix decision priority, most-specific first: an execution's PRIMARY
# decision (the flip detector's unit) is the first of these present in its
# mix. `knn` entries rank by prefix; `row` is the absence of any note.
_PRIMARY_ORDER = ("columnar-pipeline", "columnar-scan", "agg-pushdown",
                  "index", "knn", "row")


def _digest(text: str) -> str:
    import hashlib

    return hashlib.blake2b(text.encode(), digest_size=8).hexdigest()


@functools.lru_cache(maxsize=4096)
def fingerprint(text: str) -> Tuple[str, str]:
    """(fingerprint id, normalized text) of one statement's source. The
    id is a 16-hex blake2b of the normalized form; the normalized form is
    the human-readable shape the store keeps as its sample."""
    normalized = _normalize(text)
    return _digest(normalized), normalized


def _normalize(text: str) -> str:
    from surrealdb_tpu.err import ParseError
    from surrealdb_tpu.syn.lexer import Lexer

    try:
        tokens = Lexer(text).lex()
    except (ParseError, RecursionError):
        # unlexable text (a statement that reached execution some other
        # way): a regex-light erasure keeps the fingerprint total
        t = _FB_STRING.sub("?", text)
        t = _FB_PARAM.sub("$?", t)
        t = _FB_NUMBER.sub("?", t)
        return _FB_WS.sub(" ", t).strip()
    parts: List[str] = []
    for t in tokens:
        if t.kind == "EOF":
            break
        if t.kind in _LITERAL_KINDS:
            parts.append("?")
        elif t.kind == "PARAM":
            parts.append("$?")
        elif t.kind == "OP":
            parts.append(str(t.value))
        else:
            # IDENT: keywords fold to upper case (SurrealQL keywords are
            # case-insensitive); real identifiers keep their case —
            # `person` and `Person` are different tables
            v = str(t.value)
            parts.append(v.upper() if v.lower() in _KEYWORDS else v)
    out = _LIST_RUN.sub("?..", " ".join(parts))
    return _LIST_ONE.sub("[ ?.. ]", out)


# ------------------------------------------------------------------ store
class _Entry:
    """One fingerprint's cumulative statistics (mutated under _lock)."""

    __slots__ = (
        "fp", "text", "kind", "calls", "errors", "slow", "dur_sum",
        "dur_max", "buckets", "rows_out", "rows_in", "plan_mix",
        "dispatch_splits", "dispatch_retries", "last_primary", "flips",
        "flip_log", "first_ts", "last_ts",
        "cost_chosen", "cost_declined", "cost_margin", "cost_notes",
    )

    def __init__(self, fp: str, text: str, kind: str):
        from surrealdb_tpu import telemetry

        self.fp = fp
        self.text = text
        self.kind = kind
        self.calls = 0
        self.errors = 0
        self.slow = 0
        self.dur_sum = 0.0
        self.dur_max = 0.0
        self.buckets = [0] * (len(telemetry.DURATION_BUCKETS) + 1)
        self.rows_out = 0
        self.rows_in = 0
        self.plan_mix: Dict[str, int] = {}
        self.dispatch_splits = 0
        self.dispatch_retries = 0
        self.last_primary: Optional[str] = None
        # planner cost-hook accumulators (choose_strategy's est_cost
        # note): chosen AND declined modeled costs in row-visit units —
        # the break-even margin the advisor's index math consumes
        self.cost_chosen = 0.0
        self.cost_declined = 0.0
        self.cost_margin = 0.0
        self.cost_notes = 0
        self.flips = 0
        self.flip_log: List[dict] = []  # bounded: newest _FLIP_LOG_CAP
        self.first_ts = time.time()
        self.last_ts = self.first_ts

    def quantile(self, q: float) -> Optional[float]:
        """Approximate latency quantile (seconds) off the fixed buckets:
        the upper bound of the bucket the q-th call falls in (the +Inf
        overflow reports the observed max)."""
        from surrealdb_tpu import telemetry

        if not self.calls:
            return None
        want = max(int(self.calls * q), 1)
        cum = 0
        for i, n in enumerate(self.buckets):
            cum += n
            if cum >= want:
                if i < len(telemetry.DURATION_BUCKETS):
                    return telemetry.DURATION_BUCKETS[i]
                return self.dur_max
        return self.dur_max

    def to_dict(self) -> dict:
        return {
            "fingerprint": self.fp,
            "sql": self.text,
            "kind": self.kind,
            "calls": self.calls,
            "errors": self.errors,
            "slow": self.slow,
            "total_s": round(self.dur_sum, 6),
            "mean_ms": round(self.dur_sum / self.calls * 1e3, 3)
            if self.calls
            else None,
            "max_ms": round(self.dur_max * 1e3, 3),
            "p50_ms": _ms(self.quantile(0.50)),
            "p99_ms": _ms(self.quantile(0.99)),
            "rows_out": self.rows_out,
            "rows_in": self.rows_in,
            "plan_mix": dict(self.plan_mix),
            "cost": {
                "unit": "row-visits",
                "chosen": round(self.cost_chosen, 2),
                "declined": round(self.cost_declined, 2),
                "margin": round(self.cost_margin, 2),
                "notes": self.cost_notes,
                "margin_per_call": round(self.cost_margin / self.calls, 3)
                if self.calls
                else None,
            }
            if self.cost_notes
            else None,
            "primary": self.last_primary,
            "plan_flips": self.flips,
            "flip_log": list(self.flip_log),
            "dispatch": {
                "splits": self.dispatch_splits,
                "retries": self.dispatch_retries,
            },
            "first_ts": round(self.first_ts, 3),
            "last_ts": round(self.last_ts, 3),
        }


def _ms(seconds: Optional[float]) -> Optional[float]:
    return round(seconds * 1e3, 3) if seconds is not None else None


_FLIP_LOG_CAP = 8

_lock = _locks.Lock("stats.store")
_store: "OrderedDict[str, _Entry]" = OrderedDict()  # fp -> entry, LRU order
_evicted = 0

# thread ident -> fingerprint of the statement EXECUTING on that thread —
# the profiler's attribution table (profiler.py samples other threads, so
# a contextvar cannot carry this across; GIL-atomic dict ops, no lock)
_active_by_thread: Dict[int, str] = {}


def activate(fp: str) -> Tuple[int, Optional[str]]:
    """Mark `fp` as the statement executing on the CURRENT thread (the
    profiler attributes wall-clock samples through this). Returns a token
    for deactivate(); nested activations restore the outer statement."""
    ident = threading.get_ident()
    prev = _active_by_thread.get(ident)
    _active_by_thread[ident] = fp
    return (ident, prev)


def deactivate(token: Tuple[int, Optional[str]]) -> None:
    ident, prev = token
    if prev is None:
        _active_by_thread.pop(ident, None)
    else:
        _active_by_thread[ident] = prev


def active_fingerprint(ident: Optional[int] = None) -> Optional[str]:
    """The fingerprint executing on `ident` (default: current thread)."""
    return _active_by_thread.get(
        threading.get_ident() if ident is None else ident
    )


# ------------------------------------------------------------------ plan mix
def plan_mix_from(
    plan_notes: Optional[List[dict]],
) -> Tuple[Dict[str, int], Optional[str]]:
    """(mix increments, primary decision) of one execution, derived from
    the statement's drained plan notes. An EMPTY list is the plain row
    path (the statement ran locally and left no note); None means the
    caller has no visibility into the scan decision at all (a cluster
    coordinator's scatter record) and contributes nothing."""
    if plan_notes is None:
        return {}, None
    mix: Dict[str, int] = {}
    for note in plan_notes or ():
        if not isinstance(note, dict):
            continue
        strategy = note.get("strategy")
        plan = note.get("plan")
        if strategy in ("columnar-pipeline", "columnar-scan"):
            mix[strategy] = mix.get(strategy, 0) + 1
        elif note.get("knn") is not None:
            key = f"knn-{note['knn']}"
            mix[key] = mix.get(key, 0) + 1
        elif plan == "ColumnScanPlan":
            # the planner's plan-time note; the mirror's scan-time note
            # (strategy above) says which columnar flavor actually served
            mix["columnar-scan"] = mix.get("columnar-scan", 0) + 1
        elif plan == "TableScan":
            mix["row"] = mix.get("row", 0) + 1
        elif plan is not None:
            mix["index"] = mix.get("index", 0) + 1
    if not mix:
        mix["row"] = 1
    return mix, _primary_of(mix)


def _primary_of(mix: Dict[str, int]) -> str:
    for key in _PRIMARY_ORDER:
        if key == "knn":
            knn = sorted(k for k in mix if k.startswith("knn-"))
            if knn:
                return knn[0]
        elif key in mix:
            return key
    return "row"


# ------------------------------------------------------------------ recording
def record(
    fp: str,
    text: str,
    kind: str,
    duration_s: float,
    *,
    error: bool = False,
    slow: bool = False,
    rows_out: int = 0,
    rows_in: int = 0,
    plan: Optional[List[dict]] = None,
    dispatch: Optional[Dict[str, float]] = None,
    extra_mix: Optional[Dict[str, int]] = None,
    primary: Any = "auto",
) -> None:
    """Fold one execution into the fingerprint's cumulative stats. The
    ONLY write door into the store (graftlint GL012).

    `plan` is the statement's drained plan-note list; `extra_mix` adds
    decisions the notes cannot carry (cluster scatter/degraded/pushdown).
    `primary="auto"` derives the flip-detection unit from the notes; pass
    `None` for records whose scan decision happened elsewhere (the cluster
    coordinator's scatter record — its shards record the real decision
    under the same fingerprint) so they never ping-pong the flip counter.
    """
    from bisect import bisect_left

    from surrealdb_tpu import cnf, telemetry

    mix, derived = plan_mix_from(plan)
    if primary == "auto":
        primary = derived
    if extra_mix:
        for k, v in extra_mix.items():
            mix[k] = mix.get(k, 0) + int(v)
    # planner cost-hook extraction (outside the lock): every plan note
    # carrying choose_strategy's est_cost contributes its chosen AND
    # declined modeled costs, so the entry accumulates the margin — the
    # delta the advisor's break-even math needs, not just the decision
    c_chosen = c_declined = c_margin = 0.0
    c_notes = 0
    for note in plan or ():
        ec = note.get("cost", {}).get("est_cost") if isinstance(note, dict) else None
        if isinstance(ec, dict):
            c_chosen += float(ec.get("chosen") or 0.0)
            c_declined += float(ec.get("declined") or 0.0)
            c_margin += float(ec.get("margin") or 0.0)
            c_notes += 1
    flip: Optional[Tuple[str, str]] = None
    evictions = 0
    now = time.time()
    with _lock:
        e = _store.get(fp)
        if e is None:
            e = _store[fp] = _Entry(fp, text, kind)
        _store.move_to_end(fp)
        e.calls += 1
        e.last_ts = now
        e.errors += 1 if error else 0
        e.slow += 1 if slow else 0
        e.dur_sum += duration_s
        e.dur_max = max(e.dur_max, duration_s)
        e.buckets[bisect_left(telemetry.DURATION_BUCKETS, duration_s)] += 1
        e.rows_out += int(rows_out)
        e.rows_in += int(rows_in)
        for k, v in mix.items():
            e.plan_mix[k] = e.plan_mix.get(k, 0) + v
        if dispatch:
            e.dispatch_splits += int(dispatch.get("splits", 0) or 0)
            e.dispatch_retries += int(dispatch.get("retries", 0) or 0)
        if c_notes:
            e.cost_chosen += c_chosen
            e.cost_declined += c_declined
            e.cost_margin += c_margin
            e.cost_notes += c_notes
        if primary is not None:
            if e.last_primary is not None and e.last_primary != primary:
                flip = (e.last_primary, primary)
                e.flips += 1
                e.flip_log.append(
                    {"ts": round(now, 3), "from": flip[0], "to": flip[1]}
                )
                del e.flip_log[:-_FLIP_LOG_CAP]
            e.last_primary = primary
        cap = max(int(getattr(cnf, "STATEMENTS_STORE_SIZE", 512)), 8)
        while len(_store) > cap:
            _store.popitem(last=False)
            evictions += 1
    # observability side effects OUTSIDE the store lock: telemetry and the
    # event ring are lower observability leaves than stats.store in
    # locks.HIERARCHY and must never nest under it
    if evictions:
        _note_evictions(evictions)
    if flip is not None:
        telemetry.inc("statement_plan_flips")
        from surrealdb_tpu import events

        events.emit(
            "stats.plan_flip",
            fingerprint=fp,
            sql=text[:120],
            **{"from": flip[0], "to": flip[1]},
        )
        # a flipped primary means every cached plan decision for this
        # shape is suspect: evict the fingerprint's plan-cache entry
        # (dbs/plan_cache.py; also outside the store lock — the plan
        # cache's own lock is a peer level-85 leaf and must not nest)
        from surrealdb_tpu.dbs import plan_cache as _plan_cache

        _plan_cache.on_plan_flip(fp)


def _note_evictions(n: int) -> None:
    global _evicted
    from surrealdb_tpu import telemetry

    with _lock:
        _evicted += n
    telemetry.inc("statements_evicted_total", by=float(n))


# ------------------------------------------------------------------ views
def statements(
    limit: int = 50,
    fingerprint: Optional[str] = None,
    sort: str = "total_s",
) -> List[dict]:
    """Top statements by cumulative time (default) or calls — the
    `GET /statements` payload. `fingerprint` filters to one shape."""
    with _lock:
        entries = [e.to_dict() for e in _store.values()]
    if fingerprint:
        entries = [e for e in entries if e["fingerprint"] == fingerprint]
    key = sort if sort in ("total_s", "calls", "errors", "max_ms") else "total_s"
    entries.sort(key=lambda e: (e.get(key) or 0, e["calls"]), reverse=True)
    return entries[: max(int(limit), 1)]


def get(fp: str) -> Optional[dict]:
    with _lock:
        e = _store.get(fp)
        return e.to_dict() if e is not None else None


def size() -> int:
    with _lock:
        return len(_store)


def snapshot(limit: int = 50) -> dict:
    """The bundle's `statements` section: store state + top entries."""
    with _lock:
        n, ev = len(_store), _evicted
    return {
        "fingerprints": n,
        "evicted": ev,
        "top": statements(limit=limit),
    }


def export_state(limit: int = 100) -> List[dict]:
    """Per-node entries for cluster federation (the `statements` RPC op):
    the coordinator tags each with node=<id> and merges."""
    return statements(limit=limit)


def reset() -> None:
    """Drop every entry (tests / bench accounting windows)."""
    global _evicted
    with _lock:
        _store.clear()
        _evicted = 0
    fingerprint.cache_clear()
