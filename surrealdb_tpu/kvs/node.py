"""Cluster node membership + failure detection.

Role of the reference's node lifecycle (reference: core/src/kvs/node.rs,
ds.rs:623-668 — bootstrap registers the node, background tasks refresh the
heartbeat, expire stale nodes, and clean up archived nodes' live queries;
SDK engine/tasks.rs:45-51 drives the loops). Nodes coordinate only through
the shared keyspace:

    /!nd{uuid}          -> {id, hb (nanos), gc (archived flag)}
    /!nl{uuid}{liveid}  -> {ns, db, tb} pointer to a node's live query

`tick()` on the Datastore calls heartbeat + expire + cleanup, so a periodic
server loop (or an embedded caller) gets the full membership protocol.
"""

from __future__ import annotations

from typing import List, Optional

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.utils.ser import pack, unpack

# a node is considered dead after missing heartbeats for this long
DEFAULT_EXPIRY_NANOS = 30 * 1_000_000_000


def register(ds) -> None:
    """Write/refresh this node's registration (reference ds.rs:623 insert_node)."""
    txn = ds.transaction(True)
    try:
        txn.set(
            keys.node(ds.node_id.bytes),
            pack({"id": str(ds.node_id), "hb": ds.clock.now_nanos(), "gc": False}),
        )
        txn.commit()
    except BaseException:
        if not txn.done:
            txn.cancel()
        raise


def heartbeat(ds) -> None:
    """Refresh this node's hb timestamp (reference update_node ds.rs:636)."""
    register(ds)


def list_nodes(ds) -> List[dict]:
    txn = ds.transaction(False)
    try:
        pre = keys.node_prefix()
        return [unpack(v) for _, v in txn.scan(pre, prefix_end(pre))]
    finally:
        txn.cancel()


def expire_nodes(ds, expiry_nanos: int = DEFAULT_EXPIRY_NANOS) -> List[str]:
    """Archive nodes whose heartbeat is stale (reference expire_nodes
    ds.rs:647). Returns the archived node ids."""
    now = ds.clock.now_nanos()
    archived = []
    txn = ds.transaction(True)
    try:
        pre = keys.node_prefix()
        for k, v in txn.scan(pre, prefix_end(pre)):
            nd = unpack(v)
            if nd.get("gc"):
                continue
            if str(nd.get("id")) == str(ds.node_id):
                continue  # never expire ourselves
            if now - int(nd.get("hb", 0)) > expiry_nanos:
                nd["gc"] = True
                txn.set(k, pack(nd))
                archived.append(str(nd["id"]))
        txn.commit()
    except BaseException:
        if not txn.done:
            txn.cancel()
        raise
    return archived


def remove_archived(ds) -> int:
    """Delete archived nodes and their live queries (reference
    remove_nodes + cleanup ds.rs:658, node.rs). Returns LQs cleaned."""
    import uuid as _uuid

    cleaned = 0
    txn = ds.transaction(True)
    try:
        pre = keys.node_prefix()
        dead: List[bytes] = []
        for k, v in txn.scan(pre, prefix_end(pre)):
            nd = unpack(v)
            if nd.get("gc"):
                dead.append(_uuid.UUID(str(nd["id"])).bytes)
                txn.delete(k)
        for nd_bytes in dead:
            npre = keys.node_lq_prefix(nd_bytes)
            for k, v in txn.scan(npre, prefix_end(npre)):
                ptr = unpack(v)
                live_id = k[len(npre) :]
                txn.delete(
                    keys.live_query(ptr["ns"], ptr["db"], ptr["tb"], live_id)
                )
                txn.invalidate_tb_lives(ptr["ns"], ptr["db"], ptr["tb"])
                txn.delete(k)
                cleaned += 1
        txn.commit()
    except BaseException:
        if not txn.done:
            txn.cancel()
        raise
    return cleaned


def bootstrap(ds) -> None:
    """Startup protocol (reference ds.rs:623 bootstrap): register this node,
    archive anything stale, and clean up dead nodes' live queries."""
    register(ds)
    expire_nodes(ds)
    remove_archived(ds)
