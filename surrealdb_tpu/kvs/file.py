"""File-backed datastore: MVCC memstore + write-ahead log + snapshot.

Role of the reference's persistent backends (reference: core/src/kvs/
surrealkv/mod.rs, kvs/rocksdb/mod.rs — LSM stores with a WAL) behind the
same trait. Design:

- every commit batch appends ONE length+CRC-framed record batch to
  `<path>.wal` (append-only, O(batch) per commit — replacing the previous
  whole-database rewrite per flush);
- opening loads the `<path>` snapshot then replays intact WAL frames in
  order; a torn tail frame (crash mid-append) is detected by length/CRC and
  discarded, so a kill -9 loses at most transactions that had not finished
  their commit append;
- when the WAL outgrows max(snapshot size, SURREAL_WAL_COMPACT_MIN) the
  committing thread compacts: full snapshot to a temp file, atomic rename,
  WAL truncated.

Durability knob: SURREAL_SYNC_DATA=1 fsyncs the WAL on every commit
(power-loss safety); default is OS-buffered appends (process-crash safety),
matching the reference's default surrealkv configuration.
"""

from __future__ import annotations

import os
import struct
from surrealdb_tpu.utils import locks as _locks
import zlib

from surrealdb_tpu import cnf
from .api import BackendDatastore, BackendTransaction
from .mem import MemDatastore, MemTransaction

MAGIC = b"STPU1\n"
WAL_MAGIC = b"STPUW1\n"

# ---------------------------------------------------------------- versioning
# On-disk format versions (role of the reference's storage version gate +
# migration path, core/src/kvs/version/mod.rs + ds.rs:524): the snapshot
# magic encodes the version; opening an older-but-known version runs the
# registered migrations then rewrites the snapshot at CURRENT_VERSION.
KNOWN_MAGICS = {MAGIC: 1}
CURRENT_VERSION = 1
# {from_version: fn(snapshot_items) -> snapshot_items} — chained upward.
# v1 is the first released format, so the chain is empty today; the gate
# and `surreal upgrade` exist so a v2 change is a registry entry, not a
# breaking release.
MIGRATIONS: dict = {}


def storage_version(path: str) -> int:
    """Version of an on-disk datastore; raises on unrecognized files."""
    with open(path, "rb") as f:
        head = f.read(16)
    for magic, ver in KNOWN_MAGICS.items():
        if head.startswith(magic):
            return ver
    raise ValueError(f"{path} is not a surrealdb_tpu datastore")
_TOMBSTONE = 0xFFFFFFFF


def _frame(writes) -> bytes:
    """Serialize one commit batch: u32 len | u32 crc | records."""
    parts = []
    for k, v in writes.items():
        if v is None:
            parts.append(struct.pack(">II", len(k), _TOMBSTONE))
            parts.append(k)
        else:
            parts.append(struct.pack(">II", len(k), len(v)))
            parts.append(k)
            parts.append(v)
    payload = b"".join(parts)
    return struct.pack(">II", len(payload), zlib.crc32(payload)) + payload


def _iter_frames(data: bytes, start: int):
    """Yield (payload, end_offset) for every intact frame; stops at the
    first torn/corrupt frame."""
    pos = start
    n = len(data)
    while pos + 8 <= n:
        ln, crc = struct.unpack_from(">II", data, pos)
        if pos + 8 + ln > n:
            return  # torn tail: frame body never fully landed
        payload = data[pos + 8 : pos + 8 + ln]
        if zlib.crc32(payload) != crc:
            return  # corrupt frame: discard it and everything after
        pos += 8 + ln
        yield payload, pos


def _iter_records(payload: bytes):
    pos = 0
    n = len(payload)
    while pos + 8 <= n:
        klen, vmark = struct.unpack_from(">II", payload, pos)
        pos += 8
        k = payload[pos : pos + klen]
        pos += klen
        if vmark == _TOMBSTONE:
            yield k, None
        else:
            v = payload[pos : pos + vmark]
            pos += vmark
            yield k, v


class FileDatastore(BackendDatastore):
    def __init__(self, path: str):
        self.path = path
        self.wal_path = path + ".wal"
        self.mem = MemDatastore()
        self._lock = _locks.Lock("kvs.file")
        self._wal_f = None
        self._wal_size = 0
        if os.path.exists(path):
            self._load_snapshot()
        if os.path.exists(self.wal_path):
            self._replay_wal()
        self._open_wal()

    # ------------------------------------------------------------ open
    def _load_snapshot(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        ver = None
        for magic, v in KNOWN_MAGICS.items():
            if data.startswith(magic):
                ver, pos = v, len(magic)
                break
        if ver is None:
            raise ValueError(f"{self.path} is not a surrealdb_tpu datastore")
        n = len(data)
        items = []
        while pos < n:
            if pos + 8 > n:
                raise ValueError(
                    f"{self.path}: truncated snapshot record at byte {pos} "
                    "— run `surreal fix` to repair"
                )
            klen, vlen = struct.unpack_from(">II", data, pos)
            pos += 8
            if pos + klen + vlen > n:
                raise ValueError(
                    f"{self.path}: truncated snapshot record at byte {pos} "
                    "— run `surreal fix` to repair"
                )
            k = data[pos : pos + klen]
            pos += klen
            v = data[pos : pos + vlen]
            pos += vlen
            items.append((k, v))
        while ver < CURRENT_VERSION:
            items = MIGRATIONS[ver](items)
            ver += 1
        keys = []
        for k, v in items:
            self.mem.data[k] = [(0, v)]
            keys.append(k)
        self.mem.sorted_keys.update(keys)

    def _replay_wal(self) -> None:
        with open(self.wal_path, "rb") as f:
            data = f.read()
        if not data.startswith(WAL_MAGIC):
            return  # unrecognized/empty WAL: nothing intact to replay
        good_end = len(WAL_MAGIC)
        mem = self.mem
        new_keys = []
        for payload, end in _iter_frames(data, good_end):
            mem.version += 1
            ver = mem.version
            for k, v in _iter_records(payload):
                chain = mem.data.get(k)
                if chain is None:
                    mem.data[k] = [(ver, v)]
                    new_keys.append(k)
                else:
                    chain.append((ver, v))
            good_end = end
        mem.sorted_keys.update(new_keys)
        if good_end < len(data):
            # torn tail from a crash mid-append: truncate to the intact prefix
            with open(self.wal_path, "r+b") as f:
                f.truncate(good_end)

    def _open_wal(self) -> None:
        if not os.path.exists(self.wal_path):
            with open(self.wal_path, "wb") as f:
                f.write(WAL_MAGIC)
        self._wal_f = open(self.wal_path, "ab")
        self._wal_size = self._wal_f.tell()

    # ------------------------------------------------------------ commit path
    def append_commit(self, writes) -> None:
        """Called by FileTransaction.commit AFTER the mem apply, under the
        datastore lock (WAL frame order == commit version order)."""
        frame = _frame(writes)
        self._wal_f.write(frame)
        self._wal_f.flush()
        if cnf.SYNC_DATA:
            os.fsync(self._wal_f.fileno())
        self._wal_size += len(frame)
        if self._wal_size >= self._compact_threshold():
            self._compact()

    def _compact_threshold(self) -> int:
        try:
            snap = os.path.getsize(self.path)
        except OSError:
            snap = 0
        return max(snap, cnf.WAL_COMPACT_MIN)

    def _compact(self) -> None:
        """Snapshot the live state and truncate the WAL. Runs on the
        committing thread while holding the datastore lock."""
        with self.mem.lock:
            snapshot = [
                (k, chain[-1][1])
                for k, chain in self.mem.data.items()
                if chain[-1][1] is not None
            ]
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            for k, v in snapshot:
                f.write(struct.pack(">II", len(k), len(v)))
                f.write(k)
                f.write(v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._wal_f.close()
        with open(self.wal_path, "wb") as f:
            f.write(WAL_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        self._open_wal()

    def transaction(self, write: bool) -> BackendTransaction:
        return FileTransaction(self, write)

    def close(self) -> None:
        with self._lock:
            if self._wal_f is not None:
                self._wal_f.flush()
                os.fsync(self._wal_f.fileno())
                self._wal_f.close()
                self._wal_f = None

    def flush(self) -> None:
        with self._lock:
            self._compact()


def repair(path: str) -> dict:
    """`surreal fix` (reference: src/cli/fix.rs): tolerantly re-read a
    possibly-damaged store — keep every intact snapshot record, drop the
    torn tail, replay every intact WAL frame — then rewrite a clean
    snapshot + empty WAL. Returns repair statistics."""
    stats = {"keys": 0, "snapshot_dropped_bytes": 0, "wal_frames": 0, "version": None}
    if not os.path.exists(path):
        raise ValueError(f"{path} does not exist")
    with open(path, "rb") as f:
        data = f.read()
    ver, pos = None, 0
    for magic, v in KNOWN_MAGICS.items():
        if data.startswith(magic):
            ver, pos = v, len(magic)
            break
    if data and ver is None:
        raise ValueError(f"{path} is not a surrealdb_tpu datastore")
    stats["version"] = ver or CURRENT_VERSION
    items = {}
    n = len(data)
    while pos < n:
        if pos + 8 > n:
            break
        klen, vlen = struct.unpack_from(">II", data, pos)
        if pos + 8 + klen + vlen > n:
            break
        k = data[pos + 8 : pos + 8 + klen]
        v = data[pos + 8 + klen : pos + 8 + klen + vlen]
        items[k] = v
        pos += 8 + klen + vlen
    stats["snapshot_dropped_bytes"] = n - pos
    if ver is not None:
        lst = list(items.items())
        while ver < CURRENT_VERSION:
            lst = MIGRATIONS[ver](lst)
            ver += 1
        items = dict(lst)
    wal_path = path + ".wal"
    if os.path.exists(wal_path):
        with open(wal_path, "rb") as f:
            wal = f.read()
        if wal.startswith(WAL_MAGIC):
            for payload, _end in _iter_frames(wal, len(WAL_MAGIC)):
                stats["wal_frames"] += 1
                for k, v in _iter_records(payload):
                    if v is None:
                        items.pop(k, None)
                    else:
                        items[k] = v
    stats["keys"] = len(items)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        for k, v in sorted(items.items()):
            f.write(struct.pack(">II", len(k), len(v)))
            f.write(k)
            f.write(v)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    with open(wal_path, "wb") as f:
        f.write(WAL_MAGIC)
        f.flush()
        os.fsync(f.fileno())
    return stats


def upgrade(path: str) -> dict:
    """`surreal upgrade`: migrate an on-disk store to CURRENT_VERSION
    (a no-op rewrite when already current)."""
    before = storage_version(path)
    stats = repair(path)
    stats["from_version"], stats["to_version"] = before, CURRENT_VERSION
    return stats


class FileTransaction(MemTransaction):
    def __init__(self, store: FileDatastore, write: bool):
        super().__init__(store.mem, write)
        self.fstore = store

    def commit(self) -> None:
        writes = dict(self.writes)
        with self.fstore._lock:
            super().commit()  # raises TxConflictError before any WAL append
            if writes:
                self.fstore.append_commit(writes)
