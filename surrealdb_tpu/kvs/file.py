"""File-backed datastore: MVCC memstore + snapshot persistence.

Stands in for the reference's rocksdb/surrealkv persistent backends behind the
same trait (reference: core/src/kvs/rocksdb/, kvs/surrealkv/). The full store
is loaded at open and snapshotted to disk on every commit batch boundary
(cheap for the embedded use; a C++ LSM backend can slot in behind
`BackendDatastore` later without touching callers).
"""

from __future__ import annotations

import os
import struct
import threading

from .api import BackendDatastore, BackendTransaction
from .mem import MemDatastore, MemTransaction

MAGIC = b"STPU1\n"


class FileDatastore(BackendDatastore):
    def __init__(self, path: str):
        self.path = path
        self.mem = MemDatastore()
        self._dirty = 0
        self._lock = threading.Lock()
        if os.path.exists(path):
            self._load()

    def _load(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        if not data.startswith(MAGIC):
            raise ValueError(f"{self.path} is not a surrealdb_tpu datastore")
        pos = len(MAGIC)
        n = len(data)
        while pos < n:
            klen, vlen = struct.unpack_from(">II", data, pos)
            pos += 8
            k = data[pos : pos + klen]
            pos += klen
            v = data[pos : pos + vlen]
            pos += vlen
            self.mem.data[k] = [(0, v)]

    def flush(self) -> None:
        with self._lock:
            with self.mem.lock:
                snapshot = [
                    (k, chain[-1][1])
                    for k, chain in self.mem.data.items()
                    if chain[-1][1] is not None
                ]
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                for k, v in snapshot:
                    f.write(struct.pack(">II", len(k), len(v)))
                    f.write(k)
                    f.write(v)
            os.replace(tmp, self.path)

    def transaction(self, write: bool) -> BackendTransaction:
        return FileTransaction(self, write)

    def close(self) -> None:
        self.flush()


class FileTransaction(MemTransaction):
    def __init__(self, store: FileDatastore, write: bool):
        super().__init__(store.mem, write)
        self.fstore = store

    def commit(self) -> None:
        had_writes = bool(self.writes)
        super().commit()
        if had_writes:
            self.fstore.flush()
