"""Datastore: the engine root.

Role of the reference's Datastore (reference: core/src/kvs/ds.rs:60): owns the
storage backend, hands out transactions, runs queries (execute/process), holds
the node identity, the versionstamp oracle, the device-side index store
registry, and the live-query notification channel.
"""

from __future__ import annotations

import contextvars
import threading
import time as _time
import weakref
from surrealdb_tpu.utils import locks as _locks
import uuid as _uuid
from typing import Any, Dict, List, Optional

from surrealdb_tpu import cnf
from surrealdb_tpu.err import KvsError
from .api import BackendDatastore
from .mem import MemDatastore
from .tx import Transaction
from .vs import Oracle, SystemClock

_gc_tls = threading.local()  # .in_flusher: group-commit re-entrancy guard


class _CommitSlot:
    """One queued commit's outcome channel."""

    __slots__ = ("done", "error")

    def __init__(self):
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class _ColumnSink:
    """Combines one group-commit flush's column-mirror work: per-table
    version-bump counts and bulk delta blocks across every member txn,
    applied in ONE pass after all backend commits — a 5-statement bulk
    stream appends to the mirror once, not five times."""

    def __init__(self):
        self.cm = None
        self.cv = None  # newest member commit version (serve floor)
        self.bumps: Dict[tuple, int] = {}
        self.parts: Dict[tuple, list] = {}
        self.poisoned: set = set()  # tables some member wrote row-at-a-time
        self.touched: set = set()

    def add(self, txn, touched) -> None:
        if txn._column_mirrors is not None:
            self.cm = txn._column_mirrors
        cv = getattr(txn.tr, "commit_version", None)
        if cv is not None:
            self.cv = cv if self.cv is None else max(self.cv, cv)
        self.touched |= touched
        for t in touched:
            self.bumps[t] = self.bumps.get(t, 0) + 1
        delta_tables = set()
        for key3, ids, eks, docs in txn.column_deltas:
            if key3 not in txn.touched_row_tables:
                self.parts.setdefault(key3, []).append((ids, eks, docs))
                delta_tables.add(key3)
        for t in touched:
            # a touched table whose writes this member did NOT fully express
            # as a bulk block can never delta-apply in this flush
            if t not in delta_tables or cv is None:
                self.poisoned.add(t)

    def flush(self) -> None:
        cm = self.cm
        if cm is None:
            return
        applied = set()
        for key3, parts in self.parts.items():
            if key3 in self.poisoned:
                continue
            try:
                ok = cm.apply_bulk(key3, parts, self.bumps.get(key3, 1), self.cv)
            except Exception:
                ok = False  # commit is durable; rebuild fallback below
            if ok:
                applied.add(key3)
        left = self.touched - applied
        if left:
            cm.schedule_rebuild(left)


class GroupCommit:
    """Bounded-latency write-commit coalescer (the ingest group-commit).

    Write transactions submit themselves and block until a per-datastore
    flusher thread (flight-recorder-visible as `bg:group_commit:flush`)
    drains the queue: each flush commits every queued backend txn under ONE
    commit-lock hold, then applies the combined column-mirror deltas and
    per-table rebuild scheduling once for the whole group. Commit
    SEMANTICS are unchanged — submit() returns only after this txn's own
    backend commit (or conflict error) completed; the coalescer batches
    work, it never defers acknowledgement or visibility. The flusher is
    ephemeral: it exits after GROUP_COMMIT_LINGER_SECS idle and respawns
    on the next write commit, so idle datastores hold no thread."""

    def __init__(self, ds):
        self._ds = weakref.ref(ds)
        self._lock = _locks.Lock("kvs.group_commit")
        self._wake = threading.Event()  # raw: pure wakeup, no state guarded
        self._queue: List[tuple] = []  # [(txn, contextvars ctx, slot)]
        self._live = False  # a flusher incarnation is (being) spawned
        self._gen = 0  # incarnation counter (crash recovery, see _body)
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------ submit
    def submit(self, txn) -> bool:
        """Queue a write commit and wait for its flush; False = caller
        must commit inline (coalescer off/closed, or already on the
        flusher thread — an on_commit callback committing a txn)."""
        if not cnf.GROUP_COMMIT or getattr(_gc_tls, "in_flusher", False):
            return False
        slot = _CommitSlot()
        ctx = contextvars.copy_context()
        entry = (txn, ctx, slot)
        with self._lock:
            if self._closed:
                return False
            self._queue.append(entry)
            spawn = not self._live
            if spawn:
                self._live = True
                self._gen += 1
                gen = self._gen
        if spawn:
            try:
                self._spawn(gen)
            except BaseException:
                # our txn must NOT stay queued behind a raised commit —
                # a later flusher would durably commit a transaction whose
                # owner was told the commit failed
                with self._lock:
                    if entry in self._queue:
                        self._queue.remove(entry)
                raise
        self._wake.set()
        while not slot.done.wait(0.25):
            # self-rescue: if the flusher died (spawn failure, crash)
            # without serving us, drain the queue on this thread
            with self._lock:
                rescue = not self._live and any(
                    s is slot for _, _, s in self._queue
                )
                if rescue:
                    self._live = True
                    self._gen += 1
                    rgen = self._gen
            if rescue:
                from surrealdb_tpu import events

                # timeline entry under the submitter's own trace: a commit
                # that had to rescue a dead flusher is exactly the latency
                # outlier the event log exists to explain
                events.emit("txn.group_commit_rescue")
                _gc_tls.in_flusher = True
                try:
                    self._drain(linger=0.0)
                finally:
                    _gc_tls.in_flusher = False
                    with self._lock:
                        if self._gen == rgen and self._live:
                            self._live = False
        if slot.error is not None:
            raise slot.error
        return True

    # ------------------------------------------------------------ flusher
    def _spawn(self, gen: int) -> None:
        from surrealdb_tpu import bg

        ds = self._ds()
        try:
            t = bg.spawn_service(
                "group_commit", "flush", self._body, gen,
                owner=id(ds) if ds is not None else None,
            )
            with self._lock:
                self._thread = t
        except BaseException:
            with self._lock:
                if self._gen == gen:
                    self._live = False  # submitters self-rescue
            raise

    def _body(self, gen: int) -> None:
        _gc_tls.in_flusher = True
        try:
            self._drain(cnf.GROUP_COMMIT_LINGER_SECS)
        finally:
            _gc_tls.in_flusher = False
            # crash recovery: an exception escaping _drain must not leave
            # _live latched True — submitters would poll forever with no
            # flusher alive. Gen-guarded so a crashed incarnation's cleanup
            # can't clobber a successor spawned after a normal exit.
            with self._lock:
                if self._gen == gen and self._live:
                    self._live = False

    def _drain(self, linger: float) -> None:
        cap = max(cnf.GROUP_COMMIT_MAX_TXNS, 1)
        while True:
            # clear BEFORE reading the queue: a submitter appends before it
            # sets the event, so either the drain below sees its txn or the
            # wait below sees its wakeup — no lost-signal linger stall
            self._wake.clear()
            with self._lock:
                batch = self._queue[:cap]
                del self._queue[: len(batch)]
            if batch:
                try:
                    self._flush(batch)
                except BaseException as e:
                    # a crash past the drain must still resolve every
                    # drained slot — these txns are no longer in the queue,
                    # so the submitter self-rescue can never reach them.
                    # Slots _flush already resolved (done set) are left
                    # alone: a member whose backend commit succeeded must
                    # not be re-marked failed after its submitter returned.
                    for _, _, slot in batch:
                        if not slot.done.is_set():
                            if slot.error is None:
                                slot.error = e
                            slot.done.set()
                    raise
                continue
            if linger <= 0 or self._closed or not self._wake.wait(linger):
                with self._lock:
                    if not self._queue:
                        self._live = False
                        return
                    # work arrived between timeout and lock: keep going

    def _flush(self, batch: List[tuple]) -> None:
        from surrealdb_tpu import faults, telemetry

        # chaos hook: a flusher that dies HERE exercises the whole rescue
        # chain — drained slots resolve with the error (commit callers see
        # a clean failure), _live un-latches, submitters self-rescue
        faults.fire("kvs.group_commit.flush")
        ds = self._ds()
        sink = _ColumnSink()
        lock = ds.commit_lock if ds is not None else None
        # ONE commit-lock hold for the whole group: per-member version
        # bumps + backend commits, then one combined delta application.
        # The span feeds the txn_group_commit duration histogram (and the
        # flight recorder names the thread bg:group_commit:flush).
        with telemetry.span("txn_group_commit"):
            if lock is not None:
                lock.acquire()
            try:
                for i, (txn, ctx, slot) in enumerate(batch):
                    try:
                        # the submitter's contextvars (trace/span identity)
                        # ride along: txn_commit spans attribute to the
                        # right request, not to the flusher thread
                        ctx.run(txn.commit_direct, sink)
                    except Exception as e:  # per-member outcome channel
                        slot.error = e
                    except BaseException as e:
                        # process-shutdown class (KeyboardInterrupt /
                        # SystemExit / injected panics): resolve THIS member
                        # and every not-yet-committed one, then propagate —
                        # already-committed members keep their success, and
                        # the flush must not keep committing through it
                        slot.error = e
                        for _, _, s in batch[i + 1:]:
                            if s.error is None:
                                s.error = e
                        raise
                try:
                    sink.flush()
                except Exception:
                    # derived-state upkeep is best-effort past this point:
                    # commits are durable, stale mirrors can't serve
                    # (version mismatch), and the flusher must stay alive —
                    # but the decline has to be countable
                    telemetry.inc("column_mirror_delta", outcome="flush_error")
            finally:
                if lock is not None:
                    lock.release()
                for _, _, slot in batch:
                    slot.done.set()
        telemetry.observe_hist(
            "txn_group_commit_width", len(batch), buckets=telemetry.COUNT_BUCKETS
        )

    # ------------------------------------------------------------ teardown
    def close(self, timeout: float = 5.0) -> None:
        """Flush anything queued and retire the flusher thread."""
        with self._lock:
            self._closed = True
            t = self._thread
        self._wake.set()
        if t is not None and t.is_alive():
            t.join(timeout)


class Datastore:
    def __init__(self, path: str = "memory", clock=None):
        self.path = path
        self.backend = self._open(path)
        self.clock = clock or SystemClock()
        self.oracle = Oracle()
        self.node_id = _uuid.uuid4()
        # device-resident index mirrors (vector / graph / ft columnar snapshots)
        from surrealdb_tpu.idx.store import IndexStores
        from surrealdb_tpu.idx.graph_csr import GraphMirrors

        from surrealdb_tpu.dbs.dispatch import DispatchQueue
        from surrealdb_tpu.idx.builder import IndexBuilder

        self.index_stores = IndexStores()
        self.graph_mirrors = GraphMirrors()
        # ingest-time mirror builds + count-kernel prewarm need a Datastore
        # to open scan transactions from the background timer thread
        self.graph_mirrors.bind_ds(self)
        # columnar table mirrors backing the vectorized WHERE/projection
        # scan path (idx/column_mirror.py)
        from surrealdb_tpu.idx.column_mirror import ColumnMirrors

        self.column_mirrors = ColumnMirrors()
        self.column_mirrors.bind_ds(self)
        # cross-query device dispatch coalescing (dbs/dispatch.py)
        self.dispatch = DispatchQueue()
        # fingerprint-keyed plan & pipeline cache (dbs/plan_cache.py):
        # hot statement shapes serve their template AST, dispatch
        # skeleton, pipeline lowering, and planner schema prefetch
        # without re-parsing or re-planning (validation-on-serve)
        from surrealdb_tpu.dbs.plan_cache import PlanCache

        self.plan_cache = PlanCache(self)
        # background index builds (DEFINE INDEX ... CONCURRENTLY)
        self.index_builder = IndexBuilder(self)
        # serializes backend commit + mirror-delta application so two
        # concurrently committing transactions can't apply graph/vector
        # deltas in the opposite order of their backend commits (advisor r2)
        self.commit_lock = _locks.Lock("kvs.commit")
        # bounded-latency write-commit coalescer (bulk-ingest group commit)
        self.group_commit = GroupCommit(self)
        # live queries: uuid(hex) -> LiveSubscription (registered in M10)
        self.notifications = None  # set by enable_notifications()
        self.auth_enabled = False
        # operator-controllable allow/deny policy (dbs/capabilities.py;
        # reference core/src/dbs/capabilities.rs). Servers override from
        # CLI/env; embedded use keeps the defaults.
        from surrealdb_tpu.dbs.capabilities import Capabilities

        self.capabilities = Capabilities.default()
        # always-on sampling profiler (profiler.py): one process-global
        # supervised service, started with the first engine instance
        # (SURREAL_PROFILE_HZ=0 keeps it off); every later call is a no-op
        from surrealdb_tpu import profiler as _profiler

        _profiler.ensure_started()
        # advisor plane (advisor.py): observe->propose sweeps over this
        # instance's planes; same one-shot process-global service shape
        # (SURREAL_ADVISOR=0 keeps it off), later instances just register
        from surrealdb_tpu import advisor as _advisor

        _advisor.ensure_started(self)
        # cluster mode (surrealdb_tpu/cluster/): when attach()ed, execute()
        # routes through the distributed scatter/gather executor; the
        # internal /cluster channel and the executor's own sub-queries run
        # execute_local() against this node's shard
        self.cluster = None

    @staticmethod
    def _open(path: str) -> BackendDatastore:
        scheme, _, rest = path.partition("://")
        if path in ("memory", "mem") or scheme in ("mem", "memory"):
            return MemDatastore()
        if scheme in ("file", "surrealkv", "rocksdb"):
            from .file import FileDatastore

            return FileDatastore(rest)
        raise KvsError(f"Unknown datastore path {path!r}")

    # ------------------------------------------------------------ txns
    def transaction(self, write: bool = False) -> Transaction:
        txn = Transaction(
            self.backend.transaction(write), self.oracle, self.clock, self.graph_mirrors
        )
        txn._index_stores = self.index_stores
        txn._column_mirrors = self.column_mirrors
        txn._commit_lock = self.commit_lock
        txn._group = self.group_commit
        cluster = self.cluster
        if cluster is not None:
            # cluster mode: every record write mints an HLC stamp under
            # this node's identity (cluster/hlc.py LWW convergence)
            txn.hlc_node = cluster.node_id
        return txn

    # ------------------------------------------------------------ notifications
    def enable_notifications(self) -> None:
        from surrealdb_tpu.dbs.notification import NotificationHub

        if self.notifications is None:
            self.notifications = NotificationHub()

    # ------------------------------------------------------------ execution
    def execute(
        self,
        text: str,
        session=None,
        vars: Optional[Dict[str, Any]] = None,
    ) -> List[dict]:
        """Parse and run a SurrealQL query string; returns a list of response
        dicts {status, result|error, time} (reference kvs/ds.rs:768). In
        cluster mode the statement routes through the distributed executor
        (scatter to shard owners, merge results) instead of running against
        this node's local shard alone."""
        if self.cluster is not None:
            from surrealdb_tpu.dbs.session import Session

            return self.cluster.executor.execute(
                text, session or Session.owner(), vars
            )
        return self.execute_local(text, session, vars)

    def execute_local(
        self,
        text: str,
        session=None,
        vars: Optional[Dict[str, Any]] = None,
    ) -> List[dict]:
        """Single-node execution against THIS node's data — the only entry
        the cluster executor and the /cluster RPC channel use (routing back
        through execute() would recurse the scatter)."""
        from surrealdb_tpu import tracing
        from surrealdb_tpu.syn import parse_query
        from surrealdb_tpu.dbs.session import Session

        # the executor level of the span tree: a root trace for embedded
        # callers (SDK/bench), a child span under an HTTP/WS/RPC ingress.
        # The sql label is trace-only (tracing never feeds metric families,
        # so truncated statement text can't mint unbounded series).
        with tracing.request("execute", sql=text[:120]):
            # plan-cache front: a hot shape serves its shared template AST
            # (with this text's literal values bound as executor slots)
            # and skips the parse entirely; cold parses are observed so
            # the shape installs once it crosses the min-hits floor
            served = self.plan_cache.fetch(text)
            if served is not None:
                return self.process(
                    served.query,
                    session or Session.owner(),
                    vars,
                    slot_values=served.slot_values,
                    cache_warm=True,
                )
            t0 = _time.perf_counter()
            ast = parse_query(text)
            self.plan_cache.observe(
                text, ast, (_time.perf_counter() - t0) * 1e6
            )
            return self.process(ast, session or Session.owner(), vars)

    def process(
        self,
        ast,
        session,
        vars: Optional[Dict[str, Any]] = None,
        slot_values: Optional[tuple] = None,
        cache_warm: bool = False,
    ) -> List[dict]:
        from surrealdb_tpu.dbs.executor import Executor

        ex = Executor(self, session, vars or {})
        # plan-cache slot bindings ride the per-query executor (every
        # child Context shares it), never the shared template AST
        ex.slot_values = slot_values
        ex.cache_warm = cache_warm
        return ex.execute(ast)

    def compute(self, expr, session, vars: Optional[Dict[str, Any]] = None):
        """Evaluate one expression against a fresh read transaction
        (reference kvs/ds.rs compute/evaluate)."""
        from surrealdb_tpu.dbs.executor import Executor

        ex = Executor(self, session, vars or {})
        return ex.compute_expression(expr)

    # ------------------------------------------------------------ mesh
    _mesh_cache = ("unset", None)

    def mesh(self):
        """The device mesh for sharded mirrors: a 1-D 'data' mesh over all
        visible devices when there are 2+, else None (single-chip path).
        Shared across datastores — the devices are process-global."""
        kind, m = Datastore._mesh_cache
        if kind != "unset":
            return m
        import jax

        devs = jax.devices()
        if len(devs) < 2:
            Datastore._mesh_cache = ("none", None)
            return None
        from surrealdb_tpu.parallel.mesh import make_mesh

        m = make_mesh(len(devs))
        Datastore._mesh_cache = ("mesh", m)
        return m

    # ------------------------------------------------------------ maintenance
    def tick(self) -> int:
        """One maintenance pass (reference kvs/ds.rs tick + the SDK's
        background tasks engine/tasks.rs:45-51): refresh this node's
        heartbeat, archive stale nodes, clean up dead nodes' live queries,
        then changefeed GC. Called periodically by the server loop;
        embedded users may call it directly. Returns the number of change
        entries collected."""
        from surrealdb_tpu.cf.gc import gc_all
        from surrealdb_tpu.kvs import node as _node

        _node.heartbeat(self)
        _node.expire_nodes(self)
        _node.remove_archived(self)
        return gc_all(self)

    def bootstrap(self) -> None:
        """Startup membership protocol (reference ds.rs:623)."""
        from surrealdb_tpu.kvs import node as _node

        _node.bootstrap(self)

    def close(self) -> None:
        """Close the backend AND tear down this datastore's background
        machinery: cancel armed mirror-rebuild/prewarm timers, join running
        tasks, and (when the whole registry goes idle) park the flight-
        recorder watchdog — no daemon-thread leaks under pytest."""
        from surrealdb_tpu import bg

        try:
            if self.cluster is not None:
                if self.cluster.client is not None:
                    self.cluster.client.shutdown()
                if self.cluster.executor is not None:
                    self.cluster.executor.shutdown()
            self.group_commit.close()
            self.column_mirrors.shutdown()
            self.graph_mirrors.shutdown()
            bg.shutdown(owner=id(self))
        except Exception:  # noqa: BLE001 — teardown must never mask close()
            # counted, not silent: a teardown failure that skipped the rest
            # of the shutdown chain is a leak suspect worth a metric. The
            # recording itself is best-effort (interpreter shutdown can have
            # torn modules down) — backend.close() below must still run.
            import contextlib

            with contextlib.suppress(Exception):
                from surrealdb_tpu import telemetry

                telemetry.inc("teardown_errors", stage="datastore_close")
        self.backend.close()
