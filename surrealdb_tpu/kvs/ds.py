"""Datastore: the engine root.

Role of the reference's Datastore (reference: core/src/kvs/ds.rs:60): owns the
storage backend, hands out transactions, runs queries (execute/process), holds
the node identity, the versionstamp oracle, the device-side index store
registry, and the live-query notification channel.
"""

from __future__ import annotations

from surrealdb_tpu.utils import locks as _locks
import uuid as _uuid
from typing import Any, Dict, List, Optional

from surrealdb_tpu.err import KvsError
from .api import BackendDatastore
from .mem import MemDatastore
from .tx import Transaction
from .vs import Oracle, SystemClock


class Datastore:
    def __init__(self, path: str = "memory", clock=None):
        self.path = path
        self.backend = self._open(path)
        self.clock = clock or SystemClock()
        self.oracle = Oracle()
        self.node_id = _uuid.uuid4()
        # device-resident index mirrors (vector / graph / ft columnar snapshots)
        from surrealdb_tpu.idx.store import IndexStores
        from surrealdb_tpu.idx.graph_csr import GraphMirrors

        from surrealdb_tpu.dbs.dispatch import DispatchQueue
        from surrealdb_tpu.idx.builder import IndexBuilder

        self.index_stores = IndexStores()
        self.graph_mirrors = GraphMirrors()
        # ingest-time mirror builds + count-kernel prewarm need a Datastore
        # to open scan transactions from the background timer thread
        self.graph_mirrors.bind_ds(self)
        # columnar table mirrors backing the vectorized WHERE/projection
        # scan path (idx/column_mirror.py)
        from surrealdb_tpu.idx.column_mirror import ColumnMirrors

        self.column_mirrors = ColumnMirrors()
        self.column_mirrors.bind_ds(self)
        # cross-query device dispatch coalescing (dbs/dispatch.py)
        self.dispatch = DispatchQueue()
        # background index builds (DEFINE INDEX ... CONCURRENTLY)
        self.index_builder = IndexBuilder(self)
        # serializes backend commit + mirror-delta application so two
        # concurrently committing transactions can't apply graph/vector
        # deltas in the opposite order of their backend commits (advisor r2)
        self.commit_lock = _locks.Lock("kvs.commit")
        # live queries: uuid(hex) -> LiveSubscription (registered in M10)
        self.notifications = None  # set by enable_notifications()
        self.auth_enabled = False
        # operator-controllable allow/deny policy (dbs/capabilities.py;
        # reference core/src/dbs/capabilities.rs). Servers override from
        # CLI/env; embedded use keeps the defaults.
        from surrealdb_tpu.dbs.capabilities import Capabilities

        self.capabilities = Capabilities.default()
        # cluster mode (surrealdb_tpu/cluster/): when attach()ed, execute()
        # routes through the distributed scatter/gather executor; the
        # internal /cluster channel and the executor's own sub-queries run
        # execute_local() against this node's shard
        self.cluster = None

    @staticmethod
    def _open(path: str) -> BackendDatastore:
        scheme, _, rest = path.partition("://")
        if path in ("memory", "mem") or scheme in ("mem", "memory"):
            return MemDatastore()
        if scheme in ("file", "surrealkv", "rocksdb"):
            from .file import FileDatastore

            return FileDatastore(rest)
        raise KvsError(f"Unknown datastore path {path!r}")

    # ------------------------------------------------------------ txns
    def transaction(self, write: bool = False) -> Transaction:
        txn = Transaction(
            self.backend.transaction(write), self.oracle, self.clock, self.graph_mirrors
        )
        txn._index_stores = self.index_stores
        txn._column_mirrors = self.column_mirrors
        txn._commit_lock = self.commit_lock
        return txn

    # ------------------------------------------------------------ notifications
    def enable_notifications(self) -> None:
        from surrealdb_tpu.dbs.notification import NotificationHub

        if self.notifications is None:
            self.notifications = NotificationHub()

    # ------------------------------------------------------------ execution
    def execute(
        self,
        text: str,
        session=None,
        vars: Optional[Dict[str, Any]] = None,
    ) -> List[dict]:
        """Parse and run a SurrealQL query string; returns a list of response
        dicts {status, result|error, time} (reference kvs/ds.rs:768). In
        cluster mode the statement routes through the distributed executor
        (scatter to shard owners, merge results) instead of running against
        this node's local shard alone."""
        if self.cluster is not None:
            from surrealdb_tpu.dbs.session import Session

            return self.cluster.executor.execute(
                text, session or Session.owner(), vars
            )
        return self.execute_local(text, session, vars)

    def execute_local(
        self,
        text: str,
        session=None,
        vars: Optional[Dict[str, Any]] = None,
    ) -> List[dict]:
        """Single-node execution against THIS node's data — the only entry
        the cluster executor and the /cluster RPC channel use (routing back
        through execute() would recurse the scatter)."""
        from surrealdb_tpu import tracing
        from surrealdb_tpu.syn import parse_query
        from surrealdb_tpu.dbs.session import Session

        # the executor level of the span tree: a root trace for embedded
        # callers (SDK/bench), a child span under an HTTP/WS/RPC ingress.
        # The sql label is trace-only (tracing never feeds metric families,
        # so truncated statement text can't mint unbounded series).
        with tracing.request("execute", sql=text[:120]):
            ast = parse_query(text)
            return self.process(ast, session or Session.owner(), vars)

    def process(self, ast, session, vars: Optional[Dict[str, Any]] = None) -> List[dict]:
        from surrealdb_tpu.dbs.executor import Executor

        ex = Executor(self, session, vars or {})
        return ex.execute(ast)

    def compute(self, expr, session, vars: Optional[Dict[str, Any]] = None):
        """Evaluate one expression against a fresh read transaction
        (reference kvs/ds.rs compute/evaluate)."""
        from surrealdb_tpu.dbs.executor import Executor

        ex = Executor(self, session, vars or {})
        return ex.compute_expression(expr)

    # ------------------------------------------------------------ mesh
    _mesh_cache = ("unset", None)

    def mesh(self):
        """The device mesh for sharded mirrors: a 1-D 'data' mesh over all
        visible devices when there are 2+, else None (single-chip path).
        Shared across datastores — the devices are process-global."""
        kind, m = Datastore._mesh_cache
        if kind != "unset":
            return m
        import jax

        devs = jax.devices()
        if len(devs) < 2:
            Datastore._mesh_cache = ("none", None)
            return None
        from surrealdb_tpu.parallel.mesh import make_mesh

        m = make_mesh(len(devs))
        Datastore._mesh_cache = ("mesh", m)
        return m

    # ------------------------------------------------------------ maintenance
    def tick(self) -> int:
        """One maintenance pass (reference kvs/ds.rs tick + the SDK's
        background tasks engine/tasks.rs:45-51): refresh this node's
        heartbeat, archive stale nodes, clean up dead nodes' live queries,
        then changefeed GC. Called periodically by the server loop;
        embedded users may call it directly. Returns the number of change
        entries collected."""
        from surrealdb_tpu.cf.gc import gc_all
        from surrealdb_tpu.kvs import node as _node

        _node.heartbeat(self)
        _node.expire_nodes(self)
        _node.remove_archived(self)
        return gc_all(self)

    def bootstrap(self) -> None:
        """Startup membership protocol (reference ds.rs:623)."""
        from surrealdb_tpu.kvs import node as _node

        _node.bootstrap(self)

    def close(self) -> None:
        """Close the backend AND tear down this datastore's background
        machinery: cancel armed mirror-rebuild/prewarm timers, join running
        tasks, and (when the whole registry goes idle) park the flight-
        recorder watchdog — no daemon-thread leaks under pytest."""
        from surrealdb_tpu import bg

        try:
            if self.cluster is not None:
                if self.cluster.client is not None:
                    self.cluster.client.shutdown()
                if self.cluster.executor is not None:
                    self.cluster.executor.shutdown()
            self.column_mirrors.shutdown()
            self.graph_mirrors.shutdown()
            bg.shutdown(owner=id(self))
        except Exception:  # noqa: BLE001 — teardown must never mask close()
            pass
        self.backend.close()
