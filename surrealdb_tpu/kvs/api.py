"""The pluggable storage-backend boundary.

Same role as the reference's backend trait (reference: core/src/kvs/api.rs:12-365):
every backend provides a transaction object with get/set/put/putc/del/delc/
exists/keys/scan/batch plus range deletes, and the Datastore hands these out.
Keys and values are raw bytes; ordering is bytewise.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Iterable, List, Optional, Tuple

from surrealdb_tpu.err import (
    TxConditionNotMetError,
    TxFinishedError,
    TxKeyAlreadyExistsError,
    TxReadonlyError,
)

KV = Tuple[bytes, bytes]


class BackendTransaction(ABC):
    """One transaction against a backend. Write=False means read-only."""

    def __init__(self, write: bool):
        self.write = write
        self.done = False

    # -- lifecycle ---------------------------------------------------------
    @abstractmethod
    def commit(self) -> None: ...

    @abstractmethod
    def cancel(self) -> None: ...

    def _check_open(self, needs_write: bool = False) -> None:
        if self.done:
            raise TxFinishedError()
        if needs_write and not self.write:
            raise TxReadonlyError()

    # -- point ops ---------------------------------------------------------
    @abstractmethod
    def get(self, key: bytes, version: Optional[int] = None) -> Optional[bytes]: ...

    @abstractmethod
    def set(self, key: bytes, val: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    def exists(self, key: bytes) -> bool:
        return self.get(key) is not None

    def version_of(self, key: bytes):
        """MVCC version of the newest committed value for `key`, when the
        backend tracks versions (mem does); None disables version-pinned
        features (changefeed bulk-entry expansion reads current values)."""
        return None

    def oldest_retained(self, key: bytes):
        """Oldest committed value still retained for `key` (None when the
        key is absent or its oldest retained entry is a tombstone). The
        changefeed reader's fallback when a pinned version was GC'd past
        the MVCC horizon — best-effort, same contract as retention GC."""
        return None

    def put(self, key: bytes, val: bytes) -> None:
        """Insert only-if-absent."""
        self._check_open(True)
        if self.get(key) is not None:
            raise TxKeyAlreadyExistsError()
        self.set(key, val)

    def putc(self, key: bytes, val: bytes, chk: Optional[bytes]) -> None:
        """Set only if current value == chk (None = must be absent)."""
        self._check_open(True)
        if self.get(key) != chk:
            raise TxConditionNotMetError()
        self.set(key, val)

    def delc(self, key: bytes, chk: Optional[bytes]) -> None:
        self._check_open(True)
        if self.get(key) != chk:
            raise TxConditionNotMetError()
        self.delete(key)

    # -- range ops ---------------------------------------------------------
    @abstractmethod
    def keys(self, beg: bytes, end: bytes, limit: int = -1) -> List[bytes]: ...

    @abstractmethod
    def scan(self, beg: bytes, end: bytes, limit: int = -1) -> List[KV]: ...

    def getr(self, beg: bytes, end: bytes) -> List[KV]:
        return self.scan(beg, end)

    def delr(self, beg: bytes, end: bytes) -> None:
        self._check_open(True)
        for k in self.keys(beg, end):
            self.delete(k)

    def getm(self, keys: Iterable[bytes]) -> List[Optional[bytes]]:
        return [self.get(k) for k in keys]

    def batch(self, beg: bytes, end: bytes, batch_size: int) -> Iterable[List[KV]]:
        """Stream a key range in batches (reference kvs/scanner.rs role)."""
        cur = beg
        while True:
            chunk = self.scan(cur, end, batch_size)
            if not chunk:
                return
            yield chunk
            if len(chunk) < batch_size:
                return
            cur = chunk[-1][0] + b"\x00"


class BackendDatastore(ABC):
    """Backend root: a factory of transactions."""

    @abstractmethod
    def transaction(self, write: bool) -> BackendTransaction: ...

    def close(self) -> None:
        pass
