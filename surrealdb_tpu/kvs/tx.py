"""Transaction: catalog-aware wrapper over a backend transaction.

Role of the reference's cached Transaction + Transactor pair (reference:
core/src/kvs/tx.rs:42, core/src/kvs/tr.rs:76): raw KV verbs plus ~70 typed
catalog accessors with a per-transaction cache, changefeed buffering completed
at commit, and record/graph helpers.

Definitions (namespace/database/table/field/index/...) are stored as plain
dicts (produced by the DEFINE statement AST) packed with the value codec.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import DbNotFoundError, NsNotFoundError, TbNotFoundError
from surrealdb_tpu.utils.ser import pack, unpack

from .api import KV, BackendTransaction
from .vs import Oracle


class Transaction:
    def __init__(self, backend: BackendTransaction, oracle: Oracle, clock, graph_mirrors=None):
        self.tr = backend
        self.oracle = oracle
        self.clock = clock
        self.cache: Dict[bytes, Any] = {}
        # changefeed buffer: (ns, db, tb) -> list of mutation dicts
        self.cf_buffer: Dict[Tuple[str, str, str], List[dict]] = {}
        # index-mirror deltas buffered until commit, then applied to the
        # shared device mirrors (incremental maintenance — idx/graph_csr.py,
        # idx/knn.py); a cancelled transaction never touches the mirrors
        self.graph_deltas: List[tuple] = []
        self.vector_deltas: List[tuple] = []
        self.ft_deltas: List[tuple] = []
        # tables whose RECORD keyspace this txn wrote (set_record/del_record/
        # bulk ingest) + coarser dropped scopes (REMOVE ns/db/table): at
        # commit these bump the columnar-mirror version counters so a stale
        # column mask can never serve (idx/column_mirror.py protocol)
        self.touched_tables: set = set()
        self.touched_scopes: set = set()
        # tables written ROW-AT-A-TIME (set_record/del_record/raw deletes):
        # a bulk column delta for such a table is not the complete picture
        # of this txn's writes, so the delta-feed must decline it
        self.touched_row_tables: set = set()
        # bulk ingest delta-feed blocks: (key3, ids, enc_keys, docs) handed
        # to ColumnMirrors.apply_bulk after a successful backend commit
        self.column_deltas: List[tuple] = []
        self._graph_mirrors = graph_mirrors
        self._column_mirrors = None  # set by Datastore.transaction
        self._group = None  # set by Datastore.transaction (GroupCommit)
        self._index_stores = None  # set by Datastore.transaction
        # callbacks run strictly after a successful commit (mirror drops on
        # REMOVE …— running them at statement time would let a concurrent
        # rebuild resurrect state the uncommitted delete was about to erase)
        self._on_commit: List = []
        self._commit_lock = None  # set by Datastore.transaction
        # HLC last-writer-wins stamping (cluster/hlc.py): the node id to
        # mint per-record write stamps under, or None (single-node mode —
        # the stamp keyspace stays empty, zero overhead)
        self.hlc_node: Optional[str] = None
        self.write = backend.write

    # ------------------------------------------------------------ lifecycle
    def __del__(self):
        """Leak detector (reference: core/src/kvs/mem/mod.rs:29-56 — the
        mem backend asserts a transaction is completed before drop). A
        transaction garbage-collected unfinished is an engine bug: its
        buffered writes silently vanish and its MVCC snapshot pins the
        version-chain GC horizon. Count it, release the snapshot, warn —
        and raise under pytest, which surfaces as a loud unraisable-
        exception traceback + PytestUnraisableExceptionWarning (a raise in
        __del__ cannot fail the test itself, and GC timing may attribute
        it to a later test than the leaker)."""
        try:
            tr = self.tr
            if tr.done:
                return
            leaked_write = bool(self.write)
            tr.cancel()  # always release the snapshot refcount
            if not leaked_write:
                return
            import warnings

            from surrealdb_tpu import cnf, telemetry

            telemetry.inc("unfinished_txns")
            msg = (
                "write transaction garbage-collected with uncommitted writes "
                "(missing commit()/cancel())"
            )
            if cnf.under_pytest():
                raise RuntimeError(msg)
            warnings.warn(msg, ResourceWarning, stacklevel=2)
        except (AttributeError, ImportError, TypeError):
            pass  # interpreter shutdown: modules may already be torn down

    def commit(self) -> None:
        # write commits coalesce through the datastore's GroupCommit flusher
        # (kvs/ds.py): same semantics — this call still returns only after
        # THIS transaction's backend commit (or conflict error) — but a
        # stream/burst of bulk commits drains as one flush: one commit-lock
        # hold, combined per-table version bumps and ONE combined column
        # delta application
        group = self._group
        if group is not None and self.write and not self.done:
            if group.submit(self):
                return
        self.commit_direct()

    def commit_direct(self, column_sink=None) -> None:
        from surrealdb_tpu import faults, telemetry

        # chaos hook: a commit that fails HERE fails before the backend
        # commit — the caller sees the error and the write provably did
        # not land (the no-lost-acknowledged-writes invariant's dual)
        faults.fire("kvs.commit")
        # the kvs level of the request's span tree (+ a write-labeled
        # duration histogram): commit-lock waits and mirror-delta
        # application show up here when they stall a query
        with telemetry.span("txn_commit", write=str(bool(self.write)).lower()):
            self.complete_changes()
            # backend commit + mirror-delta application must be one atomic
            # unit across threads: without the datastore-level lock two
            # committing transactions could apply their deltas in the
            # opposite order of their backend commits and leave shared
            # mirrors diverged from KV
            if self._commit_lock is not None and (
                self.graph_deltas
                or self.vector_deltas
                or self.ft_deltas
                or self._on_commit
                or self.touched_tables
                or self.touched_scopes
            ):
                if column_sink is not None:
                    # group-commit leader: already inside the commit lock
                    from surrealdb_tpu.utils import locks as _locks

                    _locks.assert_held(self._commit_lock, "group commit drain")
                    self._commit_and_apply(column_sink)
                else:
                    with self._commit_lock:
                        self._commit_and_apply()
            else:
                self._commit_and_apply(column_sink)

    def _commit_and_apply(self, column_sink=None) -> None:
        cm = self._column_mirrors
        if cm is not None and (self.touched_tables or self.touched_scopes):
            # BEFORE the backend commit (and under the datastore commit
            # lock, see commit()): any reader whose snapshot will include
            # these writes then provably sees the bumped version too
            if self._commit_lock is not None:
                from surrealdb_tpu.utils import locks as _locks

                _locks.assert_held(
                    self._commit_lock, "column_mirror.versions (commit bump)"
                )
            cm.invalidate(self.touched_tables, self.touched_scopes)
        self.tr.commit()
        touched, self.touched_tables = self.touched_tables, set()
        self.touched_scopes = set()
        if cm is not None and touched:
            if column_sink is not None:
                # group-commit leader combines the whole flush's deltas
                # into one application pass after every backend commit
                column_sink.add(self, touched)
            else:
                self._apply_column_deltas(cm, touched)
        self.column_deltas = []
        if self.graph_deltas and self._graph_mirrors is not None:
            self._graph_mirrors.apply_deltas(self.graph_deltas)
            self.graph_deltas = []
        if self.vector_deltas and self._index_stores is not None:
            from surrealdb_tpu import faults

            # chaos hook AFTER the backend commit: an injected failure here
            # exercises the mirror-diverged recovery story (the commit is
            # durable; a stale vector mirror must rebuild, never serve)
            faults.fire("vector.delta_apply")
            for ns, db, tb, name, rid, vec in self.vector_deltas:
                mirror = self._index_stores.get(ns, db, tb, name)
                if mirror is None:
                    continue
                if isinstance(rid, list):
                    # bulk block: one lock hold + one [B, D] array append
                    if hasattr(mirror, "apply_many"):
                        mirror.apply_many(rid, vec)
                    elif hasattr(mirror, "apply"):
                        for r, v in zip(rid, vec):
                            mirror.apply(r, v)
                elif hasattr(mirror, "apply"):
                    # apply() buffers during a build and no-ops when unbuilt
                    mirror.apply(rid, vec)
            self.vector_deltas = []
        if self.ft_deltas and self._index_stores is not None:
            for d in self.ft_deltas:
                mirror = self._index_stores.get(d[1], d[2], d[3], d[4])
                if mirror is None:
                    continue
                if d[0] == "doc" and hasattr(mirror, "apply_ft"):
                    mirror.apply_ft(*d[5:])
                elif d[0] == "bulk" and hasattr(mirror, "apply_ft_bulk"):
                    mirror.apply_ft_bulk(*d[5:])
            self.ft_deltas = []
        for fn in self._on_commit:
            fn()
        self._on_commit = []

    def _apply_column_deltas(self, cm, touched) -> None:
        """Post-commit mirror upkeep for this txn's bulk blocks: tables whose
        delta applied cleanly serve the mirror immediately and skip the
        debounced re-scan rebuild; everything else falls back to it."""
        applied: set = set()
        if self.column_deltas:
            cv = getattr(self.tr, "commit_version", None)
            by_tb: Dict[tuple, List[tuple]] = {}
            for key3, ids, eks, docs in self.column_deltas:
                by_tb.setdefault(key3, []).append((ids, eks, docs))
            for key3, parts in by_tb.items():
                try:
                    ok = (
                        key3 in touched
                        and key3 not in self.touched_row_tables
                        and cm.apply_bulk(key3, parts, 1, cv)
                    )
                except Exception:
                    # a delta-apply failure must never fail the COMMIT —
                    # the KV write is already durable; fall back to the
                    # debounced rebuild (the stale mirror cannot serve:
                    # its version no longer matches)
                    ok = False
                if ok:
                    applied.add(key3)
        left = touched - applied
        if left:
            cm.schedule_rebuild(left)

    def on_commit(self, fn) -> None:
        """Defer a side effect until this transaction has committed."""
        self._on_commit.append(fn)

    # ------------------------------------------------------------ savepoints
    def savepoint(self):
        """Mark the uncommitted state so a mid-record failure can roll back
        just its own writes (role of the reference's kvs savepoints backing
        the RetryWithId protocol, doc/process.rs:24-120). O(1): the backend
        records an undo log from here on; delta buffers are append-only so
        their lengths suffice."""
        tr = self.tr
        if getattr(tr, "undo", None) is None:
            tr.undo = []
        return (
            len(tr.undo),
            {k: len(v) for k, v in self.cf_buffer.items()},
            len(self.graph_deltas),
            len(self.vector_deltas),
            len(self.ft_deltas),
            len(self._on_commit),
            len(self.column_deltas),
        )

    def rollback_to(self, sp) -> None:
        n_undo, cf_lens, ng, nv, nf, noc, ncd = sp
        tr = self.tr
        undo = getattr(tr, "undo", None)
        if undo is not None:
            from surrealdb_tpu.kvs.mem import _ABSENT

            for key, prev in reversed(undo[n_undo:]):
                if prev is _ABSENT:
                    tr.writes.pop(key, None)
                else:
                    tr.writes[key] = prev
            del undo[n_undo:]
        for k in list(self.cf_buffer):
            if k in cf_lens:
                del self.cf_buffer[k][cf_lens[k] :]
            else:
                del self.cf_buffer[k]
        self.graph_deltas = self.graph_deltas[:ng]
        self.vector_deltas = self.vector_deltas[:nv]
        self.ft_deltas = self.ft_deltas[:nf]
        self._on_commit = self._on_commit[:noc]
        self.column_deltas = self.column_deltas[:ncd]
        # catalog entries written in the rolled-back span (ensure_tb etc.)
        # would otherwise survive in the cache while their KV rows are gone
        self.cache.clear()

    def graph_delta(self, ns, db, src_tb, d: bytes, ft: str, src, dst, add: bool) -> None:
        """Record one edge-pointer mutation for post-commit mirror upkeep."""
        self.graph_deltas.append((ns, db, src_tb, bytes(d), ft, src, dst, add))

    def vector_delta(self, ns, db, tb, name, rid, vec) -> None:
        """Record one vector-row mutation for post-commit mirror upkeep."""
        self.vector_deltas.append((ns, db, tb, name, rid, vec))

    def vector_bulk_delta(self, ns, db, tb, name, rids, vecs) -> None:
        """Record one bulk-ingested vector block ([B, D] f32) — applied as
        ONE mirror append (VectorMirror.apply_many) instead of B per-row
        lock round-trips."""
        self.vector_deltas.append((ns, db, tb, name, list(rids), vecs))

    def bulk_column_delta(self, ns, db, tb, ids, enc_keys, docs) -> None:
        """Record one bulk op's decoded rows for the column-mirror delta
        feed (idx/column_mirror.py apply_bulk): the batch was decoded once
        by doc/bulk.py, so the mirror appends typed blocks at commit
        instead of arming a full re-scan rebuild."""
        self.touched_tables.add((ns, db, tb))
        self.column_deltas.append(((ns, db, tb), ids, enc_keys, docs))

    def ft_delta(self, ns, db, tb, name, rid, did, old_tf, new_tf, new_len) -> None:
        """Record one full-text document mutation for post-commit mirror
        upkeep (idx/ft_mirror.py)."""
        self.ft_deltas.append(("doc", ns, db, tb, name, rid, did, old_tf, new_tf, new_len))

    def ft_bulk_delta(self, ns, db, tb, name, start, terms, lens, rids) -> None:
        """Record one bulk-ingested batch (packed chunk arrays) for
        post-commit mirror upkeep (idx/ft_mirror.py apply_ft_bulk)."""
        self.ft_deltas.append(("bulk", ns, db, tb, name, start, terms, lens, rids))

    def cancel(self) -> None:
        self.tr.cancel()

    @property
    def done(self) -> bool:
        return self.tr.done

    # ------------------------------------------------------------ raw verbs
    def get(self, key: bytes, version: Optional[int] = None) -> Optional[bytes]:
        return self.tr.get(key, version)

    def set(self, key: bytes, val: bytes) -> None:
        self.tr.set(key, val)

    def put(self, key: bytes, val: bytes) -> None:
        self.tr.put(key, val)

    def putc(self, key: bytes, val: bytes, chk: Optional[bytes]) -> None:
        self.tr.putc(key, val, chk)

    def delete(self, key: bytes) -> None:
        self.tr.delete(key)

    def delc(self, key: bytes, chk: Optional[bytes]) -> None:
        self.tr.delc(key, chk)

    def exists(self, key: bytes) -> bool:
        return self.tr.exists(key)

    def keys(self, beg: bytes, end: bytes, limit: int = -1) -> List[bytes]:
        return self.tr.keys(beg, end, limit)

    def scan(self, beg: bytes, end: bytes, limit: int = -1) -> List[KV]:
        return self.tr.scan(beg, end, limit)

    def batch(self, beg: bytes, end: bytes, batch_size: int) -> Iterable[List[KV]]:
        return self.tr.batch(beg, end, batch_size)

    def delr(self, beg: bytes, end: bytes) -> None:
        self.tr.delr(beg, end)

    def scan_prefix(self, prefix: bytes, limit: int = -1) -> List[KV]:
        from surrealdb_tpu.key.encode import prefix_end

        return self.tr.scan(prefix, prefix_end(prefix), limit)

    # ------------------------------------------------------------ obj verbs
    def get_obj(self, key: bytes) -> Optional[Any]:
        raw = self.tr.get(key)
        return None if raw is None else unpack(raw)

    def set_obj(self, key: bytes, val: Any) -> None:
        self.tr.set(key, pack(val))

    def _cached(self, key: bytes, loader):
        if key in self.cache:
            return self.cache[key]
        v = loader()
        self.cache[key] = v
        return v

    def _get_obj_cached(self, key: bytes) -> Optional[Any]:
        return self._cached(key, lambda: self.get_obj(key))

    def _scan_objs(self, prefix: bytes) -> List[Any]:
        from surrealdb_tpu.key.encode import prefix_end

        return [unpack(v) for _, v in self.tr.scan(prefix, prefix_end(prefix))]

    # ------------------------------------------------------------ namespaces
    def all_ns(self) -> List[dict]:
        return self._scan_objs(keys.namespace_prefix())

    def get_ns(self, ns: str) -> Optional[dict]:
        return self._get_obj_cached(keys.namespace(ns))

    def expect_ns(self, ns: str) -> dict:
        d = self.get_ns(ns)
        if d is None:
            raise NsNotFoundError(ns)
        return d

    def put_ns(self, ns: str, d: dict) -> None:
        k = keys.namespace(ns)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_ns(self, ns: str) -> None:
        k = keys.namespace(ns)
        self.tr.delete(k)
        self.cache.pop(k, None)

    def ensure_ns(self, ns: str) -> dict:
        d = self.get_ns(ns)
        if d is None:
            d = {"name": ns, "comment": None}
            self.put_ns(ns, d)
        return d

    # ------------------------------------------------------------ databases
    def all_db(self, ns: str) -> List[dict]:
        return self._scan_objs(keys.database_prefix(ns))

    def get_db(self, ns: str, db: str) -> Optional[dict]:
        return self._get_obj_cached(keys.database(ns, db))

    def expect_db(self, ns: str, db: str) -> dict:
        d = self.get_db(ns, db)
        if d is None:
            raise DbNotFoundError(db)
        return d

    def put_db(self, ns: str, db: str, d: dict) -> None:
        k = keys.database(ns, db)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_db(self, ns: str, db: str) -> None:
        k = keys.database(ns, db)
        self.tr.delete(k)
        self.cache.pop(k, None)

    def ensure_db(self, ns: str, db: str) -> dict:
        self.ensure_ns(ns)
        d = self.get_db(ns, db)
        if d is None:
            d = {"name": db, "comment": None, "changefeed": None}
            self.put_db(ns, db, d)
        return d

    # ------------------------------------------------------------ tables
    def all_tb(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.table_prefix(ns, db))

    def get_tb(self, ns: str, db: str, tb: str) -> Optional[dict]:
        return self._get_obj_cached(keys.table(ns, db, tb))

    def expect_tb(self, ns: str, db: str, tb: str) -> dict:
        d = self.get_tb(ns, db, tb)
        if d is None:
            raise TbNotFoundError(tb)
        return d

    def put_tb(self, ns: str, db: str, tb: str, d: dict) -> None:
        k = keys.table(ns, db, tb)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_tb(self, ns: str, db: str, tb: str) -> None:
        k = keys.table(ns, db, tb)
        self.tr.delete(k)
        self.cache.pop(k, None)

    def ensure_tb(self, ns: str, db: str, tb: str) -> dict:
        self.ensure_db(ns, db)
        d = self.get_tb(ns, db, tb)
        if d is None:
            d = {
                "name": tb,
                "drop": False,
                "schemafull": False,
                "kind": "ANY",  # ANY | NORMAL | RELATION
                "relation_in": None,
                "relation_out": None,
                "enforced": False,
                "view": None,
                "permissions": None,
                "changefeed": None,
                "comment": None,
            }
            self.put_tb(ns, db, tb, d)
        return d

    # ------------------------------------------------------------ fields
    def all_tb_fields(self, ns: str, db: str, tb: str) -> List[dict]:
        return self._cached(
            keys.field_prefix(ns, db, tb),
            lambda: self._scan_objs(keys.field_prefix(ns, db, tb)),
        )

    def get_tb_field(self, ns: str, db: str, tb: str, fd: str) -> Optional[dict]:
        return self.get_obj(keys.field(ns, db, tb, fd))

    def put_tb_field(self, ns: str, db: str, tb: str, fd: str, d: dict) -> None:
        self.set_obj(keys.field(ns, db, tb, fd), d)
        self.cache.pop(keys.field_prefix(ns, db, tb), None)

    def del_tb_field(self, ns: str, db: str, tb: str, fd: str) -> None:
        self.tr.delete(keys.field(ns, db, tb, fd))
        self.cache.pop(keys.field_prefix(ns, db, tb), None)

    # ------------------------------------------------------------ indexes
    def all_tb_indexes(self, ns: str, db: str, tb: str) -> List[dict]:
        return self._cached(
            keys.index_def_prefix(ns, db, tb),
            lambda: self._scan_objs(keys.index_def_prefix(ns, db, tb)),
        )

    def get_tb_index(self, ns: str, db: str, tb: str, ix: str) -> Optional[dict]:
        return self.get_obj(keys.index_def(ns, db, tb, ix))

    def put_tb_index(self, ns: str, db: str, tb: str, ix: str, d: dict) -> None:
        self.set_obj(keys.index_def(ns, db, tb, ix), d)
        self.cache.pop(keys.index_def_prefix(ns, db, tb), None)

    def del_tb_index(self, ns: str, db: str, tb: str, ix: str) -> None:
        self.tr.delete(keys.index_def(ns, db, tb, ix))
        self.cache.pop(keys.index_def_prefix(ns, db, tb), None)

    # ------------------------------------------------------------ events
    def all_tb_events(self, ns: str, db: str, tb: str) -> List[dict]:
        return self._cached(
            keys.event_prefix(ns, db, tb),
            lambda: self._scan_objs(keys.event_prefix(ns, db, tb)),
        )

    # ------------------------------------------------------------ live queries
    def all_tb_lives(self, ns: str, db: str, tb: str) -> List[bytes]:
        """Raw packed live-query records for a table, catalog-cached so the
        per-record mutation hook doesn't rescan the keyspace on every write
        (reference: doc/lives.rs lq caching via Transaction)."""
        pre = keys.live_query_prefix(ns, db, tb)
        from surrealdb_tpu.key.encode import prefix_end

        return self._cached(
            pre, lambda: [raw for _, raw in self.scan(pre, prefix_end(pre))]
        )

    def invalidate_tb_lives(self, ns: str, db: str, tb: str) -> None:
        self.cache.pop(keys.live_query_prefix(ns, db, tb), None)

    def get_tb_event(self, ns: str, db: str, tb: str, ev: str) -> Optional[dict]:
        return self.get_obj(keys.event(ns, db, tb, ev))

    def put_tb_event(self, ns: str, db: str, tb: str, ev: str, d: dict) -> None:
        self.set_obj(keys.event(ns, db, tb, ev), d)
        self.cache.pop(keys.event_prefix(ns, db, tb), None)

    def del_tb_event(self, ns: str, db: str, tb: str, ev: str) -> None:
        self.tr.delete(keys.event(ns, db, tb, ev))
        self.cache.pop(keys.event_prefix(ns, db, tb), None)

    # ------------------------------------------------------------ views
    def all_tb_views(self, ns: str, db: str, tb: str) -> List[dict]:
        """Foreign tables: views defined AS SELECT ... FROM tb."""
        return self._cached(
            keys.foreign_table_prefix(ns, db, tb),
            lambda: self._scan_objs(keys.foreign_table_prefix(ns, db, tb)),
        )

    def put_tb_view(self, ns: str, db: str, tb: str, ft: str, d: dict) -> None:
        self.set_obj(keys.foreign_table(ns, db, tb, ft), d)
        self.cache.pop(keys.foreign_table_prefix(ns, db, tb), None)

    def del_tb_view(self, ns: str, db: str, tb: str, ft: str) -> None:
        self.tr.delete(keys.foreign_table(ns, db, tb, ft))
        self.cache.pop(keys.foreign_table_prefix(ns, db, tb), None)

    # ------------------------------------------------------------ analyzers
    def all_az(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.analyzer_prefix(ns, db))

    def get_az(self, ns: str, db: str, az: str) -> Optional[dict]:
        return self._get_obj_cached(keys.analyzer(ns, db, az))

    def put_az(self, ns: str, db: str, az: str, d: dict) -> None:
        k = keys.analyzer(ns, db, az)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_az(self, ns: str, db: str, az: str) -> None:
        k = keys.analyzer(ns, db, az)
        self.tr.delete(k)
        self.cache.pop(k, None)

    # ------------------------------------------------------------ functions
    def all_fc(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.function_prefix(ns, db))

    def get_fc(self, ns: str, db: str, fc: str) -> Optional[dict]:
        return self._get_obj_cached(keys.function(ns, db, fc))

    def put_fc(self, ns: str, db: str, fc: str, d: dict) -> None:
        k = keys.function(ns, db, fc)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_fc(self, ns: str, db: str, fc: str) -> None:
        k = keys.function(ns, db, fc)
        self.tr.delete(k)
        self.cache.pop(k, None)

    # ------------------------------------------------------------ params
    def all_pa(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.param_prefix(ns, db))

    def get_pa(self, ns: str, db: str, pa: str) -> Optional[dict]:
        return self._get_obj_cached(keys.param(ns, db, pa))

    def put_pa(self, ns: str, db: str, pa: str, d: dict) -> None:
        k = keys.param(ns, db, pa)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_pa(self, ns: str, db: str, pa: str) -> None:
        k = keys.param(ns, db, pa)
        self.tr.delete(k)
        self.cache.pop(k, None)

    # ------------------------------------------------------------ models
    def all_ml(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.model_prefix(ns, db))

    def get_ml(self, ns: str, db: str, ml: str, version: str) -> Optional[dict]:
        return self._get_obj_cached(keys.model(ns, db, ml, version))

    def put_ml(self, ns: str, db: str, ml: str, version: str, d: dict) -> None:
        k = keys.model(ns, db, ml, version)
        self.set_obj(k, d)
        self.cache[k] = d

    def del_ml(self, ns: str, db: str, ml: str, version: str) -> None:
        k = keys.model(ns, db, ml, version)
        self.tr.delete(k)
        self.cache.pop(k, None)

    # ------------------------------------------------------------ users
    def get_root_user(self, user: str) -> Optional[dict]:
        return self.get_obj(keys.root_user(user))

    def all_root_users(self) -> List[dict]:
        return self._scan_objs(keys.root_user_prefix())

    def put_root_user(self, user: str, d: dict) -> None:
        self.set_obj(keys.root_user(user), d)

    def del_root_user(self, user: str) -> None:
        self.tr.delete(keys.root_user(user))

    def get_ns_user(self, ns: str, user: str) -> Optional[dict]:
        return self.get_obj(keys.ns_user(ns, user))

    def all_ns_users(self, ns: str) -> List[dict]:
        return self._scan_objs(keys.ns_user_prefix(ns))

    def put_ns_user(self, ns: str, user: str, d: dict) -> None:
        self.set_obj(keys.ns_user(ns, user), d)

    def del_ns_user(self, ns: str, user: str) -> None:
        self.tr.delete(keys.ns_user(ns, user))

    def get_db_user(self, ns: str, db: str, user: str) -> Optional[dict]:
        return self.get_obj(keys.db_user(ns, db, user))

    def all_db_users(self, ns: str, db: str) -> List[dict]:
        return self._scan_objs(keys.db_user_prefix(ns, db))

    def put_db_user(self, ns: str, db: str, user: str, d: dict) -> None:
        self.set_obj(keys.db_user(ns, db, user), d)

    def del_db_user(self, ns: str, db: str, user: str) -> None:
        self.tr.delete(keys.db_user(ns, db, user))

    # ------------------------------------------------------------ accesses
    def get_access(self, level: tuple, ac: str) -> Optional[dict]:
        return self.get_obj(self._access_key(level, ac))

    def all_accesses(self, level: tuple) -> List[dict]:
        if len(level) == 0:
            return self._scan_objs(keys.root_access_prefix())
        if len(level) == 1:
            return self._scan_objs(keys.ns_access_prefix(level[0]))
        return self._scan_objs(keys.db_access_prefix(level[0], level[1]))

    def put_access(self, level: tuple, ac: str, d: dict) -> None:
        self.set_obj(self._access_key(level, ac), d)

    def del_access(self, level: tuple, ac: str) -> None:
        self.tr.delete(self._access_key(level, ac))

    # ------------------------------------------------------------ access grants
    def get_grant(self, level: tuple, ac: str, gr: str) -> Optional[dict]:
        return self.get_obj(keys.access_grant(level, ac, gr))

    def put_grant(self, level: tuple, ac: str, gr: str, d: dict) -> None:
        self.set_obj(keys.access_grant(level, ac, gr), d)

    def all_grants(self, level: tuple, ac: str) -> List[dict]:
        return self._scan_objs(keys.access_grant_prefix(level, ac))

    def del_grant(self, level: tuple, ac: str, gr: str) -> None:
        self.tr.delete(keys.access_grant(level, ac, gr))

    @staticmethod
    def _access_key(level: tuple, ac: str) -> bytes:
        if len(level) == 0:
            return keys.root_access(ac)
        if len(level) == 1:
            return keys.ns_access(level[0], ac)
        return keys.db_access(level[0], level[1], ac)

    # ------------------------------------------------------------ records
    def touch_table(self, ns: str, db: str, tb: str) -> None:
        """Mark a table's record keyspace as written row-at-a-time by this
        transaction (columnar-mirror invalidation; raw-write paths like the
        view maintainer call this explicitly)."""
        self.touched_tables.add((ns, db, tb))
        self.touched_row_tables.add((ns, db, tb))

    def touch_table_bulk(self, ns: str, db: str, tb: str) -> None:
        """Mark a table written ONLY through the bulk block path: versions
        still bump at commit, but the write-set stays representable as a
        column delta (touch_table would poison the delta feed)."""
        self.touched_tables.add((ns, db, tb))

    def touch_scope(self, scope: tuple) -> None:
        """Coarse invalidation for REMOVE NAMESPACE/DATABASE/TABLE."""
        self.touched_scopes.add(tuple(scope))

    def get_record(self, ns: str, db: str, tb: str, id_: Any) -> Optional[dict]:
        raw = self.tr.get(keys.thing(ns, db, tb, id_))
        return None if raw is None else unpack(raw)

    def set_record(self, ns: str, db: str, tb: str, id_: Any, doc: dict) -> None:
        self.touched_tables.add((ns, db, tb))
        self.touched_row_tables.add((ns, db, tb))
        self.tr.set(keys.thing(ns, db, tb, id_), pack(doc))
        if self.hlc_node is not None:
            self.mint_stamp(ns, db, tb, id_)

    def del_record(self, ns: str, db: str, tb: str, id_: Any) -> None:
        self.touched_tables.add((ns, db, tb))
        self.touched_row_tables.add((ns, db, tb))
        self.tr.delete(keys.thing(ns, db, tb, id_))
        if self.hlc_node is not None:
            # tombstone: anti-entropy must tell "deleted" from "never
            # written", or a stale replica's copy would resurrect the record
            self.mint_stamp(ns, db, tb, id_, dead=True)

    # ------------------------------------------------------------ HLC stamps
    def mint_stamp(self, ns: str, db: str, tb: str, id_: Any, dead: bool = False) -> None:
        """Mint + write this record's LWW stamp under THIS node's identity
        (the cluster write path; no-op shape — callers gate on hlc_node)."""
        from surrealdb_tpu import faults
        from surrealdb_tpu.cluster import hlc

        # chaos hook BEFORE the mint: an injected failure here fails the
        # statement pre-commit — the write provably did not land half-stamped
        faults.fire("cluster.hlc.stamp")
        self.put_stamp(ns, db, tb, id_, hlc.now(self.hlc_node), dead=dead)

    def put_stamp(
        self, ns: str, db: str, tb: str, id_: Any, stamp, dead: bool = False
    ) -> None:
        """Write an EXPLICIT stamp (repair/migration apply: the origin
        replica's stamp must ride along, not be re-minted)."""
        from surrealdb_tpu.cluster import hlc

        meta: Dict[str, Any] = {"hlc": hlc.encode(stamp)}
        if dead:
            meta["dead"] = True
        self.tr.set(keys.record_meta(ns, db, tb, id_), pack(meta))

    def get_record_meta(self, ns: str, db: str, tb: str, id_: Any) -> Optional[dict]:
        """The record's replication meta ({"hlc": [...], "dead"?: true}),
        or None when never stamped (pre-cluster data)."""
        raw = self.tr.get(keys.record_meta(ns, db, tb, id_))
        return None if raw is None else unpack(raw)

    def record_exists(self, ns: str, db: str, tb: str, id_: Any) -> bool:
        return self.tr.exists(keys.thing(ns, db, tb, id_))

    # ------------------------------------------------------------ changefeed
    def buffer_change(self, ns: str, db: str, tb: str, mutation: dict) -> None:
        self.cf_buffer.setdefault((ns, db, tb), []).append(mutation)

    def buffer_bulk_change(self, ns: str, db: str, tb: str, rids) -> None:
        """ONE compact changefeed mutation for a whole bulk op: the record
        ids only, not a per-row copy of every document. SHOW CHANGES
        expands it reader-side (cf/reader.py) with a versioned read at the
        entry's own commit version, so replay values are exactly the
        committed documents."""
        self.cf_buffer.setdefault((ns, db, tb), []).append(
            {"bulk_ids": [r.id for r in rids]}
        )

    def complete_changes(self) -> None:
        """Write buffered changefeed mutations under versionstamped keys
        (reference Transactor::complete_changes, kvs/tr.rs:600)."""
        if not self.cf_buffer:
            return
        by_db: Dict[Tuple[str, str], Dict[str, List[dict]]] = {}
        for (ns, db, tb), muts in self.cf_buffer.items():
            by_db.setdefault((ns, db), {}).setdefault(tb, []).extend(muts)
        for (ns, db), tables in by_db.items():
            now = self.clock.now_nanos()
            vs = self.oracle.next_vs(now)
            # ts enables datetime SINCE filtering and retention GC
            self.tr.set(
                keys.change(ns, db, vs), pack({"vs": vs, "ts": now, "tables": tables})
            )
        self.cf_buffer = {}
