"""Logical export/import of a database as .surql text.

Role of the reference's export machinery (reference: core/src/kvs/export.rs,
ds.rs:1115-1175): stream OPTION header, catalog DEFINEs, then table records
as INSERT batches; import = re-execution of the statements.
"""

from __future__ import annotations

from typing import List

from surrealdb_tpu import cnf
from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.value import Thing, format_value
from surrealdb_tpu.utils.ser import unpack


def export_database(ds, session) -> str:
    from surrealdb_tpu.dbs.executor import Executor
    from surrealdb_tpu.dbs.info import _r_az, _r_fc, _r_fd, _r_ix, _r_pa, _r_tb, _r_ev

    ns, db = session.ns, session.db
    out: List[str] = [
        "-- ------------------------------",
        "-- OPTION",
        "-- ------------------------------",
        "",
        "OPTION IMPORT;",
        "",
    ]
    txn = ds.transaction(False)
    try:
        def section(title: str):
            out.extend([
                "-- ------------------------------",
                f"-- {title}",
                "-- ------------------------------",
                "",
            ])

        for az in txn.all_az(ns, db):
            section(f"ANALYZER {az['name']}")
            out.append(_r_az(az) + ";")
        for fc in txn.all_fc(ns, db):
            section(f"FUNCTION fn::{fc['name']}")
            out.append(_r_fc(fc) + ";")
        for pa in txn.all_pa(ns, db):
            section(f"PARAM ${pa['name']}")
            out.append(_r_pa(pa) + ";")

        for tb in txn.all_tb(ns, db):
            name = tb["name"]
            section(f"TABLE: {name}")
            out.append(_r_tb(tb) + ";")
            for fd in txn.all_tb_fields(ns, db, name):
                out.append(_r_fd(fd) + ";")
            for ix in txn.all_tb_indexes(ns, db, name):
                out.append(_r_ix(ix) + ";")
            for ev in txn.all_tb_events(ns, db, name):
                out.append(_r_ev(ev) + ";")
            out.append("")

            # record data in INSERT batches; edge records go through
            # INSERT RELATION so import re-creates the graph pointers
            pre = keys.thing_prefix(ns, db, name)
            batch: List[str] = []
            for chunk in txn.batch(pre, prefix_end(pre), cnf.EXPORT_BATCH_SIZE):
                rows, rel_rows = [], []
                for _, raw in chunk:
                    doc = unpack(raw)
                    is_edge = isinstance(doc, dict) and isinstance(
                        doc.get("in"), Thing
                    ) and isinstance(doc.get("out"), Thing)
                    (rel_rows if is_edge else rows).append(format_value(doc))
                if rows:
                    batch.append(f"INSERT [{', '.join(rows)}];")
                if rel_rows:
                    batch.append(f"INSERT RELATION [{', '.join(rel_rows)}];")
            if batch:
                section(f"TABLE DATA: {name}")
                out.extend(batch)
                out.append("")
    finally:
        txn.cancel()
    return "\n".join(out) + "\n"


def import_database(ds, session, text: str) -> List[dict]:
    """Re-execute an exported .surql script (reference importer role)."""
    return ds.execute(text, session)
