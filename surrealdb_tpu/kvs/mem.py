"""In-memory MVCC ordered KV store.

Role of the reference's `mem` backend (reference: core/src/kvs/mem/mod.rs) but
designed differently: a dict of key -> version chain gives true snapshot
isolation (each transaction reads as-of its begin version) plus versioned
reads (`scan_all_versions` analog), with optimistic first-committer-wins
conflict detection at commit — the semantics SurrealDB gets from surrealkv.
Ordering for range scans comes from a SortedList of keys maintained alongside
the dict: large commit batches merge into it wholesale (SortedList.update's
bulk path) instead of paying one insort per key — the difference between
~7µs and ~0.5µs per key during bulk ingest. Single-process; commits are
applied atomically (no awaits inside).
"""

from __future__ import annotations

from surrealdb_tpu.utils import locks as _locks
from typing import Dict, List, Optional, Tuple

try:
    from sortedcontainers import SortedList
except ImportError:  # gate the missing dep: minimal bisect-backed fallback
    from bisect import bisect_left, bisect_right, insort

    class SortedList:  # type: ignore[no-redef]
        """Drop-in subset of sortedcontainers.SortedList (add/update/remove/
        irange) over a plain sorted list. update() keeps the bulk-merge
        property that matters here: one sort of the combined batch instead
        of per-key insorts."""

        __slots__ = ("_data",)

        def __init__(self, iterable=()):
            self._data = sorted(iterable)

        def add(self, value) -> None:
            insort(self._data, value)

        def update(self, iterable) -> None:
            items = list(iterable)
            if not items:
                return
            if len(items) <= 8:
                for v in items:
                    insort(self._data, v)
            else:
                self._data.extend(items)
                self._data.sort()

        def remove(self, value) -> None:
            i = bisect_left(self._data, value)
            if i == len(self._data) or self._data[i] != value:
                raise ValueError(f"{value!r} not in list")
            del self._data[i]

        def irange(self, minimum=None, maximum=None, inclusive=(True, True)):
            data = self._data
            if minimum is None:
                lo = 0
            else:
                lo = (
                    bisect_left(data, minimum)
                    if inclusive[0]
                    else bisect_right(data, minimum)
                )
            if maximum is None:
                hi = len(data)
            else:
                hi = (
                    bisect_right(data, maximum)
                    if inclusive[1]
                    else bisect_left(data, maximum)
                )
            # lazy, like sortedcontainers: _merged_range islices a CHUNK at a
            # time with an advancing cursor — materializing data[lo:hi] here
            # would copy the whole remaining range per chunk (quadratic scan)
            return (data[i] for i in range(lo, hi))

        def __len__(self) -> int:
            return len(self._data)

        def __iter__(self):
            return iter(self._data)

        def __contains__(self, value) -> bool:
            i = bisect_left(self._data, value)
            return i < len(self._data) and self._data[i] == value


from surrealdb_tpu.err import TxConflictError
from .api import KV, BackendDatastore, BackendTransaction


class MemDatastore(BackendDatastore):
    def __init__(self):
        # key -> list[(version, value|None)] ascending by version; None = tombstone
        self.data: Dict[bytes, list] = {}
        self.sorted_keys: SortedList = SortedList()
        self.version: int = 0
        self.lock = _locks.RLock("kvs.mem")
        self.active: Dict[int, int] = {}  # snapshot version -> refcount

    # -- snapshots ---------------------------------------------------------
    def _acquire_snapshot(self) -> int:
        with self.lock:
            v = self.version
            self.active[v] = self.active.get(v, 0) + 1
            return v

    def _release_snapshot(self, v: int) -> None:
        with self.lock:
            n = self.active.get(v, 0) - 1
            if n <= 0:
                self.active.pop(v, None)
            else:
                self.active[v] = n

    def transaction(self, write: bool) -> "MemTransaction":
        return MemTransaction(self, write)

    # -- version-chain helpers --------------------------------------------
    def _read_at(self, key: bytes, snapshot: int) -> Optional[bytes]:
        with self.lock:  # gc() truncates chains in place
            chain = self.data.get(key)
            if not chain:
                return None
            # chains are short; linear scan from the end
            for ver, val in reversed(chain):
                if ver <= snapshot:
                    return val
            return None

    def _latest_version(self, key: bytes) -> int:
        with self.lock:
            chain = self.data.get(key)
            return chain[-1][0] if chain else 0

    def gc(self) -> None:
        """Drop version-chain entries older than the oldest active snapshot."""
        with self.lock:
            horizon = min(self.active) if self.active else self.version
            dead = []
            for key, chain in self.data.items():
                if len(chain) > 1:
                    keep_from = 0
                    for i in range(len(chain) - 1, -1, -1):
                        if chain[i][0] <= horizon:
                            keep_from = i
                            break
                    if keep_from > 0:
                        del chain[:keep_from]
                if len(chain) == 1 and chain[0][1] is None and chain[0][0] <= horizon:
                    dead.append(key)
            for key in dead:
                del self.data[key]
                self.sorted_keys.remove(key)


_ABSENT = object()  # "key had no local write" marker in the undo log


class MemTransaction(BackendTransaction):
    def __init__(self, store: MemDatastore, write: bool):
        super().__init__(write)
        self.store = store
        self.snapshot = store._acquire_snapshot()
        self.writes: Dict[bytes, Optional[bytes]] = {}
        # savepoint undo log: (key, previous write-buffer state) per
        # mutation while recording; None = not recording (zero overhead)
        self.undo: Optional[List[tuple]] = None

    # -- lifecycle ---------------------------------------------------------
    def commit(self) -> None:
        self._check_open(self.write and bool(self.writes))
        store = self.store
        with store.lock:
            # first-committer-wins: conflict iff any written key changed
            # after our snapshot. Nothing at all committed since our snapshot
            # (store.version unchanged) ⇒ no key can have — skip the scan;
            # bulk ingest commits hundreds of thousands of keys per txn.
            data = store.data
            if store.version != self.snapshot:
                for key in self.writes:
                    chain = data.get(key)
                    if chain is not None and chain[-1][0] > self.snapshot:
                        self._finish()
                        raise TxConflictError()
            if self.writes:
                store.version += 1
                ver = store.version
                # the MVCC version this commit's writes landed at: the
                # column-mirror delta feed uses it as the served snapshot
                # floor, the changefeed batch reader as its expansion point
                self.commit_version = ver
                new_keys = []
                for key, val in self.writes.items():
                    chain = data.get(key)
                    if chain is None:
                        data[key] = [(ver, val)]
                        new_keys.append(key)
                    else:
                        chain.append((ver, val))
                if new_keys:
                    # bulk merge: SortedList.update sorts the batch and
                    # merges wholesale when it is large relative to the list
                    store.sorted_keys.update(new_keys)
        self._finish()

    def version_of(self, key: bytes) -> Optional[int]:
        """MVCC version of the newest committed chain entry for `key`
        (None when absent) — the changefeed reader resolves a bulk entry's
        expansion point from the entry key's own commit version."""
        with self.store.lock:
            chain = self.store.data.get(key)
            return chain[-1][0] if chain else None

    def oldest_retained(self, key: bytes) -> Optional[bytes]:
        """Oldest committed value still in `key`'s chain (gc() compacts
        chains from the front) — the changefeed bulk-entry expansion
        fallback when its pinned version predates the GC horizon."""
        with self.store.lock:
            chain = self.store.data.get(key)
            return chain[0][1] if chain else None

    def cancel(self) -> None:
        if not self.done:
            self._finish()

    def _finish(self) -> None:
        self.done = True
        self.store._release_snapshot(self.snapshot)
        self.writes = {}

    # -- point ops ---------------------------------------------------------
    def get(self, key: bytes, version: Optional[int] = None) -> Optional[bytes]:
        self._check_open()
        if version is not None:
            return self.store._read_at(key, version)
        if key in self.writes:
            return self.writes[key]
        return self.store._read_at(key, self.snapshot)

    def set(self, key: bytes, val: bytes) -> None:
        self._check_open(True)
        if self.undo is not None:
            self.undo.append((key, self.writes.get(key, _ABSENT)))
        self.writes[key] = val

    def delete(self, key: bytes) -> None:
        self._check_open(True)
        if self.undo is not None:
            self.undo.append((key, self.writes.get(key, _ABSENT)))
        self.writes[key] = None

    # -- range ops ---------------------------------------------------------
    _RANGE_CHUNK = 4096

    def _merged_range(self, beg: bytes, end: bytes):
        """Iterate live (key, value) pairs in [beg, end) merging local writes.

        Committed keys are pulled from the SortedList in fixed chunks rather
        than materialized whole: `batch()` walks multi-million-key ranges
        (mirror builds, exports) by repeated scans with an advancing cursor,
        and materializing the full remaining range per scan made that
        quadratic — ~10^9 list appends over a 12M-posting range. Chunked
        irange keeps every scan O(limit).
        """
        from itertools import islice

        store = self.store
        local = sorted(k for k in self.writes if beg <= k < end)
        li = 0
        n_local = len(local)
        cursor = beg
        exhausted = False
        while not exhausted:
            with store.lock:
                committed = list(
                    islice(
                        store.sorted_keys.irange(cursor, end, inclusive=(True, False)),
                        self._RANGE_CHUNK,
                    )
                )
            if len(committed) < self._RANGE_CHUNK:
                exhausted = True
            for k in committed:
                while li < n_local and local[li] < k:
                    lk = local[li]
                    li += 1
                    v = self.writes[lk]
                    if v is not None:
                        yield lk, v
                if li < n_local and local[li] == k:
                    li += 1
                    v = self.writes[k]
                else:
                    v = store._read_at(k, self.snapshot)
                if v is not None:
                    yield k, v
            if committed:
                cursor = committed[-1] + b"\x00"
        while li < n_local:
            lk = local[li]
            li += 1
            v = self.writes[lk]
            if v is not None:
                yield lk, v

    def keys(self, beg: bytes, end: bytes, limit: int = -1) -> List[bytes]:
        self._check_open()
        out = []
        for k, _ in self._merged_range(beg, end):
            out.append(k)
            if limit >= 0 and len(out) >= limit:
                break
        return out

    def scan(self, beg: bytes, end: bytes, limit: int = -1) -> List[KV]:
        self._check_open()
        out = []
        for kv in self._merged_range(beg, end):
            out.append(kv)
            if limit >= 0 and len(out) >= limit:
                break
        return out
