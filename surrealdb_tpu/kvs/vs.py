"""Versionstamps: 10-byte monotone stamps (8-byte version + 2-byte sequence).

Same shape as the reference's versionstamps (reference: core/src/vs/mod.rs:17).
Used to order changefeed entries.
"""

from __future__ import annotations

import struct


def versionstamp(version: int, seq: int = 0) -> bytes:
    return struct.pack(">QH", version, seq)


def decode_versionstamp(vs: bytes) -> tuple[int, int]:
    return struct.unpack(">QH", vs)


def vs_to_u64(vs: bytes) -> int:
    return struct.unpack(">Q", vs[:8])[0]


def u64_to_vs(v: int) -> bytes:
    return struct.pack(">QH", v, 0)


class Oracle:
    """Monotone versionstamp source, one per datastore."""

    def __init__(self):
        from surrealdb_tpu.utils import locks as _locks

        self._last = 0
        self._lock = _locks.Lock("kvs.version_store")

    def next_vs(self, now_nanos: int) -> bytes:
        with self._lock:
            v = max(now_nanos, self._last + 1)
            self._last = v
            return versionstamp(v)


class SystemClock:
    def now_nanos(self) -> int:
        import time

        return time.time_ns()


class FakeClock:
    """Deterministic clock for tests (reference kvs/clock.rs SizedClock role)."""

    def __init__(self, start: int = 0, tick: int = 1):
        self.t = start
        self.tick = tick

    def now_nanos(self) -> int:
        self.t += self.tick
        return self.t
