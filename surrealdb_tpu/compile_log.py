"""Compile-event log: every XLA compile recorded, attributed, exportable.

Every distinct padded shape a jitted kernel is called with is a separate
XLA compile — seconds each on a tunneled chip, and the classic cause of an
unexplained latency swing when one is minted ON DEMAND inside a live query
instead of by the background shape warmers. This module wraps the kernel
call sites (idx/knn.py, idx/ivf.py, idx/graph_csr.py):

- the FIRST call per (subsystem, shape key) is the compile: its duration,
  subsystem, shape and mode land in a bounded event log, a
  `compile_events{subsystem,mode}` counter and an `xla_compile` duration
  histogram;
- `mode` is `prewarm` when a background warmer minted it, `on_demand` when
  it happened under (or on behalf of) a live request — in which case an
  `xla_compile` span is recorded into exactly ONE trace (the active
  request's, or the dispatch batch's first rider via the attribution
  contextvar dbs/dispatch.py sets) — the smoking gun for latency swings;
- subsequent calls count as `compile_cache{subsystem,shape,outcome=hit}`
  — riders of a coalesced batch see a cache hit, not a second compile.

Shape keys are value tuples of static dims (tile, dim, cap, k, ...), the
same things XLA keys its own cache on, so "first call per key" == "this
call traced + compiled". The log is bounded by SURREAL_COMPILE_LOG_CAP.

The registry below (KERNEL_SITES) makes the tracked sites ENUMERABLE:
every subsystem name ever passed to tracked() maps to the import path of
a `graftcheck_sites()` provider in the module that owns the kernel. The
provider declares the kernel's audit contract — representative shape
matrix, abstract-lowering builder, allowed collectives, declared output
dtypes — and `python -m scripts.graftcheck` lowers each one to
jaxpr/StableHLO and checks the GC001–GC004 contracts against the IR. A
new jitted kernel MUST register here (tests/test_graftcheck.py asserts
source-tracked subsystems ⊆ KERNEL_SITES), so it cannot ship unaudited.
"""

from __future__ import annotations

import contextvars
from surrealdb_tpu.utils import locks as _locks
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Optional, Tuple

# ---------------------------------------------------------------- registry
# subsystem -> "import.path:provider" of the module that owns the kernel.
# The provider is a zero-arg callable returning a list of audit-contract
# dicts (one per subsystem it hosts); scripts/graftcheck/registry.py
# resolves and validates them. Keys are EXACTLY the subsystem strings
# passed to tracked() — the registry-completeness test diffs the two.
KERNEL_SITES = {
    "knn_exact": "surrealdb_tpu.idx.knn:graftcheck_sites",
    "knn_sharded": "surrealdb_tpu.parallel.mesh:graftcheck_sites",
    "ivf": "surrealdb_tpu.idx.ivf:graftcheck_sites",
    "ivf_sharded": "surrealdb_tpu.parallel.mesh:graftcheck_sites",
    "graph_dense": "surrealdb_tpu.idx.graph_csr:graftcheck_sites",
    "graph_csc": "surrealdb_tpu.idx.graph_csr:graftcheck_sites",
    "graph_chain": "surrealdb_tpu.idx.graph_csr:graftcheck_sites",
    "bm25": "surrealdb_tpu.ops.bm25:graftcheck_sites",
    "ml_forward": "surrealdb_tpu.ml.model:graftcheck_sites",
}


_lock = _locks.Lock("compile_log")
_seen: set = set()  # (subsystem, shape_key) already compiled
_inflight: set = set()  # keys whose FIRST call is still inside tracked()
_events: Deque[dict] = deque(maxlen=512)  # re-bounded lazily from cnf

# dispatch attribution: the leader launches kernels with tracing detached
# (spans are re-parented per rider), so an on-demand compile under a batch
# would otherwise be unattributable. dbs/dispatch.py parks the FIRST
# rider's SpanCtx here for the duration of the launch/collect/retry call.
_attr_ctx: "contextvars.ContextVar[Optional[Any]]" = contextvars.ContextVar(
    "surreal_compile_attr", default=None
)


@contextmanager
def attribution(trace_ctx) -> Any:
    """Attribute any compile inside this block to `trace_ctx` (a tracing
    SpanCtx) when no trace is otherwise active."""
    token = _attr_ctx.set(trace_ctx)
    try:
        yield
    finally:
        _attr_ctx.reset(token)


def _cap() -> int:
    from surrealdb_tpu import cnf

    return max(cnf.COMPILE_LOG_CAP, 16)


def seen(subsystem: str, shape: Tuple) -> bool:
    with _lock:
        return (subsystem, shape) in _seen


@contextmanager
def tracked(subsystem: str, shape: Tuple, prewarmed: bool = False):
    """Wrap one shape-keyed kernel invocation. First call per key = the
    compile event (timed, logged, attributed); later calls = cache hits."""
    global _events
    from surrealdb_tpu import telemetry

    key = (subsystem, tuple(shape))
    with _lock:
        first = key not in _seen
        if first:
            _seen.add(key)
            _inflight.add(key)
            waiting = False
        else:
            waiting = key in _inflight
    shape_label = "x".join(str(s) for s in shape)
    if not first:
        if not waiting:
            telemetry.inc(
                "compile_cache", subsystem=subsystem, shape=shape_label, outcome="hit"
            )
            yield False
            return
        # the first call is STILL compiling on another thread (e.g. a
        # prewarm warmer won the race): this caller blocks behind XLA's
        # compile lock for the full duration — record that wait as its own
        # attributed event, not a phantom instant "hit"
        telemetry.inc(
            "compile_cache", subsystem=subsystem, shape=shape_label, outcome="wait"
        )
        t0w = time.perf_counter()
        werr: Optional[BaseException] = None
        try:
            yield False
        except BaseException as e:
            werr = e
            raise
        finally:
            from surrealdb_tpu import tracing

            dur = time.perf_counter() - t0w
            telemetry.observe("xla_compile_wait", dur, subsystem=subsystem)
            sc = tracing.current()
            wctx = sc if sc is not None else _attr_ctx.get()
            if wctx is not None:
                tracing.record_span_into(
                    wctx, "xla_compile_wait",
                    {"subsystem": subsystem, "shape": shape_label},
                    t0w, dur, werr,
                )
        return
    telemetry.inc(
        "compile_cache", subsystem=subsystem, shape=shape_label, outcome="miss"
    )
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield True
    except BaseException as e:
        err = e
        raise
    finally:
        dur = time.perf_counter() - t0
        from surrealdb_tpu import tracing

        with _lock:
            _inflight.discard(key)
            if err is not None:
                # a failed first call did NOT leave a cached executable:
                # the next call through this shape is the real compile and
                # must be recorded as one, not mislogged as a cache hit
                _seen.discard(key)
        ctx = None
        if not prewarmed:
            sc = tracing.current()
            ctx = sc if sc is not None else _attr_ctx.get()
        mode = "prewarm" if prewarmed else ("on_demand" if ctx is not None else "startup")
        trace_id = ctx.trace.trace_id if ctx is not None else None
        event = {
            "ts": time.time(),
            "subsystem": subsystem,
            "shape": shape_label,
            "duration_ms": round(dur * 1e3, 3),
            "mode": mode,
            "trace_id": trace_id,
            "error": type(err).__name__ if err is not None else None,
        }
        with _lock:
            if _events.maxlen != _cap():
                _events = deque(_events, maxlen=_cap())
            _events.append(event)
        telemetry.inc("compile_events", subsystem=subsystem, mode=mode)
        telemetry.observe("xla_compile", dur, subsystem=subsystem, mode=mode)
        if ctx is not None:
            # exactly one trace carries the compile span: the request that
            # triggered it (or led the batch that did)
            tracing.record_span_into(
                ctx,
                "xla_compile",
                {"subsystem": subsystem, "shape": shape_label, "mode": mode},
                t0,
                dur,
                err,
            )
            # pin that trace into the store regardless of tail sampling —
            # the event's trace_id must resolve via /trace/:id, and an
            # on-demand compile IS the smoking gun the store exists for
            ctx.trace.force = True


# ------------------------------------------------------------------ views
def events(since: Optional[float] = None) -> list:
    """Logged compile events, oldest first (optionally only ts >= since)."""
    with _lock:
        out = list(_events)
    if since is not None:
        out = [e for e in out if e["ts"] >= since]
    return out


def snapshot() -> dict:
    """Compile-log section of the debug bundle."""
    from surrealdb_tpu import telemetry

    evs = events()
    hits: dict = {}
    for labels, v in telemetry.counters_matching("compile_cache").items():
        d = dict(labels)
        hits[f"{d.get('subsystem')}:{d.get('shape')}:{d.get('outcome')}"] = int(v)
    return {
        "events": evs,
        "shapes_compiled": len(evs),
        "on_demand": sum(1 for e in evs if e["mode"] == "on_demand"),
        "prewarmed": sum(1 for e in evs if e["mode"] == "prewarm"),
        "cache": hits,
    }


def reset() -> None:
    with _lock:
        _seen.clear()
        _inflight.clear()
        _events.clear()
