"""Request-scoped hierarchical tracing: trace ids, span trees, trace store.

Role of the reference's OTEL trace layer (reference: src/telemetry/traces/ —
every HTTP request and RPC command opens a span, child spans nest under it,
and an OTLP exporter ships finished trees to a collector). This environment
has no collector, so finished traces land in a bounded in-memory store with
tail-based sampling, served by `GET /trace/:id` + `GET /traces` and
exportable as Chrome-trace JSON (`?format=chrome`) so a request tree drops
into chrome://tracing / Perfetto next to the `jax.profiler` device traces
that `bench.py --profile` captures.

Mechanics:

- context propagates via `contextvars` (one `SpanCtx` = active trace +
  current span id), minted at every ingress — HTTP routes, WS RPC frames,
  `RpcContext.execute`, `Datastore.execute` — and honored from an inbound
  W3C `traceparent` or `surreal-trace-id` header / frame field;
- every `telemetry.span()` that runs under an active trace becomes a node
  (name, labels, start, duration, error class) instead of only feeding the
  duration histograms; with no active trace the cost is one ContextVar read;
- the dispatch queue re-parents kernel spans onto EVERY rider of a
  coalesced batch (`record_span_into`), so a query that rode someone
  else's kernel launch still shows its own dispatch/kernel levels;
- retention is tail-based: traces with errors, over the slow-query
  threshold, force-kept (slow-query log), or client-tagged are always
  stored; the rest with probability `cnf.TRACE_SAMPLE`. Recording itself is
  always on while `cnf.TRACE_ENABLED` — you cannot sample a head you
  didn't record.
"""

from __future__ import annotations

import contextvars
import itertools
import random
import re
from surrealdb_tpu.utils import locks as _locks
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

# (span_id, parent_id, name, labels, start_perf, dur_s, error)
_SpanRec = Tuple[int, Optional[int], str, Dict[str, Any], float, float, Optional[str]]

_HEX32 = re.compile(r"^[0-9a-f]{32}$")
_SAFE_ID = re.compile(r"[^0-9a-zA-Z._-]")


class Trace:
    """Mutable accumulator for one request's span tree. Span appends are
    single tuples (GIL-atomic list.append), so the dispatch leader can
    record into a blocked rider's trace without a per-trace lock."""

    __slots__ = (
        "trace_id", "t0", "ts", "explicit", "force", "spans", "_ids",
        "dropped", "meta", "client_parent",
    )

    def __init__(self, trace_id: str, explicit: bool = False, client_parent: Optional[str] = None):
        self.trace_id = trace_id
        self.t0 = time.perf_counter()
        self.ts = time.time()
        self.explicit = explicit  # client supplied the id: always retained
        self.force = False  # slow-query log / error accounting pinned it
        self.spans: List[_SpanRec] = []
        self._ids = itertools.count(1)
        self.dropped = 0
        self.meta: Dict[str, Any] = {}  # session info (ns/db/auth level)
        self.client_parent = client_parent  # inbound traceparent span id

    def next_id(self) -> int:
        return next(self._ids)

    def add(
        self,
        span_id: int,
        parent_id: Optional[int],
        name: str,
        labels: Dict[str, Any],
        start: float,
        dur: float,
        error: Optional[str],
    ) -> None:
        from surrealdb_tpu import cnf

        if len(self.spans) >= cnf.TRACE_MAX_SPANS:
            self.dropped += 1
            return
        self.spans.append((span_id, parent_id, name, labels, start, dur, error))


class SpanCtx:
    __slots__ = ("trace", "span_id")

    def __init__(self, trace: Trace, span_id: int):
        self.trace = trace
        self.span_id = span_id


_current: "contextvars.ContextVar[Optional[SpanCtx]]" = contextvars.ContextVar(
    "surreal_trace", default=None
)

_store_lock = _locks.Lock("tracing.store")
_store: "OrderedDict[str, dict]" = OrderedDict()  # trace_id -> finished doc


def enabled() -> bool:
    from surrealdb_tpu import cnf

    return cnf.TRACE_ENABLED


def new_trace_id() -> str:
    return uuid.uuid4().hex


def is_hex_trace_id(tid: str) -> bool:
    """True when `tid` is W3C-shaped (32 hex chars) — only such ids may be
    echoed in a `traceparent` header; opaque sanitized ids would otherwise
    derive a second, unresolvable id."""
    return bool(_HEX32.match(tid))


def normalize_trace_id(tid: Any) -> str:
    """Client ids: 32-hex passes through; anything else is reduced to a
    filterable opaque token (or replaced when nothing survives)."""
    t = str(tid).strip().lower()
    if _HEX32.match(t):
        return t
    t = _SAFE_ID.sub("", str(tid).strip())[:64]
    return t or new_trace_id()


def parse_traceparent(header: str) -> Optional[Tuple[str, str]]:
    """W3C `traceparent: 00-<32hex trace>-<16hex parent>-<flags>` ->
    (trace_id, parent_span_id), or None when malformed."""
    try:
        parts = header.strip().split("-")
        if len(parts) < 4:
            return None
        tid, pid = parts[1].lower(), parts[2].lower()
        if len(tid) != 32 or len(pid) != 16 or tid == "0" * 32:
            return None
        int(tid, 16)
        int(pid, 16)
        return tid, pid
    except (ValueError, AttributeError):
        return None


def format_traceparent(trace_id: str, span_id: int) -> str:
    tid = trace_id if _HEX32.match(trace_id) else uuid.uuid5(uuid.NAMESPACE_OID, trace_id).hex
    return f"00-{tid}-{span_id & (2**64 - 1):016x}-01"


def _error_name(e: Optional[BaseException]) -> Optional[str]:
    if e is None:
        return None
    from surrealdb_tpu.err import ControlFlow, ReturnError

    # RETURN / BREAK / CONTINUE are control flow, not failures — marking
    # them would force-retain every RETURN-using request
    if isinstance(e, (ControlFlow, ReturnError)):
        return None
    return type(e).__name__


# ------------------------------------------------------------------ context
def current() -> Optional[SpanCtx]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    ctx = _current.get()
    return ctx.trace.trace_id if ctx is not None else None


def annotate(**meta: Any) -> None:
    """Attach request metadata (ns/db/auth LEVEL — never tokens) to the
    active trace; no-op outside one."""
    ctx = _current.get()
    if ctx is not None:
        ctx.trace.meta.update(meta)


def annotate_append(key: str, value: Any) -> None:
    """Append `value` to a LIST-valued meta key on the active trace (e.g.
    the cluster executor accumulating one per-shard profile per statement
    across a multi-statement request); no-op outside a trace."""
    ctx = _current.get()
    if ctx is not None:
        ctx.trace.meta.setdefault(key, []).append(value)


def force_keep() -> None:
    """Pin the active trace into the store regardless of sampling (called
    when a slow-query / error record cites its trace_id — the `/slow` ->
    `/trace/:id` hop must not dangle)."""
    ctx = _current.get()
    if ctx is not None:
        ctx.trace.force = True


def push() -> Optional[tuple]:
    """Open a child span under the active trace. Returns an opaque token
    for pop(), or None when no trace is active (the no-op fast path)."""
    ctx = _current.get()
    if ctx is None:
        return None
    sid = ctx.trace.next_id()
    token = _current.set(SpanCtx(ctx.trace, sid))
    return (token, ctx.trace, sid, ctx.span_id)


def pop(
    tok: tuple,
    name: str,
    labels: Dict[str, Any],
    start: float,
    dur: float,
    err: Optional[BaseException] = None,
) -> None:
    token, trace, sid, parent = tok
    _current.reset(token)
    trace.add(sid, parent, name, labels, start, dur, _error_name(err))


@contextmanager
def span_only(name: str, **labels: Any):
    """Trace-only child span: records a tree node but feeds NO metric
    family (labels here may be high-cardinality, e.g. truncated SQL)."""
    tok = push()
    if tok is None:
        yield
        return
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield
    except BaseException as e:
        err = e
        raise
    finally:
        pop(tok, name, labels, t0, time.perf_counter() - t0, err)


@contextmanager
def detached():
    """Run with NO active trace (the dispatch leader executes a batch on
    behalf of many riders; its own context must not swallow the kernel
    spans that record_span_into re-parents onto each rider)."""
    token = _current.set(None)
    try:
        yield
    finally:
        _current.reset(token)


def record_span_into(
    ctx: Optional[SpanCtx],
    name: str,
    labels: Dict[str, Any],
    start: float,
    dur: float,
    error: Any = None,
) -> None:
    """Record a completed span into ANOTHER request's trace, parented at
    the span that was active when that request captured `ctx` (dispatch
    fan-out: the leader stamps launch/collect onto every rider)."""
    if ctx is None:
        return
    tr = ctx.trace
    err = error if (error is None or isinstance(error, str)) else _error_name(error)
    tr.add(tr.next_id(), ctx.span_id, name, labels, start, dur, err)


# ------------------------------------------------------------- cross-node
def export_spans() -> List[dict]:
    """Serialize the ACTIVE trace's finished spans for a cluster RPC
    response (cluster/rpc.py): times are relative to the trace start so the
    coordinator can rebase them into its own clock. The still-open ingress
    root isn't in the list (it finishes after the response is built); its
    children surface as roots and re-parent under the coordinator's RPC
    span when grafted."""
    ctx = _current.get()
    if ctx is None:
        return []
    tr = ctx.trace
    return [
        {
            "id": sid,
            "parent": parent,
            "name": name,
            "labels": {k: str(v) for k, v in labels.items()},
            "rel_start": start - tr.t0,
            "dur": dur,
            "error": err,
        }
        for (sid, parent, name, labels, start, dur, err) in list(tr.spans)
    ]


def graft_spans(spans: List[dict], base_start: float, node: str) -> None:
    """Splice a remote node's exported spans into the ACTIVE trace, under
    the current span (the coordinator's cluster_rpc span): span ids are
    remapped into this trace's id space, orphans parent at the graft
    point, and every span is labeled with the serving node — one request,
    ONE span tree across the cluster."""
    ctx = _current.get()
    if ctx is None or not spans:
        return
    from surrealdb_tpu.sql.value import is_none as _is_none, is_null as _is_null

    tr = ctx.trace
    idmap: Dict[Any, int] = {}
    for s in sorted(spans, key=lambda s: s.get("rel_start", 0.0)):
        try:
            nid = tr.next_id()
            idmap[s.get("id")] = nid
            parent = idmap.get(s.get("parent"), ctx.span_id)
            err = s.get("error")
            if err is not None and (_is_none(err) or _is_null(err)):
                # the CBOR hop decodes a None error as the engine NULL
                # sentinel — normalize back, or exported trace docs stop
                # being JSON-serializable
                err = None
            tr.add(
                nid,
                parent,
                str(s.get("name", "?")),
                dict(s.get("labels") or {}, node=node),
                base_start + float(s.get("rel_start", 0.0)),
                float(s.get("dur", 0.0)),
                err,
            )
        except (TypeError, ValueError):
            continue  # a malformed remote span must not break the trace


# ------------------------------------------------------------------ ingress
@contextmanager
def request(
    name: str,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    nest: bool = True,
    **labels: Any,
):
    """Ingress seam: mint a trace whose root span is `name`, honoring a
    client-supplied trace id / traceparent. Nested ingresses (HTTP /sql ->
    Datastore.execute) become plain child spans of the active trace —
    unless nest=False, for seams whose adjacent telemetry.span() already
    provides the node (RpcContext.execute under a transport ingress).
    Yields the Trace (or None when tracing is disabled)."""
    if not enabled():
        yield None
        return
    active = _current.get()
    if active is not None:
        if not nest:
            yield active.trace
            return
        with span_only(name, **labels):
            yield active.trace
        return
    explicit = trace_id is not None
    tid = normalize_trace_id(trace_id) if explicit else new_trace_id()
    tr = Trace(tid, explicit=explicit, client_parent=parent_id)
    sid = tr.next_id()
    token = _current.set(SpanCtx(tr, sid))
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield tr
    except BaseException as e:
        err = e
        raise
    finally:
        dur = time.perf_counter() - t0
        _current.reset(token)
        tr.add(sid, None, name, labels, t0, dur, _error_name(err))
        _finish(tr, name, dur)


# retention classes, weakest first: probabilistic samples are evicted
# before client-tagged traces, which are evicted before operator-relevant
# pins (slow/error/force) — an unauthenticated flood of traceparent-tagged
# requests must not flush the diagnostics the slow-query log cites
_RANK = {"probabilistic": 0, "client": 1, "pinned": 2}


def _finish(tr: Trace, name: str, dur: float) -> None:
    from surrealdb_tpu import cnf

    first_error = next((e for (_, _, _, _, _, _, e) in tr.spans if e), None)
    if tr.force or first_error is not None or dur >= cnf.SLOW_QUERY_THRESHOLD_SECS:
        sampled = "pinned"
    elif tr.explicit:
        sampled = "client"
    elif random.random() < cnf.TRACE_SAMPLE:
        sampled = "probabilistic"
    else:
        return
    doc = {
        "trace_id": tr.trace_id,
        "name": name,
        "ts": tr.ts,
        "duration_ms": round(dur * 1e3, 3),
        "error": first_error,
        "sampled": sampled,
        "client_parent": tr.client_parent,
        "dropped_spans": tr.dropped,
        **tr.meta,
        "spans": [
            {
                "id": sid,
                "parent": parent,
                "name": n,
                "labels": {k: str(v) for k, v in labels.items()},
                "start_ms": round((start - tr.t0) * 1e3, 3),
                "dur_ms": round(d * 1e3, 3),
                "error": e,
            }
            for (sid, parent, n, labels, start, d, e) in sorted(
                tr.spans, key=lambda s: s[4]
            )
        ],
    }
    with _store_lock:
        prev = _store.get(tr.trace_id)
        if prev is not None and _RANK[prev["sampled"]] > _RANK[sampled]:
            # a reused id never downgrades what it names: the pinned doc a
            # slow-log entry cites must not be replaced by a later
            # unrelated (weaker) request wearing the same trace id
            return
        _store[tr.trace_id] = doc
        _store.move_to_end(tr.trace_id)
        while len(_store) > max(cnf.TRACE_STORE_SIZE, 1):
            # rank-ordered victim scan: O(store size) worst case, but it
            # only runs on an already-full store, once per RETAINED trace
            # (sampled-out requests never reach it), and stops at the first
            # weak entry — for the default 512-entry store this is
            # microseconds under the lock. bench.py additionally resets
            # the store per accounting window so its hot path never fills.
            victim = next(
                (
                    k
                    for rank in ("probabilistic", "client")
                    for k, d in _store.items()
                    if d["sampled"] == rank
                ),
                None,
            )
            if victim is not None:
                del _store[victim]
            else:
                _store.popitem(last=False)


# ------------------------------------------------------------------ store
def get_trace(trace_id: str) -> Optional[dict]:
    with _store_lock:
        return _store.get(normalize_trace_id(trace_id))


def trace_ids() -> List[str]:
    with _store_lock:
        return list(_store)


def list_traces(limit: int = 100) -> List[dict]:
    """Newest-first summaries (the `GET /traces` index)."""
    with _store_lock:
        docs = list(_store.values())
    out = []
    for d in reversed(docs[-max(limit, 1):]):
        out.append(
            {
                k: d.get(k)
                for k in (
                    "trace_id", "name", "ts", "duration_ms", "error",
                    "sampled", "ns", "db", "auth", "fingerprint",
                )
            }
            | {"spans": len(d["spans"])}
        )
    return out


def store_reset() -> None:
    with _store_lock:
        _store.clear()


# ------------------------------------------------------------------ export
def span_tree(doc: dict) -> List[dict]:
    """Nest a stored doc's flat span list into parent->children trees
    (roots first; orphans — parent evicted by the span cap — surface as
    roots rather than vanishing)."""
    nodes = {s["id"]: dict(s, children=[]) for s in doc["spans"]}
    roots: List[dict] = []
    for s in doc["spans"]:
        node = nodes[s["id"]]
        parent = nodes.get(s["parent"]) if s["parent"] is not None else None
        if parent is None:
            roots.append(node)
        else:
            parent["children"].append(node)
    return roots


def to_chrome(doc: dict) -> dict:
    """Chrome-trace-format JSON (`chrome://tracing` / Perfetto `Open`):
    complete ('X') events in microseconds, one process per trace."""
    events = []
    for s in doc["spans"]:
        events.append(
            {
                "name": s["name"],
                "cat": "surreal",
                "ph": "X",
                "ts": round(s["start_ms"] * 1e3, 1),
                "dur": max(round(s["dur_ms"] * 1e3, 1), 0.1),
                "pid": 1,
                "tid": 1,
                "args": {
                    "span_id": s["id"],
                    "parent": s["parent"],
                    **s["labels"],
                    **({"error": s["error"]} if s["error"] else {}),
                },
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": doc["trace_id"],
            "name": doc["name"],
            "duration_ms": doc["duration_ms"],
        },
    }
