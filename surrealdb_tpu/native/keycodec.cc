/* Native order-preserving key codec (role of the reference's derive(Key)
 * order-preserving serializer, core/src/key/mod.rs:1-77 — there a Rust
 * proc-macro; here a CPython extension compiled by the in-tree toolchain,
 * surrealdb_tpu/native/__init__.py).
 *
 * Implements the hot primitives of surrealdb_tpu/key/encode.py with
 * identical byte-for-byte output (property-tested against the Python
 * twins in tests/test_native_codec.py):
 *   enc_str / enc_bytes    0x00 -> 0x00 0xFF escape + 0x00 terminator
 *   dec_bytes              inverse, returns (bytes, next_pos)
 *   enc_int_key            T_NUMBER tag + f64 offset-bits + i64 offset
 *   enc_value_key_fast     int/str fast path; None for other types (the
 *                          Python layer handles the full Value domain)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <cstdint>
#include <cstring>

static const uint8_t T_NUMBER = 0x10;
static const uint8_t T_STRAND = 0x20;

/* escape src into dst (dst must hold 2*n+1); returns bytes written */
static Py_ssize_t escape_terminate(const uint8_t *src, Py_ssize_t n, uint8_t *dst) {
    Py_ssize_t w = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        uint8_t c = src[i];
        dst[w++] = c;
        if (c == 0x00) dst[w++] = 0xFF;
    }
    dst[w++] = 0x00;
    return w;
}

static PyObject *enc_escaped(const uint8_t *src, Py_ssize_t n) {
    /* common case: no NUL bytes -> one memchr + one copy */
    if (memchr(src, 0, (size_t)n) == NULL) {
        PyObject *out = PyBytes_FromStringAndSize(NULL, n + 1);
        if (!out) return NULL;
        uint8_t *d = (uint8_t *)PyBytes_AS_STRING(out);
        memcpy(d, src, (size_t)n);
        d[n] = 0x00;
        return out;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, 2 * n + 1);
    if (!out) return NULL;
    Py_ssize_t w = escape_terminate(src, n, (uint8_t *)PyBytes_AS_STRING(out));
    if (_PyBytes_Resize(&out, w) < 0) return NULL;
    return out;
}

static PyObject *py_enc_str(PyObject *self, PyObject *arg) {
    if (!PyUnicode_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "enc_str expects str");
        return NULL;
    }
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(arg, &n);
    if (!s) return NULL;
    return enc_escaped((const uint8_t *)s, n);
}

static PyObject *py_enc_bytes(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    PyObject *out = enc_escaped((const uint8_t *)view.buf, view.len);
    PyBuffer_Release(&view);
    return out;
}

static PyObject *py_dec_bytes(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t pos;
    if (!PyArg_ParseTuple(args, "y*n", &view, &pos)) return NULL;
    const uint8_t *b = (const uint8_t *)view.buf;
    Py_ssize_t n = view.len;
    if (pos < 0 || pos > n) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "position out of range");
        return NULL;
    }
    /* first pass: find terminator, count escapes */
    Py_ssize_t i = pos, esc = 0, end = -1;
    while (i < n) {
        if (b[i] == 0x00) {
            if (i + 1 < n && b[i + 1] == 0xFF) { esc++; i += 2; continue; }
            end = i; break;
        }
        i++;
    }
    if (end < 0) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "unterminated string in key");
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, end - pos - esc);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    uint8_t *d = (uint8_t *)PyBytes_AS_STRING(out);
    for (i = pos; i < end; ) {
        uint8_t c = b[i];
        *d++ = c;
        i += (c == 0x00) ? 2 : 1;  /* skip the 0xFF escape byte */
    }
    PyObject *ret = Py_BuildValue("Nn", out, end + 1);
    PyBuffer_Release(&view);
    return ret;
}

static inline void store_be64(uint8_t *d, uint64_t v) {
    for (int i = 7; i >= 0; i--) { d[i] = (uint8_t)(v & 0xFF); v >>= 8; }
}

/* T_NUMBER | f64-orderbits | i64-offset — byte-compatible with
 * encode.py _enc_int_key */
static int enc_int_key_raw(int64_t v, uint8_t out[17]) {
    double dv = (double)v;
    uint64_t bits;
    memcpy(&bits, &dv, 8);
    if (bits & 0x8000000000000000ULL) bits = ~bits;
    else bits |= 0x8000000000000000ULL;
    out[0] = T_NUMBER;
    store_be64(out + 1, bits);
    store_be64(out + 9, (uint64_t)v ^ 0x8000000000000000ULL);
    return 0;
}

static PyObject *py_enc_int_key(PyObject *self, PyObject *arg) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(arg, &overflow);
    if (overflow || (v == -1 && PyErr_Occurred())) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "integer key component out of i64 range");
        return NULL;
    }
    uint8_t buf[17];
    enc_int_key_raw((int64_t)v, buf);
    return PyBytes_FromStringAndSize((const char *)buf, 17);
}

/* int/str fast path of enc_value_key; returns None for any other type so
 * the Python layer can handle the full Value domain (bool is a PyLong
 * subtype — exclude it exactly like the Python `type(v) is int` check). */
static PyObject *py_enc_value_key_fast(PyObject *self, PyObject *arg) {
    if (PyLong_CheckExact(arg)) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(arg, &overflow);
        if (overflow) {
            PyErr_SetString(PyExc_ValueError, "integer key component out of i64 range");
            return NULL;
        }
        if (v == -1 && PyErr_Occurred()) return NULL;
        uint8_t buf[17];
        enc_int_key_raw((int64_t)v, buf);
        return PyBytes_FromStringAndSize((const char *)buf, 17);
    }
    if (PyUnicode_CheckExact(arg)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(arg, &n);
        if (!s) return NULL;
        if (memchr(s, 0, (size_t)n) == NULL) {
            PyObject *out = PyBytes_FromStringAndSize(NULL, n + 2);
            if (!out) return NULL;
            uint8_t *d = (uint8_t *)PyBytes_AS_STRING(out);
            d[0] = T_STRAND;
            memcpy(d + 1, s, (size_t)n);
            d[n + 1] = 0x00;
            return out;
        }
        PyObject *out = PyBytes_FromStringAndSize(NULL, 2 * n + 2);
        if (!out) return NULL;
        uint8_t *d = (uint8_t *)PyBytes_AS_STRING(out);
        d[0] = T_STRAND;
        Py_ssize_t w = escape_terminate((const uint8_t *)s, n, d + 1);
        if (_PyBytes_Resize(&out, w + 1) < 0) return NULL;
        return out;
    }
    Py_RETURN_NONE;
}

static PyMethodDef Methods[] = {
    {"enc_str", py_enc_str, METH_O, "order-preserving string encode"},
    {"enc_bytes", py_enc_bytes, METH_O, "order-preserving bytes encode"},
    {"dec_bytes", py_dec_bytes, METH_VARARGS, "decode escaped bytes at pos"},
    {"enc_int_key", py_enc_int_key, METH_O, "T_NUMBER int key component"},
    {"enc_value_key_fast", py_enc_value_key_fast, METH_O,
     "int/str value-key fast path (None for other types)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_keycodec", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit__keycodec(void) { return PyModule_Create(&moduledef); }
