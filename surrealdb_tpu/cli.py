"""Command-line interface.

Role of the reference's clap CLI (reference: src/cli/mod.rs:1-16 subcommands
start, sql, import, export, ml, isready, upgrade, validate, fix, version).

    python -m surrealdb_tpu start [--bind 127.0.0.1:8000] [--path memory]
                                  [--user root --pass root] [--unauthenticated]
    python -m surrealdb_tpu sql   [--endpoint mem://] [--ns t --db t]
    python -m surrealdb_tpu import <file> --endpoint ... --ns ... --db ...
    python -m surrealdb_tpu export <file> --endpoint ... --ns ... --db ...
    python -m surrealdb_tpu validate <file...>
    python -m surrealdb_tpu isready --endpoint http://...
    python -m surrealdb_tpu version
"""

from __future__ import annotations

import argparse
import sys

from surrealdb_tpu import __version__


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="surrealdb-tpu")
    sub = ap.add_subparsers(dest="cmd")

    p_start = sub.add_parser("start", help="start the server")
    p_start.add_argument("path", nargs="?", default="memory")
    p_start.add_argument("--bind", "-b", default="127.0.0.1:8000")
    p_start.add_argument("--user", "-u")
    p_start.add_argument("--pass", "-p", dest="password")
    p_start.add_argument("--unauthenticated", action="store_true")
    p_start.add_argument("--web-crt", dest="web_crt", help="TLS certificate (PEM)")
    p_start.add_argument("--web-key", dest="web_key", help="TLS private key (PEM)")
    p_start.add_argument("--profile", action="store_true",
                         help="record timed spans around statements and kernel dispatches")
    p_start.add_argument("--cluster", dest="cluster",
                         help="cluster topology JSON (multi-node sharded serving)")
    p_start.add_argument("--cluster-node", dest="cluster_node",
                         help="this node's id in the topology (overrides the file's \"self\")")
    # capability flags (reference: surreal start --allow-*/--deny-*)
    p_start.add_argument("--allow-all", "-A", dest="allow_all", action="store_const", const="all", default=None)
    p_start.add_argument("--deny-all", dest="deny_all", action="store_const", const="all", default=None)
    p_start.add_argument("--allow-scripting", dest="allow_scripting", action="store_const", const="all", default=None)
    p_start.add_argument("--allow-guests", dest="allow_guests", action="store_const", const="all", default=None)
    p_start.add_argument("--deny-guests", dest="allow_guests", action="store_const", const="none")
    p_start.add_argument("--allow-funcs", dest="allow_funcs", nargs="?", const="all", default=None)
    p_start.add_argument("--deny-funcs", dest="deny_funcs", nargs="?", const="all", default=None)
    p_start.add_argument("--allow-net", dest="allow_net", nargs="?", const="all", default=None)
    p_start.add_argument("--deny-net", dest="deny_net", nargs="?", const="all", default=None)
    p_start.add_argument("--allow-rpc", dest="allow_rpc", nargs="?", const="all", default=None)
    p_start.add_argument("--deny-rpc", dest="deny_rpc", nargs="?", const="all", default=None)
    p_start.add_argument("--allow-http", dest="allow_http", nargs="?", const="all", default=None)
    p_start.add_argument("--deny-http", dest="deny_http", nargs="?", const="all", default=None)

    p_sql = sub.add_parser("sql", help="interactive SurrealQL shell")
    p_sql.add_argument("--endpoint", "-e", default="mem://")
    p_sql.add_argument("--ns", default=None)
    p_sql.add_argument("--db", default=None)
    p_sql.add_argument("--user", "-u")
    p_sql.add_argument("--pass", "-p", dest="password")
    p_sql.add_argument("--pretty", action="store_true")

    p_imp = sub.add_parser("import", help="import a .surql file")
    p_imp.add_argument("file")
    for p in (p_imp,):
        p.add_argument("--endpoint", "-e", default="mem://")
        p.add_argument("--ns", required=True)
        p.add_argument("--db", required=True)
        p.add_argument("--user", "-u")
        p.add_argument("--pass", "-p", dest="password")

    p_exp = sub.add_parser("export", help="export to a .surql file")
    p_exp.add_argument("file", nargs="?", default="-")
    p_exp.add_argument("--endpoint", "-e", default="mem://")
    p_exp.add_argument("--ns", required=True)
    p_exp.add_argument("--db", required=True)
    p_exp.add_argument("--user", "-u")
    p_exp.add_argument("--pass", "-p", dest="password")

    p_ml = sub.add_parser("ml", help="import/export ML models")
    ml_sub = p_ml.add_subparsers(dest="ml_cmd")
    p_mli = ml_sub.add_parser("import", help="import a JSON model spec")
    p_mli.add_argument("file")
    p_mle = ml_sub.add_parser("export", help="export a model spec as JSON")
    p_mle.add_argument("name")
    p_mle.add_argument("model_version", nargs="?", default="")
    p_mle.add_argument("file", nargs="?", default="-")
    for p in (p_mli, p_mle):
        p.add_argument("--endpoint", "-e", default="mem://")
        p.add_argument("--ns", required=True)
        p.add_argument("--db", required=True)
        p.add_argument("--user", "-u")
        p.add_argument("--pass", "-p", dest="password")

    p_val = sub.add_parser("validate", help="parse-check SurrealQL files")
    p_val.add_argument("files", nargs="+")

    p_ready = sub.add_parser("isready", help="check a server is responding")
    p_ready.add_argument("--endpoint", "-e", default="http://127.0.0.1:8000")

    p_fix = sub.add_parser("fix", help="repair a damaged file datastore")
    p_fix.add_argument("path")

    p_up = sub.add_parser("upgrade", help="migrate a file datastore to the current storage version")
    p_up.add_argument("path")

    sub.add_parser("version", help="print version")

    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 1
    return {
        "start": _start,
        "sql": _sql,
        "import": _import,
        "export": _export,
        "ml": _ml,
        "validate": _validate,
        "isready": _isready,
        "fix": _fix,
        "upgrade": _upgrade,
        "version": _version,
    }[args.cmd](args)


def _version(args) -> int:
    print(f"surrealdb-tpu {__version__}")
    return 0


def _start(args) -> int:
    from surrealdb_tpu.net.server import serve
    from surrealdb_tpu.dbs.session import Session

    from surrealdb_tpu.dbs.capabilities import from_env_and_args

    from surrealdb_tpu import cnf

    if getattr(args, "profile", False) or cnf.PROFILE:
        from surrealdb_tpu import telemetry

        telemetry.enable(True)

    cluster_config = None
    if getattr(args, "cluster", None):
        from surrealdb_tpu.cluster import load_config

        cluster_config = load_config(args.cluster, getattr(args, "cluster_node", None))

    host, _, port = args.bind.partition(":")
    srv = serve(
        args.path, host or "127.0.0.1", int(port or 8000),
        auth_enabled=not args.unauthenticated,
        capabilities=from_env_and_args(args),
        tls_cert=getattr(args, "web_crt", None),
        tls_key=getattr(args, "web_key", None),
        cluster_config=cluster_config,
    )
    if cluster_config is not None:
        print(
            f"cluster node {cluster_config.node_id!r}: "
            f"{len(cluster_config.nodes)} member(s), {cluster_config.vnodes} vnodes",
            file=sys.stderr,
        )
    if args.user and args.password:
        from surrealdb_tpu.sql.value import format_value

        srv.httpd.RequestHandlerClass.ds.execute(
            f"DEFINE USER {args.user} ON ROOT PASSWORD {format_value(args.password)} ROLES OWNER;",
            Session.owner(None, None),
        )
    print(f"Started surrealdb-tpu on {srv.url} (storage: {args.path})", file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


def _connect(args):
    from surrealdb_tpu.sdk import Surreal

    db = Surreal(args.endpoint)
    if args.user and args.password:
        db.signin(user=args.user, password=args.password)
    if args.ns or args.db:
        db.use(args.ns, args.db)
    return db


def _sql(args) -> int:
    from surrealdb_tpu.sql.value import format_value

    db = _connect(args)
    print(f"surrealdb-tpu {__version__} — interactive shell (exit with ^D)", file=sys.stderr)
    while True:
        try:
            line = input("> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not line.strip():
            continue
        try:
            for resp in db.query(line):
                status = resp.get("status")
                body = resp.get("result")
                if status == "OK":
                    print(format_value(body, pretty=args.pretty))
                else:
                    print(f"ERR: {body}", file=sys.stderr)
        except Exception as e:
            print(f"ERR: {e}", file=sys.stderr)


def _import(args) -> int:
    db = _connect(args)
    with open(args.file) as f:
        db.import_(f.read())
    print("import completed", file=sys.stderr)
    return 0


def _export(args) -> int:
    db = _connect(args)
    dump = db.export()
    if args.file == "-":
        sys.stdout.write(dump)
    else:
        with open(args.file, "w") as f:
            f.write(dump)
    return 0


def _ml(args) -> int:
    """`surrealdb-tpu ml import|export` (reference: src/cli/ml/)."""
    import json

    if args.ml_cmd == "import":
        db = _connect(args)
        with open(args.file, "rb") as f:
            raw = f.read()
        if args.file.endswith(".surml") or raw[:1] not in (b"{", b"["):
            entry = db.import_surml(raw)
        else:
            entry = db.import_model(json.loads(raw))
        print(f"model ml::{entry['name']}<{entry['version']}> stored", file=sys.stderr)
        return 0
    if args.ml_cmd == "export":
        db = _connect(args)
        spec = db.export_model(args.name, args.model_version)
        text = json.dumps(spec)
        if args.file == "-":
            sys.stdout.write(text)
        else:
            with open(args.file, "w") as f:
                f.write(text)
        return 0
    print("usage: surrealdb-tpu ml {import,export} ...", file=sys.stderr)
    return 1


def _fix(args) -> int:
    from surrealdb_tpu.kvs.file import repair

    try:
        stats = repair(args.path)
    except (ValueError, OSError) as e:
        print(f"fix failed: {e}", file=sys.stderr)
        return 1
    print(
        f"{args.path}: repaired — {stats['keys']} keys, "
        f"{stats['wal_frames']} WAL frames replayed, "
        f"{stats['snapshot_dropped_bytes']} torn snapshot bytes dropped"
    )
    return 0


def _upgrade(args) -> int:
    from surrealdb_tpu.kvs.file import upgrade

    try:
        stats = upgrade(args.path)
    except (ValueError, OSError) as e:
        print(f"upgrade failed: {e}", file=sys.stderr)
        return 1
    print(
        f"{args.path}: storage version {stats['from_version']} -> "
        f"{stats['to_version']} ({stats['keys']} keys)"
    )
    return 0


def _validate(args) -> int:
    from surrealdb_tpu.syn import parse_query
    from surrealdb_tpu.err import ParseError

    bad = 0
    for path in args.files:
        try:
            with open(path) as f:
                parse_query(f.read())
            print(f"{path}: OK")
        except ParseError as e:
            print(f"{path}: {e}", file=sys.stderr)
            bad += 1
    return 1 if bad else 0


def _isready(args) -> int:
    import http.client
    from urllib.parse import urlparse

    u = urlparse(args.endpoint)
    try:
        conn = http.client.HTTPConnection(u.hostname, u.port or 8000, timeout=5)
        conn.request("GET", "/health")
        ok = conn.getresponse().status == 200
    except OSError:
        ok = False
    print("OK" if ok else "not ready")
    return 0 if ok else 1
