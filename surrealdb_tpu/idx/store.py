"""Registry of device-resident index mirrors.

Role of the reference's IndexStores / TreeCache generation machinery
(reference: core/src/idx/trees/store/mod.rs:217, store/cache.rs): each
(ns, db, tb, ix) owns a mirror object (vector matrix, CSR graph, FT arrays)
that is rebuilt/refreshed by generation and shared across transactions.
Writes go to the KV first; mirrors refresh lazily when their generation
is behind the KV state generation.
"""

from __future__ import annotations

from surrealdb_tpu.utils import locks as _locks
from typing import Any, Dict, Optional, Tuple

IndexKey = Tuple[str, str, str, str]  # ns, db, tb, ix


class IndexStores:
    def __init__(self):
        self._stores: Dict[IndexKey, Any] = {}
        self._lock = _locks.RLock("idx.store")

    def get(self, ns: str, db: str, tb: str, ix: str) -> Optional[Any]:
        with self._lock:
            return self._stores.get((ns, db, tb, ix))

    def get_or_create(self, ns: str, db: str, tb: str, ix: str, factory):
        with self._lock:
            k = (ns, db, tb, ix)
            st = self._stores.get(k)
            if st is None:
                st = factory()
                self._stores[k] = st
            return st

    def remove(self, ns: str, db: str, tb: str, ix: str) -> None:
        with self._lock:
            self._stores.pop((ns, db, tb, ix), None)

    def remove_table(self, ns: str, db: str, tb: str) -> None:
        with self._lock:
            for k in [k for k in self._stores if k[:3] == (ns, db, tb)]:
                del self._stores[k]

    def remove_db(self, ns: str, db: str) -> None:
        """Forget every mirror of one database (REMOVE DATABASE) — a
        recreated database must not reuse stale device state."""
        with self._lock:
            for k in [k for k in self._stores if k[:2] == (ns, db)]:
                del self._stores[k]

    def remove_ns(self, ns: str) -> None:
        """Forget every mirror of one namespace (REMOVE NAMESPACE)."""
        with self._lock:
            for k in [k for k in self._stores if k[0] == ns]:
                del self._stores[k]

    def clear(self) -> None:
        with self._lock:
            self._stores.clear()
