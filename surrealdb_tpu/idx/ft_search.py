"""MATCHES (@@) query plan over the inverted index.

Role of the reference's MatchesThingIterator + per-doc matches()/score()/
highlight() hooks (reference: core/src/idx/planner/iterators.rs:849-904,
executor.rs:878-1102, fnc/search.rs). The plan object implements the
QueryExecutor protocol consulted by the MATCHES operator and the search::
functions during document processing.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from surrealdb_tpu.sql.value import NONE, Thing

from .ft_index import FtIndex


class MatchesPlan:
    def __init__(self, tb: str, ix: dict, op, query):
        self.tb = tb
        self.ix = ix
        self.op = op
        self.query = query if isinstance(query, str) else str(query)
        self.ft = FtIndex.for_index(None, ix)
        self.results = None  # FtResults after iterate()
        self.provides_order = False  # set by the planner (score-order pushdown)
        self.order_pushed = False  # set by stmt_exec when it's the only source

    def explain(self) -> dict:
        return {
            "index": self.ix["name"],
            "operator": f"@{self.op.ref if self.op.ref is not None else ''}@",
            "query": self.query,
        }

    # ------------------------------------------------------------ iteration
    def iterate(self, ctx):
        ctx.qe = self
        ns, db = ctx.ns_db()
        want = (ns, db, self.tb, self.ix["name"])
        pending = getattr(ctx.txn(), "ft_deltas", None)
        if pending and any(d[1:5] == want for d in pending):
            # this txn has uncommitted writes to the index: exact KV search
            # (sees the txn's own writes; the shared mirror must not)
            self.results = self.ft.search(ctx, self.query)
        else:
            from .ft_index import FtResults
            from .ft_mirror import FtMirror

            mirror = ctx.ds().index_stores.get_or_create(
                ns, db, self.tb, self.ix["name"], FtMirror
            )
            mirror.ensure_built(ctx, self.ix)
            terms = self.ft.analyzer(ctx).terms(self.query)
            k1 = float(self.ix["index"].get("k1", 1.2))
            b = float(self.ix["index"].get("b", 0.75))
            # cluster mode: the coordinator injects merged GLOBAL corpus
            # stats so per-shard scoring matches one single-node corpus
            # (cluster/executor.py two-phase BM25)
            stats = ctx.get_param("__cluster_ft_stats")
            dids, scores = mirror.search(
                terms, k1, b,
                stats_override=stats if isinstance(stats, dict) else None,
            )
            import numpy as np

            order = np.argsort(-scores, kind="stable")
            if self.order_pushed:
                # single-source score-ordered scan: LIMIT stops iteration
                # after a handful of rows, so materialize rids lazily and
                # fill the score lookup as docs are yielded (only yielded
                # docs are ever probed by matches()/score())
                self.results = FtResults(self.ft, {}, terms)
                by_rid = self.results.by_rid
                for i in order:
                    rid = mirror.rid_for(int(dids[i]))
                    if rid is None:
                        continue
                    s = float(scores[i])
                    by_rid[(rid.tb, repr(rid.id))] = (rid, s)
                    yield rid, None, {"score": s}
                return
            by_rid = {}
            for i in order:
                rid = mirror.rid_for(int(dids[i]))
                if rid is not None:
                    by_rid[(rid.tb, repr(rid.id))] = (rid, float(scores[i]))
            self.results = FtResults(self.ft, by_rid, terms)
            for rid, score in by_rid.values():
                yield rid, None, {"score": score}
            return
        ranked = sorted(self.results, key=lambda rs: -rs[1])
        for rid, score in ranked:
            yield rid, None, {"score": score}

    # ------------------------------------------------------------ executor protocol
    def matches(self, ctx, doc, op) -> bool:
        if self.results is None or doc.rid is None:
            return False
        return self.results.contains(doc.rid)

    def knn(self, ctx, doc, op) -> bool:
        return False

    def knn_distance(self, rid) -> Optional[float]:
        return None

    def score(self, ctx, doc, ref=None) -> Optional[float]:
        if self.results is None or doc.rid is None:
            return None
        return self.results.score(doc.rid)

    def highlight(self, ctx, doc, prefix: str, suffix: str, ref=None):
        if self.results is None or doc.rid is None:
            return NONE
        offs = self.ft.offsets_for(ctx, doc.rid, self.results.terms)
        if not offs:
            return NONE
        # apply to the indexed field's current value
        field = self.op.l
        with ctx.with_doc_value(doc.current, rid=doc.rid) as c:
            text = field.compute(c)
        if not isinstance(text, str):
            return NONE
        out = []
        last = 0
        for s, e in offs:
            if s < last or e > len(text):
                continue
            out.append(text[last:s])
            out.append(prefix + text[s:e] + suffix)
            last = e
        out.append(text[last:])
        return "".join(out)

    def offsets(self, ctx, doc, ref=None):
        if self.results is None or doc.rid is None:
            return NONE
        offs = self.ft.offsets_for(ctx, doc.rid, self.results.terms)
        return {"0": [{"s": s, "e": e} for s, e in offs]} if offs else NONE
