"""MATCHES (@@) query plan.

Role of the reference's MatchesThingIterator + per-doc matches() check
(reference: core/src/idx/planner/iterators.rs:849-904, executor.rs:878-937).
Until the inverted-index milestone lands this executes as a streamed scan
with naive whitespace/lowercase analysis; the plan object already implements
the QueryExecutor protocol (matches / score / highlight hooks) so the
operator wiring is final.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from surrealdb_tpu.sql.value import Thing

_TOKEN = re.compile(r"\w+", re.UNICODE)


def _analyze(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN.findall(text)]


class MatchesPlan:
    def __init__(self, tb: str, ix: dict, op, query):
        self.tb = tb
        self.ix = ix
        self.op = op
        self.query = query if isinstance(query, str) else str(query)
        self.terms = _analyze(self.query)
        self._matched: Dict[Any, float] = {}

    def explain(self) -> dict:
        return {
            "index": self.ix["name"],
            "operator": f"@{self.op.ref if self.op.ref is not None else ''}@",
            "query": self.query,
        }

    # ------------------------------------------------------------ iteration
    def iterate(self, ctx):
        ctx.qe = self
        from surrealdb_tpu.dbs.iterator import scan_table

        field = self.op.l
        for rid, doc in scan_table(ctx, self.tb):
            with ctx.with_doc_value(doc, rid=rid) as c:
                v = field.compute(c)
            texts = v if isinstance(v, list) else [v]
            toks: List[str] = []
            for t in texts:
                if isinstance(t, str):
                    toks.extend(_analyze(t))
            if toks and all(t in toks for t in self.terms):
                score = float(sum(toks.count(t) for t in self.terms))
                self._matched[(rid.tb, repr(rid.id))] = score
                yield rid, doc, {"score": score}

    # ------------------------------------------------------------ executor protocol
    def _key(self, rid: Thing):
        return (rid.tb, repr(rid.id))

    def matches(self, ctx, doc, op) -> bool:
        rid = doc.rid
        return rid is not None and self._key(rid) in self._matched

    def knn(self, ctx, doc, op) -> bool:
        return False

    def knn_distance(self, rid) -> Optional[float]:
        return None

    def score(self, ctx, doc, ref=None) -> Optional[float]:
        rid = doc.rid
        if rid is None:
            return None
        return self._matched.get(self._key(rid))
