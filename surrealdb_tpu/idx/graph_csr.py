"""Device-resident CSR graph mirrors + batched frontier expansion.

Role of the reference's per-record edge-prefix scans (reference:
core/src/dbs/processor.rs:610-701 collect_edges, sql/value/get.rs:404-446 —
hop N over R records ⇒ R separate KV range scans) re-designed TPU-first
(SURVEY §3.5): each (src_table, direction, foreign_table) pointer keyspace is
packed into CSR arrays (indptr/indices) over a node id space shared across
all mirrors of a database, so a multi-hop idiom like `->knows->person` is a
sequence of fixed-shape gather kernels with on-device dedup instead of
R₁+R₂+… pointer chases.

Maintenance is incremental: the base adjacency is built with ONE scan over
the source table's `~` keyspace (all directions/foreign-tables at once), and
every committed RELATE/DELETE applies per-edge deltas through the
transaction's graph-delta buffer (kvs/tx.py) — no corpus rescans on write
(reference analog: trees/store/cache.rs generation swap, improved). Device
arrays are recompacted lazily from the host adjacency when dirty; queries
inside a transaction that has its own uncommitted edge writes fall back to
the exact KV walk (sql/path.py graph_hop).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.value import Thing
from surrealdb_tpu.utils.num import next_pow2 as _next_pow2


class NodeInterner:
    """Thing ↔ dense-int mapping shared by every mirror of one (ns, db)."""

    def __init__(self):
        self.id_of: Dict[Tuple[str, str], int] = {}
        self.node_of: List[Thing] = []
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.node_of)

    def intern(self, t: Thing) -> int:
        k = (t.tb, repr(t.id))
        i = self.id_of.get(k)
        if i is None:
            with self._lock:
                i = self.id_of.get(k)
                if i is None:
                    i = len(self.node_of)
                    self.node_of.append(t)
                    self.id_of[k] = i
        return i

    def lookup(self, t: Thing) -> Optional[int]:
        return self.id_of.get((t.tb, repr(t.id)))


class PointerCsr:
    """Adjacency for one (src_tb, direction, foreign_tb) pointer keyspace.

    Host side: `adj` dict of global-int lists — authoritative, updated by
    deltas. Device side: indptr/indices arrays compacted lazily.
    """

    def __init__(self, interner: NodeInterner):
        self.interner = interner
        self.adj: Dict[int, List[int]] = {}
        self.dirty = True
        self.indptr: Optional[np.ndarray] = None
        self.indices: Optional[np.ndarray] = None
        self._dev = None  # (jnp indptr, jnp indices) cache
        self.n_built = 0
        self.max_degree = 0
        self._lock = threading.Lock()

    def load(self, adj: Dict[int, List[int]]) -> None:
        with self._lock:
            self.adj = adj
            self.dirty = True

    def apply(self, src: int, dst: int, add: bool) -> None:
        """Idempotent delta: pointer keys are unique in KV, so the mirror
        holds at most one (src, dst) entry per keyspace."""
        with self._lock:
            lst = self.adj.setdefault(src, [])
            if add:
                if dst not in lst:
                    lst.append(dst)
            else:
                try:
                    lst.remove(dst)
                except ValueError:
                    pass
                if not lst:
                    del self.adj[src]
            self.dirty = True

    def ensure_arrays(self) -> None:
        """Compact host adjacency into CSR arrays (numpy only — no KV)."""
        n = len(self.interner)
        with self._lock:
            if not self.dirty and self.n_built == n and self.indptr is not None:
                return
            # indptr spans a pow2-padded node capacity and indices a pow2
            # buffer so XLA kernel shapes stay stable while edges trickle in
            # (a recompile per RELATE would dwarf the gather itself)
            cap = _next_pow2(max(n, 1))
            indptr = np.zeros(cap + 1, dtype=np.int32)
            for src, lst in self.adj.items():
                if src < n:
                    indptr[src + 1] = len(lst)
            self.max_degree = int(indptr.max()) if n else 0
            np.cumsum(indptr, out=indptr)
            indices = np.zeros(_next_pow2(max(int(indptr[-1]), 1)), dtype=np.int32)
            fill = indptr[:-1].copy()
            for src, lst in self.adj.items():
                if src >= n:
                    continue
                k = fill[src]
                indices[k : k + len(lst)] = lst
            self.indptr = indptr
            self.indices = indices
            self._dev = None
            self.n_built = n
            self.dirty = False

    def device_arrays(self):
        import jax.numpy as jnp

        self.ensure_arrays()
        if self._dev is None:
            self._dev = (jnp.asarray(self.indptr), jnp.asarray(self.indices))
        return self._dev


# ------------------------------------------------------------------ kernels
_JITTED: dict = {}


def _kernels():
    """Lazily build the jitted hop kernels (keeps jax off the import path).

    The whole remaining chain compiles into ONE jitted call — on a tunneled
    or queued device each dispatch costs ~100ms RTT, so per-hop kernels made
    a 3-hop query ~7 round trips (BENCH_r03 p50 816ms); fused it is one.
    """
    if _JITTED:
        return _JITTED["chain"]
    import jax
    import jax.numpy as jnp

    def gather_hop(ptr, idx, frontier, weights, md):
        # one weighted CSR gather: frontier [F] ints with multiplicities →
        # neighbor slots [F*md] + per-slot weight (0 = padding). Carrying a
        # count per node instead of a bare frontier makes the hop an SpMV
        # over the adjacency, which preserves the reference's flatten-
        # without-dedup result multiplicity (sql/value/get.rs:404-446)
        # while still deduplicating the *frontier* between hops.
        n = ptr.shape[0] - 1
        fr = jnp.clip(frontier, 0, jnp.maximum(n - 1, 0))
        s = ptr[fr]
        deg = ptr[fr + 1] - s
        offs = jnp.arange(md)[None, :]
        take = jnp.clip(s[:, None] + offs, 0, idx.shape[0] - 1)
        valid = (offs < deg[:, None]) & (weights > 0)[:, None] & (frontier < n)[:, None]
        w = jnp.where(valid, weights[:, None], 0)
        return idx[take].reshape(-1), w.reshape(-1)

    def accum_cap(nodes, w, n_nodes, out_size):
        # dense scatter-add dedup: per-node path counts survive the frontier
        # compaction (capped, jit-static output size)
        safe = jnp.where(w > 0, jnp.clip(nodes, 0, n_nodes), n_nodes)
        dense = jnp.zeros(n_nodes + 1, dtype=jnp.int32).at[safe].add(w)
        dense = dense.at[n_nodes].set(0)
        present = jnp.nonzero(dense > 0, size=out_size, fill_value=n_nodes)[0]
        return present, jnp.where(present < n_nodes, dense[present], 0)

    @partial(
        jax.jit, static_argnames=("mds", "n_cap", "out_sizes", "count_only")
    )
    def chain_kernel(hops, frontier, weights, mds, n_cap, out_sizes, count_only):
        """Full multi-hop chain in one dispatch. hops: tuple (one per hop) of
        tuples of (indptr, indices) device arrays (one per contributing
        mirror); mds/out_sizes: matching static pow2 paddings. count_only
        skips the final compaction and returns the scalar path count."""
        frj, cwj = frontier, weights
        last = len(hops) - 1
        for h, mirrors in enumerate(hops):
            pieces, ws = [], []
            for (ptr, idx), md in zip(mirrors, mds[h]):
                nodes, w = gather_hop(ptr, idx, frj, cwj, md)
                pieces.append(nodes)
                ws.append(w)
            allnodes = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
            allw = jnp.concatenate(ws) if len(ws) > 1 else ws[0]
            if h == last and count_only:
                return allw.sum()
            frj, cwj = accum_cap(allnodes, allw, n_cap, out_sizes[h])
        return frj, cwj

    _JITTED["chain"] = chain_kernel
    return chain_kernel


class GraphMirrors:
    """Per-datastore registry: (ns, db, src_tb, dir, ft) → PointerCsr, with a
    shared NodeInterner per (ns, db) so hops compose across tables."""

    def __init__(self):
        self._interners: Dict[Tuple[str, str], NodeInterner] = {}
        self._m: Dict[tuple, PointerCsr] = {}
        self._built: Set[Tuple[str, str, str]] = set()
        # tables mid-build: deltas committed during the build scan are
        # buffered here and replayed after load (closes the scan→built gap)
        self._building: Dict[Tuple[str, str, str], List[tuple]] = {}
        self._build_locks: Dict[Tuple[str, str, str], threading.Lock] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------ plumbing
    def interner(self, ns: str, db: str) -> NodeInterner:
        with self._lock:
            it = self._interners.get((ns, db))
            if it is None:
                it = NodeInterner()
                self._interners[(ns, db)] = it
            return it

    def _get_or_create(self, ns, db, src_tb, d: bytes, ft: str) -> PointerCsr:
        k = (ns, db, src_tb, bytes(d), ft)
        with self._lock:
            m = self._m.get(k)
            if m is None:
                m = PointerCsr(self.interner(ns, db))
                self._m[k] = m
            return m

    def get(self, ns, db, src_tb, d: bytes, ft: str) -> Optional[PointerCsr]:
        return self._m.get((ns, db, src_tb, bytes(d), ft))

    def table_built(self, ns: str, db: str, src_tb: str) -> bool:
        return (ns, db, src_tb) in self._built

    def drop_table(self, ns: str, db: str, tb: str) -> None:
        """Forget a table's mirrors (REMOVE TABLE / bulk invalidation)."""
        with self._lock:
            self._built.discard((ns, db, tb))
            self._building.pop((ns, db, tb), None)
            for k in [k for k in self._m if k[:3] == (ns, db, tb)]:
                del self._m[k]

    def drop_db(self, ns: str, db: str) -> None:
        """Forget everything of one database (REMOVE DATABASE)."""
        with self._lock:
            self._built = {k for k in self._built if k[:2] != (ns, db)}
            self._building = {k: v for k, v in self._building.items() if k[:2] != (ns, db)}
            for k in [k for k in self._m if k[:2] == (ns, db)]:
                del self._m[k]
            self._interners.pop((ns, db), None)

    def drop_ns(self, ns: str) -> None:
        """Forget everything of one namespace (REMOVE NAMESPACE)."""
        with self._lock:
            self._built = {k for k in self._built if k[0] != ns}
            self._building = {k: v for k, v in self._building.items() if k[0] != ns}
            for k in [k for k in self._m if k[0] == ns]:
                del self._m[k]
            for k in [k for k in self._interners if k[0] == ns]:
                del self._interners[k]

    def clear(self) -> None:
        with self._lock:
            self._m.clear()
            self._built.clear()
            self._building.clear()
            self._interners.clear()

    # ------------------------------------------------------------ build
    def ensure_table(self, ctx, src_tb: str) -> None:
        """Build every (dir, ft) mirror of `src_tb` with ONE scan over its
        `~` pointer keyspace. The scan runs on a FRESH snapshot opened after
        delta-buffering starts, so (a) deltas committed concurrently with
        the scan are buffered and replayed afterwards (apply is idempotent)
        and no committed edge can fall between the scan and the built flag,
        and (b) the querying transaction's own uncommitted writes never
        leak into the shared mirror (they force the exact KV walk anyway)."""
        ns, db = ctx.ns_db()
        key3 = (ns, db, src_tb)
        with self._lock:
            if key3 in self._built:
                return
            bl = self._build_locks.setdefault(key3, threading.Lock())
        with bl:
            with self._lock:
                if key3 in self._built:
                    return
                self._building[key3] = []
            it = self.interner(ns, db)
            adjs: Dict[Tuple[bytes, str], Dict[int, List[int]]] = {}
            pre = keys.graph_prefix(ns, db, src_tb)
            txn = ctx.ds().transaction(False)
            try:
                for chunk in txn.batch(pre, prefix_end(pre), 4096):
                    for k, _ in chunk:
                        id_, d, ft, fk = keys.decode_graph(k, ns, db, src_tb)
                        if not isinstance(fk, Thing):
                            continue
                        s = it.intern(Thing(src_tb, id_))
                        t = it.intern(fk)
                        adjs.setdefault((bytes(d), ft), {}).setdefault(s, []).append(t)
            finally:
                txn.cancel()
            with self._lock:
                for (d, ft), adj in adjs.items():
                    self._get_or_create(ns, db, src_tb, d, ft).load(adj)
                pending = self._building.pop(key3, [])
                for delta in pending:
                    self._apply_one(delta)
                self._built.add(key3)

    # ------------------------------------------------------------ deltas
    def _apply_one(self, delta: tuple) -> None:
        ns, db, src_tb, d, ft, src, dst, add = delta
        it = self.interner(ns, db)
        m = self._get_or_create(ns, db, src_tb, d, ft)
        m.apply(it.intern(src), it.intern(dst), add)

    def apply_deltas(self, deltas: Sequence[tuple]) -> None:
        """Apply committed edge-pointer deltas to built (or mid-build)
        tables. Each delta: (ns, db, src_tb, dir, ft, src, dst, add).
        Unbuilt tables ignore deltas — their eventual build scan sees the
        committed KV state anyway.
        """
        for delta in deltas:
            key3 = tuple(delta[:3])
            with self._lock:
                if key3 in self._building:
                    self._building[key3].append(delta)
                    continue
                if key3 not in self._built:
                    continue
                self._apply_one(delta)

    # ------------------------------------------------------------ traversal
    def _hop_mirrors(self, ns, db, spec) -> List[PointerCsr]:
        srcs, dirs, fts = spec
        out = []
        for tb in srcs:
            for d in dirs:
                for ft in fts:
                    m = self.get(ns, db, tb, d, ft)
                    if m is not None and m.adj:
                        out.append(m)
        return out

    def _host_hop(self, ns, db, frontier: np.ndarray, counts: np.ndarray, spec):
        out: Dict[int, int] = {}
        for m in self._hop_mirrors(ns, db, spec):
            with m._lock:  # deltas may mutate adj lists concurrently
                for i, c in zip(frontier.tolist(), counts.tolist()):
                    for dst in m.adj.get(int(i), ()):
                        out[dst] = out.get(dst, 0) + c
        nodes = np.fromiter(sorted(out), dtype=np.int32, count=len(out))
        return nodes, np.array([out[int(n)] for n in nodes], dtype=np.int32)

    def _device_chain(
        self, ns, db, frontier: np.ndarray, counts: np.ndarray, specs,
        count_only: bool = False,
    ):
        """Run the remaining hops entirely on device in ONE fused dispatch:
        one upload, H weighted gathers with on-device scatter-add dedup
        between hops, one download at the end (a scalar when count_only).
        Every static dimension (frontier size, max degree, node capacity,
        dedup output) is pow2-rounded so steady writes don't recompile."""
        import jax.numpy as jnp

        chain_kernel = _kernels()
        it = self.interner(ns, db)
        n_cap = _next_pow2(len(it))
        fsz = _next_pow2(frontier.size)
        fr = np.full(fsz, n_cap, dtype=np.int32)
        fr[: frontier.size] = frontier
        cw = np.zeros(fsz, dtype=np.int32)
        cw[: counts.size] = counts

        hops, mds, out_sizes = [], [], []
        width = fsz
        for spec in specs:
            mirrors = self._hop_mirrors(ns, db, spec)
            if not mirrors:
                if count_only:
                    return 0
                e = np.empty(0, dtype=np.int32)
                return e, e
            hop_arrs, hop_mds, total = [], [], 0
            for m in mirrors:
                hop_arrs.append(m.device_arrays())
                md = _next_pow2(max(m.max_degree, 1))
                hop_mds.append(md)
                total += width * md
            hops.append(tuple(hop_arrs))
            mds.append(tuple(hop_mds))
            width = _next_pow2(min(total, n_cap))
            out_sizes.append(width)
        out = chain_kernel(
            tuple(hops), jnp.asarray(fr), jnp.asarray(cw),
            mds=tuple(mds), n_cap=n_cap, out_sizes=tuple(out_sizes),
            count_only=count_only,
        )
        if count_only:
            return int(out)
        u = np.asarray(out[0])
        c = np.asarray(out[1])
        keep = c > 0
        return u[keep].astype(np.int32), c[keep].astype(np.int32)

    def _chain_frontier(self, ctx, start: List[Thing], parts: List, count_only: bool = False):
        """Shared frontier machinery for chain()/chain_count(): returns
        (frontier int32[], counts int32[], interner) — or the scalar path
        count when count_only (the device chain then downloads one int)."""
        from surrealdb_tpu import cnf

        ns, db = ctx.ns_db()
        it = self.interner(ns, db)
        dir_map = {"out": [keys.DIR_OUT], "in": [keys.DIR_IN], "both": [keys.DIR_IN, keys.DIR_OUT]}
        # pre-resolve hop specs; a hop filtered on foreign-table ft lands
        # entirely in table ft, so the next hop's sources are exactly p.what
        tables = {t.tb for t in start}
        specs = []
        for p in parts:
            for tb in tables:
                self.ensure_table(ctx, tb)
            specs.append((sorted(tables), dir_map[p.dir], p.what))
            tables = set(p.what)
        cmap: Dict[int, int] = {}
        for t in start:
            i = it.lookup(t)
            if i is not None:
                cmap[i] = cmap.get(i, 0) + 1
        frontier = np.fromiter(sorted(cmap), dtype=np.int32, count=len(cmap))
        counts = np.array([cmap[int(i)] for i in frontier], dtype=np.int32)
        i = 0
        while i < len(specs):
            if (
                not cnf.TPU_DISABLE
                and frontier.size >= cnf.TPU_GRAPH_ONDEVICE_THRESHOLD
            ):
                res = self._device_chain(
                    ns, db, frontier, counts, specs[i:], count_only=count_only
                )
                if count_only:
                    return res
                frontier, counts = res
                break
            frontier, counts = self._host_hop(ns, db, frontier, counts, specs[i])
            i += 1
        if count_only:
            return int(counts.sum())
        return frontier, counts, it

    def chain(
        self,
        ctx,
        start: List[Thing],
        parts: List,  # List[PGraph]
    ) -> List[Thing]:
        """Run a maximal chain of cond-free graph parts `->a->b->c` as
        batched frontier hops: host adjacency while the frontier is small,
        then the rest of the chain on device once it crosses
        TPU_GRAPH_ONDEVICE_THRESHOLD.

        Multiplicity matches the reference's flatten-without-dedup semantics
        (sql/value/get.rs:404-446): the frontier is deduplicated between hops
        but each node carries its path count, and the final result expands
        each node count times. Result order is deterministic (ascending
        intern order ≈ build-scan key order, with delta-added nodes after)
        but not identical to the KV walk's key order; graph hop ordering is
        unspecified upstream.
        """
        frontier, counts, it = self._chain_frontier(ctx, start, parts)
        out: List[Thing] = []
        for j, c in zip(frontier, counts):
            out.extend([it.node_of[int(j)]] * int(c))
        return out

    def chain_count(self, ctx, start: List[Thing], parts: List) -> int:
        """Path count of a chain WITHOUT materializing the expanded result —
        `count(->a->b->c)` sums the frontier's path counts directly (on a
        3-hop over 1M edges the Python expansion would dominate the whole
        query; the device already holds the counts, and the fused chain
        kernel downloads a single scalar)."""
        return self._chain_frontier(ctx, start, parts, count_only=True)
