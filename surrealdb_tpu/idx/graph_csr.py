"""Device-resident CSR graph mirrors + batched frontier expansion.

Role of the reference's per-record edge-prefix scans (reference:
core/src/dbs/processor.rs:610-701 collect_edges, sql/value/get.rs:404-446 —
hop N over R records ⇒ R separate KV range scans) re-designed TPU-first
(SURVEY §3.5): each (src_table, direction, foreign_table) pointer keyspace is
packed into CSR arrays (indptr/indices) over a node id space shared across
all mirrors of a database, so a multi-hop idiom like `->knows->person` is a
sequence of fixed-shape gather kernels with on-device dedup instead of
R₁+R₂+… pointer chases.

Maintenance is incremental: the base adjacency is built with ONE scan over
the source table's `~` keyspace (all directions/foreign-tables at once), and
every committed RELATE/DELETE applies per-edge deltas through the
transaction's graph-delta buffer (kvs/tx.py) — no corpus rescans on write
(reference analog: trees/store/cache.rs generation swap, improved). Device
arrays are recompacted lazily from the host adjacency when dirty; queries
inside a transaction that has its own uncommitted edge writes fall back to
the exact KV walk (sql/path.py graph_hop).
"""

from __future__ import annotations

import threading
from surrealdb_tpu.utils import locks as _locks
from functools import partial
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.value import Thing
from surrealdb_tpu.utils.num import next_pow2 as _next_pow2


class NodeInterner:
    """Thing ↔ dense-int mapping shared by every mirror of one (ns, db)."""

    def __init__(self):
        self.id_of: Dict[Tuple[str, str], int] = {}
        self.node_of: List[Thing] = []
        self._lock = _locks.Lock("idx.graph.interner")

    def __len__(self) -> int:
        return len(self.node_of)

    def intern(self, t: Thing) -> int:
        k = (t.tb, repr(t.id))
        i = self.id_of.get(k)
        if i is None:
            with self._lock:
                i = self.id_of.get(k)
                if i is None:
                    i = len(self.node_of)
                    self.node_of.append(t)
                    self.id_of[k] = i
        return i

    def lookup(self, t: Thing) -> Optional[int]:
        return self.id_of.get((t.tb, repr(t.id)))


class PointerCsr:
    """Adjacency for one (src_tb, direction, foreign_tb) pointer keyspace.

    Host side: `adj` dict of global-int lists — authoritative, updated by
    deltas. Device side: indptr/indices arrays compacted lazily.
    """

    def __init__(self, interner: NodeInterner):
        self.interner = interner
        self.adj: Dict[int, List[int]] = {}
        self.version = 0  # bumped on every mutation (dense-operator cache key)
        self.dirty = True
        self.indptr: Optional[np.ndarray] = None
        self.indices: Optional[np.ndarray] = None
        self._dev = None  # (jnp indptr, jnp indices) cache
        self._dev_csc = None  # (jnp cptr, jnp csrc) dst-sorted cache
        self.edge_count = 0
        self.n_built = 0
        self.max_degree = 0
        self._lock = _locks.Lock("idx.graph.mirror")

    def load(self, adj: Dict[int, List[int]]) -> None:
        with self._lock:
            _locks.assert_held(self._lock, "graph.adjacency")
            self.adj = adj
            self.edge_count = sum(len(v) for v in adj.values())
            self.version += 1
            self.dirty = True

    def apply(self, src: int, dst: int, add: bool) -> None:
        """Idempotent delta: pointer keys are unique in KV, so the mirror
        holds at most one (src, dst) entry per keyspace."""
        with self._lock:
            # adjacency/version/dirty are one guarded unit: a mutation
            # outside idx.graph.mirror races ensure_arrays' compaction
            _locks.assert_held(self._lock, "graph.adjacency")
            lst = self.adj.setdefault(src, [])
            if add:
                if dst not in lst:
                    lst.append(dst)
                    self.edge_count += 1
            else:
                try:
                    lst.remove(dst)
                    self.edge_count -= 1
                except ValueError:
                    pass
                if not lst:
                    del self.adj[src]
            self.version += 1
            self.dirty = True

    def ensure_arrays(self) -> None:
        """Compact host adjacency into CSR arrays (numpy only — no KV)."""
        n = len(self.interner)
        with self._lock:
            _locks.assert_held(self._lock, "graph.adjacency")
            if not self.dirty and self.n_built == n and self.indptr is not None:
                return
            # indptr spans a pow2-padded node capacity and indices a pow2
            # buffer so XLA kernel shapes stay stable while edges trickle in
            # (a recompile per RELATE would dwarf the gather itself)
            cap = _next_pow2(max(n, 1))
            indptr = np.zeros(cap + 1, dtype=np.int32)
            for src, lst in self.adj.items():
                if src < n:
                    indptr[src + 1] = len(lst)
            self.max_degree = int(indptr.max()) if n else 0
            np.cumsum(indptr, out=indptr)
            indices = np.zeros(_next_pow2(max(int(indptr[-1]), 1)), dtype=np.int32)
            fill = indptr[:-1].copy()
            for src, lst in self.adj.items():
                if src >= n:
                    continue
                k = fill[src]
                indices[k : k + len(lst)] = lst
            self.indptr = indptr
            self.indices = indices
            self._dev = None
            self._dev_csc = None
            self.n_built = n
            self.dirty = False

    def device_arrays(self):
        import jax.numpy as jnp

        self.ensure_arrays()
        if self._dev is None:
            self._dev = (jnp.asarray(self.indptr), jnp.asarray(self.indices))
        return self._dev

    def device_csc(self):
        """Destination-sorted (cptr, csrc) device arrays for scatter-free
        dense SpMV hops (batched count chains): y[v] = Σ x[src] over edges
        into v becomes cumsum over dst-sorted x[csrc] + a boundary gather —
        gathers and a prefix-scan only, no scatter (TPU scatter-add is
        serial-slow; cumsum + gather ride the VPU). Padding edges carry the
        sentinel src/dst `cap` and fall outside every real bin."""
        import jax.numpy as jnp

        self.ensure_arrays()
        if self._dev_csc is None:
            cap = len(self.indptr) - 1
            nnz = int(self.indptr[-1])
            E = len(self.indices)
            esrc = np.full(E, cap, dtype=np.int32)
            esrc[:nnz] = np.repeat(
                np.arange(cap, dtype=np.int32), np.diff(self.indptr)
            )
            edst = self.indices.astype(np.int64, copy=True)
            edst[nnz:] = cap
            order = np.argsort(edst, kind="stable")
            csrc = esrc[order]
            counts = np.bincount(edst, minlength=cap + 1)
            cptr = np.zeros(cap + 2, dtype=np.int32)
            np.cumsum(counts, out=cptr[1:])
            self._dev_csc = (jnp.asarray(cptr[: cap + 1]), jnp.asarray(csrc))
        return self._dev_csc


# ------------------------------------------------------------------ kernels
_JITTED: dict = {}


def _dense_shape_key(lanes: int, fsz: int, n0: int, As) -> tuple:
    """Compile-cache key of the dense count kernel: lane count, frontier
    pad, source space + each operator's padded dims (what XLA keys on)."""
    return (lanes, fsz, n0, tuple(tuple(int(d) for d in a.shape) for a in As))


def _csc_shape_key(lanes: int, fsz: int, n_cap: int, csc_hops, last_hop) -> tuple:
    """Compile-cache key of the batched CSC count kernel: per-hop array
    paddings decide the executable shape."""
    return (
        lanes, fsz, n_cap,
        tuple(int(a.shape[0]) for hop in csc_hops for pair in hop for a in pair),
        tuple(int(p.shape[0]) for (p,) in last_hop),
    )


def _kernels():
    """Lazily build the jitted hop kernels (keeps jax off the import path).

    The whole remaining chain compiles into ONE jitted call — on a tunneled
    or queued device each dispatch costs ~100ms RTT, so per-hop kernels made
    a 3-hop query ~7 round trips (BENCH_r03 p50 816ms); fused it is one.
    """
    if _JITTED:
        return _JITTED["chain"]
    import jax
    import jax.numpy as jnp

    def gather_hop(ptr, idx, frontier, weights, md):
        # one weighted CSR gather: frontier [F] ints with multiplicities →
        # neighbor slots [F*md] + per-slot weight (0 = padding). Carrying a
        # count per node instead of a bare frontier makes the hop an SpMV
        # over the adjacency, which preserves the reference's flatten-
        # without-dedup result multiplicity (sql/value/get.rs:404-446)
        # while still deduplicating the *frontier* between hops.
        n = ptr.shape[0] - 1
        fr = jnp.clip(frontier, 0, jnp.maximum(n - 1, 0))
        s = ptr[fr]
        deg = ptr[fr + 1] - s
        offs = jnp.arange(md)[None, :]
        take = jnp.clip(s[:, None] + offs, 0, idx.shape[0] - 1)
        valid = (offs < deg[:, None]) & (weights > 0)[:, None] & (frontier < n)[:, None]
        w = jnp.where(valid, weights[:, None], 0)
        return idx[take].reshape(-1), w.reshape(-1)

    def accum_cap(nodes, w, n_nodes, out_size):
        # dense scatter-add dedup: per-node path counts survive the frontier
        # compaction (capped, jit-static output size)
        safe = jnp.where(w > 0, jnp.clip(nodes, 0, n_nodes), n_nodes)
        dense = jnp.zeros(n_nodes + 1, dtype=jnp.int32).at[safe].add(w)
        dense = dense.at[n_nodes].set(0)
        present = jnp.nonzero(dense > 0, size=out_size, fill_value=n_nodes)[0]
        return present, jnp.where(present < n_nodes, dense[present], 0)

    def chain_impl(hops, frontier, weights, mds, n_cap, out_sizes, count_only):
        frj, cwj = frontier, weights
        last = len(hops) - 1
        for h, mirrors in enumerate(hops):
            if h == last and count_only:
                # the final hop of a count never materializes neighbors:
                # paths through node v multiply by deg(v), so the count is
                # one weighted degree reduction (no gather, no scatter —
                # the batched form stays tiny at any frontier width)
                total = 0
                for (ptr, _idx), _md in zip(mirrors, mds[h]):
                    n = ptr.shape[0] - 1
                    fr_c = jnp.clip(frj, 0, jnp.maximum(n - 1, 0))
                    deg = ptr[fr_c + 1] - ptr[fr_c]
                    valid = (frj < n) & (cwj > 0)
                    total = total + jnp.where(valid, deg * cwj, 0).sum()
                return total
            pieces, ws = [], []
            for (ptr, idx), md in zip(mirrors, mds[h]):
                nodes, w = gather_hop(ptr, idx, frj, cwj, md)
                pieces.append(nodes)
                ws.append(w)
            allnodes = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
            allw = jnp.concatenate(ws) if len(ws) > 1 else ws[0]
            frj, cwj = accum_cap(allnodes, allw, n_cap, out_sizes[h])
        return frj, cwj

    @partial(
        jax.jit, static_argnames=("mds", "n_cap", "out_sizes", "count_only")
    )
    def chain_kernel(hops, frontier, weights, mds, n_cap, out_sizes, count_only):
        """Full multi-hop chain in one dispatch. hops: tuple (one per hop) of
        tuples of (indptr, indices) device arrays (one per contributing
        mirror); mds/out_sizes: matching static pow2 paddings. count_only
        skips the final compaction and returns the scalar path count."""
        return chain_impl(hops, frontier, weights, mds, n_cap, out_sizes, count_only)

    def _deg(ptr, frj, cwj):
        """Weighted degree reduction: Σ cw[v]·deg(v) over a compact frontier."""
        n = ptr.shape[0] - 1
        fr_c = jnp.clip(frj, 0, jnp.maximum(n - 1, 0))
        deg = ptr[fr_c + 1] - ptr[fr_c]
        return jnp.where((frj < n) & (cwj > 0), deg * cwj, 0).sum(axis=-1)

    @partial(jax.jit, static_argnames=("n_cap",))
    def chain_count_batch(csc_hops, last_hop, frontiers, weights, n_cap):
        """Batched count-only chains for B concurrent queries over the SAME
        adjacency (the cross-query coalescing seam, dbs/dispatch.py).
        Scatter-free: TPU scatter-add is serial-slow and vmapped
        nonzero/compaction is worse, so every hop is cast as dense SpMV in
        cumsum form —
        - seeds densify with one tiny scatter (B x frontier-width updates)
        - each non-final hop: gather counts at dst-sorted edge sources,
          prefix-scan, difference at bin boundaries (y[v] = S[end_v] -
          S[start_v]) — gathers + one cumsum, VPU-friendly at any width
        - the final hop of a count never materializes neighbors: it is a
          degree dot-product
        csc_hops: tuple per non-final hop of ((cptr, csrc), ...);
        last_hop: ((ptr,), ...)."""
        B = frontiers.shape[0]
        if not csc_hops and not last_hop:
            return jnp.zeros((B,), dtype=jnp.int32)
        if not csc_hops:
            # 1-hop count: weighted degree over the compact seed frontier
            total = 0
            for (ptr,) in last_hop:
                n = ptr.shape[0] - 1
                fr_c = jnp.clip(frontiers, 0, jnp.maximum(n - 1, 0))
                deg = ptr[fr_c + 1] - ptr[fr_c]
                total = total + jnp.where(
                    (frontiers < n) & (weights > 0), deg * weights, 0
                ).sum(axis=1)
            return total
        # densify the seed frontier: [B, n_cap+1] (sentinel column n_cap)
        lane_off = (jnp.arange(B) * (n_cap + 1))[:, None]
        safe = jnp.where(weights > 0, jnp.clip(frontiers, 0, n_cap), n_cap)
        x = (
            jnp.zeros(B * (n_cap + 1), dtype=jnp.int32)
            .at[(lane_off + safe).reshape(-1)]
            .add(weights.reshape(-1))
            .reshape(B, n_cap + 1)
        )
        zcol = jnp.zeros((B, 1), dtype=jnp.int32)
        for mirrors in csc_hops:
            x = x.at[:, n_cap].set(0)
            y = 0
            for cptr, csrc in mirrors:
                vals = x[:, csrc]  # sentinel src reads the zeroed column
                s = jnp.concatenate([zcol, jnp.cumsum(vals, axis=1)], axis=1)
                y = y + (s[:, cptr[1:]] - s[:, cptr[:-1]])
            x = jnp.concatenate([y, zcol], axis=1)
        xr = x[:, :n_cap]
        total = 0
        for (ptr,) in last_hop:
            deg = ptr[1 : n_cap + 1] - ptr[:n_cap]
            total = total + (xr * deg[None, :]).sum(axis=1)
        return total

    @partial(jax.jit, static_argnames=("n0",))
    def dense_count_batch(As, outdeg, frontiers, weights, n0):
        """Batched count chains as MXU matmuls: each logical `->edge->node`
        pair is pre-composed into a dense node->node adjacency (bf16, exact
        for small integer multiplicities), so B concurrent 3-hop counts are
        TWO [B, n]x[n, n] matmuls + a degree dot-product in ONE dispatch —
        the gather/scatter-free formulation of graph traversal this
        hardware actually wants. seeds arrive as compact LOCAL ids."""
        B = frontiers.shape[0]
        lane = (jnp.arange(B) * (n0 + 1))[:, None]
        safe = jnp.where(weights > 0, jnp.clip(frontiers, 0, n0), n0)
        x = (
            jnp.zeros(B * (n0 + 1), dtype=jnp.float32)
            .at[(lane + safe).reshape(-1)]
            .add(weights.reshape(-1).astype(jnp.float32))
            .reshape(B, n0 + 1)[:, :n0]
        )
        for A in As:
            x = jnp.dot(x, A.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST)
        return (x * outdeg[None, :]).sum(axis=1)

    _JITTED["chain"] = chain_kernel
    _JITTED["chain_count_batch"] = chain_count_batch
    _JITTED["dense_count_batch"] = dense_count_batch
    return chain_kernel


class GraphMirrors:
    """Per-datastore registry: (ns, db, src_tb, dir, ft) → PointerCsr, with a
    shared NodeInterner per (ns, db) so hops compose across tables."""

    def __init__(self):
        self._interners: Dict[Tuple[str, str], NodeInterner] = {}
        self._m: Dict[tuple, PointerCsr] = {}
        self._built: Set[Tuple[str, str, str]] = set()
        # dense composed operators + per-table compact id spaces
        self._spaces: Dict[tuple, dict] = {}  # (ns,db,tb) -> space dict
        self._dense: Dict[tuple, dict] = {}  # pair key -> operator dict
        # tables mid-build: deltas committed during the build scan are
        # buffered here and replayed after load (closes the scan→built gap)
        self._building: Dict[Tuple[str, str, str], List[tuple]] = {}
        self._build_locks: Dict[Tuple[str, str, str], threading.Lock] = {}
        self._lock = _locks.RLock("idx.graph.registry")
        # ingest-time prewarm (cnf.GRAPH_PREWARM): RELATE commits into a
        # not-yet-mirrored table arm a debounced timer; when ingest
        # quiesces, the mirror build + batched-count-kernel compiles run in
        # the background so the FIRST query doesn't pay the multi-second
        # (at scale, multi-minute) build + XLA-compile cliff
        self._ds = None  # weakref to the owning Datastore (set by bind_ds)
        self._prewarm_timers: Dict[Tuple[str, str, str], threading.Timer] = {}
        self._prewarm_deadline: Dict[Tuple[str, str, str], float] = {}
        self._prewarm_running: Set[Tuple[str, str, str]] = set()
        self._warmed_pairs: Set[tuple] = set()
        # flight-recorder task ids of armed prewarms (bg.py lifecycle)
        self._task_ids: Dict[Tuple[str, str, str], int] = {}
        self._owner = None  # id(ds), for bg teardown scoping

    # ------------------------------------------------------------ plumbing
    def bind_ds(self, ds) -> None:
        """Bind the owning Datastore (weakly): prewarm builds open their own
        read transactions, which needs more than the commit-path hook has."""
        import weakref

        self._ds = weakref.ref(ds)
        self._owner = id(ds)

    def interner(self, ns: str, db: str) -> NodeInterner:
        with self._lock:
            it = self._interners.get((ns, db))
            if it is None:
                it = NodeInterner()
                self._interners[(ns, db)] = it
            return it

    def _get_or_create(self, ns, db, src_tb, d: bytes, ft: str) -> PointerCsr:
        k = (ns, db, src_tb, bytes(d), ft)
        with self._lock:
            m = self._m.get(k)
            if m is None:
                m = PointerCsr(self.interner(ns, db))
                self._m[k] = m
            return m

    def get(self, ns, db, src_tb, d: bytes, ft: str) -> Optional[PointerCsr]:
        return self._m.get((ns, db, src_tb, bytes(d), ft))

    def table_built(self, ns: str, db: str, src_tb: str) -> bool:
        return (ns, db, src_tb) in self._built

    def drop_table(self, ns: str, db: str, tb: str) -> None:
        """Forget a table's mirrors (REMOVE TABLE / bulk invalidation)."""
        with self._lock:
            self._built.discard((ns, db, tb))
            self._building.pop((ns, db, tb), None)
            for k in [k for k in self._m if k[:3] == (ns, db, tb)]:
                del self._m[k]

    def drop_db(self, ns: str, db: str) -> None:
        """Forget everything of one database (REMOVE DATABASE)."""
        with self._lock:
            self._built = {k for k in self._built if k[:2] != (ns, db)}
            self._building = {k: v for k, v in self._building.items() if k[:2] != (ns, db)}
            for k in [k for k in self._m if k[:2] == (ns, db)]:
                del self._m[k]
            self._interners.pop((ns, db), None)

    def drop_ns(self, ns: str) -> None:
        """Forget everything of one namespace (REMOVE NAMESPACE)."""
        with self._lock:
            self._built = {k for k in self._built if k[0] != ns}
            self._building = {k: v for k, v in self._building.items() if k[0] != ns}
            for k in [k for k in self._m if k[0] == ns]:
                del self._m[k]
            for k in [k for k in self._interners if k[0] == ns]:
                del self._interners[k]

    def clear(self) -> None:
        with self._lock:
            self._m.clear()
            self._built.clear()
            self._building.clear()
            self._interners.clear()

    # ------------------------------------------------------------ build
    def ensure_table(self, ctx, src_tb: str) -> None:
        """Build every (dir, ft) mirror of `src_tb` with ONE scan over its
        `~` pointer keyspace. The scan runs on a FRESH snapshot opened after
        delta-buffering starts, so (a) deltas committed concurrently with
        the scan are buffered and replayed afterwards (apply is idempotent)
        and no committed edge can fall between the scan and the built flag,
        and (b) the querying transaction's own uncommitted writes never
        leak into the shared mirror (they force the exact KV walk anyway)."""
        ns, db = ctx.ns_db()
        self.build_table(ctx.ds(), ns, db, src_tb)

    def build_table(self, ds, ns: str, db: str, src_tb: str) -> None:
        """ensure_table's engine: also callable from the background prewarm
        thread, which has a Datastore but no request context."""
        key3 = (ns, db, src_tb)
        with self._lock:
            if key3 in self._built:
                return
            bl = self._build_locks.setdefault(key3, _locks.Lock("idx.graph.build"))
        with bl:
            with self._lock:
                if key3 in self._built:
                    return
                self._building[key3] = []
            it = self.interner(ns, db)
            adjs: Dict[Tuple[bytes, str], Dict[int, List[int]]] = {}
            pre = keys.graph_prefix(ns, db, src_tb)
            txn = ds.transaction(False)
            try:
                for chunk in txn.batch(pre, prefix_end(pre), 4096):
                    for k, _ in chunk:
                        id_, d, ft, fk = keys.decode_graph(k, ns, db, src_tb)
                        if not isinstance(fk, Thing):
                            continue
                        s = it.intern(Thing(src_tb, id_))
                        t = it.intern(fk)
                        adjs.setdefault((bytes(d), ft), {}).setdefault(s, []).append(t)
            finally:
                txn.cancel()
            with self._lock:
                for (d, ft), adj in adjs.items():
                    self._get_or_create(ns, db, src_tb, d, ft).load(adj)
                pending = self._building.pop(key3, [])
                for delta in pending:
                    self._apply_one(delta)
                self._built.add(key3)

    # ------------------------------------------------------------ deltas
    def _apply_one(self, delta: tuple) -> None:
        ns, db, src_tb, d, ft, src, dst, add = delta
        it = self.interner(ns, db)
        m = self._get_or_create(ns, db, src_tb, d, ft)
        m.apply(it.intern(src), it.intern(dst), add)

    def apply_deltas(self, deltas: Sequence[tuple]) -> None:
        """Apply committed edge-pointer deltas to built (or mid-build)
        tables. Each delta: (ns, db, src_tb, dir, ft, src, dst, add).
        Unbuilt tables ignore deltas — their eventual build scan sees the
        committed KV state anyway — but each such commit (re-)arms the
        debounced prewarm so the build + kernel compiles happen in the
        ingest→first-query gap instead of inside the first query.
        """
        unbuilt: Set[Tuple[str, str, str]] = set()
        for delta in deltas:
            key3 = tuple(delta[:3])
            with self._lock:
                if key3 in self._building:
                    self._building[key3].append(delta)
                    continue
                if key3 not in self._built:
                    unbuilt.add(key3)
                    continue
                self._apply_one(delta)
        if unbuilt:
            self._schedule_prewarm(unbuilt)

    # ------------------------------------------------------------ prewarm
    def _arm_timer(self, key3: Tuple[str, str, str], delay: float) -> None:
        """Start one self-identifying timer for key3 (caller holds _lock)."""
        from surrealdb_tpu import bg

        timer = bg.timer(
            delay, self._prewarm, key3, None,
            task_id=self._task_ids.get(key3),
            name=f"bg:graph_prewarm:{key3[2]}", start=False,
        )
        timer.args = (key3, timer)  # the callback must recognise itself
        self._prewarm_timers[key3] = timer
        timer.start()

    def _schedule_prewarm(self, keys3: Set[Tuple[str, str, str]]) -> None:
        """Debounce by DEADLINE, not by timer churn: each commit just moves
        the key's deadline forward; at most ONE live timer exists per key
        (it re-arms itself if it wakes early), so a million single-edge
        commits cost a million dict writes, not a million thread spawns."""
        import time as _time

        from surrealdb_tpu import cnf

        from surrealdb_tpu import bg

        if not cnf.GRAPH_PREWARM or self._ds is None:
            return
        delay = cnf.GRAPH_PREWARM_DELAY_SECS
        now = _time.monotonic()
        with self._lock:
            for key3 in keys3:
                self._prewarm_deadline[key3] = now + delay
                if key3 not in self._prewarm_timers:
                    # flight-recorder record: scheduled now, running when
                    # ingest quiesces and the build + kernel compiles start
                    self._task_ids[key3] = bg.register(
                        "graph_prewarm", target=".".join(key3), owner=self._owner
                    )
                    self._arm_timer(key3, delay)
                else:
                    tid = self._task_ids.get(key3)
                    if tid is not None:
                        bg.touch(tid)

    def _prewarm(self, key3: Tuple[str, str, str], timer) -> None:
        """Timer body (background thread): build the table's mirrors, then
        compile the batched count kernels its chains will hit. Best-effort —
        any failure leaves the lazy first-query path fully intact."""
        import time as _time

        from surrealdb_tpu import telemetry

        ns, db, tb = key3
        with self._lock:
            if self._prewarm_timers.get(key3) is not timer:
                return  # superseded — the newer timer owns this key
            remaining = self._prewarm_deadline.get(key3, 0.0) - _time.monotonic()
            if remaining > 0.001:
                # woke before the (commit-advanced) deadline: re-arm
                self._arm_timer(key3, remaining)
                return
            del self._prewarm_timers[key3]
            self._prewarm_deadline.pop(key3, None)
            self._prewarm_running.add(key3)
            task_id = self._task_ids.pop(key3, None)
        from surrealdb_tpu import bg

        if task_id is None:
            task_id = bg.register(
                "graph_prewarm", target=".".join(key3), owner=self._owner,
                trace_id=None,
            )
        try:
            with bg.run(task_id):
                ds = self._ds() if self._ds is not None else None
                if ds is None:
                    return
                telemetry.inc("graph_prewarm", stage="build")
                self.build_table(ds, ns, db, tb)
                self.warm_count_kernels(ns, db)
        except Exception:
            # the bg task record carries the error detail; the counter makes
            # a string of failed prewarms visible on /metrics
            telemetry.inc("prewarm_errors", subsystem="graph")
        finally:
            with self._lock:
                self._prewarm_running.discard(key3)

    def wait_prewarm(self, timeout: float = 30.0) -> bool:
        """Block until no prewarm timer or build is pending (test/bench
        determinism helper, never used on the query path)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                if not self._prewarm_timers and not self._prewarm_running:
                    return True
            _time.sleep(0.01)
        return False

    def shutdown(self, timeout: float = 10.0) -> None:
        """Teardown on Datastore.close(): cancel armed prewarm timers
        (resolving their flight-recorder records) and wait out in-flight
        builds, so no prewarm thread outlives its datastore."""
        from surrealdb_tpu import bg

        with self._lock:
            timers = list(self._prewarm_timers.values())
            self._prewarm_timers.clear()
            self._prewarm_deadline.clear()
            task_ids = list(self._task_ids.values())
            self._task_ids.clear()
        for t in timers:
            t.cancel()
        for tid in task_ids:
            bg.cancel(tid, "cancelled: datastore closed")
        self.wait_prewarm(timeout)

    def warm_count_kernels(self, ns: str, db: str) -> None:
        """Compile the batched count kernels for every composable
        `->edge->node` OUT-pair over built mirrors, at the lane counts and
        frontier pad the serving runners use — so a post-ingest burst of
        count-chain queries starts on pre-compiled shapes (the r6 scale-1.0
        log showed 84.8s/26.4s first-query stalls that were exactly these
        compiles). Results are discarded; zero-weight lanes are harmless."""
        from surrealdb_tpu import cnf, telemetry

        if cnf.TPU_DISABLE:
            return
        import jax.numpy as jnp

        _kernels()
        dense_kernel = _JITTED["dense_count_batch"]
        csc_kernel = _JITTED["chain_count_batch"]
        with self._lock:
            mkeys = [k for k in self._m if k[0] == ns and k[1] == db]
        pairs = [
            (tb, ft, ft2)
            for (_, _, tb, d, ft) in mkeys
            if d == keys.DIR_OUT
            for (_, _, tb2, d2, ft2) in mkeys
            if tb2 == ft and d2 == keys.DIR_OUT
        ]
        fsz = _next_pow2(max(1, cnf.TPU_GRAPH_FRONTIER_PAD))
        # every lane count the serving runners can pad to: bp =
        # max(_next_pow2(B), LANES) with B capped by the dispatcher width,
        # so the shape set is {LANES, ..., pow2(DISPATCH_MAX_WIDTH)}
        lane_set = []
        b = max(cnf.TPU_GRAPH_BATCH_LANES, 1)
        top = max(_next_pow2(cnf.DISPATCH_MAX_WIDTH), b)
        while b <= top:
            lane_set.append(b)
            b *= 2
        for tb, et, dt_ in pairs:
            pkey = (ns, db, tb, et, dt_)
            with self._lock:
                if pkey in self._warmed_pairs:
                    continue
                self._warmed_pairs.add(pkey)
            spec1 = ([tb], [keys.DIR_OUT], [et])
            spec2 = ([et], [keys.DIR_OUT], [dt_])
            # chains self-compose only when the pair loops back to its
            # source table (person->knows->person); otherwise warm 1 pair
            max_pairs = 3 if dt_ == tb else 1
            telemetry.inc("graph_prewarm", stage="kernels")
            try:
                op = self._dense_pair(ns, db, spec1, spec2)
            except Exception:
                op = None
            if op is not None:
                from surrealdb_tpu import compile_log

                n0 = op["ns_pad"]
                for lanes in lane_set:
                    frs = jnp.asarray(np.full((lanes, fsz), n0, dtype=np.int32))
                    cws = jnp.asarray(np.zeros((lanes, fsz), dtype=np.int32))
                    for c in range(1, max_pairs + 1):
                        try:
                            As = (op["A"],) * (c - 1)
                            with compile_log.tracked(
                                "graph_dense",
                                _dense_shape_key(lanes, fsz, n0, As),
                                prewarmed=True,
                            ):
                                dense_kernel(As, op["outdeg"], frs, cws, n0=n0)
                        except Exception:
                            telemetry.inc(
                                "prewarm_errors", subsystem="graph_count"
                            )
                continue
            # dense doesn't fit (oversized tables / fat multiplicities):
            # warm the CSC cumsum form the serving path will use instead
            try:
                m1 = self._hop_mirrors(ns, db, spec1)
                m2 = self._hop_mirrors(ns, db, spec2)
                if len(m1) != 1 or len(m2) != 1:
                    continue
                n_cap = _next_pow2(len(self.interner(ns, db)))
                csc1, csc2 = m1[0].device_csc(), m2[0].device_csc()
                ptr2 = m2[0].device_arrays()[0]
                from surrealdb_tpu import compile_log

                for lanes in lane_set:
                    frs = jnp.asarray(np.full((lanes, fsz), n_cap, dtype=np.int32))
                    cws = jnp.asarray(np.zeros((lanes, fsz), dtype=np.int32))
                    for hops in range(1, max_pairs + 1):
                        # `->et->tb` repeated `hops` times = 2*hops specs;
                        # the final spec is a degree reduction (no CSC)
                        csc_hops = tuple(
                            ((csc1,) if i % 2 == 0 else (csc2,))
                            for i in range(2 * hops - 1)
                        )
                        with compile_log.tracked(
                            "graph_csc",
                            _csc_shape_key(lanes, fsz, n_cap, csc_hops, ((ptr2,),)),
                            prewarmed=True,
                        ):
                            csc_kernel(csc_hops, ((ptr2,),), frs, cws, n_cap=n_cap)
            except Exception:
                telemetry.inc("prewarm_errors", subsystem="graph_count")

    # ------------------------------------------------------------ traversal
    def _hop_mirrors(self, ns, db, spec) -> List[PointerCsr]:
        srcs, dirs, fts = spec
        out = []
        for tb in srcs:
            for d in dirs:
                for ft in fts:
                    m = self.get(ns, db, tb, d, ft)
                    if m is not None and m.adj:
                        out.append(m)
        return out

    def _host_hop(self, ns, db, frontier: np.ndarray, counts: np.ndarray, spec):
        out: Dict[int, int] = {}
        for m in self._hop_mirrors(ns, db, spec):
            with m._lock:  # deltas may mutate adj lists concurrently
                for i, c in zip(frontier.tolist(), counts.tolist()):
                    for dst in m.adj.get(int(i), ()):
                        out[dst] = out.get(dst, 0) + c
        nodes = np.fromiter(sorted(out), dtype=np.int32, count=len(out))
        return nodes, np.array([out[int(n)] for n in nodes], dtype=np.int32)

    def _chain_work_estimate(self, ns, db, specs, counts) -> float:
        """Expected edges traversed by a count chain: Σ over hops of the
        frontier size estimate × that hop's average degree (random-graph
        expectation from mirror edge counts). Decides device routing — a
        1-seed chain over a degree-4 graph is ~40 edges of HOST work no
        matter how many total edges the graph has, while the same seed on
        a degree-100 social graph explodes past any host budget."""
        frontier_est = float(counts.sum())
        work = 0.0
        for sp in specs:
            deg = 0.0
            for m in self._hop_mirrors(ns, db, sp):
                deg += m.edge_count / max(len(m.adj), 1)
            frontier_est *= deg
            work += frontier_est
            if work >= 1e12:
                break
        return work

    # ------------------------------------------------ dense composed counts
    def table_space(self, ns: str, db: str, tb: str) -> dict:
        """Compact per-table id space over the shared interner: sorted
        global ids of `tb`'s nodes + a global->local inverse array.
        Incrementally extended as the interner grows (append-only)."""
        it = self.interner(ns, db)
        with self._lock:
            sp = self._spaces.get((ns, db, tb))
            if sp is None:
                sp = self._spaces[(ns, db, tb)] = {
                    "globals": [], "inv": {}, "scanned": 0,
                }
            n = len(it.node_of)
            if sp["scanned"] < n:
                g, inv = sp["globals"], sp["inv"]
                for i in range(sp["scanned"], n):
                    if it.node_of[i].tb == tb:
                        inv[i] = len(g)
                        g.append(i)
                sp["scanned"] = n
            return sp

    @staticmethod
    def _pad128(n: int) -> int:
        return max(((n + 127) // 128) * 128, 128)

    def _dense_pair(self, ns, db, spec1, spec2):
        """Composed dense operator for one `->edge->node` spec pair:
        A[local_src, local_dst] = number of 2-hop paths through the edge
        table (bf16 on device — exact for multiplicities < 256; falls back
        to None if anything about the pair doesn't fit the dense form)."""
        import jax.numpy as jnp
        from surrealdb_tpu import cnf

        srcs1, dirs1, fts1 = spec1
        srcs2, dirs2, fts2 = spec2
        if len(srcs1) != 1 or len(fts1) != 1 or len(dirs1) != 1:
            return None
        if len(fts2) != 1 or len(dirs2) != 1:
            return None
        src_tb, edge_tb, dst_tb = srcs1[0], fts1[0], fts2[0]
        m1s = self._hop_mirrors(ns, db, spec1)
        m2s = self._hop_mirrors(ns, db, spec2)
        if len(m1s) != 1 or len(m2s) != 1:
            return None
        m1, m2 = m1s[0], m2s[0]
        sp_s = self.table_space(ns, db, src_tb)
        sp_d = self.table_space(ns, db, dst_tb)
        n_s, n_d = len(sp_s["globals"]), len(sp_d["globals"])
        if not n_s or not n_d:
            return None
        if max(n_s, n_d) > cnf.TPU_GRAPH_DENSE_MAX:
            return None
        key = (ns, db, src_tb, dirs1[0], edge_tb, dirs2[0], dst_tb)
        gen = (m1.version, m2.version, n_s, n_d)
        with self._lock:
            op = self._dense.get(key)
        if op is not None and op["gen"] == gen:
            return op
        # host composition: one pass over m1's edges, mapping each middle
        # edge-record to its m2 destinations
        inv_s, inv_d = sp_s["inv"], sp_d["inv"]
        ns_pad, nd_pad = self._pad128(n_s), self._pad128(n_d)
        A = np.zeros((ns_pad + 1, nd_pad), dtype=np.float32)
        # copy both adjacencies up front: the O(paths) composition loop must
        # not hold mirror locks (it would stall every concurrent RELATE)
        with m1._lock:
            adj1 = {k: list(v) for k, v in m1.adj.items()}
        with m2._lock:
            adj2 = {k: list(v) for k, v in m2.adj.items()}
        rows_s, rows_d = [], []
        for g_src, mids in adj1.items():
            ls = inv_s.get(g_src)
            if ls is None:
                continue
            for mid in mids:
                for g_dst in adj2.get(mid, ()):
                    ld = inv_d.get(g_dst)
                    if ld is not None:
                        rows_s.append(ls)
                        rows_d.append(ld)
        if rows_s:
            np.add.at(
                A,
                (np.asarray(rows_s, np.int64), np.asarray(rows_d, np.int64)),
                1.0,
            )
        if float(A.max(initial=0.0)) >= 256.0:
            return None  # bf16 would round the multiplicity
        outdeg = A[:ns_pad].sum(axis=1).astype(np.float32)
        import ml_dtypes

        op = {
            "gen": gen,
            "n_src": n_s,
            "n_dst": n_d,
            "ns_pad": ns_pad,
            "nd_pad": nd_pad,
            "A": jnp.asarray(A[:ns_pad].astype(ml_dtypes.bfloat16)),
            "outdeg": jnp.asarray(outdeg),
            # ∞-norm of the operator: bounds count growth per hop for the
            # f32-exactness guard in _dense_chain_count
            "rowmax": float(outdeg.max(initial=0.0)),
            "space_src": sp_s,
        }
        with self._lock:
            self._dense[key] = op
        return op

    def _dense_chain_count(self, ns, db, frontier, counts, specs, dispatch):
        """Count chain as composed dense matmuls (see dense_count_batch).
        Returns None when the chain doesn't fit the dense form (odd spec
        count, multi-table hops, oversized tables, fat multiplicities) —
        the caller then uses the CSC path."""
        import jax.numpy as jnp
        from surrealdb_tpu import cnf

        if len(specs) < 2 or len(specs) % 2 != 0:
            return None
        ops = []
        for i in range(0, len(specs), 2):
            op = self._dense_pair(ns, db, specs[i], specs[i + 1])
            if op is None:
                return None
            ops.append(op)
        # chain spaces must line up: pair i's dst space is pair i+1's src
        for a, b in zip(ops, ops[1:]):
            if a["nd_pad"] != b["ns_pad"] or a["n_dst"] != b["n_src"]:
                return None
        # f32 matmuls are exact only below 2^24: bound the worst-case count
        # (Σ seed weights × Π per-hop ∞-norms) and fall back to the exact
        # int32 CSC path when it could overflow the mantissa
        bound = float(counts.sum())
        for op in ops:
            bound *= max(op["rowmax"], 1.0)
        if bound >= float(1 << 24):
            return None
        _kernels()
        kernel = _JITTED["dense_count_batch"]
        n0 = ops[0]["ns_pad"]
        inv0 = ops[0]["space_src"]["inv"]
        fsz = _next_pow2(max(frontier.size, cnf.TPU_GRAPH_FRONTIER_PAD))
        fr = np.full(fsz, n0, dtype=np.int32)
        cw = np.zeros(fsz, dtype=np.int32)
        j = 0
        for g, c in zip(frontier.tolist(), counts.tolist()):
            loc = inv0.get(int(g))
            if loc is not None:
                fr[j] = loc
                cw[j] = c
                j += 1
        if j == 0:
            return 0
        As = tuple(op["A"] for op in ops[:-1])
        outdeg = ops[-1]["outdeg"]
        key = (
            "gdense", fsz, n0,
            tuple(id(a) for a in As), id(outdeg),
        )

        def runner(payloads):
            from surrealdb_tpu import compile_log

            B = len(payloads)
            bp = max(_next_pow2(B), cnf.TPU_GRAPH_BATCH_LANES)
            frs = np.full((bp, fsz), n0, dtype=np.int32)
            cws = np.zeros((bp, fsz), dtype=np.int32)
            for i, (f, c) in enumerate(payloads):
                frs[i] = f
                cws[i] = c
            with compile_log.tracked(
                "graph_dense", _dense_shape_key(bp, fsz, n0, As)
            ):
                out = kernel(
                    As, outdeg, jnp.asarray(frs), jnp.asarray(cws), n0=n0
                )

            def collect():
                vals = np.asarray(out)
                return [int(round(float(vals[i]))) for i in range(B)]

            return collect

        return dispatch.submit(key, (fr, cw), runner)

    def _device_chain(
        self, ns, db, frontier: np.ndarray, counts: np.ndarray, specs,
        count_only: bool = False, dispatch=None,
    ):
        """Run the remaining hops entirely on device in ONE fused dispatch:
        one upload, H weighted gathers with on-device scatter-add dedup
        between hops, one download at the end (a scalar when count_only).
        Every static dimension (frontier size, max degree, node capacity,
        dedup output) is pow2-rounded so steady writes don't recompile."""
        import jax.numpy as jnp

        from surrealdb_tpu import cnf

        chain_kernel = _kernels()
        it = self.interner(ns, db)
        n_cap = _next_pow2(len(it))
        # floor the frontier pad: XLA compiles per static shape (~20s+ on a
        # tunneled chip), and chains arriving with 90- vs 130-node frontiers
        # must share ONE compiled kernel to coalesce
        fsz = _next_pow2(max(frontier.size, cnf.TPU_GRAPH_FRONTIER_PAD))
        fr = np.full(fsz, n_cap, dtype=np.int32)
        fr[: frontier.size] = frontier
        cw = np.zeros(fsz, dtype=np.int32)
        cw[: counts.size] = counts

        hops, mds, out_sizes = [], [], []
        width = fsz
        for spec in specs:
            mirrors = self._hop_mirrors(ns, db, spec)
            if not mirrors:
                if count_only:
                    return 0
                e = np.empty(0, dtype=np.int32)
                return e, e
            hop_arrs, hop_mds, total = [], [], 0
            for m in mirrors:
                hop_arrs.append(m.device_arrays())
                md = _next_pow2(max(m.max_degree, 1))
                hop_mds.append(md)
                total += width * md
            hops.append(tuple(hop_arrs))
            mds.append(tuple(hop_mds))
            width = _next_pow2(min(total, n_cap))
            out_sizes.append(width)
        hops, mds, out_sizes = tuple(hops), tuple(mds), tuple(out_sizes)
        if count_only and dispatch is not None:
            # coalesce concurrent count-chains with identical shape/adjacency
            # into one batched dispatch (dbs/dispatch.py leader-follower)
            batch_kernel = _JITTED["chain_count_batch"]
            csc_hops = tuple(
                tuple(m.device_csc() for m in self._hop_mirrors(ns, db, sp))
                for sp in specs[:-1]
            )
            last_hop = tuple((pair[0],) for pair in hops[-1])
            key = (
                "gchain", fsz, n_cap, len(specs),
                tuple(id(a) for hop in csc_hops for pair in hop for a in pair),
                tuple(id(p) for (p,) in last_hop),
            )

            def runner(payloads):
                from surrealdb_tpu import compile_log

                B = len(payloads)
                # fixed lane count: a batch of 1 and a batch of 32 share the
                # same compiled executable (padding lanes carry zero weights
                # and cost nothing next to the dispatch RTT)
                bp = max(_next_pow2(B), cnf.TPU_GRAPH_BATCH_LANES)
                frs = np.full((bp, fsz), n_cap, dtype=np.int32)
                cws = np.zeros((bp, fsz), dtype=np.int32)
                for i, (f, c) in enumerate(payloads):
                    frs[i] = f
                    cws[i] = c
                with compile_log.tracked(
                    "graph_csc", _csc_shape_key(bp, fsz, n_cap, csc_hops, last_hop)
                ):
                    out = batch_kernel(
                        csc_hops, last_hop,
                        jnp.asarray(frs), jnp.asarray(cws),
                        n_cap=n_cap,
                    )

                def collect():
                    vals = np.asarray(out)
                    return [int(vals[i]) for i in range(B)]

                return collect

            return dispatch.submit(key, (fr, cw), runner)
        from surrealdb_tpu import compile_log

        with compile_log.tracked(
            "graph_chain", (fsz, n_cap, mds, out_sizes, bool(count_only))
        ):
            out = chain_kernel(
                hops, jnp.asarray(fr), jnp.asarray(cw),
                mds=mds, n_cap=n_cap, out_sizes=out_sizes,
                count_only=count_only,
            )
        if count_only:
            return int(out)
        u = np.asarray(out[0])
        c = np.asarray(out[1])
        keep = c > 0
        return u[keep].astype(np.int32), c[keep].astype(np.int32)

    def _chain_frontier(self, ctx, start: List[Thing], parts: List, count_only: bool = False):
        """Shared frontier machinery for chain()/chain_count(): returns
        (frontier int32[], counts int32[], interner) — or the scalar path
        count when count_only (the device chain then downloads one int)."""
        from surrealdb_tpu import cnf

        ns, db = ctx.ns_db()
        it = self.interner(ns, db)
        dir_map = {"out": [keys.DIR_OUT], "in": [keys.DIR_IN], "both": [keys.DIR_IN, keys.DIR_OUT]}
        # pre-resolve hop specs; a hop filtered on foreign-table ft lands
        # entirely in table ft, so the next hop's sources are exactly p.what
        tables = {t.tb for t in start}
        specs = []
        for p in parts:
            for tb in tables:
                self.ensure_table(ctx, tb)
            specs.append((sorted(tables), dir_map[p.dir], p.what))
            tables = set(p.what)
        cmap: Dict[int, int] = {}
        for t in start:
            i = it.lookup(t)
            if i is not None:
                cmap[i] = cmap.get(i, 0) + 1
        frontier = np.fromiter(sorted(cmap), dtype=np.int32, count=len(cmap))
        counts = np.array([cmap[int(i)] for i in frontier], dtype=np.int32)
        dispatch = getattr(ctx.ds(), "dispatch", None)
        if (
            count_only
            and not cnf.TPU_DISABLE
            and dispatch is not None
            and frontier.size
            and self._chain_work_estimate(ns, db, specs, counts)
            >= cnf.TPU_GRAPH_COUNT_EDGES
        ):
            # big count chain: straight to device from the seed — the whole
            # chain is one tiny-upload batched dispatch (no host hops means
            # no GIL serialization across concurrent clients, and every
            # query shares one compiled shape so they coalesce). Preferred
            # form: composed dense matmuls on the MXU; CSC cumsum otherwise.
            res = self._dense_chain_count(ns, db, frontier, counts, specs, dispatch)
            if res is not None:
                return res
            return self._device_chain(
                ns, db, frontier, counts, specs,
                count_only=True, dispatch=dispatch,
            )
        i = 0
        while i < len(specs):
            # a hop goes on device once the CURRENT frontier is device-sized,
            # or — for count-only chains — as soon as the NEXT frontier would
            # be: the whole remaining chain fuses into one dispatch either
            # way, and skipping the host hops keeps every concurrent query's
            # shapes identical so they coalesce into one vmapped launch
            md = max(
                (m.max_degree for m in self._hop_mirrors(ns, db, specs[i])),
                default=0,
            )
            device_now = frontier.size >= cnf.TPU_GRAPH_ONDEVICE_THRESHOLD or (
                count_only
                and frontier.size * md >= cnf.TPU_GRAPH_ONDEVICE_THRESHOLD
            )
            if not cnf.TPU_DISABLE and device_now:
                res = self._device_chain(
                    ns, db, frontier, counts, specs[i:],
                    count_only=count_only, dispatch=dispatch,
                )
                if count_only:
                    return res
                frontier, counts = res
                break
            frontier, counts = self._host_hop(ns, db, frontier, counts, specs[i])
            i += 1
        if count_only:
            return int(counts.sum())
        return frontier, counts, it

    def chain(
        self,
        ctx,
        start: List[Thing],
        parts: List,  # List[PGraph]
    ) -> List[Thing]:
        """Run a maximal chain of cond-free graph parts `->a->b->c` as
        batched frontier hops: host adjacency while the frontier is small,
        then the rest of the chain on device once it crosses
        TPU_GRAPH_ONDEVICE_THRESHOLD.

        Multiplicity matches the reference's flatten-without-dedup semantics
        (sql/value/get.rs:404-446): the frontier is deduplicated between hops
        but each node carries its path count, and the final result expands
        each node count times. Result order is deterministic (ascending
        intern order ≈ build-scan key order, with delta-added nodes after)
        but not identical to the KV walk's key order; graph hop ordering is
        unspecified upstream.
        """
        frontier, counts, it = self._chain_frontier(ctx, start, parts)
        out: List[Thing] = []
        for j, c in zip(frontier, counts):
            out.extend([it.node_of[int(j)]] * int(c))
        return out

    def chain_count(self, ctx, start: List[Thing], parts: List) -> int:
        """Path count of a chain WITHOUT materializing the expanded result —
        `count(->a->b->c)` sums the frontier's path counts directly (on a
        3-hop over 1M edges the Python expansion would dominate the whole
        query; the device already holds the counts, and the fused chain
        kernel downloads a single scalar)."""
        return self._chain_frontier(ctx, start, parts, count_only=True)


def graftcheck_sites():
    """Audit contracts of the three graph count/expand kernels (compile_log
    subsystems `graph_dense` / `graph_csc` / `graph_chain`): representative
    2-hop chains over pow2-padded adjacencies at the dispatch lane widths
    the batched count paths serve."""
    import jax
    import jax.numpy as jnp

    n0, n_cap, fsz, E = 256, 256, 64, 1024

    def build_dense(shape):
        import ml_dtypes

        _kernels()
        kernel = _JITTED["dense_count_batch"]
        lanes = shape["lanes"]
        As = tuple(
            jax.ShapeDtypeStruct((n0, n0), jnp.dtype(ml_dtypes.bfloat16))
            for _ in range(shape["hops"])
        )
        args = (
            As,
            jax.ShapeDtypeStruct((n0,), jnp.float32),
            jax.ShapeDtypeStruct((lanes, fsz), jnp.int32),
            jax.ShapeDtypeStruct((lanes, fsz), jnp.int32),
        )
        return (lambda A, od, fr, cw: kernel(A, od, fr, cw, n0=n0)), args

    def build_csc(shape):
        _kernels()
        kernel = _JITTED["chain_count_batch"]
        lanes = shape["lanes"]
        csc_hops = tuple(
            ((jax.ShapeDtypeStruct((n_cap + 1,), jnp.int32),
              jax.ShapeDtypeStruct((E,), jnp.int32)),)
            for _ in range(shape["hops"] - 1)
        )
        last_hop = ((jax.ShapeDtypeStruct((n_cap + 1,), jnp.int32),),)
        args = (
            csc_hops,
            last_hop,
            jax.ShapeDtypeStruct((lanes, fsz), jnp.int32),
            jax.ShapeDtypeStruct((lanes, fsz), jnp.int32),
        )
        return (
            lambda ch, lh, fr, cw: kernel(ch, lh, fr, cw, n_cap=n_cap),
            args,
        )

    def build_chain(shape):
        kernel = _kernels()
        hops = tuple(
            ((jax.ShapeDtypeStruct((n_cap + 1,), jnp.int32),
              jax.ShapeDtypeStruct((E,), jnp.int32)),)
            for _ in range(shape["hops"])
        )
        mds = tuple((8,) for _ in range(shape["hops"]))
        out_sizes = tuple(n_cap for _ in range(shape["hops"]))
        count_only = shape["count_only"]
        args = (
            hops,
            jax.ShapeDtypeStruct((fsz,), jnp.int32),
            jax.ShapeDtypeStruct((fsz,), jnp.int32),
        )
        return (
            lambda h, fr, cw: kernel(
                h, fr, cw, mds=mds, n_cap=n_cap, out_sizes=out_sizes,
                count_only=count_only,
            ),
            args,
        )

    lane_shapes = [
        {"label": f"l{lanes}_f{fsz}_n{n0}_h2", "lanes": lanes, "hops": 2}
        for lanes in (1, 8)
    ]
    return [
        {
            "subsystem": "graph_dense",
            "module": __name__,
            "kind": "single",
            "allowed_collectives": (),
            "out_dtypes": ("float32",),
            "shapes": lane_shapes,
            "build": build_dense,
        },
        {
            "subsystem": "graph_csc",
            "module": __name__,
            "kind": "single",
            "allowed_collectives": (),
            "out_dtypes": ("int32",),
            "shapes": lane_shapes,
            "build": build_csc,
        },
        {
            "subsystem": "graph_chain",
            "module": __name__,
            "kind": "single",
            "allowed_collectives": (),
            "out_dtypes": ("int32",),
            "shapes": [
                {"label": "f64_n256_h2_expand", "hops": 2, "count_only": False},
                {"label": "f64_n256_h3_count", "hops": 3, "count_only": True},
            ],
            "build": build_chain,
        },
    ]
