"""Device-resident CSR graph mirror + batched frontier expansion.

Role of the reference's per-record edge-prefix scans (reference:
core/src/dbs/processor.rs:610-701 collect_edges, sql/value/get.rs:404-446 —
hop N over R records ⇒ R separate KV range scans) re-designed TPU-first
(SURVEY §3.5): the edge keyspace of a table is packed once into CSR arrays
(indptr/indices) mirrored on device by generation; a multi-hop traversal is
then H fixed-shape gather kernels with on-device dedup instead of R₁+R₂+…
pointer chases.

The mirror covers one (table, direction) pair and maps record ids to dense
ints. `->edge->target` two-segment hops compose: node --OUT--> edge-record
--OUT--> node, i.e. one logical hop = 2 CSR hops (endpoint→edge, edge→endpoint),
which the builder pre-composes into a node→node CSR per edge table.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.value import Thing


class CsrGraphMirror:
    """node→node adjacency for one (src_table, edge_table, dir) triple."""

    def __init__(self, src_tb: str, edge_tb: str, direction: bytes):
        self.src_tb = src_tb
        self.edge_tb = edge_tb
        self.direction = direction
        self.generation = -1
        self._lock = threading.Lock()
        # id maps
        self.id_of: Dict[Tuple[str, str], int] = {}  # (tb, repr(id)) -> int
        self.node_of: List[Thing] = []
        self.indptr: Optional[np.ndarray] = None
        self.indices: Optional[np.ndarray] = None
        self.edge_of: Optional[np.ndarray] = None  # edge-record int per slot
        self.max_degree = 0

    def _intern(self, t: Thing) -> int:
        k = (t.tb, repr(t.id))
        i = self.id_of.get(k)
        if i is None:
            i = len(self.node_of)
            self.id_of[k] = i
            self.node_of.append(t)
        return i

    def lookup(self, t: Thing) -> Optional[int]:
        return self.id_of.get((t.tb, repr(t.id)))

    def refresh(self, ctx) -> None:
        """Rebuild from the KV edge pointers. One scan over the source
        table's `~` keyspace composes node→edge→node into node→node."""
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        with self._lock:
            self.id_of.clear()
            self.node_of = []
            adj: Dict[int, List[Tuple[int, int]]] = {}

            # pass 1: node --dir--> edge-record pointers on the source table
            pre = keys.graph_prefix(ns, db, self.src_tb)
            node_edges: List[Tuple[int, Thing]] = []
            for chunk in txn.batch(pre, prefix_end(pre), 2000):
                for k, _ in chunk:
                    id_, d, ft, fk = keys.decode_graph(k, ns, db, self.src_tb)
                    if d != self.direction or ft != self.edge_tb:
                        continue
                    src = self._intern(Thing(self.src_tb, id_))
                    if isinstance(fk, Thing):
                        node_edges.append((src, fk))

            # pass 2: edge-record --same dir--> endpoint
            for src, edge in node_edges:
                e_int = self._intern(edge)
                pre2 = keys.graph_prefix(
                    ns, db, edge.tb, edge.id, self.direction
                )
                for k2 in txn.keys(pre2, prefix_end(pre2)):
                    _, _, _, fk2 = keys.decode_graph(k2, ns, db, edge.tb)
                    if isinstance(fk2, Thing):
                        dst = self._intern(fk2)
                        adj.setdefault(src, []).append((dst, e_int))

            n = len(self.node_of)
            indptr = np.zeros(n + 1, dtype=np.int32)
            for src, lst in adj.items():
                indptr[src + 1] = len(lst)
            self.max_degree = int(indptr.max()) if n else 0
            np.cumsum(indptr, out=indptr)
            indices = np.zeros(max(int(indptr[-1]), 1), dtype=np.int32)
            edge_of = np.zeros_like(indices)
            fill = indptr[:-1].copy()
            for src, lst in adj.items():
                for dst, e_int in lst:
                    indices[fill[src]] = dst
                    edge_of[fill[src]] = e_int
                    fill[src] += 1
            self.indptr = indptr
            self.indices = indices
            self.edge_of = edge_of

    # ------------------------------------------------------------ traversal
    def hop_batch(self, srcs: List[Thing], want_edges: bool = False) -> List[List[Thing]]:
        """Expand a batch of source nodes one logical hop. Returns the
        neighbor list per source (edge records instead when want_edges)."""
        if self.indptr is None:
            return [[] for _ in srcs]
        out: List[List[Thing]] = []
        for t in srcs:
            i = self.lookup(t)
            if i is None or i >= len(self.indptr) - 1:
                out.append([])
                continue
            lo, hi = int(self.indptr[i]), int(self.indptr[i + 1])
            table = self.edge_of if want_edges else self.indices
            out.append([self.node_of[int(j)] for j in table[lo:hi]])
        return out

    def multi_hop_device(self, start: List[Thing], hops: int) -> List[Thing]:
        """H-hop frontier expansion fully on device (bench/north-star path):
        fixed-shape gathers + dense-bitmap dedup per hop."""
        import jax.numpy as jnp
        from surrealdb_tpu.parallel.mesh import dedup_frontier
        import jax

        if self.indptr is None:
            return []
        n = len(self.node_of)
        ptr = jnp.asarray(self.indptr)
        idx = jnp.asarray(self.indices)
        starts = [self.lookup(t) for t in start]
        starts = [s for s in starts if s is not None]
        if not starts:
            return []
        frontier = jnp.asarray(np.array(starts, dtype=np.int32))
        mask = jnp.ones_like(frontier, dtype=bool)
        md = max(self.max_degree, 1)

        @jax.jit
        def one_hop(fr, fm):
            s = ptr[fr]
            degs = ptr[fr + 1] - s
            offs = jnp.arange(md)[None, :]
            take = jnp.clip(s[:, None] + offs, 0, idx.shape[0] - 1)
            valid = (offs < degs[:, None]) & fm[:, None]
            nb = idx[take].reshape(-1)
            return nb, valid.reshape(-1)

        for _ in range(hops):
            nodes, m = one_hop(frontier, mask)
            frontier, mask = dedup_frontier(nodes, m, n)
        out_idx = np.asarray(frontier)[np.asarray(mask)]
        return [self.node_of[int(i)] for i in out_idx]


class GraphMirrors:
    """Per-datastore registry of CSR mirrors keyed by
    (ns, db, src_tb, edge_tb, dir)."""

    def __init__(self):
        self._m: Dict[tuple, CsrGraphMirror] = {}
        self._lock = threading.Lock()

    def get(self, ctx, src_tb: str, edge_tb: str, direction: bytes) -> CsrGraphMirror:
        ns, db = ctx.ns_db()
        k = (ns, db, src_tb, edge_tb, bytes(direction))
        with self._lock:
            m = self._m.get(k)
            if m is None:
                m = CsrGraphMirror(src_tb, edge_tb, direction)
                self._m[k] = m
        return m

    def invalidate(self) -> None:
        with self._lock:
            for m in self._m.values():
                m.generation = -1
