"""Device-resident full-text mirror: CSR postings + batched BM25 search.

Role of the reference's per-query posting B-tree walks (reference:
core/src/idx/ft/postings.rs, termdocs.rs, scorer.rs:13-92) re-designed
TPU-first, the same way idx/knn.py mirrors vectors and idx/graph_csr.py
mirrors edges: the inverted index's postings are packed into CSR arrays
(term → sorted doc ids + term frequencies) kept in sync with committed
writes, so a MATCHES query is numpy slicing + searchsorted intersection +
ONE batched BM25 kernel (ops/bm25.py) instead of a per-posting KV
scan-and-unpack loop.

The mirror's base state is the bulk ingest's packed chunks
(idx/ft_index.py P/L/R keys) loaded wholesale as numpy arrays — the build
never unpacks per-(term, doc) keys for bulk data. Single-document changes
land in small per-term overlay dicts (tf<=0 = tombstone) merged into the
CSR lazily, mirroring the KV layout's chunk+overlay split exactly.

The KV inverted index stays authoritative/durable; this is the compute
replica (reference analog: TreeCache generation swap,
trees/store/cache.rs — improved to incremental deltas, VERDICT r1 item 4).
"""

from __future__ import annotations

import bisect
import threading
from surrealdb_tpu.utils import locks as _locks
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import dec_u64, prefix_end
from surrealdb_tpu.sql.value import Thing
from surrealdb_tpu.utils.ser import unpack
from surrealdb_tpu.idx.ft_index import (
    rid_chunk_get,
    unpack_lens,
    unpack_plist,
    unpack_posting,
)


class FtMirror:
    """One search index's postings: packed base chunks + overlay dicts,
    lazily compacted into CSR arrays (pattern of idx/graph_csr.py)."""

    def __init__(self):
        self.built = False
        self.term_ids: Dict[str, int] = {}  # term -> local tid
        # base postings: per tid, list of (dids asc, tfs) chunk arrays in
        # ascending did order (chunk starts are allocated monotonically)
        self.chunks: List[List[Tuple[np.ndarray, np.ndarray]]] = []
        self.overlay: List[Dict[int, float]] = []  # per tid; tf<=0 tombstone
        # doc lengths: [(start, lens f32)] + overlay {did: len} (0 = absent)
        self.len_chunks: List[Tuple[int, np.ndarray]] = []
        self.len_overlay: Dict[int, float] = {}
        # did -> rid: [(start, rid list)] + overlay {did: rid | None}
        self.rid_chunks: List[Tuple[int, list]] = []
        self.rid_overlay: Dict[int, Optional[Thing]] = {}
        self._chunk_starts: set = set()  # bulk idempotence guard
        self.next_did = 0
        self.dc = 0
        self.tl = 0.0
        self.dirty = True
        # compacted arrays
        self.t_indptr: Optional[np.ndarray] = None
        self.t_dids: Optional[np.ndarray] = None
        self.t_tfs: Optional[np.ndarray] = None
        self.doclen_arr: Optional[np.ndarray] = None
        self._pending: Optional[List[tuple]] = None
        # filtered-stats cache (replicated clusters): the responsibility
        # mask depends only on (compacted-array generation, liveness view),
        # so one O(corpus) rid/ring walk serves every BM25 query until a
        # mutation recompacts the arrays or the live set changes
        self._stats_gen = 0
        self._stats_mask: Optional[Tuple[tuple, np.ndarray]] = None
        self._lock = _locks.RLock("idx.ft.state")
        self._build_lock = _locks.Lock("idx.ft.build")

    # ------------------------------------------------------------ build
    def ensure_built(self, ctx, ix: dict) -> None:
        """One scan over the index's KV state builds the mirror. Runs on a
        fresh snapshot opened after delta buffering starts (same protocol as
        idx/knn.py VectorMirror.ensure_built)."""
        if self.built:
            return
        with self._build_lock:
            if self.built:
                return
            with self._lock:
                self._pending = []
            ns, db = ctx.ns_db()
            tb, name = ix["table"], ix["name"]
            txn = ctx.ds().transaction(False)
            try:
                base = keys.index_state(ns, db, tb, name, b"")
                st_raw = txn.get(base + b"s")
                st = unpack(st_raw) if st_raw else {"dc": 0, "tl": 0, "nt": 0, "nd": 0}
                kv_tid_local: Dict[int, int] = {}
                term_ids: Dict[str, int] = {}
                # terms: t{term} -> {id, df}
                pre = base + b"t"
                for chunk in txn.batch(pre, prefix_end(pre), 4096):
                    for k, v in chunk:
                        meta = unpack(v)
                        if meta.get("df", 0) <= 0:
                            continue
                        term = self._dec_term(k, len(pre))
                        local = len(term_ids)
                        term_ids[term] = local
                        kv_tid_local[meta["id"]] = local
                chunks: List[List[Tuple[np.ndarray, np.ndarray]]] = [
                    [] for _ in range(len(term_ids))
                ]
                overlay: List[Dict[int, float]] = [{} for _ in range(len(term_ids))]
                # packed posting chunks: P{tid}{start}
                chunk_starts: set = set()
                pre = base + b"P"
                for batch in txn.batch(pre, prefix_end(pre), 1024):
                    for k, v in batch:
                        tid, off = dec_u64(k, len(pre))
                        start, _ = dec_u64(k, off)
                        local = kv_tid_local.get(tid)
                        if local is not None:
                            chunks[local].append(unpack_plist(v))
                        chunk_starts.add(start)
                # posting overlay: p{tid}{did}
                pre = base + b"p"
                for batch in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in batch:
                        tid, off = dec_u64(k, len(pre))
                        did, _ = dec_u64(k, off)
                        local = kv_tid_local.get(tid)
                        if local is not None:
                            overlay[local][did] = float(unpack_posting(v)["tf"])
                # doc lengths
                len_chunks: List[Tuple[int, np.ndarray]] = []
                pre = base + b"L"
                for batch in txn.batch(pre, prefix_end(pre), 1024):
                    for k, v in batch:
                        start, _ = dec_u64(k, len(pre))
                        len_chunks.append((start, unpack_lens(v)))
                len_overlay: Dict[int, float] = {}
                pre = base + b"l"
                for batch in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in batch:
                        did, _ = dec_u64(k, len(pre))
                        len_overlay[did] = float(unpack(v))
                # rid maps
                # rid chunks stay raw bytes until a result lands in them
                # (rid_for decodes on demand — searches touch few chunks)
                rid_chunks: List[Tuple[int, Any]] = []
                pre = base + b"R"
                for batch in txn.batch(pre, prefix_end(pre), 256):
                    for k, v in batch:
                        start, _ = dec_u64(k, len(pre))
                        rid_chunks.append((start, v))
                rid_overlay: Dict[int, Optional[Thing]] = {}
                pre = base + b"r"
                for batch in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in batch:
                        did, _ = dec_u64(k, len(pre))
                        rid_overlay[did] = unpack(v)
            finally:
                txn.cancel()
            len_chunks.sort(key=lambda c: c[0])
            rid_chunks.sort(key=lambda c: c[0])
            with self._lock:
                self.term_ids = term_ids
                self.chunks = chunks
                self.overlay = overlay
                self.len_chunks = len_chunks
                self.len_overlay = len_overlay
                self.rid_chunks = rid_chunks
                self.rid_overlay = rid_overlay
                self._chunk_starts = chunk_starts | {s for s, _ in len_chunks}
                self.next_did = st["nd"]
                self.dc = st["dc"]
                self.tl = float(st["tl"])
                self.dirty = True
                self.built = True
                pending, self._pending = self._pending, None
                for tag, args in pending:
                    if tag == "doc":
                        self.apply_ft(*args)
                    else:
                        self.apply_ft_bulk(*args)

    @staticmethod
    def _dec_term(k: bytes, off: int) -> str:
        from surrealdb_tpu.key.encode import dec_str

        return dec_str(k, off)[0]

    # ------------------------------------------------------------ deltas
    def _tid_for(self, term: str) -> int:
        tid = self.term_ids.get(term)
        if tid is None:
            tid = len(self.term_ids)
            self.term_ids[term] = tid
            self.chunks.append([])
            self.overlay.append({})
        return tid

    def _len_of(self, did: int) -> Optional[float]:
        """Current doc length, or None when the doc is not indexed. The
        overlay stores -1.0 as its removal tombstone so a present zero-token
        doc (length 0) stays distinguishable from an absent one — dc/tl
        accounting depends on that distinction."""
        v = self.len_overlay.get(did)
        if v is not None:
            return None if v < 0 else v
        i = bisect.bisect_right(self.len_chunks, did, key=lambda c: c[0]) - 1
        if i >= 0:
            start, lens = self.len_chunks[i]
            off = did - start
            if 0 <= off < len(lens):
                return float(lens[off])
        return None

    def apply_ft(
        self,
        rid,
        did: int,
        old_tf: Optional[Dict[str, int]],
        new_tf: Optional[Dict[str, int]],
        new_len: int,
    ) -> None:
        """One committed document change. old/new term-frequency maps follow
        idx/ft_index.py index_document's diff semantics; None = absent."""
        with self._lock:
            if self._pending is not None:
                self._pending.append(("doc", (rid, did, old_tf, new_tf, new_len)))
                return
            if not self.built:
                return
            if old_tf is not None:
                for term in old_tf:
                    tid = self.term_ids.get(term)
                    if tid is not None:
                        self.overlay[tid][did] = 0.0
                prev = self._len_of(did)
                if prev is not None:
                    self.tl -= prev
                    self.dc -= 1
                self.len_overlay[did] = -1.0
            if new_tf is not None:
                # idempotence (the build-window replay protocol relies on
                # it): a delta whose doc the build scan already loaded must
                # not double-count dc/tl
                prev = self._len_of(did)
                if prev is not None:
                    self.tl -= prev
                    self.dc -= 1
                for term, tf in new_tf.items():
                    self.overlay[self._tid_for(term)][did] = float(tf)
                self.len_overlay[did] = float(new_len)
                self.rid_overlay[did] = rid
                self.dc += 1
                self.tl += new_len
                if did >= self.next_did:
                    self.next_did = did + 1
            elif old_tf is not None:
                self.rid_overlay[did] = None
            self.dirty = True

    def apply_ft_bulk(self, start: int, terms: Dict[str, tuple], lens, rids) -> None:
        """One committed bulk batch: append its packed arrays as new base
        chunks (no per-doc work)."""
        with self._lock:
            if self._pending is not None:
                self._pending.append(("bulk", (start, terms, lens, rids)))
                return
            if not self.built:
                return
            if start in self._chunk_starts:
                return  # the build scan already loaded this batch
            self._chunk_starts.add(start)
            for term, (dids, tfs) in terms.items():
                self.chunks[self._tid_for(term)].append(
                    (np.asarray(dids), np.asarray(tfs, dtype=np.float32))
                )
            lens = np.asarray(lens, dtype=np.float32)
            self.len_chunks.append((start, lens))
            self.rid_chunks.append((start, list(rids)))
            self.dc += len(lens)
            self.tl += float(lens.sum())
            if start + len(lens) > self.next_did:
                self.next_did = start + len(lens)
            self.dirty = True

    # ------------------------------------------------------------ rid map
    def rid_for(self, did: int) -> Optional[Thing]:
        with self._lock:
            if did in self.rid_overlay:
                return self.rid_overlay[did]
            i = bisect.bisect_right(self.rid_chunks, did, key=lambda c: c[0]) - 1
            if i >= 0:
                start, rids = self.rid_chunks[i]
                if isinstance(rids, bytes):
                    rids = unpack(rids)  # columnar dict or generic list
                    self.rid_chunks[i] = (start, rids)
                return rid_chunk_get(rids, did - start)
            return None

    # ------------------------------------------------------------ arrays
    def _ensure_arrays(self) -> None:
        if not self.dirty and self.t_indptr is not None:
            return
        T = len(self.term_ids)
        rows: List[Tuple[np.ndarray, np.ndarray]] = []
        for tid in range(T):
            parts = self.chunks[tid]
            ov = self.overlay[tid]
            if parts and not ov:
                if len(parts) == 1:
                    rows.append(parts[0])
                else:
                    d = np.concatenate([p[0] for p in parts])
                    f = np.concatenate([p[1] for p in parts])
                    rows.append((d, f))
                    self.chunks[tid] = [rows[-1]]  # keep the compaction
                continue
            if parts:
                d = np.concatenate([p[0] for p in parts])
                f = np.concatenate([p[1] for p in parts])
            else:
                d = np.empty(0, np.int64)
                f = np.empty(0, np.float32)
            if ov:
                ov_d = np.fromiter(ov.keys(), np.int64, count=len(ov))
                ov_t = np.fromiter(ov.values(), np.float32, count=len(ov))
                if d.size:
                    keep = ~np.isin(d, ov_d)
                    d, f = d[keep], f[keep]
                live = ov_t > 0
                d = np.concatenate([d, ov_d[live]])
                f = np.concatenate([f, ov_t[live]])
                order = np.argsort(d, kind="stable")
                d, f = d[order], f[order]
            rows.append((d, f))
        counts = np.fromiter((len(r[0]) for r in rows), dtype=np.int64, count=T)
        indptr = np.zeros(T + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        nnz = int(indptr[-1])
        dids = np.empty(nnz, dtype=np.int64)
        tfs = np.empty(nnz, dtype=np.float32)
        for tid, (d, f) in enumerate(rows):
            s, e = indptr[tid], indptr[tid + 1]
            dids[s:e] = d
            tfs[s:e] = f
        cap = max(self.next_did, 1)
        dl = np.zeros(cap, dtype=np.float32)
        for start, lens in self.len_chunks:
            dl[start : start + len(lens)] = lens
        if self.len_overlay:
            idx = np.fromiter(self.len_overlay.keys(), np.int64, count=len(self.len_overlay))
            val = np.fromiter(self.len_overlay.values(), np.float32, count=len(self.len_overlay))
            ok = idx < cap
            dl[idx[ok]] = np.maximum(val[ok], 0.0)  # -1 tombstone scores as 0
        self.t_indptr, self.t_dids, self.t_tfs, self.doclen_arr = indptr, dids, tfs, dl
        self.dirty = False
        self._stats_gen += 1  # responsibility masks over old arrays are stale

    # ------------------------------------------------------------ search
    def term_stats(self, terms: List[str], doc_ok=None, filter_key=None):
        """Local corpus statistics for a term set: (doc count, total doc
        length, {term: document frequency}) — phase one of the cluster's
        two-phase BM25 (cluster/rpc.py ft_stats). Unknown terms report 0.

        `doc_ok(rid) -> bool` restricts the stats to a responsibility
        subset (replicated clusters: each node reports only the docs it is
        the first live replica of, so a doc counts once globally); pass a
        hashable `filter_key` describing what doc_ok depends on (live-node
        set + rf) and the O(corpus) mask is cached until the arrays
        recompact or the key changes. The filtered path counts live docs
        from the length array, so a zero-length doc is excluded — empty
        bodies carry no BM25 mass."""
        with self._lock:
            self._ensure_arrays()
            if doc_ok is None:
                df: Dict[str, int] = {}
                for t in dict.fromkeys(terms):
                    tid = self.term_ids.get(t)
                    df[t] = (
                        int(self.t_indptr[tid + 1] - self.t_indptr[tid])
                        if tid is not None
                        else 0
                    )
                return int(self.dc), float(self.tl), df
            cache_key = (
                (self._stats_gen, filter_key) if filter_key is not None else None
            )
            if self._stats_mask is not None and self._stats_mask[0] == cache_key:
                mask = self._stats_mask[1]
            else:
                cap = len(self.doclen_arr)
                mask = np.zeros(cap, dtype=bool)
                for did in np.nonzero(self.doclen_arr > 0)[0]:
                    rid = self.rid_for(int(did))
                    if rid is not None and doc_ok(rid):
                        mask[did] = True
                if cache_key is not None:
                    self._stats_mask = (cache_key, mask)
            df = {}
            for t in dict.fromkeys(terms):
                tid = self.term_ids.get(t)
                if tid is None:
                    df[t] = 0
                    continue
                s, e = int(self.t_indptr[tid]), int(self.t_indptr[tid + 1])
                df[t] = int(np.count_nonzero(mask[self.t_dids[s:e]]))
            return (
                int(np.count_nonzero(mask)),
                float(self.doclen_arr[mask].sum()),
                df,
            )

    def search(self, terms: List[str], k1: float, b: float, stats_override=None):
        """AND-match the analyzed query terms; returns (dids, scores) —
        empty arrays when any term is unknown. `stats_override`
        ({dc, tl, df: {term: n}}) swaps the corpus statistics BM25 scores
        with — the cluster executor passes the merged GLOBAL stats so every
        shard scores exactly as one single-node corpus would."""
        from surrealdb_tpu import cnf

        with self._lock:
            self._ensure_arrays()
            uniq = list(dict.fromkeys(terms))
            if not uniq:
                return np.empty(0, np.int64), np.empty(0, np.float32)
            tids = []
            term_of: Dict[int, str] = {}
            for t in uniq:
                tid = self.term_ids.get(t)
                if tid is None or self.t_indptr[tid + 1] == self.t_indptr[tid]:
                    return np.empty(0, np.int64), np.empty(0, np.float32)
                tids.append(tid)
                term_of[tid] = t
            # rarest-first intersection over sorted did rows
            tids.sort(key=lambda tid: self.t_indptr[tid + 1] - self.t_indptr[tid])
            rows = [
                (
                    self.t_dids[self.t_indptr[t] : self.t_indptr[t + 1]],
                    self.t_tfs[self.t_indptr[t] : self.t_indptr[t + 1]],
                )
                for t in tids
            ]
            cand = rows[0][0]
            tf_cols = [rows[0][1]]
            for dids, tfs in rows[1:]:
                pos = np.searchsorted(dids, cand)
                pos_c = np.clip(pos, 0, len(dids) - 1)
                mask = dids[pos_c] == cand
                cand = cand[mask]
                tf_cols = [c[mask] for c in tf_cols]
                tf_cols.append(tfs[pos_c[mask]])
                if cand.size == 0:
                    return cand, np.empty(0, np.float32)
            tf_mat = np.stack(tf_cols, axis=1)
            df = np.array(
                [self.t_indptr[t + 1] - self.t_indptr[t] for t in tids],
                dtype=np.float32,
            )
            lens = self.doclen_arr[cand]
            dc, tl = self.dc, self.tl
            if isinstance(stats_override, dict):
                odf = stats_override.get("df") or {}
                df = np.array(
                    [float(odf.get(term_of[t], df[i])) for i, t in enumerate(tids)],
                    dtype=np.float32,
                )
                dc = float(stats_override.get("dc", dc))
                tl = float(stats_override.get("tl", tl))
        if not cnf.TPU_DISABLE and cand.size >= cnf.TPU_FT_ONDEVICE_THRESHOLD:
            from surrealdb_tpu import compile_log
            from surrealdb_tpu.ops.bm25 import bm25_scores

            # every distinct (candidates, terms) shape is one XLA compile
            # (graftlint GL002: the launch site owns the attribution)
            with compile_log.tracked(
                "bm25", (int(tf_mat.shape[0]), int(tf_mat.shape[1]))
            ):
                scores = np.asarray(
                    bm25_scores(
                        tf_mat, df, lens, np.float32(dc), np.float32(tl), k1, b
                    )
                )
        else:
            from surrealdb_tpu.ops.bm25 import bm25_scores_host

            scores = bm25_scores_host(tf_mat, df, lens, dc, tl, k1, b)
        return cand, scores

    def count(self) -> int:
        with self._lock:
            return self.dc
