"""Device-resident full-text mirror: CSR postings + batched BM25 search.

Role of the reference's per-query posting B-tree walks (reference:
core/src/idx/ft/postings.rs, termdocs.rs, scorer.rs:13-92) re-designed
TPU-first, the same way idx/knn.py mirrors vectors and idx/graph_csr.py
mirrors edges: the inverted index's postings are packed once into CSR arrays
(term → sorted doc ids + term frequencies) kept in sync with committed
writes by per-document deltas, so a MATCHES query is numpy slicing +
searchsorted intersection + ONE batched BM25 kernel (ops/bm25.py) instead of
a per-posting KV scan-and-unpack loop.

The KV inverted index (idx/ft_index.py) stays authoritative/durable; this is
the compute replica (reference analog: TreeCache generation swap,
trees/store/cache.rs — improved to incremental deltas, VERDICT r1 item 4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import dec_u64, enc_u64, prefix_end
from surrealdb_tpu.sql.value import Thing
from surrealdb_tpu.utils.ser import unpack
from surrealdb_tpu.idx.ft_index import unpack_posting


def _rid_key(rid) -> tuple:
    return (rid.tb, repr(rid.id)) if isinstance(rid, Thing) else rid


class FtMirror:
    """One search index's postings, host-authoritative dicts + lazily
    compacted CSR arrays (pattern of idx/graph_csr.py PointerCsr)."""

    def __init__(self):
        self.built = False
        self.term_ids: Dict[str, int] = {}  # term -> local tid
        self.postings: List[Dict[int, int]] = []  # tid -> {did: tf}
        self.doc_len: Dict[int, int] = {}
        self.did_of: Dict[tuple, int] = {}
        self.rid_of: Dict[int, Thing] = {}
        self.next_did = 0
        self.dc = 0  # docs indexed
        self.tl = 0  # total token length
        self.dirty = True
        # compacted arrays
        self.t_indptr: Optional[np.ndarray] = None
        self.t_dids: Optional[np.ndarray] = None
        self.t_tfs: Optional[np.ndarray] = None
        self.doclen_arr: Optional[np.ndarray] = None
        self._pending: Optional[List[tuple]] = None
        self._lock = threading.RLock()
        self._build_lock = threading.Lock()

    # ------------------------------------------------------------ build
    def ensure_built(self, ctx, ix: dict) -> None:
        """One scan over the index's KV state builds the mirror. Runs on a
        fresh snapshot opened after delta buffering starts (same protocol as
        idx/knn.py VectorMirror.ensure_built)."""
        if self.built:
            return
        with self._build_lock:
            if self.built:
                return
            with self._lock:
                self._pending = []
            ns, db = ctx.ns_db()
            tb, name = ix["table"], ix["name"]
            txn = ctx.ds().transaction(False)
            try:
                base = keys.index_state(ns, db, tb, name, b"")
                kv_tid_local: Dict[int, int] = {}
                term_ids: Dict[str, int] = {}
                postings: List[Dict[int, int]] = []
                # terms: t{term} -> {id, df}
                pre = base + b"t"
                for chunk in txn.batch(pre, prefix_end(pre), 4096):
                    for k, v in chunk:
                        meta = unpack(v)
                        if meta.get("df", 0) <= 0:
                            continue
                        term = self._dec_term(k, len(pre))
                        local = len(postings)
                        term_ids[term] = local
                        kv_tid_local[meta["id"]] = local
                        postings.append({})
                # postings: p{tid}{did} -> {tf}
                pre = base + b"p"
                for chunk in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in chunk:
                        tid, off = dec_u64(k, len(pre))
                        did, _ = dec_u64(k, off)
                        local = kv_tid_local.get(tid)
                        if local is not None:
                            postings[local][did] = unpack_posting(v)["tf"]
                # doc lengths: l{did}
                doc_len: Dict[int, int] = {}
                pre = base + b"l"
                for chunk in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in chunk:
                        did, _ = dec_u64(k, len(pre))
                        doc_len[did] = unpack(v)
                # rid maps: r{did}
                rid_of: Dict[int, Thing] = {}
                did_of: Dict[tuple, int] = {}
                pre = base + b"r"
                for chunk in txn.batch(pre, prefix_end(pre), 8192):
                    for k, v in chunk:
                        did, _ = dec_u64(k, len(pre))
                        rid = unpack(v)
                        rid_of[did] = rid
                        did_of[_rid_key(rid)] = did
            finally:
                txn.cancel()
            with self._lock:
                self.term_ids = term_ids
                self.postings = postings
                self.doc_len = doc_len
                self.rid_of = rid_of
                self.did_of = did_of
                self.next_did = max(rid_of) + 1 if rid_of else 0
                self.dc = len(doc_len)
                self.tl = sum(doc_len.values())
                self.dirty = True
                self.built = True
                pending, self._pending = self._pending, None
                for args in pending:
                    self.apply_ft(*args)

    @staticmethod
    def _dec_term(k: bytes, off: int) -> str:
        from surrealdb_tpu.key.encode import dec_str

        return dec_str(k, off)[0]

    # ------------------------------------------------------------ deltas
    def apply_ft(
        self,
        rid,
        old_tf: Optional[Dict[str, int]],
        new_tf: Optional[Dict[str, int]],
        new_len: int,
    ) -> None:
        """One committed document change. old/new term-frequency maps follow
        idx/ft_index.py index_document's diff semantics; None = absent."""
        with self._lock:
            if self._pending is not None:
                self._pending.append((rid, old_tf, new_tf, new_len))
                return
            if not self.built:
                return
            k = _rid_key(rid)
            did = self.did_of.get(k)
            if old_tf is not None and did is not None:
                for term in old_tf:
                    tid = self.term_ids.get(term)
                    if tid is not None:
                        self.postings[tid].pop(did, None)
                ln = self.doc_len.pop(did, None)
                if ln is not None:
                    self.tl -= ln
                    self.dc -= 1
            if new_tf is not None:
                if did is None:
                    did = self.next_did
                    self.next_did += 1
                    self.did_of[k] = did
                    self.rid_of[did] = rid
                # idempotence (the build-window replay protocol relies on
                # it, like VectorMirror.apply): a delta whose doc the build
                # scan already loaded must not double-count dc/tl
                prev = self.doc_len.get(did)
                if prev is not None:
                    self.tl -= prev
                    self.dc -= 1
                for term, tf in new_tf.items():
                    tid = self.term_ids.get(term)
                    if tid is None:
                        tid = len(self.postings)
                        self.term_ids[term] = tid
                        self.postings.append({})
                    self.postings[tid][did] = tf
                self.doc_len[did] = new_len
                self.dc += 1
                self.tl += new_len
            elif did is not None:
                self.did_of.pop(k, None)
                self.rid_of.pop(did, None)
            self.dirty = True

    # ------------------------------------------------------------ arrays
    def _ensure_arrays(self) -> None:
        if not self.dirty and self.t_indptr is not None:
            return
        T = len(self.postings)
        counts = np.fromiter(
            (len(p) for p in self.postings), dtype=np.int64, count=T
        )
        indptr = np.zeros(T + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        nnz = int(indptr[-1])
        dids = np.empty(nnz, dtype=np.int64)
        tfs = np.empty(nnz, dtype=np.float32)
        for tid, p in enumerate(self.postings):
            s, e = indptr[tid], indptr[tid + 1]
            if s == e:
                continue
            d = np.fromiter(p.keys(), dtype=np.int64, count=len(p))
            f = np.fromiter(p.values(), dtype=np.float32, count=len(p))
            order = np.argsort(d, kind="stable")
            dids[s:e] = d[order]
            tfs[s:e] = f[order]
        cap = max(self.next_did, 1)
        dl = np.zeros(cap, dtype=np.float32)
        if self.doc_len:
            idx = np.fromiter(self.doc_len.keys(), dtype=np.int64, count=len(self.doc_len))
            val = np.fromiter(self.doc_len.values(), dtype=np.float32, count=len(self.doc_len))
            dl[idx] = val
        self.t_indptr, self.t_dids, self.t_tfs, self.doclen_arr = indptr, dids, tfs, dl
        self.dirty = False

    # ------------------------------------------------------------ search
    def search(self, terms: List[str], k1: float, b: float):
        """AND-match the analyzed query terms; returns (dids, scores) —
        empty arrays when any term is unknown."""
        from surrealdb_tpu import cnf

        with self._lock:
            self._ensure_arrays()
            uniq = list(dict.fromkeys(terms))
            if not uniq:
                return np.empty(0, np.int64), np.empty(0, np.float32)
            tids = []
            for t in uniq:
                tid = self.term_ids.get(t)
                if tid is None or self.t_indptr[tid + 1] == self.t_indptr[tid]:
                    return np.empty(0, np.int64), np.empty(0, np.float32)
                tids.append(tid)
            # rarest-first intersection over sorted did rows
            tids.sort(key=lambda tid: self.t_indptr[tid + 1] - self.t_indptr[tid])
            rows = [
                (
                    self.t_dids[self.t_indptr[t] : self.t_indptr[t + 1]],
                    self.t_tfs[self.t_indptr[t] : self.t_indptr[t + 1]],
                )
                for t in tids
            ]
            cand = rows[0][0]
            tf_cols = [rows[0][1]]
            for dids, tfs in rows[1:]:
                pos = np.searchsorted(dids, cand)
                pos_c = np.clip(pos, 0, len(dids) - 1)
                mask = dids[pos_c] == cand
                cand = cand[mask]
                tf_cols = [c[mask] for c in tf_cols]
                tf_cols.append(tfs[pos_c[mask]])
                if cand.size == 0:
                    return cand, np.empty(0, np.float32)
            tf_mat = np.stack(tf_cols, axis=1)
            df = np.array(
                [self.t_indptr[t + 1] - self.t_indptr[t] for t in tids],
                dtype=np.float32,
            )
            lens = self.doclen_arr[cand]
            dc, tl = self.dc, self.tl
        if not cnf.TPU_DISABLE and cand.size >= cnf.TPU_FT_ONDEVICE_THRESHOLD:
            from surrealdb_tpu.ops.bm25 import bm25_scores

            scores = np.asarray(
                bm25_scores(tf_mat, df, lens, np.float32(dc), np.float32(tl), k1, b)
            )
        else:
            from surrealdb_tpu.ops.bm25 import bm25_scores_host

            scores = bm25_scores_host(tf_mat, df, lens, dc, tl, k1, b)
        return cand, scores

    def count(self) -> int:
        with self._lock:
            return self.dc
