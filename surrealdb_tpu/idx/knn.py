"""kNN query plans: device-batched exact and index-backed search.

Role of the reference's kNN plumbing (reference: core/src/idx/planner/knn.rs,
checker.rs, trees/knn.rs, and the brute-force CollectKnn→BuildKnn workflow
planner/mod.rs:208-232) re-designed TPU-first: instead of a priority queue
fed one distance at a time, the candidate vectors live in a device-resident
padded matrix (generation-swapped mirror of the KV state, like the
reference's TreeCache) and one fused kernel computes all distances + top-k.

The plan object doubles as the per-statement QueryExecutor for the
`<|k|>` operator (reference planner/executor.rs knn :282): records admitted
by the plan evaluate the operator to true and expose their distance to
vector::distance::knn().
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import cnf
from surrealdb_tpu.err import TypeError_
from surrealdb_tpu.sql.path import get_path
from surrealdb_tpu.sql.value import Thing, is_nullish

from surrealdb_tpu.ops import distances as D


def _target_vector(target) -> List[float]:
    if not isinstance(target, (list, tuple)):
        raise TypeError_("kNN operator expects a vector on the right-hand side")
    return [float(x) for x in target]


class VectorMirror:
    """Device-resident [N, D] matrix mirroring a vector index's KV rows.

    Refreshes by generation (reference trees/store/cache.rs generation swap);
    rows are padded to tile multiples so repeated queries hit the same
    compiled kernel shapes.
    """

    def __init__(self):
        self.generation = -1
        self.rids: List[Any] = []
        self.matrix: Optional[np.ndarray] = None  # padded [N*, D]
        self.mask: Optional[np.ndarray] = None
        self._lock = threading.Lock()

    def refresh(self, ctx, ix: dict) -> None:
        from surrealdb_tpu.idx.vector_index import read_generation, scan_vectors

        ns, db = ctx.ns_db()
        tb, name = ix["table"], ix["name"]
        txn = ctx.txn()
        gen = read_generation(txn, ns, db, tb, name)
        with self._lock:
            if gen == self.generation and self.matrix is not None:
                return
            rids, rows = [], []
            for rid, vec in scan_vectors(txn, ns, db, tb, name):
                rids.append(rid)
                rows.append(vec)
            self.generation = gen
            self.rids = rids
            if not rows:
                self.matrix = None
                self.mask = None
                return
            dtype = np.float32
            mat = np.asarray(rows, dtype=dtype)
            self.matrix, self.mask = D.pad_rows(mat, cnf.TPU_BATCH_MIN_TILE)


class _KnnResult:
    """Admitted record set for the operator check (reference KnnPriorityList)."""

    def __init__(self):
        self.dists: Dict[Any, float] = {}

    def key(self, rid) -> Any:
        return (rid.tb, repr(rid.id)) if isinstance(rid, Thing) else rid

    def add(self, rid, dist: float) -> None:
        self.dists[self.key(rid)] = dist

    def contains(self, rid) -> bool:
        return self.key(rid) in self.dists

    def dist(self, rid) -> Optional[float]:
        return self.dists.get(self.key(rid))


class _KnnExecutorMixin:
    """QueryExecutor protocol for the `<|k|>` operator and distance fn."""

    result: _KnnResult

    def knn(self, ctx, doc, op) -> bool:
        rid = doc.rid
        return rid is not None and self.result.contains(rid)

    def matches(self, ctx, doc, op) -> bool:
        return False

    def knn_distance(self, rid) -> Optional[float]:
        return self.result.dist(rid)

    def score(self, ctx, doc, ref=None):
        return None


class KnnPlan(_KnnExecutorMixin):
    """`<|k[,ef]|>` against a DEFINEd HNSW/MTREE index.

    v1 executes as exact device search over the index's vector mirror (the
    fused distance+top-k kernel) — recall 1.0, above the reference's asserted
    HNSW floors (reference trees/hnsw/mod.rs:828-951). The approximate HNSW
    beam path drops in behind this same interface.
    """

    def __init__(self, tb: str, ix: dict, op, target):
        self.tb = tb
        self.ix = ix
        self.op = op
        self.k = op.k
        self.target = _target_vector(target)
        self.result = _KnnResult()

    def explain(self) -> dict:
        idx = self.ix["index"]
        return {
            "index": self.ix["name"],
            "operator": f"<|{self.k}|>",
            "ann": {"type": idx["type"], "dist": idx.get("dist", "euclidean")},
        }

    def iterate(self, ctx):
        ctx.qe = self
        ds = ctx.ds()
        ns, db = ctx.ns_db()
        mirror = ds.index_stores.get_or_create(
            ns, db, self.tb, self.ix["name"], VectorMirror
        )
        mirror.refresh(ctx, self.ix)
        if mirror.matrix is None:
            return
        metric = self.ix["index"].get("dist", "euclidean")
        k = min(self.k, len(mirror.rids))
        q = np.asarray([self.target], dtype=mirror.matrix.dtype)
        if len(mirror.rids) < cnf.TPU_KNN_ONDEVICE_THRESHOLD:
            dists, idxs = D.knn_search_host(q, mirror.matrix[: len(mirror.rids)], metric, k)
        else:
            dists, idxs = D.knn_search(q, mirror.matrix, mirror.mask, metric, k)
        dists = np.asarray(dists)[0]
        idxs = np.asarray(idxs)[0]
        out = []
        for d, i in zip(dists, idxs):
            if not np.isfinite(d) or i >= len(mirror.rids):
                continue
            rid = mirror.rids[int(i)]
            if not isinstance(rid, Thing):
                rid = Thing(self.tb, rid)
            self.result.add(rid, float(d))
            out.append((rid, None, {"dist": float(d)}))
        for item in out:
            yield item


class BruteForceKnnPlan(_KnnExecutorMixin):
    """`<|k,DIST|>` with no matching index: one streamed pass gathers the
    field vectors, then a single fused device kernel does distance + top-k
    (replaces the reference's two-stage CollectKnn→BuildKnn workflow
    planner/mod.rs:208-232 with one batched pass)."""

    def __init__(self, tb: str, op, target):
        self.tb = tb
        self.op = op
        self.k = op.k
        self.metric = (op.dist or "euclidean").lower()
        self.target = _target_vector(target)
        self.result = _KnnResult()

    def explain(self) -> dict:
        return {
            "operator": f"<|{self.k},{self.metric.upper()}|>",
            "table": self.tb,
            "strategy": "brute-force (device batch)",
        }

    def iterate(self, ctx):
        ctx.qe = self
        from surrealdb_tpu.dbs.iterator import scan_table

        field = self.op.l
        rids: List[Thing] = []
        rows: List[List[float]] = []
        docs: Dict[Any, dict] = {}
        dim = len(self.target)
        for rid, doc in scan_table(ctx, self.tb):
            with ctx.with_doc_value(doc, rid=rid) as c:
                v = field.compute(c)
            if not isinstance(v, (list, tuple)) or len(v) != dim:
                continue
            try:
                rows.append([float(x) for x in v])
            except (TypeError, ValueError):
                continue
            rids.append(rid)
            docs[(rid.tb, repr(rid.id))] = doc
        if not rows:
            return
        k = min(self.k, len(rids))
        q = np.asarray([self.target], dtype=np.float32)
        if len(rids) < cnf.TPU_KNN_ONDEVICE_THRESHOLD:
            dists, idxs = D.knn_search_host(q, np.asarray(rows, dtype=np.float32), self.metric, k)
        else:
            mat, mask = D.pad_rows(np.asarray(rows, dtype=np.float32), cnf.TPU_BATCH_MIN_TILE)
            dists, idxs = D.knn_search(q, mat, mask, self.metric, k)
        dists = np.asarray(dists)[0]
        idxs = np.asarray(idxs)[0]
        for d, i in zip(dists, idxs):
            if not np.isfinite(d) or i >= len(rids):
                continue
            rid = rids[int(i)]
            self.result.add(rid, float(d))
            yield rid, docs[(rid.tb, repr(rid.id))], {"dist": float(d)}
