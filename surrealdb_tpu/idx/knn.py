"""kNN query plans: device-batched exact and index-backed search.

Role of the reference's kNN plumbing (reference: core/src/idx/planner/knn.rs,
checker.rs, trees/knn.rs, and the brute-force CollectKnn→BuildKnn workflow
planner/mod.rs:208-232) re-designed TPU-first: instead of a priority queue
fed one distance at a time, the candidate vectors live in a device-resident
padded matrix (generation-swapped mirror of the KV state, like the
reference's TreeCache) and one fused kernel computes all distances + top-k.

The plan object doubles as the per-statement QueryExecutor for the
`<|k|>` operator (reference planner/executor.rs knn :282): records admitted
by the plan evaluate the operator to true and expose their distance to
vector::distance::knn().
"""

from __future__ import annotations

import threading
from surrealdb_tpu.utils import locks as _locks
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import cnf
from surrealdb_tpu.err import TypeError_
from surrealdb_tpu.sql.path import get_path
from surrealdb_tpu.sql.value import Thing, is_nullish

from surrealdb_tpu.ops import distances as D
from surrealdb_tpu.utils.num import next_pow2 as _pow2


def _target_vector(target) -> List[float]:
    if not isinstance(target, (list, tuple)):
        raise TypeError_("kNN operator expects a vector on the right-hand side")
    return [float(x) for x in target]


def _rid_key(rid) -> Any:
    return (rid.tb, repr(rid.id)) if isinstance(rid, Thing) else rid


class VectorMirror:
    """Device-resident [N, D] matrix mirroring a vector index's KV rows.

    Built ONCE with a single scan, then maintained incrementally: committed
    writes apply per-row deltas (append / overwrite / tombstone a slot) via
    the transaction's vector-delta buffer — no corpus rescans (VERDICT r1
    item 4; improves on the reference's generation-swap full reload,
    trees/store/cache.rs:28-60). Device arrays recompact lazily with pow2
    row padding so steady writes don't change kernel shapes. Dead slots are
    compacted away once they exceed a quarter of capacity.

    An optional IVF state (idx/ivf.py) rides on the same slot space and is
    kept in sync by the same deltas.
    """

    def __init__(self):
        self.built = False
        self.rids: List[Any] = []  # slot -> rid
        self.slot_of: Dict[Any, int] = {}
        self.data: Optional[np.ndarray] = None  # [cap, D] float32
        self.alive: Optional[np.ndarray] = None  # [cap] bool
        self.n_slots = 0
        self.dirty = True
        self.gen = 0  # bumped on every mutation; caches key off it
        self.matrix = None  # device jnp [cap, D]
        self.mask: Optional[np.ndarray] = None
        self._dev_matrix = None
        self._dev_mask = None  # sharded mask (mesh placement only)
        self._mesh = None  # mesh the device arrays are placed over
        self.ivf = None  # IvfState, built on demand
        self._ivf_building = False
        self._ivf_done = threading.Event()  # signals a finished train round
        self._train_touched: Optional[set] = None  # slots mutated mid-train
        self._renumber = 0  # bumped when compaction renumbers slots
        self._pending: Optional[List[tuple]] = None  # deltas during build
        self._host_cache = None  # (contig data, sq-norms, rids) for host search
        self._lock = _locks.RLock("idx.knn.state")
        self._build_lock = _locks.Lock("idx.knn.build")
        self.label = ""  # "<table>.<index>", set on build (task attribution)
        self._owner = None  # id(ds), for bg teardown scoping

    # ------------------------------------------------------------ build
    def ensure_built(self, ctx, ix: dict) -> None:
        """One scan builds the mirror. The scan runs on a FRESH snapshot
        opened after delta-buffering starts, so (a) no committed write can
        fall between the scan and the built flag, and (b) the querying
        transaction's own uncommitted writes never leak into the shared
        mirror (they are served by the exact overlay path instead)."""
        from surrealdb_tpu.idx.vector_index import scan_vectors

        if self.built:
            return
        with self._build_lock:
            if self.built:
                return
            with self._lock:
                self._pending = []
            ns, db = ctx.ns_db()
            tb, name = ix["table"], ix["name"]
            self.label = f"{tb}.{name}"
            self._owner = id(ctx.ds())
            txn = ctx.ds().transaction(False)
            try:
                rids, rows = [], []
                for rid, vec in scan_vectors(txn, ns, db, tb, name):
                    rids.append(rid)
                    rows.append(vec)
            finally:
                txn.cancel()
            with self._lock:
                dim = len(rows[0]) if rows else int(ix["index"].get("dimension") or 0)
                cap = max(_pow2(len(rows)), cnf.TPU_BATCH_MIN_TILE)
                self.data = np.zeros((cap, max(dim, 1)), dtype=np.float32)
                self.alive = np.zeros(cap, dtype=bool)
                if rows:
                    self.data[: len(rows)] = np.asarray(rows, dtype=np.float32)
                    self.alive[: len(rows)] = True
                self.rids = rids
                self.slot_of = {_rid_key(r): i for i, r in enumerate(rids)}
                self.n_slots = len(rids)
                self.dirty = True
                self.gen += 1
                self.built = True
                pending, self._pending = self._pending, None
                # replay INSIDE the lock (RLock): a delta committed after
                # built flips must order after the buffered ones, never
                # be overwritten by a stale replay
                for rid, vec in pending:
                    self.apply(rid, vec)

    # ------------------------------------------------------------ deltas
    def apply(self, rid, vec) -> None:
        """One committed row change; vec=None tombstones the record.
        Idempotent, so a build-window delta replayed over a scan that
        already saw the row is harmless."""
        with self._lock:
            if self._pending is not None:
                self._pending.append((rid, vec))
                return
            if not self.built:
                return
            k = _rid_key(rid)
            slot = self.slot_of.get(k)
            if vec is None:
                if slot is not None:
                    self.alive[slot] = False
                    if self.ivf is not None:
                        self.ivf.remove(slot, self.data[slot])
                    del self.slot_of[k]
                self.dirty = True
                self.gen += 1
                return
            v = np.asarray(vec, dtype=np.float32)
            if slot is not None:  # overwrite in place
                if self.ivf is not None:
                    self.ivf.remove(slot, self.data[slot])
                self.data[slot] = v
                if self.ivf is not None:
                    self.ivf.add(slot, v)
                if self._train_touched is not None:
                    self._train_touched.add(slot)
                self.dirty = True
                self.gen += 1
                return
            if self.n_slots >= self.data.shape[0] or v.shape[0] != self.data.shape[1]:
                self._grow(v.shape[0])
            slot = self.n_slots
            self.n_slots += 1
            self.data[slot] = v
            self.alive[slot] = True
            if slot < len(self.rids):
                self.rids[slot] = rid
            else:
                self.rids.append(rid)
            self.slot_of[k] = slot
            if self.ivf is not None:
                self.ivf.add(slot, v)
            self.dirty = True
            self.gen += 1

    def apply_many(self, rids, vecs) -> None:
        """One committed bulk block ([B, D] float32): the all-new-rows fast
        path appends the whole block under ONE lock hold with one array
        copy — the per-row path cost B lock round-trips and B numpy row
        writes per bulk statement. Rows that already have a slot (or a
        building mirror) fall back to the per-row apply, which is always
        correct."""
        with self._lock:
            if self._pending is not None:
                self._pending.extend(zip(rids, vecs))
                return
            if not self.built:
                return
            vecs = np.asarray(vecs, dtype=np.float32)
            if (
                vecs.ndim != 2
                or len(rids) != vecs.shape[0]
                or self.data is None
                or (self.data.shape[1] not in (vecs.shape[1], 1) and self.n_slots)
            ):
                for rid, vec in zip(rids, vecs):
                    self.apply(rid, vec)
                return
            n0, B = self.n_slots, len(rids)
            if len(self.rids) != n0 or any(
                _rid_key(r) in self.slot_of for r in rids
            ):
                for rid, vec in zip(rids, vecs):
                    self.apply(rid, vec)
                return
            if n0 + B > self.data.shape[0] or vecs.shape[1] != self.data.shape[1]:
                self._grow(vecs.shape[1], need=n0 + B)
            self.data[n0 : n0 + B] = vecs
            self.alive[n0 : n0 + B] = True
            self.rids.extend(rids)
            for i, r in enumerate(rids):
                self.slot_of[_rid_key(r)] = n0 + i
            if self.ivf is not None:
                for i in range(B):
                    self.ivf.add(n0 + i, vecs[i])
            self.n_slots = n0 + B
            self.dirty = True
            self.gen += 1

    def _grow(self, dim: int, need: Optional[int] = None) -> None:
        cap = max(_pow2(max(self.n_slots + 1, need or 0)), cnf.TPU_BATCH_MIN_TILE)
        d = max(dim, self.data.shape[1])
        data = np.zeros((cap, d), dtype=np.float32)
        data[: self.data.shape[0], : self.data.shape[1]] = self.data
        alive = np.zeros(cap, dtype=bool)
        alive[: self.alive.shape[0]] = self.alive
        self.data, self.alive = data, alive

    def _maybe_compact(self) -> None:
        """Drop dead slots once they dominate; pure numpy, no KV."""
        dead = self.n_slots - int(self.alive[: self.n_slots].sum())
        if dead <= self.n_slots // 4 or dead < 256:
            return
        live = np.nonzero(self.alive[: self.n_slots])[0]
        cap = max(_pow2(live.size), cnf.TPU_BATCH_MIN_TILE)
        data = np.zeros((cap, self.data.shape[1]), dtype=np.float32)
        data[: live.size] = self.data[live]
        alive = np.zeros(cap, dtype=bool)
        alive[: live.size] = True
        self.rids = [self.rids[i] for i in live.tolist()]
        self.slot_of = {_rid_key(r): i for i, r in enumerate(self.rids)}
        self.data, self.alive, self.n_slots = data, alive, live.size
        self.gen += 1  # slot space renumbered
        self._renumber += 1
        self.ivf = None  # slot space changed; retrain on next ANN query

    # ------------------------------------------------------------ views
    def count(self) -> int:
        with self._lock:
            return int(self.alive[: self.n_slots].sum()) if self.built and self.alive is not None else 0

    def device_view(self, mesh=None):
        """(jnp matrix [cap, D], host mask [cap]) for the fused kernels.

        On accelerator backends the matrix uploads as cnf.TPU_VECTOR_DTYPE
        (bf16 by default: half the host->device transfer, MXU-native
        matmuls; distance accumulation stays f32 via
        preferred_element_type). CPU keeps f32 exactness. With a device
        mesh the matrix is placed row-SHARDED over the 'data' axis (cap is
        pow2, so it divides across any pow2 device count) and the mask is
        sharded alongside — the distributed-kNN layout (parallel/mesh.py)."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            self._maybe_compact()
            if self.dirty or self._dev_matrix is None or self._mesh is not mesh:
                data = self.data
                if (
                    cnf.TPU_VECTOR_DTYPE == "bfloat16"
                    and jax.devices()[0].platform != "cpu"
                ):
                    import ml_dtypes

                    data = data.astype(ml_dtypes.bfloat16)  # host-side cast
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec as P

                    axis = mesh.axis_names[0]
                    self._dev_matrix = jax.device_put(
                        data, NamedSharding(mesh, P(axis, None))
                    )
                    self._dev_mask = jax.device_put(
                        self.alive, NamedSharding(mesh, P(axis))
                    )
                else:
                    self._dev_matrix = jnp.asarray(data)
                    self._dev_mask = None
                self._mesh = mesh
                self.mask = self.alive.copy()
                self.dirty = False
            return self._dev_matrix, self.mask

    def device_snapshot(self, mesh=None):
        """(matrix, mask, rids) captured atomically: `rids` is the list
        OBJECT tied to this matrix's slot numbering. A later compaction
        installs a NEW list (never renumbering this one in place — appends
        only), so resolving kernel slots through this snapshot stays correct
        even if the mirror compacts while the batch is on device."""
        with self._lock:
            m, mask = self.device_view(mesh)
            return m, mask, self.rids

    def device_sharded_mask(self):
        with self._lock:
            return self._dev_mask

    def host_view(self):
        """(data [n, D], alive [n], rids) — numpy views for small corpora."""
        with self._lock:
            return self.data[: self.n_slots], self.alive[: self.n_slots], self.rids

    def host_search_view(self):
        """(contiguous live rows [m, D] f32, their squared norms [m], live
        rids) cached across queries, keyed off the mutation generation —
        the CPU search path must not re-copy the corpus or recompute norms
        per query (it IS the baseline the device path is judged against,
        so it gets the same care)."""
        with self._lock:
            if self._host_cache is None or self._host_cache[0] != self.gen:
                n = self.n_slots
                live = np.nonzero(self.alive[:n])[0]
                if live.size == n:
                    # fully-live slot space (the common bulk-ingest case):
                    # serve the mirror array itself — a fancy-index here
                    # would copy the whole corpus (GBs) for nothing
                    data = np.ascontiguousarray(self.data[:n], dtype=np.float32)
                    rids = list(self.rids[:n])
                else:
                    data = np.ascontiguousarray(self.data[live], dtype=np.float32)
                    rids = [self.rids[i] for i in live.tolist()]
                # f64 accumulation without materializing an f64 corpus copy
                norms = np.einsum(
                    "ij,ij->i", data, data, dtype=np.float64
                ).astype(np.float32)
                self._host_cache = (self.gen, data, norms, rids)
            return self._host_cache[1:]

    def ensure_ivf(self, matrix=None):
        """Return the current IVF state WITHOUT ever blocking the query:
        a missing or outgrown quantizer kicks a background training thread
        and the caller serves this query from the stale IVF (or, when None,
        the exact fused kernel). No query pays the multi-second training
        cliff (reference analog: the async builder, kvs/index.rs:28-41)."""
        with self._lock:
            ivf = self.ivf
            if ivf is not None and not ivf.needs_retrain():
                return ivf
            if self._ivf_building or matrix is None:
                return ivf
            self._ivf_building = True
            self._ivf_done.clear()
            self._train_touched = set()
            alive = self.alive[: self.n_slots].copy()
            data = self.data
            renum0 = self._renumber
        from surrealdb_tpu import bg

        # flight-recorder record: the multi-second training cliff is now an
        # attributable task (linked to the query that kicked it), named so
        # stack dumps say WHICH index is training
        task_id = bg.register("ivf_train", target=self.label, owner=self._owner)
        bg.start_thread(task_id, self._train_ivf, data, alive, matrix, renum0, task_id)
        return ivf

    def _train_ivf(self, data, alive, matrix, renum0: int, task_id=None) -> None:
        from surrealdb_tpu import bg
        from surrealdb_tpu.idx.ivf import IvfState

        try:
            if task_id is None:
                task_id = bg.register("ivf_train", target=self.label, trace_id=None)
            with bg.run(task_id):
                new = IvfState.train(data[: alive.size], alive, matrix=matrix)
        except BaseException:
            with self._lock:
                self._ivf_building = False
                self._train_touched = None
                self._ivf_done.set()
            raise
        with self._lock:
            self._ivf_building = False
            touched, self._train_touched = self._train_touched, None
            self._ivf_done.set()
            if self._renumber != renum0:
                return  # slot space renumbered mid-train; next query re-kicks
            # reconcile rows that changed while training ran on the snapshot
            cur = self.alive[: self.n_slots]
            for slot in range(alive.size, self.n_slots):  # appended rows
                if cur[slot]:
                    new.add(slot, self.data[slot])
            for slot in np.nonzero(~cur[: alive.size] & alive)[0]:  # tombstoned
                new.remove(int(slot), None)
            for slot in touched or ():  # overwritten in place mid-train
                new.remove(slot, None)
                if slot < self.n_slots and cur[slot]:
                    new.add(slot, self.data[slot])
            self.ivf = new

    def wait_ivf(self, timeout: float = 60.0) -> bool:
        """Block until the in-flight training round (if any) finishes —
        test/bench determinism helper, never used on the query path."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                if self.ivf is not None and not self._ivf_building:
                    return True
                building = self._ivf_building
            if not building:
                return False  # nothing training and no ivf (e.g. never kicked)
            self._ivf_done.wait(min(1.0, timeout))
        return False

    def ivf_status(self) -> dict:
        """INFO FOR INDEX 'ann' section."""
        with self._lock:
            if self._ivf_building:
                state = "training"
            elif self.ivf is None:
                state = "none"
            elif self.ivf.needs_retrain():
                state = "stale"
            else:
                state = "ready"
            out = {"state": state}
            if self.ivf is not None:
                out["nlists"] = self.ivf.nlists
                out["trained_n"] = self.ivf.trained_n
            return out





def _exact_device_launch(qs: np.ndarray, matrix, mask, metric: str, k: int, owner=None):
    """Async fused exact distance+top-k over a [Q, D] query batch, Q padded
    to a pow2 tile (≤64) so coalesced batches of any size reuse one compiled
    kernel shape. Returns a collect() closure (two-phase dispatch)."""
    import jax.numpy as jnp

    from surrealdb_tpu.idx.ivf import _start_host_copy
    from surrealdb_tpu.utils.num import dispatch_tile, pad_tail, tile_slices

    from surrealdb_tpu import compile_log

    nq = qs.shape[0]
    tile = dispatch_tile(nq)
    mj = jnp.asarray(mask)
    pending = []
    # every distinct (tile, dim, cap, k, metric) is one XLA executable: the
    # first call through a new shape IS the compile — record + attribute it
    shape_key = _exact_shape_key(tile, matrix, metric, k)
    with compile_log.tracked("knn_exact", shape_key):
        for lo, hi in tile_slices(nq, tile):
            d, r = D.knn_search(pad_tail(qs[lo:hi], tile), matrix, mj, metric, k)
            _start_host_copy(d, r)
            pending.append((lo, hi, d, r))

    def collect():
        dd = np.empty((nq, k), dtype=np.float32)
        rr = np.empty((nq, k), dtype=np.int64)
        for lo, hi, d, r in pending:
            dd[lo:hi] = np.asarray(d)[: hi - lo]
            rr[lo:hi] = np.asarray(r)[: hi - lo]
        return dd, rr

    _warm_exact_tiles(qs.shape[1], matrix, mj, metric, k, tile, owner)
    return collect


_EXACT_WARMED: set = set()


def _exact_shape_key(tile: int, matrix, metric: str, k: int):
    """Compile-cache key of the exact fused kernel: the static dims XLA
    keys its own executable cache on."""
    return (tile, int(matrix.shape[1]), int(matrix.shape[0]), str(matrix.dtype), metric, k)


def _warm_exact_tiles(dim, matrix, mask_j, metric, k, served_tile, owner=None) -> None:
    """Background-compile the other dispatch tile shapes of the exact fused
    kernel (same rationale as IvfState._warm_tiles). The warm set tracks
    the dispatcher's width cap, so every width the coalescer can hand a
    runner has a compiled shape waiting."""
    from surrealdb_tpu.utils.num import warm_tile_sizes

    todo = []
    for t in warm_tile_sizes():
        key = (t, id(matrix), metric, k)
        if t != served_tile and key not in _EXACT_WARMED:
            _EXACT_WARMED.add(key)
            todo.append(t)
    _EXACT_WARMED.add((served_tile, id(matrix), metric, k))
    if not todo:
        return

    def warm():
        import jax.numpy as jnp

        from surrealdb_tpu import compile_log

        for t in todo:
            try:
                with compile_log.tracked(
                    "knn_exact", _exact_shape_key(t, matrix, metric, k),
                    prewarmed=True,
                ):
                    D.knn_search(
                        jnp.zeros((t, dim), jnp.float32), matrix, mask_j, metric, k
                    )
            except Exception:
                from surrealdb_tpu import telemetry

                # a failed tile warm means the first real query at this
                # width pays the XLA compile — count it so a cold p99 is
                # attributable from metrics alone
                telemetry.inc("prewarm_errors", subsystem="knn_exact")

    from surrealdb_tpu import bg

    bg.spawn("shape_warm", f"knn_exact:k{k}", warm, owner=owner)


def _exact_device_batch(qs: np.ndarray, matrix, mask, metric: str, k: int):
    return _exact_device_launch(qs, matrix, mask, metric, k)()


def graftcheck_sites():
    """Audit contract of the exact fused distance+top-k kernel
    (compile_log subsystem `knn_exact`; scripts/graftcheck lowers every
    shape here to StableHLO and checks GC001–GC004). The shape matrix is
    the dispatcher's warm-tile vocabulary — the same shapes the background
    warmers pre-compile — over the serving metrics, plus the bf16 corpus
    variant the accelerator upload path uses."""
    from surrealdb_tpu.utils.num import warm_tile_sizes

    dim, cap, k = 64, 2048, 10

    def build(shape):
        import jax
        import jax.numpy as jnp

        from surrealdb_tpu.ops.distances import knn_search

        if shape["dtype"] == "bfloat16":
            import ml_dtypes

            cdt = jnp.dtype(ml_dtypes.bfloat16)
        else:
            cdt = jnp.float32
        args = (
            jax.ShapeDtypeStruct((shape["tile"], dim), jnp.float32),
            jax.ShapeDtypeStruct((cap, dim), cdt),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
        )
        metric, kk = shape["metric"], shape["k"]
        return (lambda q, x, m: knn_search(q, x, m, metric, kk)), args

    shapes = [
        {"label": f"t{t}_d{dim}_c{cap}_{m}_k{k}_{dt}",
         "tile": t, "metric": m, "k": k, "dtype": dt}
        for t, m, dt in (
            [(t, "euclidean", "float32") for t in warm_tile_sizes()]
            + [(8, "cosine", "float32"), (8, "euclidean", "bfloat16")]
        )
    ]
    return [
        {
            "subsystem": "knn_exact",
            "module": __name__,
            "kind": "single",
            "allowed_collectives": (),
            "out_dtypes": ("float32", "int32"),
            "shapes": shapes,
            "build": build,
        }
    ]


class _KnnResult:
    """Admitted record set for the operator check (reference KnnPriorityList)."""

    def __init__(self):
        self.dists: Dict[Any, float] = {}

    def key(self, rid) -> Any:
        return (rid.tb, repr(rid.id)) if isinstance(rid, Thing) else rid

    def add(self, rid, dist: float) -> None:
        self.dists[self.key(rid)] = dist

    def contains(self, rid) -> bool:
        return self.key(rid) in self.dists

    def dist(self, rid) -> Optional[float]:
        return self.dists.get(self.key(rid))


class _KnnExecutorMixin:
    """QueryExecutor protocol for the `<|k|>` operator and distance fn."""

    result: _KnnResult

    def knn(self, ctx, doc, op) -> bool:
        rid = doc.rid
        return rid is not None and self.result.contains(rid)

    def matches(self, ctx, doc, op) -> bool:
        return False

    def knn_distance(self, rid) -> Optional[float]:
        return self.result.dist(rid)

    def score(self, ctx, doc, ref=None):
        return None


class KnnPlan(_KnnExecutorMixin):
    """`<|k[,ef]|>` against a DEFINEd HNSW/MTREE index.

    Above TPU_ANN_MIN_ROWS the search is approximate-but-reranked IVF
    (idx/ivf.py — sublinear, recall governed by ef→nprobe, floors asserted
    like the reference's trees/hnsw/mod.rs:828-951 suite). Below it, exact
    fused distance+top-k (recall 1.0). A transaction with uncommitted writes
    to this index searches an exact overlay merge instead.
    """

    def __init__(self, tb: str, ix: dict, op, target):
        self.tb = tb
        self.ix = ix
        self.op = op
        self.k = op.k
        self.ef = getattr(op, "ef", None)
        self.target = _target_vector(target)
        self.result = _KnnResult()
        self.strategy = "?"
        # residual-WHERE mask lowered onto the table's column mirror
        # (set by the planner): exact strategies prefilter with it
        self.prefilter = None

    def _prefilter_slot_mask(self, ctx, rids, cap):
        """(mask over vector-mirror slots, coalescing key tag) — or None
        when the column mirror can't serve this reader exactly. The mask
        marks slots whose record satisfies the residual WHERE, so the
        kernel's top-k is computed among matching rows only."""
        from surrealdb_tpu import telemetry
        from surrealdb_tpu.idx.column_mirror import columnar_mask

        res = columnar_mask(ctx, self.tb, self.prefilter)
        if res is None:
            telemetry.inc("knn_prefilter", outcome="unavailable")
            return None
        mask, needs_row, col = res
        if needs_row.any():
            # the mask abstained on mixed-type rows: post-filter semantics
            # stay (dropping those rows from the search would be wrong)
            telemetry.inc("knn_prefilter", outcome="mixed_rows")
            return None
        perm = col.slot_permutation(rids, cap)
        ok = perm >= 0
        out = np.zeros(cap, dtype=bool)
        out[ok] = mask[perm[ok]]
        telemetry.inc("knn_prefilter", outcome="applied")
        # key the dispatch batch by MASK CONTENT, not predicate text: the
        # same SQL with different $param bindings lowers to different masks,
        # and a rider must never be served through a leader's tighter mask.
        # Identical masks (same predicate+constants, same column build)
        # still coalesce into one launch.
        return out, (hash(out.tobytes()), id(col))

    def explain(self) -> dict:
        idx = self.ix["index"]
        return {
            "index": self.ix["name"],
            "operator": f"<|{self.k}|>",
            "ann": {"type": idx["type"], "dist": idx.get("dist", "euclidean")},
        }

    def _pending_overlay(self, ctx, ns, db) -> Optional[Dict[Any, Any]]:
        """Uncommitted vector writes of this txn against this index."""
        deltas = getattr(ctx.txn(), "vector_deltas", None)
        if not deltas:
            return None
        want = (ns, db, self.tb, self.ix["name"])
        overlay = {}
        for ns_, db_, tb_, name_, rid, vec in deltas:
            if (ns_, db_, tb_, name_) != want:
                continue
            if isinstance(rid, list):
                # bulk block (vector_bulk_delta): rid is the rid LIST and
                # vec the [B, D] matrix — expand to per-row entries
                for r, v in zip(rid, vec):
                    overlay[_rid_key(r)] = (r, v)
            else:
                overlay[(_rid_key(rid))] = (rid, vec)
        return overlay or None

    def iterate(self, ctx):
        ctx.qe = self
        ds = ctx.ds()
        ns, db = ctx.ns_db()
        mirror = ds.index_stores.get_or_create(
            ns, db, self.tb, self.ix["name"], VectorMirror
        )
        mirror.ensure_built(ctx, self.ix)
        metric = self.ix["index"].get("dist", "euclidean")
        overlay = self._pending_overlay(ctx, ns, db)
        if overlay is not None:
            yield from self._exact_overlay(mirror, overlay, metric)
            return
        n = mirror.count()
        if n == 0:
            return
        k = min(self.k, n)
        import time as _time

        from surrealdb_tpu import telemetry, tracing

        # kernel-level node in the request's span tree: opened BEFORE the
        # serving-path chain so the dispatch spans it triggers nest under it
        t_search = _time.perf_counter()
        _trace_tok = tracing.push()
        _search_err: Optional[BaseException] = None
        q = np.asarray(self.target, dtype=np.float32)
        try:
            # MTREE preserves the reference's exactness contract
            # (core/src/idx/trees/mtree.rs:135 — an exact metric tree): it
            # always takes the exact fused distance+top-k paths; only HNSW
            # indexes may serve approximate IVF results
            approx_ok = self.ix["index"]["type"] != "mtree"
            # ANN pays off only when k is a small fraction of the corpus; a big-k
            # query gets the exact fused kernel (IVF would cap results at the
            # probed-candidate count)
            mesh = None if cnf.TPU_DISABLE else ds.mesh()
            if mesh is not None and n >= cnf.TPU_KNN_ONDEVICE_THRESHOLD:
                # multi-chip: the mirror shards row-wise over the mesh. ANN
                # composes with the mesh (VERDICT r3 weak #1): centroids are
                # replicated, inverted-list members sharded by slot range —
                # per-shard probe + rerank, then an O(k*devices) all-gather
                # (parallel/mesh.py sharded_ivf_search). While the quantizer
                # trains in the background (or for big-k queries where IVF
                # can't pay off) the exact per-shard distance+top-k path
                # (sharded_knn) serves instead — never a latency cliff.
                matrix, mask, rids = mirror.device_snapshot(mesh)
                mask_dev = mirror.device_sharded_mask()
                want_ivf = approx_ok and n >= cnf.TPU_ANN_MIN_ROWS and self.k * 4 <= n
                ivf = mirror.ensure_ivf(matrix) if want_ivf else None
                if ivf is not None:
                    from surrealdb_tpu.idx.ivf import default_nprobe

                    self.strategy = "ivf-sharded"
                    ef = self.ef or self.ix["index"].get("efc")
                    nprobe = default_nprobe(ivf.nlists, ef)
                    key = ("knn-ivf-sharded", id(matrix), id(ivf), metric, k, nprobe)
                    # columnar residual prefilter (parity with ivf/ivf-host):
                    # the slot mask shards alongside the corpus rows and the
                    # dispatch key carries the MASK CONTENT so riders with
                    # different $param bindings never share a leader's mask
                    slot_mask = None
                    if self.prefilter is not None:
                        pre = self._prefilter_slot_mask(ctx, rids, len(mask))
                        if pre is not None:
                            slot_mask = pre[0]
                            key = key + pre[1]

                    def runner(qs):
                        qm = np.stack(qs)

                        def collect():
                            dd, rr = ivf.search_batch_sharded(
                                qm, mesh, matrix, metric, k, nprobe,
                                slot_mask=slot_mask,
                            )
                            return list(zip(dd, rr))

                        return collect

                    dists, slots = ds.dispatch.submit(key, q, runner)
                else:
                    self.strategy = (
                        "exact-sharded(ivf-training)" if want_ivf else "exact-sharded"
                    )
                    key = ("knn-sharded", id(matrix), metric, k)

                    def runner(qs):
                        from surrealdb_tpu import compile_log
                        from surrealdb_tpu.parallel.mesh import sharded_knn
                        from surrealdb_tpu.utils.num import dispatch_tile, pad_tail, tile_slices

                        qs_m = np.stack(qs)
                        nq = qs_m.shape[0]
                        tile = dispatch_tile(nq)
                        dd = np.empty((nq, k), dtype=np.float32)
                        rr = np.empty((nq, k), dtype=np.int64)

                        def one_slice(lo, hi):
                            d, r = sharded_knn(
                                mesh, matrix, mask_dev, pad_tail(qs_m[lo:hi], tile), k, metric
                            )
                            dd[lo:hi] = np.asarray(d)[: hi - lo]
                            rr[lo:hi] = np.asarray(r)[: hi - lo]

                        # one executable per (tile, corpus dims, metric, k)
                        # on the mesh: only the FIRST slice can compile, so
                        # only it is tracked — wrapping the whole loop would
                        # log N tile executions as one giant phantom
                        # "compile" (graftlint GL002)
                        slices = list(tile_slices(nq, tile))
                        with compile_log.tracked(
                            "knn_sharded",
                            (tile, int(matrix.shape[1]), int(matrix.shape[0]),
                             metric, k),
                        ):
                            one_slice(*slices[0])
                        for lo, hi in slices[1:]:
                            one_slice(lo, hi)
                        return list(zip(dd, rr))

                    dists, slots = ds.dispatch.submit(key, q, runner)
            elif (
                not cnf.TPU_DISABLE
                and approx_ok
                and n >= cnf.TPU_ANN_MIN_ROWS
                and self.k * 4 <= n
            ):
                self.strategy = "ivf"
                # snapshot first: device_view may compact dead slots, which
                # renumbers the slot space and invalidates any trained IVF; the
                # snapshot's rids list is tied to this matrix's numbering
                matrix, mask, rids = mirror.device_snapshot()
                ivf = mirror.ensure_ivf(matrix)
                if ivf is None:
                    # quantizer still training in the background: serve this
                    # query exactly (no latency cliff, full recall)
                    self.strategy = "exact-device(ivf-training)"
                    key = ("knn-exact", id(matrix), metric, k)
                    if self.prefilter is not None:
                        pre = self._prefilter_slot_mask(ctx, rids, len(mask))
                        if pre is not None:
                            mask = mask & pre[0]
                            key = key + pre[1]

                    def runner(qs):
                        collect = _exact_device_launch(
                            np.stack(qs), matrix, mask, metric, k,
                            owner=mirror._owner,
                        )

                        def finish():
                            dd, rr = collect()
                            return list(zip(dd, rr))

                        return finish

                    dists, slots = ds.dispatch.submit(key, q, runner)
                else:
                    from surrealdb_tpu.idx.ivf import default_nprobe

                    ef = self.ef or self.ix["index"].get("efc")
                    nprobe = default_nprobe(ivf.nlists, ef)
                    # concurrent same-shape queries coalesce into one kernel
                    # launch (dbs/dispatch.py — the cross-query PARALLEL seam).
                    # Keyed by the matrix/ivf identities so a batch never mixes
                    # slot numberings.
                    key = ("knn-ivf", id(matrix), id(ivf), metric, k, nprobe)
                    # residual-WHERE prefilter (parity with the exact
                    # strategies): the mask rides into the probe+rerank
                    # kernel so top-k is computed among MATCHING rows; the
                    # key carries the mask content so riders with different
                    # $param bindings never share a leader's tighter mask
                    slot_mask = None
                    if self.prefilter is not None:
                        pre = self._prefilter_slot_mask(ctx, rids, len(mask))
                        if pre is not None:
                            slot_mask = pre[0]
                            key = key + pre[1]

                    def runner(qs):
                        collect = ivf.search_batch_launch(
                            np.stack(qs), matrix, metric, k, nprobe,
                            owner=mirror._owner, slot_mask=slot_mask,
                        )

                        def finish():
                            dd, rr = collect()
                            return list(zip(dd, rr))

                        return finish

                    dists, slots = ds.dispatch.submit(key, q, runner)
            elif not cnf.TPU_DISABLE and n >= cnf.TPU_KNN_ONDEVICE_THRESHOLD:
                self.strategy = "exact-device"
                matrix, mask, rids = mirror.device_snapshot()
                key = ("knn-exact", id(matrix), metric, k)
                if self.prefilter is not None:
                    pre = self._prefilter_slot_mask(ctx, rids, len(mask))
                    if pre is not None:
                        mask = mask & pre[0]
                        key = key + pre[1]

                def runner(qs):
                    collect = _exact_device_launch(
                        np.stack(qs), matrix, mask, metric, k,
                        owner=mirror._owner,
                    )

                    def finish():
                        dd, rr = collect()
                        return list(zip(dd, rr))

                    return finish

                dists, slots = ds.dispatch.submit(key, q, runner)
            else:
                # CPU serving path: an already-trained quantizer serves ANN on
                # host too (probe + exact rerank, idx/ivf.py search_host) — the
                # same sublinear contract as the device path, and the honest
                # CPU-ANN baseline for the bench. Never trains here (training
                # needs the device matrix); exact scan otherwise.
                ivf = mirror.ivf
                if (
                    approx_ok
                    and ivf is not None
                    and not ivf.needs_retrain()
                    and metric in ("euclidean", "cosine")
                    and n >= cnf.TPU_ANN_MIN_ROWS
                    and self.k * 4 <= n
                ):
                    from surrealdb_tpu.idx.ivf import default_nprobe

                    self.strategy = "ivf-host"
                    ef = self.ef or self.ix["index"].get("efc")
                    data, alive, rids = mirror.host_view()
                    slot_mask = None
                    if self.prefilter is not None:
                        pre = self._prefilter_slot_mask(ctx, rids, len(alive))
                        if pre is not None:
                            slot_mask = pre[0]
                    dists, li = ivf.search_host(
                        q[None, :], data, metric, k,
                        default_nprobe(ivf.nlists, ef),
                        slot_mask=slot_mask,
                    )
                    dists, slots = dists[0], li[0]
                else:
                    self.strategy = "exact-host"
                    data, norms, rids = mirror.host_search_view()
                    if self.prefilter is not None:
                        pre = self._prefilter_slot_mask(ctx, rids, len(rids))
                        if pre is not None:
                            sel = np.nonzero(pre[0])[0]
                            if sel.size == 0:
                                return
                            data, norms = data[sel], norms[sel]
                            rids = [rids[int(i)] for i in sel]
                            k = min(k, sel.size)
                    dists, li = D.knn_search_host(
                        q[None, :], data, metric, k, x_sq_norms=norms
                    )
                    dists, slots = dists[0], np.asarray(li)[0]
        except BaseException as e:
            _search_err = e
            raise
        finally:
            dur = _time.perf_counter() - t_search
            telemetry.observe("knn_search", dur, strategy=self.strategy)
            if _trace_tok is not None:
                tracing.pop(
                    _trace_tok, "knn_search",
                    {"strategy": self.strategy, "n": n, "k": k},
                    t_search, dur, _search_err,
                )
        self._count_strategy(n)
        for d, s in zip(np.asarray(dists), np.asarray(slots)):
            if not np.isfinite(d) or s < 0 or s >= len(rids):
                continue
            rid = rids[int(s)]
            if not isinstance(rid, Thing):
                rid = Thing(self.tb, rid)
            self.result.add(rid, float(d))
            yield rid, None, {"dist": float(d)}

    def _count_strategy(self, n: int) -> None:
        """Record which serving path answered this kNN query: the strategy
        counter attributes recall/latency anomalies per path, and the
        fallback counter isolates queries that LOST their sublinear path
        (quantizer still training → exact serve)."""
        from surrealdb_tpu import telemetry

        telemetry.inc("knn_strategy", strategy=self.strategy)
        if "(ivf-training)" in self.strategy:
            telemetry.inc("knn_fallbacks", cause="ivf_training")
        telemetry.note_plan(
            {"knn": self.strategy, "index": self.ix["name"], "k": self.k, "n": n}
        )

    def _exact_overlay(self, mirror, overlay, metric):
        """Merge uncommitted rows over the mirror and search exactly."""
        self.strategy = "exact-overlay"
        self._count_strategy(mirror.count())
        data, alive, rids = mirror.host_view()
        rows, out_rids = [], []
        for i in np.nonzero(alive)[0].tolist():
            key = _rid_key(rids[i])
            if key in overlay:
                continue  # superseded by the pending write
            rows.append(data[i])
            out_rids.append(rids[i])
        for key, (rid, vec) in overlay.items():
            if vec is not None:
                rows.append(np.asarray(vec, dtype=np.float32))
                out_rids.append(rid)
        if not rows:
            return
        mat = np.stack(rows)
        k = min(self.k, len(rows))
        dists, idxs = D.knn_search_host(
            np.asarray([self.target], dtype=np.float32), mat, metric, k
        )
        for d, i in zip(dists[0], idxs[0]):
            if not np.isfinite(d):
                continue
            rid = out_rids[int(i)]
            if not isinstance(rid, Thing):
                rid = Thing(self.tb, rid)
            self.result.add(rid, float(d))
            yield rid, None, {"dist": float(d)}


class BruteForceKnnPlan(_KnnExecutorMixin):
    """`<|k,DIST|>` with no matching index: one streamed pass gathers the
    field vectors, then a single fused device kernel does distance + top-k
    (replaces the reference's two-stage CollectKnn→BuildKnn workflow
    planner/mod.rs:208-232 with one batched pass)."""

    def __init__(self, tb: str, op, target):
        self.tb = tb
        self.op = op
        self.k = op.k
        self.metric = (op.dist or "euclidean").lower()
        self.target = _target_vector(target)
        self.result = _KnnResult()

    def explain(self) -> dict:
        return {
            "operator": f"<|{self.k},{self.metric.upper()}|>",
            "table": self.tb,
            "strategy": "brute-force (device batch)",
        }

    def iterate(self, ctx):
        ctx.qe = self
        from surrealdb_tpu.dbs.iterator import scan_table

        field = self.op.l
        rids: List[Thing] = []
        rows: List[List[float]] = []
        docs: Dict[Any, dict] = {}
        dim = len(self.target)
        for rid, doc in scan_table(ctx, self.tb):
            with ctx.with_doc_value(doc, rid=rid) as c:
                v = field.compute(c)
            if not isinstance(v, (list, tuple)) or len(v) != dim:
                continue
            try:
                rows.append([float(x) for x in v])
            except (TypeError, ValueError):
                continue
            rids.append(rid)
            docs[(rid.tb, repr(rid.id))] = doc
        if not rows:
            return
        from surrealdb_tpu import telemetry

        telemetry.inc("knn_strategy", strategy="brute-force")
        telemetry.note_plan({"knn": "brute-force", "table": self.tb, "n": len(rows)})
        k = min(self.k, len(rids))
        q = np.asarray([self.target], dtype=np.float32)
        if cnf.TPU_DISABLE or len(rids) < cnf.TPU_KNN_ONDEVICE_THRESHOLD:
            dists, idxs = D.knn_search_host(q, np.asarray(rows, dtype=np.float32), self.metric, k)
        else:
            mat, mask = D.pad_rows(np.asarray(rows, dtype=np.float32), cnf.TPU_BATCH_MIN_TILE)
            dists, idxs = D.knn_search(q, mat, mask, self.metric, k)
        dists = np.asarray(dists)[0]
        idxs = np.asarray(idxs)[0]
        for d, i in zip(dists, idxs):
            if not np.isfinite(d) or i >= len(rids):
                continue
            rid = rids[int(i)]
            self.result.add(rid, float(d))
            yield rid, docs[(rid.tb, repr(rid.id))], {"dist": float(d)}
