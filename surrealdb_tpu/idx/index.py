"""Write-side index maintenance.

Role of the reference's IndexOperation (reference: core/src/idx/index.rs:46-
341): on every document mutation, extract the indexed field values from the
old and new versions and update each index defined on the table. Non-unique
('idx') and unique ('uniq') indexes live directly in the ordered keyspace;
'search' (full-text), 'mtree' and 'hnsw' route to their own modules.

Array-valued fields produce one index entry per element combination,
mirroring the reference's Ids cartesian iterator.
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import IndexExistsError, TypeError_
from surrealdb_tpu.sql.path import get_path
from surrealdb_tpu.sql.value import NONE, Thing, format_value, is_nullish, value_eq

_MAX_COMBINATIONS = 1024


def extract_index_values(ctx, ix: dict, doc: Optional[dict]) -> Optional[List[Any]]:
    """Evaluate the index's field idioms against a document version."""
    if doc is None:
        return None
    with ctx.with_doc_value(doc) as c:
        return [get_path(c, doc, f.parts) for f in ix["fields"]]


def _combinations(vals: Sequence[Any]) -> List[tuple]:
    """Expand array-valued columns into per-element combinations."""
    axes = []
    for v in vals:
        if isinstance(v, list):
            axes.append(v if v else [NONE])
        else:
            axes.append([v])
    total = 1
    for a in axes:
        total *= len(a)
        if total > _MAX_COMBINATIONS:
            raise TypeError_("Index value combination count exceeds the allowed limit")
    return list(itertools.product(*axes))


def index_document(ctx, rid: Thing, old_doc: Optional[dict], new_doc: Optional[dict]) -> None:
    """Diff old/new indexed values and update every index on the table."""
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    indexes = txn.all_tb_indexes(ns, db, rid.tb)
    if not indexes:
        return
    for ix in indexes:
        old_vals = extract_index_values(ctx, ix, old_doc)
        new_vals = extract_index_values(ctx, ix, new_doc)
        if old_vals is not None and new_vals is not None:
            if all(value_eq(a, b) for a, b in zip(old_vals, new_vals)):
                continue
        _apply(ctx, ix, rid, old_vals, new_vals)


def _apply(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    typ = ix["index"]["type"]
    if typ == "idx":
        _update_idx(ctx, ix, rid, old_vals, new_vals)
    elif typ == "uniq":
        _update_uniq(ctx, ix, rid, old_vals, new_vals)
    elif typ == "search":
        from surrealdb_tpu.idx.ft import update_ft_index

        update_ft_index(ctx, ix, rid, old_vals, new_vals)
    elif typ in ("mtree", "hnsw"):
        from surrealdb_tpu.idx.vector_index import update_vector_index

        update_vector_index(ctx, ix, rid, old_vals, new_vals)
    else:
        raise TypeError_(f"unknown index type {typ!r}")


def _update_idx(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb, name = ix["table"], ix["name"]
    if old_vals is not None:
        for combo in _combinations(old_vals):
            txn.delete(keys.index_entry(ns, db, tb, name, list(combo), rid))
    if new_vals is not None:
        for combo in _combinations(new_vals):
            txn.set(keys.index_entry(ns, db, tb, name, list(combo), rid), b"")


def _update_uniq(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb, name = ix["table"], ix["name"]
    from surrealdb_tpu.utils.ser import pack, unpack

    if old_vals is not None:
        for combo in _combinations(old_vals):
            if all(is_nullish(v) for v in combo):
                continue
            txn.delete(keys.unique_entry(ns, db, tb, name, list(combo)))
    if new_vals is not None:
        for combo in _combinations(new_vals):
            if all(is_nullish(v) for v in combo):
                continue  # fully-NONE tuples are not uniqueness-constrained
            k = keys.unique_entry(ns, db, tb, name, list(combo))
            raw = txn.get(k)
            if raw is not None:
                holder = unpack(raw)
                if not (isinstance(holder, Thing) and holder == rid):
                    vals_txt = ", ".join(format_value(v) for v in combo)
                    raise IndexExistsError(holder, name, f"`{vals_txt}`")
            txn.set(k, pack(rid))


def rebuild_index(ctx, tb: str, ix: dict) -> int:
    """Full rebuild: wipe the index keyspace and re-index every record
    (reference: REBUILD INDEX + kvs/index.rs initial build)."""
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    name = ix["name"]
    from surrealdb_tpu.key.encode import prefix_end

    pre = keys.index_prefix(ns, db, tb, name)
    txn.delr(pre, prefix_end(pre))
    ctx.ds().index_stores.remove(ns, db, tb, name)

    count = 0
    rpre = keys.thing_prefix(ns, db, tb)
    from surrealdb_tpu.utils.ser import unpack

    for chunk in txn.batch(rpre, prefix_end(rpre), 1000):
        for k, v in chunk:
            doc = unpack(v)
            rid = Thing(tb, keys.decode_thing_id(k, ns, db, tb))
            new_vals = extract_index_values(ctx, ix, doc)
            _apply(ctx, ix, rid, None, new_vals)
            count += 1
    return count
