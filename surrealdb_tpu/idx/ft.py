"""Full-text index write path (SEARCH index definitions).

Role of the reference's FtIndex::index_document (reference:
core/src/idx/ft/mod.rs). Delegates to the real inverted index in
idx/ft_index.py — analyzers, term dictionary, postings, doc lengths — which
also buffers the per-document mirror delta consumed by the device-resident
CSR postings mirror (idx/ft_mirror.py) at commit.
"""

from __future__ import annotations

from surrealdb_tpu.sql.value import Thing


def update_ft_index(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    from surrealdb_tpu.idx.ft_index import FtIndex

    FtIndex.for_index(ctx, ix).index_document(ctx, rid, old_vals, new_vals)
