"""Full-text index write path (SEARCH index definitions).

Role of the reference's FtIndex::index_document (reference:
core/src/idx/ft/mod.rs). The inverted index (analyzers, term dictionary,
postings, doc lengths, batched BM25 scoring on device) is built in the
full-text milestone; until ft_index lands this is a tolerant no-op so SEARCH
index definitions don't break writes.
"""

from __future__ import annotations

from surrealdb_tpu.sql.value import Thing


def update_ft_index(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    try:
        from surrealdb_tpu.idx.ft_index import FtIndex
    except ImportError:
        return
    FtIndex.for_index(ctx, ix).index_document(ctx, rid, old_vals, new_vals)
