"""IVF (inverted-file) ANN index — the TPU-native answer to HNSW/MTree.

Role of the reference's graph ANN structures (reference:
core/src/idx/trees/hnsw/mod.rs:337-416 layered beam search, trees/mtree.rs:135
ball-tree kNN) re-designed TPU-first: pointer-chasing beam searches are a poor
fit for the MXU, so `DEFINE INDEX … HNSW|MTREE` executes as a ScaNN-style
IVF: a k-means coarse quantizer (trained on device, MXU matmuls) partitions
the corpus into C lists; a query probes the nprobe nearest lists and exactly
reranks only their members — one fused gather + distance-matmul + top-k
kernel. Sublinear work (nprobe/C of the corpus), tunable recall via the
operator's ef (reference `<|k,ef|>` Ann operator, sql/operator.rs:65).

Quality floors are asserted by recall-vs-brute-force tests
(tests/test_ivf.py), mirroring the reference's hnsw recall suite
(trees/hnsw/mod.rs:828-951).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from surrealdb_tpu.ops import distances as D
from surrealdb_tpu.utils.num import next_pow2 as _next_pow2

# metrics whose geometry the coarse quantizer can probe directly; the rest
# probe in euclidean space and rely on exact rerank for the final order
_PROBE_METRICS = {"euclidean", "cosine", "manhattan", "chebyshev"}


def _start_host_copy(*arrs) -> None:
    """Kick the device→host transfer without blocking, so the download
    overlaps remaining device work (no-op on backends without the hook)."""
    for a in arrs:
        try:
            a.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass


def _ivf_shape_key(tile, cents, list_rows, matrix, metric, probe_metric, k, nprobe):
    """Compile-cache key of the fused probe+rerank kernel: every static dim
    XLA keys its executable cache on (compile_log attribution)."""
    return (
        tile, int(matrix.shape[1]), int(matrix.shape[0]), str(matrix.dtype),
        int(cents.shape[0]), int(list_rows.shape[1]), metric, probe_metric,
        k, nprobe,
    )


def default_nlists(n: int) -> int:
    """C ≈ sqrt(N), pow2-clamped to [8, 4096]."""
    return min(max(_next_pow2(int(math.sqrt(max(n, 1)))), 8), 4096)


def default_nprobe(nlists: int, ef: Optional[int]) -> int:
    """Map the HNSW-style ef beam width onto probed-list count. With
    balanced lists each probe examines ~2·N/C candidates, so ef/10 probes
    lands near the reference's beam-width semantics (search ef=80 → 8
    probes ≈ 99% recall on clustered data, see tests/test_ivf.py)."""
    if ef is not None and ef > 0:
        return min(max(4, round(ef / 10)), nlists)
    return min(max(4, nlists // 16), nlists)


@functools.partial(jax.jit, static_argnames=("k_assign",))
def _assign_chunk(chunk, cents, k_assign=1):
    """Nearest-centroid assignment for one corpus tile (euclidean)."""
    import jax.numpy as jnp

    d = D.pairwise_distance(chunk, cents, "euclidean")
    if k_assign == 1:
        return jnp.argmin(d, axis=1)
    return jax.lax.top_k(-d, k_assign)[1]


@functools.partial(jax.jit, static_argnames=("k_assign",))
def _assign_gather(matrix, idx, cents, k_assign=1):
    """Gather rows from the DEVICE-resident mirror matrix and assign them to
    their nearest centroids — only the [chunk] index vector crosses the
    host->device link, not the rows themselves (the tunnel here moves
    ~20MB/s, so re-uploading a 1Mx768 corpus for assignment would cost
    minutes)."""
    import jax.numpy as jnp

    chunk = matrix[jnp.clip(idx, 0, matrix.shape[0] - 1)]
    d = D.pairwise_distance(chunk, cents, "euclidean")
    if k_assign == 1:
        return jnp.argmin(d, axis=1)
    return jax.lax.top_k(-d, k_assign)[1]


@functools.partial(jax.jit, static_argnames=("nlists",))
def _kmeans_step(xs, c, nlists: int):
    import jax.numpy as jnp

    d = D.pairwise_distance(xs, c, "euclidean")
    a = jnp.argmin(d, axis=1)
    sums = jax.ops.segment_sum(xs.astype(jnp.float32), a, num_segments=nlists)
    cnts = jax.ops.segment_sum(jnp.ones(xs.shape[0], jnp.float32), a, num_segments=nlists)
    # empty clusters keep their previous centroid
    return jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts[:, None], 1.0), c.astype(jnp.float32))


def _kmeans_xs(xs, nlists: int, iters: int = 8, seed: int = 7):
    """Device k-means over an already-device-resident sample [n, D]."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    cents = xs[jnp.asarray(rng.choice(xs.shape[0], size=nlists, replace=False))]
    for _ in range(iters):
        cents = _kmeans_step(xs, cents, nlists)
    return cents


def _kmeans(x: np.ndarray, nlists: int, iters: int = 8, seed: int = 7) -> np.ndarray:
    """Device k-means on a host training subsample; returns [C, D] centroids."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n = x.shape[0]
    train_n = min(n, max(nlists * 64, 16384))
    sample = x[rng.choice(n, size=train_n, replace=False)] if train_n < n else x
    xs = jnp.asarray(sample)
    return np.asarray(_kmeans_xs(xs, nlists, iters, seed), dtype=np.float32)


def _full_assign(
    x: np.ndarray, cents: np.ndarray, chunk: int = 65536, k_assign: int = 1
) -> np.ndarray:
    """Assign every corpus row to its k nearest centroids, tiled so the
    [N, C] distance matrix never materializes whole."""
    import jax.numpy as jnp

    cj = jnp.asarray(cents)
    shape = (x.shape[0],) if k_assign == 1 else (x.shape[0], k_assign)
    out = np.empty(shape, dtype=np.int32)
    for lo in range(0, x.shape[0], chunk):
        hi = min(lo + chunk, x.shape[0])
        tile = x[lo:hi]
        pad = chunk - (hi - lo)
        if pad:
            tile = np.concatenate([tile, np.zeros((pad, x.shape[1]), x.dtype)])
        a = np.asarray(_assign_chunk(jnp.asarray(tile), cj, k_assign=k_assign))
        out[lo:hi] = a[: hi - lo]
    return out


class IvfState:
    """Trained quantizer + inverted lists over mirror row slots.

    Host-authoritative: `lists` maps centroid → row slots; device arrays are
    compacted lazily (numpy only — never a KV rescan). Incremental adds
    assign to the nearest existing centroid; retrain happens when the corpus
    outgrows the trained size by 50%.
    """

    def __init__(self, centroids: np.ndarray, lists: List[List[int]], trained_n: int):
        self.centroids = centroids  # [C, D] float32
        self.lists = lists  # C lists of row slots
        self.slot_list: Dict[int, int] = {s: i for i, l in enumerate(lists) for s in l}
        self.trained_n = trained_n
        self._n = len(self.slot_list)  # O(1) size, maintained by add/remove
        self.dirty = True
        self._dev = None  # (cents, list_rows, list_mask)
        self._mut = 0  # bumped on every list mutation; sharded cache keys off it
        self._sharded_cache = None  # (key, (cents, rows, mask, shard_rows))
        self._warmed: set = set()  # (tile, k, nprobe, metric) combos compiled

    @property
    def nlists(self) -> int:
        return self.centroids.shape[0]

    # ------------------------------------------------------------ build
    @staticmethod
    def train(
        data: np.ndarray,
        alive: np.ndarray,
        nlists: Optional[int] = None,
        matrix=None,
    ) -> "IvfState":
        """Train the quantizer. When `matrix` (the mirror's device-resident
        [cap, D] array) is given, the training sample and the full corpus
        assignment gather rows ON DEVICE — only index vectors and the [C, D]
        centroids cross the slow host<->device link."""
        import jax.numpy as jnp

        rows = np.nonzero(alive)[0]
        c = nlists or default_nlists(rows.size)
        if matrix is not None and rows.size:
            rng = np.random.default_rng(7)
            train_n = min(rows.size, max(c * 64, 16384))
            sample_slots = rng.choice(rows, size=train_n, replace=False)
            xs = matrix[jnp.asarray(sample_slots.astype(np.int32))]
            cents_dev = _kmeans_xs(xs, c)
            # full assignment by device gather, chunked index uploads only
            from surrealdb_tpu.utils.num import pad_tail, tile_slices

            chunk = 65536
            assign2 = np.empty((rows.size, 2), dtype=np.int32)
            for lo, hi in tile_slices(rows.size, chunk):
                idx = pad_tail(rows[lo:hi].astype(np.int32), chunk)
                a = np.asarray(
                    _assign_gather(matrix, jnp.asarray(idx), cents_dev, k_assign=2)
                )
                assign2[lo:hi] = a[: hi - lo]
            cents = np.asarray(cents_dev, dtype=np.float32)
        else:
            x = np.ascontiguousarray(data[rows], dtype=np.float32)
            cents = _kmeans(x, c)
            assign2 = _full_assign(x, cents, k_assign=2)
        # balanced assignment: top-2 candidate cells with spill to the
        # runner-up once the nearest is over 2x the mean size — bounds the
        # padded gather at ~2·N/C per probe instead of the worst cell
        cap = max(2 * (rows.size + c - 1) // c, 8)
        lists: List[List[int]] = [[] for _ in range(c)]
        for slot, (a1, a2) in zip(rows.tolist(), assign2.tolist()):
            a = a1 if len(lists[a1]) < cap or len(lists[a2]) >= len(lists[a1]) else a2
            lists[int(a)].append(slot)
        return IvfState(cents, lists, rows.size)

    # ------------------------------------------------------------ writes
    def add(self, slot: int, vec: np.ndarray) -> None:
        if slot in self.slot_list:
            return  # idempotent (reconciliation may revisit a slot)
        d2 = ((self.centroids - vec[None, :]) ** 2).sum(1)
        a1, a2 = np.argpartition(d2, 1)[:2]
        cap = max(2 * (self._n // max(self.nlists, 1) + 1), 8)
        a = int(a1) if len(self.lists[a1]) < cap or len(self.lists[a2]) >= len(self.lists[a1]) else int(a2)
        self.lists[a].append(slot)
        self.slot_list[slot] = a
        self._n += 1
        self.dirty = True
        self._mut += 1

    def remove(self, slot: int, vec=None) -> None:
        a = self.slot_list.pop(slot, None)
        if a is not None:
            try:
                self.lists[a].remove(slot)
                self._n -= 1
            except ValueError:
                pass
        self.dirty = True
        self._mut += 1

    def size(self) -> int:
        return self._n

    def needs_retrain(self) -> bool:
        return self.size() > 1.5 * max(self.trained_n, 1)

    # ------------------------------------------------------------ search
    def _device(self):
        import jax.numpy as jnp

        if not self.dirty and self._dev is not None:
            return self._dev
        c = self.nlists
        maxlen = _next_pow2(max(max((len(l) for l in self.lists), default=1), 1))
        list_rows = np.zeros((c, maxlen), dtype=np.int32)
        list_mask = np.zeros((c, maxlen), dtype=bool)
        for i, l in enumerate(self.lists):
            list_rows[i, : len(l)] = l
            list_mask[i, : len(l)] = True
        self._dev = (
            jnp.asarray(self.centroids),
            jnp.asarray(list_rows),
            jnp.asarray(list_mask),
        )
        self.dirty = False
        return self._dev

    def search_host(
        self, qs: np.ndarray, data: np.ndarray, metric: str, k: int, nprobe: int,
        slot_mask: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """CPU twin of `search_batch`: the same probe+exact-rerank recipe in
        numpy over the host mirror. This is the honest CPU-ANN baseline the
        device numbers are judged against (a sublinear competitor, not an
        exact full scan) — same role as the reference's CPU HNSW search
        (reference: core/src/idx/trees/hnsw/mod.rs:337-416).

        qs: [Q, D]; data: host [cap, D] mirror rows. Returns
        (dists [Q, k], slots [Q, k]); misses surface as +inf/-1.
        """
        if metric not in ("euclidean", "cosine"):
            raise ValueError(f"search_host supports euclidean/cosine, not {metric!r}")
        import time as _time

        from surrealdb_tpu import telemetry

        _t_probe = _time.perf_counter()
        qs = np.asarray(qs, dtype=np.float32)
        nq = qs.shape[0]
        cents = self.centroids
        cn = (cents**2).sum(1)
        nprobe = min(nprobe, self.nlists)
        out_d = np.full((nq, k), np.inf, dtype=np.float32)
        out_i = np.full((nq, k), -1, dtype=np.int64)
        # one BLAS call probes every query at once: [Q, C] + |q|^2 constant,
        # so the ordering equals true euclidean distance per row
        d2c = cn[None, :] - 2.0 * (qs @ cents.T)
        probes = np.argpartition(d2c, nprobe - 1, axis=1)[:, :nprobe]
        # concatenate every query's probed lists into ONE flat candidate
        # array with owner segments — the rerank then runs as a handful of
        # vectorized numpy calls over all queries together instead of a
        # per-query python loop (GIL thrash under concurrent clients was a
        # measured contributor to the scale-1.0 concurrent-kNN collapse)
        cand_per_q: List[np.ndarray] = []
        for qi in range(nq):
            cl = [self.lists[int(p)] for p in probes[qi]]
            total = sum(len(l) for l in cl)
            c = np.fromiter((s for l in cl for s in l), dtype=np.int64, count=total)
            if slot_mask is not None:
                # columnar residual prefilter: rerank only matching slots —
                # top-k among rows that satisfy the WHERE, the same
                # condition-checker semantics as the exact strategies
                inb = c < slot_mask.shape[0]
                c = c[inb & slot_mask[np.minimum(c, slot_mask.shape[0] - 1)]]
            cand_per_q.append(c)
            telemetry.observe_hist(
                "ivf_candidates", int(c.size), buckets=telemetry.COUNT_BUCKETS, path="host"
            )
        counts = np.array([c.size for c in cand_per_q], dtype=np.int64)
        q2 = (qs**2).sum(1)
        qn = np.maximum(np.sqrt(q2), 1e-30)
        # bound the gather: query blocks capped at ~128k candidate rows, so
        # a wide batch over a big corpus can't materialize a multi-GB
        # [T, D] temporary (the per-query peak stays what the old loop had)
        cand_block = 1 << 17
        qi0 = 0
        while qi0 < nq:
            qi1 = qi0 + 1
            tot = int(counts[qi0])
            while qi1 < nq and tot + int(counts[qi1]) <= cand_block:
                tot += int(counts[qi1])
                qi1 += 1
            if tot == 0:
                qi0 = qi1
                continue
            cand_all = np.concatenate(cand_per_q[qi0:qi1])
            owner = np.repeat(np.arange(qi0, qi1), counts[qi0:qi1])
            x = data[cand_all]  # [T, D] gather, one fancy-index per block
            dots = np.einsum("ij,ij->i", x, qs[owner])
            xn2 = np.einsum("ij,ij->i", x, x)
            if metric == "cosine":
                xn = np.maximum(np.sqrt(xn2), 1e-30)
                d = 1.0 - dots / (xn * qn[owner])
            else:
                d = xn2 - 2.0 * dots  # + |q|^2 applied after top-k below
            # per-query top-k over its segment: the remaining python loop
            # does only O(T_q) selection work, no distance math
            off = 0
            for qi in range(qi0, qi1):
                t = int(counts[qi])
                if t == 0:
                    continue
                seg = d[off : off + t]
                kk = min(k, t)
                sel = np.argpartition(seg, kk - 1)[:kk] if kk < t else np.arange(t)
                sel = sel[np.argsort(seg[sel])]
                if metric == "cosine":
                    out_d[qi, :kk] = seg[sel]
                else:
                    out_d[qi, :kk] = np.sqrt(np.maximum(seg[sel] + q2[qi], 0.0))
                out_i[qi, :kk] = cand_all[off + sel]
                off += t
            qi0 = qi1
        # probe-level node under the active request's knn_search span + a
        # path-labeled duration histogram (host twin of the device probe)
        from surrealdb_tpu import telemetry, tracing

        _dur = _time.perf_counter() - _t_probe
        telemetry.observe("ivf_probe", _dur, path="host")
        tracing.record_span_into(
            tracing.current(), "ivf_probe",
            {"path": "host", "nq": int(qs.shape[0]), "nprobe": int(nprobe)},
            _t_probe, _dur,
        )
        return out_d, out_i

    def search(
        self, q: np.ndarray, matrix, metric: str, k: int, nprobe: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Probe nprobe lists, exact-rerank their members on device.

        q: [D] query; matrix: device [N*, D] mirror matrix.
        Returns (dists [k], row slots [k]); misses surface as +inf/-1.
        """
        d, r = self.search_batch(q[None, :], matrix, metric, k, nprobe)
        return d[0], r[0]

    def search_batch_launch(
        self, qs: np.ndarray, matrix, metric: str, k: int, nprobe: int,
        tile: Optional[int] = None, owner=None, slot_mask=None,
    ):
        """Async probe+rerank: enqueue every tile's kernel + start the
        device→host copies, return a collect() closure that blocks on the
        results. Lets the dispatch queue overlap the next batch's upload
        with this batch's compute/download (double buffering). `slot_mask`
        [cap] restricts the rerank to matching corpus slots (the columnar
        residual prefilter — ROADMAP carried item)."""
        import jax.numpy as jnp

        cents, list_rows, list_mask = self._device()
        if slot_mask is None:
            slot_ok = jnp.ones(int(matrix.shape[0]), dtype=bool)
        else:
            pad = int(matrix.shape[0]) - int(slot_mask.shape[0])
            if pad > 0:
                slot_mask = np.concatenate([slot_mask, np.zeros(pad, dtype=bool)])
            slot_ok = jnp.asarray(slot_mask[: int(matrix.shape[0])])
        probe_metric = metric if metric in _PROBE_METRICS else "euclidean"
        nprobe = min(nprobe, self.nlists)
        # the kernel can return at most nprobe·L candidates per query
        k = min(k, nprobe * int(list_rows.shape[1]))
        from surrealdb_tpu.utils.num import pad_tail, tile_slices

        from surrealdb_tpu.utils.num import dispatch_tile

        qs = np.asarray(qs, dtype=np.float32)
        # small tile vocabulary: every distinct padded shape is a separate
        # XLA compile; {1, 8, tile} bounds compiles AND padding waste
        nq = qs.shape[0]
        tile = dispatch_tile(nq, tile)
        from surrealdb_tpu import telemetry

        # per-query probed-candidate ceiling (the kernel scans whole lists)
        telemetry.observe_hist(
            "ivf_candidates",
            nprobe * int(list_rows.shape[1]),
            buckets=telemetry.COUNT_BUCKETS,
            path="device",
        )
        from surrealdb_tpu import compile_log

        pending = []
        with compile_log.tracked(
            "ivf",
            _ivf_shape_key(tile, cents, list_rows, matrix, metric, probe_metric, k, nprobe),
        ):
            for lo, hi in tile_slices(nq, tile):
                d, r = _ivf_search(
                    jnp.asarray(pad_tail(qs[lo:hi], tile)), cents, list_rows,
                    list_mask, matrix, slot_ok,
                    metric=metric, probe_metric=probe_metric, k=k, nprobe=nprobe,
                )
                _start_host_copy(d, r)
                pending.append((lo, hi, d, r))

        def collect() -> Tuple[np.ndarray, np.ndarray]:
            dd = np.empty((nq, k), dtype=np.float32)
            rr = np.empty((nq, k), dtype=np.int64)
            for lo, hi, d, r in pending:
                dd[lo:hi] = np.asarray(d)[: hi - lo]
                rr[lo:hi] = np.asarray(r)[: hi - lo]
            return dd, rr

        self._warm_tiles(qs.shape[1], cents, list_rows, list_mask, matrix,
                         metric, probe_metric, k, nprobe, tile, owner)
        return collect

    def _warm_tiles(self, dim, cents, list_rows, list_mask, matrix,
                    metric, probe_metric, k, nprobe, served_tile, owner=None) -> None:
        """Background-compile the OTHER dispatch tile shapes for these query
        params: a burst of concurrent queries coalesces into 8/64-wide
        batches whose first dispatch would otherwise stall seconds on XLA
        compilation (the r3 concurrent-qps killer). Zero-queries through the
        same kernel carry no correctness risk — results are discarded."""
        from surrealdb_tpu.utils.num import warm_tile_sizes

        todo = []
        for t in warm_tile_sizes():
            key = (t, k, nprobe, metric)
            if t != served_tile and key not in self._warmed:
                self._warmed.add(key)
                todo.append(t)
        self._warmed.add((served_tile, k, nprobe, metric))
        if not todo:
            return

        def warm():
            import jax.numpy as jnp

            from surrealdb_tpu import compile_log

            for t in todo:
                try:
                    with compile_log.tracked(
                        "ivf",
                        _ivf_shape_key(
                            t, cents, list_rows, matrix, metric, probe_metric,
                            k, nprobe,
                        ),
                        prewarmed=True,
                    ):
                        _ivf_search(
                            jnp.zeros((t, dim), jnp.float32), cents, list_rows,
                            list_mask, matrix,
                            jnp.ones(int(matrix.shape[0]), dtype=bool),
                            metric=metric, probe_metric=probe_metric, k=k,
                            nprobe=nprobe,
                        )
                except Exception:
                    from surrealdb_tpu import telemetry

                    # a failed tile warm = an on-demand compile inside some
                    # future request; count it so cold latency is attributable
                    telemetry.inc("prewarm_errors", subsystem="ivf")

        from surrealdb_tpu import bg

        bg.spawn("shape_warm", f"ivf:k{k}:p{nprobe}", warm, owner=owner)

    def search_batch(
        self, qs: np.ndarray, matrix, metric: str, k: int, nprobe: int,
        tile: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Batched probe+rerank: qs [Q, D] → (dists [Q, k], slots [Q, k]).

        Queries are tiled so the [tile, nprobe·L, D] candidate gather stays
        within memory; each tile is ONE device dispatch (the cross-query
        batching seam — amortizes dispatch latency across queries).
        """
        return self.search_batch_launch(qs, matrix, metric, k, nprobe, tile)()


    # -------------------------------------------------------- mesh search
    def _device_sharded(self, mesh, n_total: int, axis: str = "data"):
        """Per-shard inverted-list tables for sharded_ivf_search: bucket each
        list's slots by owning shard (slot // shard_rows) into a
        [n_dev, C, L] local-row table placed sharded over the mesh axis —
        each chip holds only ITS slab, aligned with its corpus rows."""
        import jax as _jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        n_dev = mesh.shape[axis]
        shard_rows = n_total // n_dev
        key = (self._mut, id(mesh), n_total)
        if self._sharded_cache is not None and self._sharded_cache[0] == key:
            return self._sharded_cache[1]
        c = self.nlists
        per: List[List[List[int]]] = [[[] for _ in range(c)] for _ in range(n_dev)]
        for ci, l in enumerate(self.lists):
            for s in l:
                d = min(s // shard_rows, n_dev - 1)
                per[d][ci].append(s - d * shard_rows)
        maxlen = max((len(pl) for shard in per for pl in shard), default=1)
        maxlen = _next_pow2(max(maxlen, 1))
        rows = np.zeros((n_dev, c, maxlen), dtype=np.int32)
        mask = np.zeros((n_dev, c, maxlen), dtype=bool)
        for d in range(n_dev):
            for ci in range(c):
                pl = per[d][ci]
                rows[d, ci, : len(pl)] = pl
                mask[d, ci, : len(pl)] = True
        sh = NamedSharding(mesh, P(axis, None, None))
        dev = (
            jnp.asarray(self.centroids),
            _jax.device_put(rows, sh),
            _jax.device_put(mask, sh),
            shard_rows,
        )
        self._sharded_cache = (key, dev)
        return dev

    def search_batch_sharded(
        self, qs: np.ndarray, mesh, matrix, metric: str, k: int, nprobe: int,
        tile: Optional[int] = None, slot_mask: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Batched sharded probe+rerank over a mesh-sharded mirror matrix.
        Same contract as search_batch; misses surface as +inf/-1.
        `slot_mask` is the columnar residual prefilter over corpus slots:
        it rides into the kernel row-sharded alongside the corpus so top-k
        is computed among MATCHING rows only."""
        from surrealdb_tpu.parallel.mesh import sharded_ivf_search
        from surrealdb_tpu.utils.num import pad_tail, tile_slices
        import jax as _jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as _P

        from surrealdb_tpu.utils.num import dispatch_tile

        from surrealdb_tpu import compile_log

        cents, list_rows, list_mask, _ = self._device_sharded(mesh, matrix.shape[0])
        probe_metric = metric if metric in _PROBE_METRICS else "euclidean"
        nprobe = min(nprobe, self.nlists)
        qs = np.asarray(qs, dtype=np.float32)
        cap = int(matrix.shape[0])
        if slot_mask is not None:
            sm = np.asarray(slot_mask, dtype=bool)
            if sm.shape[0] < cap:  # pad slots are dead anyway
                sm = np.concatenate([sm, np.zeros(cap - sm.shape[0], dtype=bool)])
            sm = sm[:cap]
        else:
            # placed ONCE here (not per tile-slice launch inside the loop,
            # and never as a replicated jnp.ones the shard_map must reshard)
            sm = np.ones(cap, dtype=bool)
        slot_dev = _jax.device_put(
            sm, NamedSharding(mesh, _P(mesh.axis_names[0]))
        )
        tile = dispatch_tile(qs.shape[0], tile)
        dd = np.full((qs.shape[0], k), np.inf, dtype=np.float32)
        rr = np.full((qs.shape[0], k), -1, dtype=np.int64)
        def one_slice(lo, hi):
            d, r = sharded_ivf_search(
                mesh, cents, list_rows, list_mask, matrix,
                jnp.asarray(pad_tail(qs[lo:hi], tile)),
                k, nprobe, metric=metric, probe_metric=probe_metric,
                slot_ok=slot_dev,
            )
            k_out = int(np.asarray(d).shape[1])
            dd[lo:hi, :k_out] = np.asarray(d)[: hi - lo]
            rr[lo:hi, :k_out] = np.asarray(r)[: hi - lo]

        # the sharded probe+rerank compiles per (tile, corpus, k, nprobe,
        # metrics): only the FIRST slice can compile, so only it is tracked
        # — wrapping the whole loop would log N tile executions as one
        # giant phantom "compile" (graftlint GL002)
        slices = list(tile_slices(qs.shape[0], tile))
        with compile_log.tracked(
            "ivf_sharded",
            (tile, int(matrix.shape[1]), int(matrix.shape[0]), k, nprobe,
             metric, probe_metric),
        ):
            one_slice(*slices[0])
        for lo, hi in slices[1:]:
            one_slice(lo, hi)
        return dd, rr


def graftcheck_sites():
    """Audit contract of the fused IVF probe+rerank kernel (compile_log
    subsystem `ivf`): the warm-tile query shapes over a representative
    (C lists × L members) quantizer, euclidean + the cosine/rerank mix."""
    from surrealdb_tpu.utils.num import warm_tile_sizes

    dim, cap, k = 64, 2048, 10
    C, L, nprobe = 64, 32, 8

    def build(shape):
        import jax as _jax
        import jax.numpy as jnp

        args = (
            _jax.ShapeDtypeStruct((shape["tile"], dim), jnp.float32),
            _jax.ShapeDtypeStruct((C, dim), jnp.float32),
            _jax.ShapeDtypeStruct((C, L), jnp.int32),
            _jax.ShapeDtypeStruct((C, L), jnp.bool_),
            _jax.ShapeDtypeStruct((cap, dim), jnp.float32),
            _jax.ShapeDtypeStruct((cap,), jnp.bool_),
        )
        metric = shape["metric"]
        probe_metric = metric if metric in _PROBE_METRICS else "euclidean"

        def run(q, cents, rows, mask, x, slot_ok):
            return _ivf_search(
                q, cents, rows, mask, x, slot_ok,
                metric=metric, probe_metric=probe_metric,
                k=shape["k"], nprobe=nprobe,
            )

        return run, args

    shapes = [
        {"label": f"t{t}_d{dim}_c{cap}_C{C}_L{L}_p{nprobe}_{m}_k{k}",
         "tile": t, "metric": m, "k": k}
        for t, m in (
            [(t, "euclidean") for t in warm_tile_sizes()] + [(8, "cosine")]
        )
    ]
    return [
        {
            "subsystem": "ivf",
            "module": __name__,
            "kind": "single",
            "allowed_collectives": (),
            "out_dtypes": ("float32", "int32"),
            "shapes": shapes,
            "build": build,
        }
    ]


@functools.partial(jax.jit, static_argnames=("metric", "probe_metric", "k", "nprobe"))
def _ivf_search(q, cents, list_rows, list_mask, x, slot_ok, metric, probe_metric, k, nprobe):
    """q [Q, D] → (dists [Q, k], row slots [Q, k]); vmapped per query.
    `slot_ok` [cap] masks corpus slots (all-true without a prefilter): the
    columnar residual-WHERE mask ANDs in here, so top-k is computed among
    MATCHING rows only (the condition-checker semantics the exact
    strategies already had)."""
    import jax.numpy as jnp

    dc = D.pairwise_distance(q, cents, probe_metric)  # [Q, C]
    probes = jax.lax.top_k(-dc, nprobe)[1]  # [Q, nprobe]

    def one(qi, pr):
        rows = list_rows[pr].reshape(-1)  # [nprobe*L]
        rows_c = jnp.clip(rows, 0, x.shape[0] - 1)
        mask = list_mask[pr].reshape(-1) & slot_ok[rows_c]
        cand = x[rows_c]  # gather [nprobe*L, D]
        d = D.pairwise_distance(qi[None, :], cand, metric)[0]
        d = jnp.where(mask, d, jnp.inf)
        kk = min(k, int(rows.shape[0]))
        neg, idx = jax.lax.top_k(-d, kk)
        return -neg, jnp.where(neg > -jnp.inf, rows[idx], -1)

    return jax.vmap(one)(q, probes)
