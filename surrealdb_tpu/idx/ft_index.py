"""Persistent inverted index + device-batched BM25 search.

Role of the reference's FtIndex (reference: core/src/idx/ft/ — terms.rs
dictionary, postings.rs, doclength.rs, termdocs.rs, offsets.rs,
docids.rs). TPU-first redesign: the KV layout is flat ordered keys rather
than B-trees (the host store is already ordered), and scoring happens as one
batched BM25 kernel over the whole candidate set (ops/bm25.py) instead of a
per-document loop.

Keyspace (under the index's state prefix `+{ix}!m`):
    s                      stats {dc, tl, nt, nd}
    t{term}                term meta {id, df}
    p{tid}{did}            posting {tf, os: [[s,e],...]} (offsets if highlights)
    l{did}                 doc length
    d{rid}                 rid -> doc id
    r{did}                 doc id -> rid
    P{tid}{start}          packed posting chunk: did-offsets + tfs for one
                           bulk batch (u32 arrays; see pack_plist)
    L{start}               packed doc lengths for dids [start, start+n)
    R{start}               packed rid list for dids [start, start+n)

Bulk ingest writes ONE packed chunk per (term, batch) instead of one KV key
per (term, doc): 1M docs x 12 terms collapses from 12M posting keys to
(vocab x batches) chunk keys, which is what makes commit and the mirror
build vectorizable. The per-doc `p`/`l`/`r` keys remain as an OVERLAY for
single-document updates: an overlay entry overrides the packed chunks, and
a tf<=0 posting / length 0 / rid None is a tombstone. Search and the device
mirror merge base chunks + overlay.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import enc_str, enc_u64, dec_u64, enc_value_key, prefix_end
from surrealdb_tpu.sql.value import Thing, is_nullish
from surrealdb_tpu.utils.ser import pack, unpack

from .ft_analyzer import Analyzer, analyzer_for


def pack_posting(tf: int, offs=None) -> bytes:
    """Posting codec: without highlight offsets a posting is a bare 4-byte
    LE term frequency (the hot bulk-ingest write); with offsets it is the
    msgpack dict the highlighter consumes. Offset-less msgpack postings are
    never 4 bytes, so the decoder keys off length."""
    if offs is None:
        return struct.pack("<I", tf)
    return pack({"tf": tf, "os": offs})


def unpack_posting(raw: bytes) -> dict:
    if len(raw) == 4:
        return {"tf": struct.unpack("<I", raw)[0]}
    return unpack(raw)


# ------------------------------------------------------------ chunk codecs
def pack_plist(base: int, offs: np.ndarray, tfs: np.ndarray) -> bytes:
    """One term's postings for one bulk batch: did = base + offset."""
    return (
        struct.pack("<Iq", len(offs), base)
        + offs.astype("<u4", copy=False).tobytes()
        + tfs.astype("<u4", copy=False).tobytes()
    )


def unpack_plist(raw: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (dids int64 ascending, tfs float32)."""
    n, base = struct.unpack_from("<Iq", raw)
    offs = np.frombuffer(raw, dtype="<u4", count=n, offset=12)
    tfs = np.frombuffer(raw, dtype="<u4", count=n, offset=12 + 4 * n)
    return base + offs.astype(np.int64), tfs.astype(np.float32)


def pack_rids(rids: list) -> Any:
    """R-chunk payload: columnar {tb, packed int64 ids} when the batch is
    uniform int-id Things (the common bulk shape — decodes in O(1) instead
    of unpacking tens of thousands of Thing exts per chunk), else the
    generic rid list."""
    if rids and all(
        isinstance(r, Thing) and isinstance(r.id, int) and r.tb == rids[0].tb
        for r in rids
    ):
        try:
            ids = np.asarray([r.id for r in rids], dtype="<i8")
        except OverflowError:
            return list(rids)  # an id beyond int64: generic payload
        return {"t": rids[0].tb, "i": ids.tobytes()}
    return list(rids)


def rid_chunk_get(decoded, off: int) -> Optional[Thing]:
    """Index into a decoded R-chunk payload (columnar or list form)."""
    if isinstance(decoded, dict):
        ids = decoded["i"]
        if 0 <= off * 8 < len(ids):
            return Thing(decoded["t"], struct.unpack_from("<q", ids, off * 8)[0])
        return None
    return decoded[off] if 0 <= off < len(decoded) else None


def pack_lens(lens: np.ndarray) -> bytes:
    return struct.pack("<I", len(lens)) + lens.astype("<u4", copy=False).tobytes()


def unpack_lens(raw: bytes) -> np.ndarray:
    n = struct.unpack_from("<I", raw)[0]
    return np.frombuffer(raw, dtype="<u4", count=n, offset=4).astype(np.float32)


def _tf(tokens) -> Dict[str, Tuple[int, List[List[int]]]]:
    """Aggregate analyzed tokens into term -> (frequency, offsets)."""
    out: Dict[str, Tuple[int, List[List[int]]]] = {}
    for text, s, e in tokens:
        count, offs = out.get(text, (0, []))
        out[text] = (count + 1, offs + [[s, e]])
    return out


class FtIndex:
    def __init__(self, tb: str, ix: dict):
        self.tb = tb
        self.ix = ix
        self.name = ix["name"]
        self.highlights = bool(ix["index"].get("highlights"))
        self._pref: Optional[Tuple[Tuple[str, str], bytes]] = None

    @staticmethod
    def for_index(ctx, ix: dict) -> "FtIndex":
        return FtIndex(ix["table"], ix)

    def analyzer(self, ctx) -> Analyzer:
        return analyzer_for(ctx, self.ix["index"].get("analyzer"))

    # ------------------------------------------------------------ keys
    def _k(self, ctx, sub: bytes) -> bytes:
        ns, db = ctx.ns_db()
        if self._pref is None or self._pref[0] != (ns, db):
            self._pref = ((ns, db), keys.index_state_prefix(ns, db, self.tb, self.name))
        return self._pref[1] + sub

    def _stats(self, ctx) -> dict:
        raw = ctx.txn().get(self._k(ctx, b"s"))
        return unpack(raw) if raw else {"dc": 0, "tl": 0, "nt": 0, "nd": 0}

    def _put_stats(self, ctx, st: dict) -> None:
        ctx.txn().set(self._k(ctx, b"s"), pack(st))

    # ------------------------------------------------------------ doc ids
    def _doc_id(self, ctx, rid: Thing, st: dict, create: bool) -> Optional[int]:
        txn = ctx.txn()
        k = self._k(ctx, b"d" + enc_value_key(rid))
        raw = txn.get(k)
        if raw is not None:
            return unpack(raw)
        if not create:
            return None
        did = st["nd"]
        st["nd"] += 1
        txn.set(k, pack(did))
        txn.set(self._k(ctx, b"r" + enc_u64(did)), pack(rid))
        return did

    def _rid_resolver(self, ctx):
        """did -> rid resolver for one search: R chunk KEYS are read once
        (raw bytes, cheap), but a chunk's rid list is msgpack-decoded only
        when a candidate actually lands in it — searches resolve a handful
        of top candidates out of millions of mappings."""
        import bisect as _bisect

        txn = ctx.txn()
        pre = self._k(ctx, b"R")
        starts: List[int] = []
        raws: List[Any] = []  # raw bytes until first hit, then the list
        for chunk in txn.batch(pre, prefix_end(pre), 256):
            for k, v in chunk:
                start, _ = dec_u64(k, len(pre))
                starts.append(start)
                raws.append(v)
        rpre = self._k(ctx, b"r")

        def resolve(did: int) -> Optional[Thing]:
            raw = txn.get(rpre + enc_u64(did))
            if raw is not None:
                return unpack(raw)  # may be a None tombstone
            i = _bisect.bisect_right(starts, did) - 1
            if i >= 0:
                dec = raws[i]
                if isinstance(dec, bytes):
                    dec = raws[i] = unpack(dec)
                return rid_chunk_get(dec, did - starts[i])
            return None

        return resolve

    # -------------------------------------------------- chunk+overlay reads
    def _term_postings(self, ctx, tid: int) -> Tuple[np.ndarray, np.ndarray]:
        """One term's live postings: packed chunks merged with the per-doc
        overlay (overlay wins; tf<=0 entries are tombstones). Returns
        (dids int64 ascending, tfs float32)."""
        txn = ctx.txn()
        parts_d, parts_t = [], []
        pre = self._k(ctx, b"P" + enc_u64(tid))
        for chunk in txn.batch(pre, prefix_end(pre), 1024):
            for _k, v in chunk:
                d, t = unpack_plist(v)
                parts_d.append(d)
                parts_t.append(t)
        if parts_d:
            dids = np.concatenate(parts_d)
            tfs = np.concatenate(parts_t)
        else:
            dids = np.empty(0, np.int64)
            tfs = np.empty(0, np.float32)
        pre = self._k(ctx, b"p" + enc_u64(tid))
        ov: Dict[int, int] = {}
        for k, raw in txn.scan(pre, prefix_end(pre)):
            did, _ = dec_u64(k, len(pre))
            ov[did] = unpack_posting(raw)["tf"]
        if ov:
            ov_d = np.fromiter(ov.keys(), np.int64, count=len(ov))
            ov_t = np.fromiter(ov.values(), np.float32, count=len(ov))
            if dids.size:
                keep = ~np.isin(dids, ov_d)
                dids, tfs = dids[keep], tfs[keep]
            live = ov_t > 0
            dids = np.concatenate([dids, ov_d[live]])
            tfs = np.concatenate([tfs, ov_t[live]])
            order = np.argsort(dids, kind="stable")
            dids, tfs = dids[order], tfs[order]
        return dids, tfs

    def _cand_lens(self, ctx, cand: np.ndarray) -> np.ndarray:
        """Doc lengths for the (sorted) candidate dids: slice the covering
        packed L chunks, then per-did overlay point gets."""
        txn = ctx.txn()
        out = np.zeros(len(cand), dtype=np.float32)
        pre = self._k(ctx, b"L")
        for chunk in txn.batch(pre, prefix_end(pre), 1024):
            for k, v in chunk:
                start, _ = dec_u64(k, len(pre))
                lens = unpack_lens(v)
                lo = np.searchsorted(cand, start)
                hi = np.searchsorted(cand, start + len(lens))
                if lo < hi:
                    out[lo:hi] = lens[cand[lo:hi] - start]
        lpre = self._k(ctx, b"l")
        for i, did in enumerate(cand):
            raw = txn.get(lpre + enc_u64(int(did)))
            if raw is not None:
                out[i] = max(unpack(raw), 0)  # -1 tombstone scores as 0
        return out

    # ------------------------------------------------------------ terms
    def _term(self, ctx, term: str) -> Optional[dict]:
        raw = ctx.txn().get(self._k(ctx, b"t" + enc_str(term)))
        return unpack(raw) if raw else None

    def _put_term(self, ctx, term: str, meta: dict) -> None:
        ctx.txn().set(self._k(ctx, b"t" + enc_str(term)), pack(meta))

    # ------------------------------------------------------------ write side
    def index_document(self, ctx, rid: Thing, old_vals, new_vals) -> None:
        st = self._stats(ctx)
        txn = ctx.txn()
        az = self.analyzer(ctx)

        old_tokens = self._tokens_of(az, old_vals)
        new_tokens = self._tokens_of(az, new_vals)
        if old_tokens is None and new_tokens is None:
            return

        did = self._doc_id(ctx, rid, st, create=new_tokens is not None)
        if did is None:
            return

        # remove the old posting set: tombstones, not deletes — the old
        # postings may live inside packed bulk chunks the overlay overrides
        old_tf = _tf(old_tokens) if old_tokens is not None else None
        if old_tokens is not None:
            for term in old_tf:
                meta = self._term(ctx, term)
                if meta is None:
                    continue
                txn.set(
                    self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)),
                    pack_posting(0),
                )
                meta["df"] -= 1
                self._put_term(ctx, term, meta)
            lraw = txn.get(self._k(ctx, b"l" + enc_u64(did)))
            if lraw is not None:
                st["tl"] -= max(unpack(lraw), 0)
            else:
                st["tl"] -= int(self._chunk_len_of(ctx, did))
            # -1 = removal tombstone, distinct from a present zero-token doc
            txn.set(self._k(ctx, b"l" + enc_u64(did)), pack(-1))
            st["dc"] -= 1

        # write the new posting set
        tfs = _tf(new_tokens) if new_tokens is not None else None
        if new_tokens is not None:
            for term, (count, offs) in tfs.items():
                meta = self._term(ctx, term)
                if meta is None:
                    meta = {"id": st["nt"], "df": 0}
                    st["nt"] += 1
                meta["df"] += 1
                self._put_term(ctx, term, meta)
                txn.set(
                    self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)),
                    pack_posting(count, offs if self.highlights else None),
                )
            length = len(new_tokens)
            txn.set(self._k(ctx, b"l" + enc_u64(did)), pack(length))
            st["tl"] += length
            st["dc"] += 1
        else:
            # document no longer has the field: drop the id mapping
            # (rid map tombstone: the did may live in a packed R chunk)
            txn.delete(self._k(ctx, b"d" + enc_value_key(rid)))
            txn.set(self._k(ctx, b"r" + enc_u64(did)), pack(None))

        self._put_stats(ctx, st)
        # buffered mirror delta, applied on commit (idx/ft_mirror.py)
        ns, db = ctx.ns_db()
        txn.ft_delta(
            ns,
            db,
            self.tb,
            self.name,
            rid,
            did,
            {t: c for t, (c, _) in old_tf.items()} if old_tf is not None else None,
            {t: c for t, (c, _) in tfs.items()} if tfs is not None else None,
            len(new_tokens) if new_tokens is not None else 0,
        )

    def _chunk_len_of(self, ctx, did: int) -> float:
        """Doc length for a bulk-chunk-indexed doc (no per-doc l key):
        the covering L chunk is the last one with start <= did."""
        txn = ctx.txn()
        pre = self._k(ctx, b"L")
        last = None
        for k, v in txn.scan(pre, pre + enc_u64(did) + b"\xff"):
            last = (k, v)
        if last is None:
            return 0.0
        start, _ = dec_u64(last[0], len(pre))
        lens = unpack_lens(last[1])
        off = did - start
        return float(lens[off]) if 0 <= off < len(lens) else 0.0

    def index_documents_bulk(self, ctx, batch) -> None:
        """Index a batch of NEW documents (no prior posting sets — the bulk
        insert path verified the records did not exist). The offset-free
        path writes ONE packed chunk per touched term (plus one lengths +
        one rid chunk) instead of per-(term, doc) keys; highlight-enabled
        indexes need per-posting offsets and keep the per-doc layout."""
        if self.highlights:
            return self._bulk_with_offsets(ctx, batch)
        from collections import Counter

        st = self._stats(ctx)
        txn = ctx.txn()
        az = self.analyzer(ctx)
        ns, db = ctx.ns_db()
        base = self._k(ctx, b"")
        tset = txn.set

        start = st["nd"]
        term_offs: Dict[str, List[int]] = {}
        term_tfs: Dict[str, List[int]] = {}
        lens: List[int] = []
        rids: List[Thing] = []
        for rid, vals in batch:
            terms = self._terms_of_fast(az, vals)
            if terms is None:
                continue
            tf_counts = Counter(terms)
            # records on this path are verified-new (the bulk inserter
            # checked existence), so the id mapping cannot exist
            did = st["nd"]
            st["nd"] += 1
            tset(base + b"d" + enc_value_key(rid), pack(did))
            off = did - start
            for term, count in tf_counts.items():
                lo = term_offs.get(term)
                if lo is None:
                    lo = term_offs[term] = []
                    term_tfs[term] = []
                lo.append(off)
                term_tfs[term].append(count)
            lens.append(len(terms))
            rids.append(rid)

        if rids:
            delta_terms: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            for term, offs in term_offs.items():
                meta = self._term(ctx, term)
                if meta is None:
                    meta = {"id": st["nt"], "df": 0}
                    st["nt"] += 1
                meta["df"] += len(offs)
                self._put_term(ctx, term, meta)
                offs_a = np.asarray(offs, dtype=np.uint32)
                tfs_a = np.asarray(term_tfs[term], dtype=np.uint32)
                tset(
                    base + b"P" + enc_u64(meta["id"]) + enc_u64(start),
                    pack_plist(start, offs_a, tfs_a),
                )
                delta_terms[term] = (
                    start + offs_a.astype(np.int64),
                    tfs_a.astype(np.float32),
                )
            lens_a = np.asarray(lens, dtype=np.uint32)
            tset(base + b"L" + enc_u64(start), pack_lens(lens_a))
            tset(base + b"R" + enc_u64(start), pack(pack_rids(rids)))
            st["tl"] += int(lens_a.sum())
            st["dc"] += len(rids)
            txn.ft_bulk_delta(
                ns, db, self.tb, self.name,
                start, delta_terms, lens_a.astype(np.float32), rids,
            )
        self._put_stats(ctx, st)

    def _bulk_with_offsets(self, ctx, batch) -> None:
        """Per-doc bulk path for highlight indexes (postings carry offsets)."""
        st = self._stats(ctx)
        txn = ctx.txn()
        az = self.analyzer(ctx)
        ns, db = ctx.ns_db()
        term_cache: Dict[str, Optional[dict]] = {}
        tid_enc: Dict[str, bytes] = {}  # term -> enc_u64(term id), batch-local
        touched: set = set()
        base = self._k(ctx, b"")
        pbase = base + b"p"
        tset = txn.set
        ft_delta = txn.ft_delta

        for rid, vals in batch:
            tokens = self._tokens_of(az, vals)
            if tokens is None:
                continue
            tfs_full = _tf(tokens)
            tf_counts: Dict[str, int] = {t: c for t, (c, _) in tfs_full.items()}
            length = len(tokens)
            did = st["nd"]
            st["nd"] += 1
            did_enc = enc_u64(did)
            tset(base + b"d" + enc_value_key(rid), pack(did))
            tset(base + b"r" + did_enc, pack(rid))
            for term, count in tf_counts.items():
                meta = term_cache.get(term)
                if meta is None and term not in term_cache:
                    meta = self._term(ctx, term)
                    term_cache[term] = meta
                if meta is None:
                    meta = {"id": st["nt"], "df": 0}
                    st["nt"] += 1
                    term_cache[term] = meta
                meta["df"] += 1
                touched.add(term)
                te = tid_enc.get(term)
                if te is None:
                    te = tid_enc[term] = enc_u64(meta["id"])
                tset(pbase + te + did_enc, pack_posting(count, tfs_full[term][1]))
            tset(base + b"l" + did_enc, pack(length))
            st["tl"] += length
            st["dc"] += 1
            ft_delta(ns, db, self.tb, self.name, rid, did, None, dict(tf_counts), length)

        for term in touched:
            self._put_term(ctx, term, term_cache[term])
        self._put_stats(ctx, st)

    def _tokens_of(self, az: Analyzer, vals) -> Optional[list]:
        if vals is None:
            return None
        out = []
        found = False
        for v in vals:
            items = v if isinstance(v, list) else [v]
            for item in items:
                if isinstance(item, str):
                    found = True
                    out.extend(az.analyze(item))
        return out if found else None

    def _terms_of_fast(self, az: Analyzer, vals) -> Optional[list]:
        """Offset-free twin of _tokens_of (term strings only)."""
        if vals is None:
            return None
        out: List[str] = []
        found = False
        for v in vals:
            items = v if isinstance(v, list) else [v]
            for item in items:
                if isinstance(item, str):
                    found = True
                    out.extend(az.terms_fast(item))
        return out if found else None

    # ------------------------------------------------------------ search
    def search(self, ctx, query: str) -> "FtResults":
        """AND-match all analyzed query terms, score the candidate set with
        the batched BM25 kernel."""
        az = self.analyzer(ctx)
        terms = az.terms(query)
        txn = ctx.txn()
        st = self._stats(ctx)

        term_metas = []
        for t in dict.fromkeys(terms):
            m = self._term(ctx, t)
            if m is None or m["df"] <= 0:
                return FtResults(self, {}, terms)  # a missing term → no matches
            term_metas.append((t, m))
        if not term_metas:
            return FtResults(self, {}, terms)

        # postings per term (packed chunks + overlay), rarest first for
        # cheap sorted-array intersection
        term_metas.sort(key=lambda tm: tm[1]["df"])
        rows = [self._term_postings(ctx, meta["id"]) for _, meta in term_metas]
        cand = rows[0][0]
        tf_cols = [rows[0][1]]
        for r_dids, r_tfs in rows[1:]:
            if cand.size == 0 or r_dids.size == 0:
                return FtResults(self, {}, terms)
            pos = np.searchsorted(r_dids, cand)
            pos_c = np.clip(pos, 0, len(r_dids) - 1)
            mask = r_dids[pos_c] == cand
            cand = cand[mask]
            tf_cols = [c[mask] for c in tf_cols]
            tf_cols.append(r_tfs[pos_c[mask]])
        if cand.size == 0:
            return FtResults(self, {}, terms)

        dids = [int(d) for d in cand]
        tf_mat = np.stack(tf_cols, axis=1)
        df = np.asarray([m["df"] for _, m in term_metas], dtype=np.float32)
        lens = self._cand_lens(ctx, cand)

        k1 = float(self.ix["index"].get("k1", 1.2))
        b = float(self.ix["index"].get("b", 0.75))
        from surrealdb_tpu import cnf

        if cnf.TPU_DISABLE or len(dids) < cnf.TPU_FT_ONDEVICE_THRESHOLD:
            # tiny candidate sets score on host — a device dispatch (and
            # worse, a first-compile over a tunneled chip) costs far more
            from surrealdb_tpu.ops.bm25 import bm25_scores_host

            scores = bm25_scores_host(tf_mat, df, lens, st["dc"], st["tl"], k1, b)
        else:
            from surrealdb_tpu import compile_log
            from surrealdb_tpu.ops.bm25 import bm25_scores

            with compile_log.tracked(
                "bm25", (int(tf_mat.shape[0]), int(tf_mat.shape[1]))
            ):
                scores = np.asarray(
                    bm25_scores(
                        tf_mat, df, lens,
                        np.float32(st["dc"]), np.float32(st["tl"]), k1, b,
                    )
                )
        resolve = self._rid_resolver(ctx)
        by_rid: Dict[Tuple[str, str], Tuple[Thing, float]] = {}
        for did, s in zip(dids, scores):
            rid = resolve(did)
            if rid is not None:
                by_rid[(rid.tb, repr(rid.id))] = (rid, float(s))
        return FtResults(self, by_rid, terms)

    # ------------------------------------------------------------ highlight
    def offsets_for(self, ctx, rid: Thing, terms: List[str]) -> List[Tuple[int, int]]:
        if not self.highlights:
            return []
        txn = ctx.txn()
        raw = txn.get(self._k(ctx, b"d" + enc_value_key(rid)))
        if raw is None:
            return []
        did = unpack(raw)
        offs: List[Tuple[int, int]] = []
        for t in dict.fromkeys(terms):
            meta = self._term(ctx, t)
            if meta is None:
                continue
            p = txn.get(self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)))
            if p is not None:
                offs.extend((s, e) for s, e in unpack_posting(p).get("os", []))
        return sorted(set(offs))


class FtResults:
    """Matched doc set + scores for one MATCHES evaluation."""

    def __init__(self, index: FtIndex, by_rid: dict, terms: List[str]):
        self.index = index
        self.by_rid = by_rid  # (tb, repr(id)) -> (Thing, score)
        self.terms = terms

    def __iter__(self):
        return iter(self.by_rid.values())

    def contains(self, rid: Thing) -> bool:
        return (rid.tb, repr(rid.id)) in self.by_rid

    def score(self, rid: Thing) -> Optional[float]:
        v = self.by_rid.get((rid.tb, repr(rid.id)))
        return v[1] if v else None
