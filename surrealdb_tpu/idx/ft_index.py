"""Persistent inverted index + device-batched BM25 search.

Role of the reference's FtIndex (reference: core/src/idx/ft/ — terms.rs
dictionary, postings.rs, doclength.rs, termdocs.rs, offsets.rs,
docids.rs). TPU-first redesign: the KV layout is flat ordered keys rather
than B-trees (the host store is already ordered), and scoring happens as one
batched BM25 kernel over the whole candidate set (ops/bm25.py) instead of a
per-document loop.

Keyspace (under the index's state prefix `+{ix}!m`):
    s                      stats {dc, tl, nt, nd}
    t{term}                term meta {id, df}
    p{tid}{did}            posting {tf, os: [[s,e],...]} (offsets if highlights)
    l{did}                 doc length
    d{rid}                 rid -> doc id
    r{did}                 doc id -> rid
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import enc_str, enc_u64, dec_u64, enc_value_key, prefix_end
from surrealdb_tpu.sql.value import Thing, is_nullish
from surrealdb_tpu.utils.ser import pack, unpack

from .ft_analyzer import Analyzer, analyzer_for


def pack_posting(tf: int, offs=None) -> bytes:
    """Posting codec: without highlight offsets a posting is a bare 4-byte
    LE term frequency (the hot bulk-ingest write); with offsets it is the
    msgpack dict the highlighter consumes. Offset-less msgpack postings are
    never 4 bytes, so the decoder keys off length."""
    if offs is None:
        return struct.pack("<I", tf)
    return pack({"tf": tf, "os": offs})


def unpack_posting(raw: bytes) -> dict:
    if len(raw) == 4:
        return {"tf": struct.unpack("<I", raw)[0]}
    return unpack(raw)


def _tf(tokens) -> Dict[str, Tuple[int, List[List[int]]]]:
    """Aggregate analyzed tokens into term -> (frequency, offsets)."""
    out: Dict[str, Tuple[int, List[List[int]]]] = {}
    for text, s, e in tokens:
        count, offs = out.get(text, (0, []))
        out[text] = (count + 1, offs + [[s, e]])
    return out


class FtIndex:
    def __init__(self, tb: str, ix: dict):
        self.tb = tb
        self.ix = ix
        self.name = ix["name"]
        self.highlights = bool(ix["index"].get("highlights"))
        self._pref: Optional[Tuple[Tuple[str, str], bytes]] = None

    @staticmethod
    def for_index(ctx, ix: dict) -> "FtIndex":
        return FtIndex(ix["table"], ix)

    def analyzer(self, ctx) -> Analyzer:
        return analyzer_for(ctx, self.ix["index"].get("analyzer"))

    # ------------------------------------------------------------ keys
    def _k(self, ctx, sub: bytes) -> bytes:
        ns, db = ctx.ns_db()
        if self._pref is None or self._pref[0] != (ns, db):
            self._pref = ((ns, db), keys.index_state_prefix(ns, db, self.tb, self.name))
        return self._pref[1] + sub

    def _stats(self, ctx) -> dict:
        raw = ctx.txn().get(self._k(ctx, b"s"))
        return unpack(raw) if raw else {"dc": 0, "tl": 0, "nt": 0, "nd": 0}

    def _put_stats(self, ctx, st: dict) -> None:
        ctx.txn().set(self._k(ctx, b"s"), pack(st))

    # ------------------------------------------------------------ doc ids
    def _doc_id(self, ctx, rid: Thing, st: dict, create: bool) -> Optional[int]:
        txn = ctx.txn()
        k = self._k(ctx, b"d" + enc_value_key(rid))
        raw = txn.get(k)
        if raw is not None:
            return unpack(raw)
        if not create:
            return None
        did = st["nd"]
        st["nd"] += 1
        txn.set(k, pack(did))
        txn.set(self._k(ctx, b"r" + enc_u64(did)), pack(rid))
        return did

    def _rid_of(self, ctx, did: int) -> Optional[Thing]:
        raw = ctx.txn().get(self._k(ctx, b"r" + enc_u64(did)))
        return unpack(raw) if raw else None

    # ------------------------------------------------------------ terms
    def _term(self, ctx, term: str) -> Optional[dict]:
        raw = ctx.txn().get(self._k(ctx, b"t" + enc_str(term)))
        return unpack(raw) if raw else None

    def _put_term(self, ctx, term: str, meta: dict) -> None:
        ctx.txn().set(self._k(ctx, b"t" + enc_str(term)), pack(meta))

    # ------------------------------------------------------------ write side
    def index_document(self, ctx, rid: Thing, old_vals, new_vals) -> None:
        st = self._stats(ctx)
        txn = ctx.txn()
        az = self.analyzer(ctx)

        old_tokens = self._tokens_of(az, old_vals)
        new_tokens = self._tokens_of(az, new_vals)
        if old_tokens is None and new_tokens is None:
            return

        did = self._doc_id(ctx, rid, st, create=new_tokens is not None)
        if did is None:
            return

        # remove the old posting set
        old_tf = _tf(old_tokens) if old_tokens is not None else None
        if old_tokens is not None:
            for term in old_tf:
                meta = self._term(ctx, term)
                if meta is None:
                    continue
                txn.delete(self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)))
                meta["df"] -= 1
                self._put_term(ctx, term, meta)
            lraw = txn.get(self._k(ctx, b"l" + enc_u64(did)))
            if lraw is not None:
                st["tl"] -= unpack(lraw)
                txn.delete(self._k(ctx, b"l" + enc_u64(did)))
            st["dc"] -= 1

        # write the new posting set
        tfs = _tf(new_tokens) if new_tokens is not None else None
        if new_tokens is not None:
            for term, (count, offs) in tfs.items():
                meta = self._term(ctx, term)
                if meta is None:
                    meta = {"id": st["nt"], "df": 0}
                    st["nt"] += 1
                meta["df"] += 1
                self._put_term(ctx, term, meta)
                txn.set(
                    self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)),
                    pack_posting(count, offs if self.highlights else None),
                )
            length = len(new_tokens)
            txn.set(self._k(ctx, b"l" + enc_u64(did)), pack(length))
            st["tl"] += length
            st["dc"] += 1
        else:
            # document no longer has the field: drop the id mapping
            txn.delete(self._k(ctx, b"d" + enc_value_key(rid)))
            txn.delete(self._k(ctx, b"r" + enc_u64(did)))

        self._put_stats(ctx, st)
        # buffered mirror delta, applied on commit (idx/ft_mirror.py)
        ns, db = ctx.ns_db()
        txn.ft_delta(
            ns,
            db,
            self.tb,
            self.name,
            rid,
            {t: c for t, (c, _) in old_tf.items()} if old_tf is not None else None,
            {t: c for t, (c, _) in tfs.items()} if tfs is not None else None,
            len(new_tokens) if new_tokens is not None else 0,
        )

    def index_documents_bulk(self, ctx, batch) -> None:
        """Index a batch of NEW documents (no prior posting sets — the bulk
        insert path verified the records did not exist). Statistics and term
        metadata are merged in memory across the batch and written once per
        distinct term / once per batch, instead of the per-(term, doc)
        read-modify-write the single-document path pays."""
        from collections import Counter

        st = self._stats(ctx)
        txn = ctx.txn()
        az = self.analyzer(ctx)
        ns, db = ctx.ns_db()
        term_cache: Dict[str, Optional[dict]] = {}
        tid_enc: Dict[str, bytes] = {}  # term -> enc_u64(term id), batch-local
        touched: set = set()
        base = self._k(ctx, b"")
        pbase = base + b"p"
        hl = self.highlights
        tset = txn.set
        ft_delta = txn.ft_delta

        for rid, vals in batch:
            if hl:
                tokens = self._tokens_of(az, vals)
                if tokens is None:
                    continue
                tfs_full = _tf(tokens)
                tf_counts: Dict[str, int] = {t: c for t, (c, _) in tfs_full.items()}
                length = len(tokens)
            else:
                # offset-free fast path: bulk inserts never highlight, so
                # the analyzer can skip span tracking entirely
                terms = self._terms_of_fast(az, vals)
                if terms is None:
                    continue
                tfs_full = None
                tf_counts = Counter(terms)
                length = len(terms)
            # records on this path are verified-new (the bulk inserter checked
            # existence), so the doc-id mapping cannot exist: allocate blind
            did = st["nd"]
            st["nd"] += 1
            did_enc = enc_u64(did)
            tset(base + b"d" + enc_value_key(rid), pack(did))
            tset(base + b"r" + did_enc, pack(rid))
            for term, count in tf_counts.items():
                meta = term_cache.get(term)
                if meta is None and term not in term_cache:
                    meta = self._term(ctx, term)
                    term_cache[term] = meta
                if meta is None:
                    meta = {"id": st["nt"], "df": 0}
                    st["nt"] += 1
                    term_cache[term] = meta
                meta["df"] += 1
                touched.add(term)
                te = tid_enc.get(term)
                if te is None:
                    te = tid_enc[term] = enc_u64(meta["id"])
                tset(
                    pbase + te + did_enc,
                    pack_posting(count, tfs_full[term][1] if tfs_full else None),
                )
            tset(base + b"l" + did_enc, pack(length))
            st["tl"] += length
            st["dc"] += 1
            ft_delta(ns, db, self.tb, self.name, rid, None, dict(tf_counts), length)

        for term in touched:
            self._put_term(ctx, term, term_cache[term])
        self._put_stats(ctx, st)

    def _tokens_of(self, az: Analyzer, vals) -> Optional[list]:
        if vals is None:
            return None
        out = []
        found = False
        for v in vals:
            items = v if isinstance(v, list) else [v]
            for item in items:
                if isinstance(item, str):
                    found = True
                    out.extend(az.analyze(item))
        return out if found else None

    def _terms_of_fast(self, az: Analyzer, vals) -> Optional[list]:
        """Offset-free twin of _tokens_of (term strings only)."""
        if vals is None:
            return None
        out: List[str] = []
        found = False
        for v in vals:
            items = v if isinstance(v, list) else [v]
            for item in items:
                if isinstance(item, str):
                    found = True
                    out.extend(az.terms_fast(item))
        return out if found else None

    # ------------------------------------------------------------ search
    def search(self, ctx, query: str) -> "FtResults":
        """AND-match all analyzed query terms, score the candidate set with
        the batched BM25 kernel."""
        az = self.analyzer(ctx)
        terms = az.terms(query)
        txn = ctx.txn()
        st = self._stats(ctx)

        term_metas = []
        for t in dict.fromkeys(terms):
            m = self._term(ctx, t)
            if m is None or m["df"] <= 0:
                return FtResults(self, {}, terms)  # a missing term → no matches
            term_metas.append((t, m))
        if not term_metas:
            return FtResults(self, {}, terms)

        # postings scan per term, rarest first for cheap intersection
        term_metas.sort(key=lambda tm: tm[1]["df"])
        candidate: Optional[Dict[int, List[int]]] = None  # did -> [tf per term]
        for pos, (t, meta) in enumerate(term_metas):
            pre = self._k(ctx, b"p" + enc_u64(meta["id"]))
            found: Dict[int, dict] = {}
            for k, raw in txn.scan(pre, prefix_end(pre)):
                did, _ = dec_u64(k, len(pre))
                found[did] = unpack_posting(raw)
            if candidate is None:
                candidate = {did: [p["tf"]] for did, p in found.items()}
            else:
                nxt = {}
                for did, tfs in candidate.items():
                    if did in found:
                        nxt[did] = tfs + [found[did]["tf"]]
                candidate = nxt
            if not candidate:
                return FtResults(self, {}, terms)

        dids = list(candidate.keys())
        tf_mat = np.asarray([candidate[d] for d in dids], dtype=np.float32)
        df = np.asarray([m["df"] for _, m in term_metas], dtype=np.float32)
        lens = np.asarray(
            [
                unpack(txn.get(self._k(ctx, b"l" + enc_u64(d))) or pack(0))
                for d in dids
            ],
            dtype=np.float32,
        )

        k1 = float(self.ix["index"].get("k1", 1.2))
        b = float(self.ix["index"].get("b", 0.75))
        from surrealdb_tpu import cnf

        if cnf.TPU_DISABLE or len(dids) < cnf.TPU_FT_ONDEVICE_THRESHOLD:
            # tiny candidate sets score on host — a device dispatch (and
            # worse, a first-compile over a tunneled chip) costs far more
            from surrealdb_tpu.ops.bm25 import bm25_scores_host

            scores = bm25_scores_host(tf_mat, df, lens, st["dc"], st["tl"], k1, b)
        else:
            from surrealdb_tpu.ops.bm25 import bm25_scores

            scores = np.asarray(
                bm25_scores(
                    tf_mat, df, lens,
                    np.float32(st["dc"]), np.float32(st["tl"]), k1, b,
                )
            )
        by_rid: Dict[Tuple[str, str], Tuple[Thing, float]] = {}
        for did, s in zip(dids, scores):
            rid = self._rid_of(ctx, did)
            if rid is not None:
                by_rid[(rid.tb, repr(rid.id))] = (rid, float(s))
        return FtResults(self, by_rid, terms)

    # ------------------------------------------------------------ highlight
    def offsets_for(self, ctx, rid: Thing, terms: List[str]) -> List[Tuple[int, int]]:
        if not self.highlights:
            return []
        txn = ctx.txn()
        raw = txn.get(self._k(ctx, b"d" + enc_value_key(rid)))
        if raw is None:
            return []
        did = unpack(raw)
        offs: List[Tuple[int, int]] = []
        for t in dict.fromkeys(terms):
            meta = self._term(ctx, t)
            if meta is None:
                continue
            p = txn.get(self._k(ctx, b"p" + enc_u64(meta["id"]) + enc_u64(did)))
            if p is not None:
                offs.extend((s, e) for s, e in unpack_posting(p).get("os", []))
        return sorted(set(offs))


class FtResults:
    """Matched doc set + scores for one MATCHES evaluation."""

    def __init__(self, index: FtIndex, by_rid: dict, terms: List[str]):
        self.index = index
        self.by_rid = by_rid  # (tb, repr(id)) -> (Thing, score)
        self.terms = terms

    def __iter__(self):
        return iter(self.by_rid.values())

    def contains(self, rid: Thing) -> bool:
        return (rid.tb, repr(rid.id)) in self.by_rid

    def score(self, rid: Thing) -> Optional[float]:
        v = self.by_rid.get((rid.tb, repr(rid.id)))
        return v[1] if v else None
