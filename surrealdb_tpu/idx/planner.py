"""Query planner: choose index-backed iteration over table scans.

Role of the reference's QueryPlanner (reference: core/src/idx/planner/mod.rs:
93-232, plan.rs:27-93, tree.rs): analyze the WHERE/WITH clauses per table and
replace ITable sources with IIndex plans. Plan taxonomy mirrors the
reference: SingleIndex / SingleIndexRange / MultiIndex / TableIterator, plus
the kNN/MATCHES operator wiring.

v1 supports equality/range/kNN plans over 'idx', 'uniq', 'hnsw' and 'mtree'
indexes; unsupported shapes fall back to a table scan (always correct).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.ast import BinaryOp, Expr, KnnOp, Literal, MatchesOp, Param
from surrealdb_tpu.sql.path import Idiom
from surrealdb_tpu.sql.value import Range, Thing, is_nullish
from surrealdb_tpu.utils.ser import unpack

from .knn import KnnPlan
from .ft_search import MatchesPlan


def _rid_key(rid):
    """Dedup identity for record ids yielded by index scans."""
    return (rid.tb, repr(rid.id)) if isinstance(rid, Thing) else rid


# ------------------------------------------------------------------ plans
class OrderPushdownBailout(Exception):
    """Raised by IndexOrderPlan when it meets an array-valued entry: key
    order sorts a record at its smallest element while value_cmp sorts
    arrays after scalars, so the pushdown is unsound — the statement
    re-runs on the plain scan + post-sort path."""


class IndexEqualPlan:
    """WHERE field = value (or a compound-prefix of equalities) over an
    'idx'/'uniq' index (reference ThingIterator::IndexEqual/UniqueEqual).
    `values` may cover only a PREFIX of a compound index's fields — the
    lookup becomes a prefix scan."""

    def __init__(self, tb: str, ix: dict, values: List[Any]):
        self.tb = tb
        self.ix = ix
        self.values = values
        self.partial = len(values) < len(ix["fields"])

    def explain(self) -> dict:
        return {
            "index": self.ix["name"],
            "operator": "=",
            "value": self.values[0] if len(self.values) == 1 else self.values,
        }

    def iterate(self, ctx):
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        name = self.ix["name"]
        if self.ix["index"]["type"] == "uniq" and not self.partial:
            raw = txn.get(keys.unique_entry(ns, db, self.tb, name, self.values))
            if raw is not None:
                rid = unpack(raw)
                yield rid, None, None
            return
        # array-valued fields write one entry per element (_combinations),
        # so scans must dedup record ids or a row repeats in the output
        seen = set()
        if self.ix["index"]["type"] == "uniq":
            pre = keys.unique_entry_prefix(ns, db, self.tb, name, self.values)
            for chunk in txn.batch(pre, prefix_end(pre), 1000):
                for _, v in chunk:
                    rid = unpack(v)
                    k2 = _rid_key(rid)
                    if k2 in seen:
                        continue
                    seen.add(k2)
                    yield rid, None, None
            return
        pre = keys.index_entry_prefix(ns, db, self.tb, name, self.values)
        nvals = len(self.ix["fields"])  # keys hold ALL fields' values
        for chunk in txn.batch(pre, prefix_end(pre), 1000):
            for k, _ in chunk:
                _, rid = keys.decode_index_entry_id(k, ns, db, self.tb, name, nvals)
                k2 = _rid_key(rid)
                if k2 in seen:
                    continue
                seen.add(k2)
                yield rid, None, None


class IndexRangePlan:
    """WHERE field >/</BETWEEN over an ordered index
    (reference ThingIterator::IndexRange/UniqueRange)."""

    def __init__(self, tb: str, ix: dict, beg, end, beg_incl: bool, end_incl: bool):
        self.tb = tb
        self.ix = ix
        self.beg, self.end = beg, end
        self.beg_incl, self.end_incl = beg_incl, end_incl

    def explain(self) -> dict:
        rng: dict = {}
        if self.beg is not None:
            rng["from"] = {"inclusive": self.beg_incl, "value": self.beg}
        if self.end is not None:
            rng["to"] = {"inclusive": self.end_incl, "value": self.end}
        return {"index": self.ix["name"], "operator": "range", "range": rng}

    def iterate(self, ctx):
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        name = self.ix["name"]
        uniq = self.ix["index"]["type"] == "uniq"
        mk_pre = keys.unique_entry_prefix if uniq else keys.index_entry_prefix
        base = mk_pre(ns, db, self.tb, name)
        from surrealdb_tpu.key.encode import enc_value_key

        if self.beg is None:
            beg = base
        else:
            bk = base + enc_value_key(self.beg)
            beg = bk if self.beg_incl else prefix_end(bk)
        if self.end is None:
            end = prefix_end(base)
        else:
            ek = base + enc_value_key(self.end)
            end = prefix_end(ek) if self.end_incl else ek
        seen = set()  # array-valued fields write one entry per element
        for chunk in txn.batch(beg, end, 1000):
            for k, v in chunk:
                if uniq:
                    rid = unpack(v)
                else:
                    _, rid = keys.decode_index_entry_id(k, ns, db, self.tb, name, 1)
                k2 = _rid_key(rid)
                if k2 in seen:
                    continue
                seen.add(k2)
                yield rid, None, None


class MultiIndexPlan:
    """AND/OR condition trees over several index plans (reference
    Plan::MultiIndex + IndexUnion/IndexJoin thing iterators,
    plan.rs:27-93, iterators.rs:107-120).

    union:     every branch of an OR is indexable; stream each branch,
               dedup record ids (the reference's SyncDistinct role).
    intersect: several AND conjuncts hit different indexes; intersect the
               candidate id sets, smallest first. Residual conjuncts stay
               in the statement's WHERE, evaluated per record — plans only
               ever narrow the candidate set.
    """

    def __init__(self, tb: str, plans: List[Any], mode: str):
        self.tb = tb
        self.plans = plans
        self.mode = mode  # "union" | "intersect"

    def explain(self) -> dict:
        return {
            "type": "MultiIndex",
            "mode": self.mode,
            "parts": [p.explain() for p in self.plans],
        }

    def iterate(self, ctx):
        if self.mode == "union":
            seen = set()
            for p in self.plans:
                for rid, doc, ir in p.iterate(ctx):
                    k = _rid_key(rid)
                    if k in seen:
                        continue
                    seen.add(k)
                    yield rid, doc, ir
            return
        # intersect: materialize candidate id maps, smallest set drives
        maps = []
        for p in self.plans:
            m = {}
            for rid, _, _ in p.iterate(ctx):
                m[_rid_key(rid)] = rid
            maps.append(m)
        maps.sort(key=len)
        inter = set(maps[0])
        for m in maps[1:]:
            inter &= set(m)
        for k in inter:
            yield maps[0][k], None, None


class IndexOrderPlan:
    """ORDER BY field [ASC] served straight from an ordered index scan with
    the LIMIT pushed into the scan (reference: order/limit pushdown,
    planner/mod.rs + iterators.rs IndexRange). Only forward (ASC) order —
    the KV scans forward."""

    def __init__(self, tb: str, ix: dict, limit: Optional[int]):
        self.tb = tb
        self.ix = ix
        self.limit = limit
        self.provides_order = True

    def explain(self) -> dict:
        out = {"index": self.ix["name"], "operator": "order", "direction": "ASC"}
        if self.limit is not None:
            out["limit_pushdown"] = self.limit
        return out

    def iterate(self, ctx):
        from surrealdb_tpu.sql.path import get_path

        ns, db = ctx.ns_db()
        txn = ctx.txn()
        name = self.ix["name"]
        field_parts = self.ix["fields"][0].parts
        pre = keys.index_entry_prefix(ns, db, self.tb, name)
        n = 0
        seen = set()  # array-valued fields write one entry per element
        for chunk in txn.batch(pre, prefix_end(pre), 1000):
            for k, v in chunk:
                _, rid = keys.decode_index_entry_id(
                    k, ns, db, self.tb, name, len(self.ix["fields"])
                )
                k2 = _rid_key(rid)
                if k2 in seen:
                    continue
                # fetch the doc here (the SELECT needs it anyway) and check
                # the order field: an array value writes one entry per
                # element and key order would place the row at its smallest
                # element — unsound vs value_cmp, so abandon the pushdown
                doc = txn.get_record(ns, db, rid.tb, rid.id) if isinstance(rid, Thing) else None
                if doc is not None:
                    with ctx.with_doc_value(doc, rid=rid) as c:
                        if isinstance(get_path(c, doc, field_parts), list):
                            raise OrderPushdownBailout()
                seen.add(k2)
                yield rid, doc, None
                n += 1
                if self.limit is not None and n >= self.limit:
                    return


class TableScanPlan:
    def __init__(self, tb: str):
        self.tb = tb

    def explain(self) -> dict:
        return {"table": self.tb}


# ------------------------------------------------------------------ analysis
def plan_sources(ctx, stm, sources: List[Any]) -> List[Any]:
    """Rewrite ITable sources into IIndex plans where the WHERE/kNN shape
    allows (reference QueryPlanner::add_iterables)."""
    from surrealdb_tpu.dbs.iterator import IIndex, ITable

    with_ = getattr(stm, "with_", None)
    if with_ is not None and with_.noindex:
        return sources

    from surrealdb_tpu import telemetry

    out: List[Any] = []
    import time as _time

    t0 = _time.perf_counter()
    with telemetry.span("plan"):
        for s in sources:
            if not isinstance(s, ITable):
                out.append(s)
                continue
            plan = build_plan(ctx, stm, s.tb, with_)
            if plan is None:
                telemetry.inc("plan_strategy", strategy="TableScan")
                out.append(s)
            else:
                strategy = type(plan).__name__
                telemetry.inc("plan_strategy", strategy=strategy)
                note = {"table": s.tb, "plan": strategy}
                if strategy == "ColumnScanPlan":
                    # a slow columnar statement must name what was lowered
                    if plan.compiled is not None:
                        note["predicate"] = plan.compiled.source
                    if plan.order_specs:
                        note["order"] = [
                            {"key": s.path, "direction": "ASC" if s.asc else "DESC"}
                            for s in plan.order_specs
                        ]
                if isinstance(plan, KnnPlan):
                    # a kNN statement's latency is governed by the dispatch
                    # pipeline: pin the active knobs into the plan note so a
                    # slow-query record names the width/depth it ran under
                    from surrealdb_tpu import cnf as _cnf

                    note["dispatch"] = {
                        "max_width": _cnf.DISPATCH_MAX_WIDTH,
                        "pipeline_depth": _cnf.DISPATCH_PIPELINE_DEPTH,
                        "split_floor": _cnf.DISPATCH_SPLIT_FLOOR,
                    }
                telemetry.note_plan(note)
                out.append(IIndex(s.tb, plan))
    # plan-cache pre-kernel accounting: planner time per fingerprint,
    # warm (template served from cache) vs cold
    from surrealdb_tpu.dbs.plan_cache import active_plan_cache

    pc = active_plan_cache(ctx)
    if pc is not None:
        from surrealdb_tpu import stats as _stats

        pc.note_plan_time(
            _stats.active_fingerprint(),
            (_time.perf_counter() - t0) * 1e6,
            bool(getattr(getattr(ctx, "executor", None), "cache_warm", False)),
        )
    return out


def build_plan(ctx, stm, tb: str, with_) -> Optional[Any]:
    plan = _build_index_plan(ctx, stm, tb, with_)
    if plan is not None:
        return plan
    # no servable index shape: a simple WHERE can still leave the per-row
    # path for the vectorized columnar scan (idx/column_mirror.py)
    from surrealdb_tpu.idx.column_mirror import column_scan_plan

    return column_scan_plan(ctx, stm, tb)


def _build_index_plan(ctx, stm, tb: str, with_) -> Optional[Any]:
    ns, db = ctx.ns_db()
    # plan-cache schema prefetch: the raw index-def probe for this table
    # is generation-stamped, so hot statements skip the per-execution KV
    # scan (DDL and the builder's ready flip bump the generation)
    from surrealdb_tpu.dbs.plan_cache import active_plan_cache

    pc = active_plan_cache(ctx)
    indexes = pc.index_defs_for(ctx, ns, db, tb) if pc is not None else None
    if indexes is None:
        txn = ctx.txn()
        indexes = txn.all_tb_indexes(ns, db, tb)
        if pc is not None:
            pc.install_index_defs(ctx, ns, db, tb, indexes)
    # an index mid-build (CONCURRENTLY) must not serve reads yet
    indexes = [ix for ix in indexes if ix.get("status", "ready") == "ready"]
    if not indexes:
        return None
    if with_ is not None and with_.indexes:
        indexes = [ix for ix in indexes if ix["name"] in with_.indexes]

    cond = getattr(stm, "cond", None)

    # kNN / MATCHES operators take priority (reference executor entries)
    knn = _find_operator(cond, KnnOp)
    if knn is not None:
        plan = _plan_knn(ctx, tb, indexes, knn)
        if plan is not None:
            if isinstance(plan, KnnPlan):
                _attach_knn_prefilter(ctx, plan, cond, knn)
            return plan
    matches = _find_operator(cond, MatchesOp)
    if matches is not None:
        plan = _plan_matches(ctx, tb, indexes, matches, stm)
        if plan is not None:
            return plan

    if cond is not None:
        return _plan_condition(ctx, tb, indexes, cond)

    # no WHERE: ORDER BY field ASC [LIMIT n] can ride an ordered index scan.
    # Not under GROUP/SPLIT (rows feed an aggregator, truncation would be
    # wrong), and only over plain 'idx' (uniq indexes are sparse: records
    # with a NONE field have no entry and would vanish from the result).
    order = getattr(stm, "order", None)
    if (
        order
        and len(order) == 1
        and order[0].asc
        and not getattr(order[0], "rand", False)
        and not getattr(stm, "group", None)
        and not getattr(stm, "group_all", False)
        and not getattr(stm, "split", None)
    ):
        field_txt = repr(order[0].idiom)
        for ix in indexes:
            if ix["index"]["type"] != "idx":
                continue
            if repr(ix["fields"][0]) != field_txt:
                continue
            from surrealdb_tpu.iam.check import perms_apply

            # per-record permission filtering drops rows AFTER the plan, so
            # a plan-level limit would under-fill the result for guests /
            # record-access sessions — they keep the full ordered scan
            limit = None if perms_apply(ctx) else _static_limit(ctx, stm)
            return IndexOrderPlan(tb, ix, limit)
    return None


def _static_limit(ctx, stm) -> Optional[int]:
    try:
        limit = int(stm.limit.compute(ctx)) if stm.limit is not None else None
        start = int(stm.start.compute(ctx)) if stm.start is not None else 0
    except (TypeError, ValueError):
        return None
    return (limit + start) if limit is not None else None


def _attach_knn_prefilter(ctx, plan, cond, knn) -> None:
    """Lower the WHERE conjuncts AROUND the kNN operator onto the table's
    column mirror: the exact search strategies then mask non-matching rows
    out BEFORE top-k (the reference's condition-checker semantics — k
    results that all match — instead of post-filtering the top-k down)."""
    from surrealdb_tpu import cnf as _cnf

    if not (_cnf.KNN_COLUMN_PREFILTER and _cnf.COLUMN_MIRROR):
        return
    residual = _strip_operator(cond, knn)
    if residual is None:
        return
    from surrealdb_tpu.iam.check import perms_apply

    if perms_apply(ctx):
        return
    from surrealdb_tpu.ops.predicates import compile_where

    plan.prefilter = compile_where(ctx, residual)


def _strip_operator(expr, op_node):
    """The condition tree minus one operator reachable through ANDs."""
    if expr is op_node:
        return None
    if isinstance(expr, BinaryOp) and expr.op in ("&&", "AND"):
        l = _strip_operator(expr.l, op_node)
        r = _strip_operator(expr.r, op_node)
        if l is None:
            return r
        if r is None:
            return l
        return BinaryOp(expr.op, l, r)
    return expr


def _find_operator(expr, klass):
    """Locate a kNN/MATCHES operator reachable through ANDs."""
    if expr is None:
        return None
    if isinstance(expr, klass):
        return expr
    if isinstance(expr, BinaryOp) and expr.op in ("&&", "AND"):
        return _find_operator(expr.l, klass) or _find_operator(expr.r, klass)
    return None


def _plan_knn(ctx, tb: str, indexes: List[dict], knn: KnnOp):
    if not isinstance(knn.l, Idiom):
        return None
    field_txt = repr(knn.l)
    target = knn.r.compute(ctx)
    for ix in indexes:
        if ix["index"]["type"] not in ("hnsw", "mtree"):
            continue
        if not ix["fields"] or repr(ix["fields"][0]) != field_txt:
            continue
        return KnnPlan(tb, ix, knn, target)
    # no vector index: brute-force kNN plan over the table
    from .knn import BruteForceKnnPlan

    return BruteForceKnnPlan(tb, knn, target)


def _plan_matches(ctx, tb: str, indexes: List[dict], m: MatchesOp, stm):
    if not isinstance(m.l, Idiom):
        return None
    field_txt = repr(m.l)
    for ix in indexes:
        if ix["index"]["type"] != "search":
            continue
        if not ix["fields"] or repr(ix["fields"][0]) != field_txt:
            continue
        plan = MatchesPlan(tb, ix, m, m.r.compute(ctx))
        plan.provides_order = _matches_score_order(stm, m)
        return plan
    return None


def _matches_score_order(stm, m: MatchesOp) -> bool:
    """ORDER BY <search score> DESC — directly or through a projection
    alias — ranks rows exactly how the MATCHES iterator already yields
    them (BM25 descending), so the post-sort can be skipped and LIMIT can
    stop the scan early (the reference's top-k search shortcut;
    planner/executor.rs score-ordered iteration)."""
    order = getattr(stm, "order", None)
    if not order or len(order) != 1:
        return False
    o = order[0]
    if o.asc or getattr(o, "rand", False):
        return False
    if stm.group or getattr(stm, "group_all", False) or stm.split:
        return False
    target = repr(o.idiom)
    expr = None
    for f in getattr(stm, "fields", None) or []:
        if getattr(f, "all", False) or f.expr is None:
            continue
        name = repr(f.alias) if f.alias is not None else repr(f.expr)
        if name == target:
            expr = f.expr
            break
    if expr is None:
        return False
    from surrealdb_tpu.sql.ast import FunctionCall

    return (
        isinstance(expr, FunctionCall)
        and expr.name == "search::score"
        and len(expr.args) == 1
        and repr(expr.args[0]) == repr(m.ref)
    )


def _plan_condition(ctx, tb: str, indexes: List[dict], cond):
    """Decompose the WHERE condition tree into per-index candidate plans
    (reference planner/tree.rs analysis + plan.rs PlanBuilder). Residual
    conjuncts are fine: the iterator re-evaluates the full WHERE per
    record, so a plan only has to produce a candidate SUPERSET of one
    AND-branch… (for OR, every branch must be indexable)."""
    usable = [ix for ix in indexes if ix["index"]["type"] in ("idx", "uniq")]
    if not usable:
        return None

    if isinstance(cond, BinaryOp) and cond.op in ("||", "OR"):
        branches = _or_branches(ctx, cond)
        if branches is None:
            return None
        plans = []
        for leaves in branches:
            p = _plan_and(ctx, tb, usable, leaves)
            if p is None:
                return None  # one unindexable OR-branch forces a scan
            plans.append(p)
        if len(plans) == 1:
            return plans[0]
        return MultiIndexPlan(tb, plans, "union")

    leaves, _residual = _and_leaves(ctx, cond)
    return _plan_and(ctx, tb, usable, leaves)


def _plan_and(ctx, tb: str, usable: List[dict], leaves):
    """Best plan for one AND-branch's leaves: compound-prefix equality
    first, then single-field plans; ≥2 distinct index hits → intersect."""
    if not leaves:
        return None
    eq_by_field = {f: v for f, op, v in leaves if op == "="}
    plans: List[Any] = []
    covered: set = set()

    # compound indexes: longest equality prefix wins
    best = None
    for ix in usable:
        fields = [repr(f) for f in ix["fields"]]
        if len(fields) < 2:
            continue
        n = 0
        for f in fields:
            if f in eq_by_field:
                n += 1
            else:
                break
        if n >= 2 and (best is None or n > best[1]):
            best = (ix, n)
    if best is not None:
        ix, n = best
        fields = [repr(f) for f in ix["fields"]][:n]
        plans.append(IndexEqualPlan(tb, ix, [eq_by_field[f] for f in fields]))
        covered.update(fields)

    single = {
        repr(ix["fields"][0]): ix for ix in usable if len(ix["fields"]) == 1
    }
    for f, op, v in leaves:
        if f in covered:
            continue
        ix = single.get(f)
        if ix is None:
            continue
        p = _leaf_plan(tb, ix, op, v)
        if p is not None:
            plans.append(p)
            covered.add(f)

    if not plans:
        # last resort: a compound index whose FIRST field has an equality
        # serves as a 1-value prefix scan
        for ix in usable:
            if len(ix["fields"]) >= 2 and repr(ix["fields"][0]) in eq_by_field:
                return IndexEqualPlan(tb, ix, [eq_by_field[repr(ix["fields"][0])]])
        return None
    if len(plans) == 1:
        return plans[0]
    return MultiIndexPlan(tb, plans, "intersect")


def _leaf_plan(tb: str, ix: dict, op: str, value):
    if op == "=":
        return IndexEqualPlan(tb, ix, [value])
    if op == "<":
        return IndexRangePlan(tb, ix, None, value, True, False)
    if op == "<=":
        return IndexRangePlan(tb, ix, None, value, True, True)
    if op == ">":
        return IndexRangePlan(tb, ix, value, None, False, False)
    if op == ">=":
        return IndexRangePlan(tb, ix, value, None, True, False)
    return None


def _and_leaves(ctx, cond) -> Tuple[List[Tuple[str, str, Any]], bool]:
    """Flatten an AND chain into (leaves, residual?) — residual marks
    subtrees that couldn't be expressed as `field op constant`."""
    if isinstance(cond, BinaryOp) and cond.op in ("&&", "AND"):
        l, lr = _and_leaves(ctx, cond.l)
        r, rr = _and_leaves(ctx, cond.r)
        return l + r, lr or rr
    leaf = _extract_leaf(ctx, cond)
    return ([leaf], False) if leaf is not None else ([], True)


def _or_branches(ctx, cond) -> Optional[List[List[Tuple[str, str, Any]]]]:
    """Flatten an OR chain into per-branch AND-leaf lists; None when any
    branch contains a residual (the whole OR then needs a scan)."""
    if isinstance(cond, BinaryOp) and cond.op in ("||", "OR"):
        l = _or_branches(ctx, cond.l)
        r = _or_branches(ctx, cond.r)
        if l is None or r is None:
            return None
        return l + r
    leaves, _residual = _and_leaves(ctx, cond)
    # a residual conjunct inside a branch is fine (the iterator re-checks
    # the full WHERE); only a branch with NO indexable leaf forces a scan
    if not leaves:
        return None
    return [leaves]


def _extract_leaf(ctx, cond) -> Optional[Tuple[str, str, Any]]:
    """One `field op constant` comparison (either side)."""
    if not isinstance(cond, BinaryOp):
        return None
    op = cond.op
    if op not in ("=", "<", "<=", ">", ">="):
        return None
    l, r = cond.l, cond.r
    if isinstance(l, Idiom) and _is_const(r):
        leaf = repr(l), op, r.compute(ctx)
    elif isinstance(r, Idiom) and _is_const(l):
        flip = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}
        leaf = repr(r), flip[op], l.compute(ctx)
    else:
        return None
    # array/object constants are not servable from per-element index
    # entries (an equality on a whole array would match nothing — a
    # candidate SUBSET, which plans must never produce)
    if isinstance(leaf[2], (list, dict)):
        return None
    return leaf


def _is_const(e) -> bool:
    return isinstance(e, (Literal, Param))


# ------------------------------------------------------------------ explain
def explain(ctx, stm, sources: List[Any], full: bool = False) -> List[dict]:
    """EXPLAIN output (reference: core/src/dbs/plan.rs)."""
    from surrealdb_tpu.dbs.iterator import (
        IIndex,
        IRange,
        ITable,
        IThing,
        IValue,
    )

    planned = plan_sources(ctx, stm, sources)
    out: List[dict] = []
    for s in planned:
        if isinstance(s, IIndex):
            out.append({"detail": {"plan": s.plan.explain(), "table": s.tb}, "operation": "Iterate Index"})
        elif isinstance(s, ITable):
            out.append({"detail": {"table": s.tb}, "operation": "Iterate Table"})
        elif isinstance(s, IRange):
            out.append({"detail": {"table": s.tb}, "operation": "Iterate Range"})
        elif isinstance(s, IThing):
            out.append({"detail": {"thing": s.t}, "operation": "Iterate Thing"})
        elif isinstance(s, IValue):
            out.append({"detail": {"value": s.v}, "operation": "Iterate Value"})
    if getattr(stm, "parallel", False) and len(planned) > 1:
        from surrealdb_tpu import cnf as _cnf

        out.append(
            {
                "detail": {"workers": min(len(planned), _cnf.MAX_CONCURRENT_TASKS)},
                "operation": "Parallel",
            }
        )
    if full:
        out.append({"detail": {"type": "Memory"}, "operation": "Collector"})
    return out
