"""Query planner: choose index-backed iteration over table scans.

Role of the reference's QueryPlanner (reference: core/src/idx/planner/mod.rs:
93-232, plan.rs:27-93, tree.rs): analyze the WHERE/WITH clauses per table and
replace ITable sources with IIndex plans. Plan taxonomy mirrors the
reference: SingleIndex / SingleIndexRange / MultiIndex / TableIterator, plus
the kNN/MATCHES operator wiring.

v1 supports equality/range/kNN plans over 'idx', 'uniq', 'hnsw' and 'mtree'
indexes; unsupported shapes fall back to a table scan (always correct).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.ast import BinaryOp, Expr, KnnOp, Literal, MatchesOp, Param
from surrealdb_tpu.sql.path import Idiom
from surrealdb_tpu.sql.value import Range, Thing, is_nullish
from surrealdb_tpu.utils.ser import unpack

from .knn import KnnPlan
from .ft_search import MatchesPlan


# ------------------------------------------------------------------ plans
class IndexEqualPlan:
    """WHERE field = value over an 'idx'/'uniq' index
    (reference ThingIterator::IndexEqual/UniqueEqual)."""

    def __init__(self, tb: str, ix: dict, values: List[Any]):
        self.tb = tb
        self.ix = ix
        self.values = values

    def explain(self) -> dict:
        return {
            "index": self.ix["name"],
            "operator": "=",
            "value": self.values[0] if len(self.values) == 1 else self.values,
        }

    def iterate(self, ctx):
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        name = self.ix["name"]
        if self.ix["index"]["type"] == "uniq":
            raw = txn.get(keys.unique_entry(ns, db, self.tb, name, self.values))
            if raw is not None:
                rid = unpack(raw)
                yield rid, None, None
            return
        pre = keys.index_entry_prefix(ns, db, self.tb, name, self.values)
        for chunk in txn.batch(pre, prefix_end(pre), 1000):
            for k, _ in chunk:
                _, rid = keys.decode_index_entry_id(
                    k, ns, db, self.tb, name, len(self.values)
                )
                yield rid, None, None


class IndexRangePlan:
    """WHERE field >/</BETWEEN over an ordered index
    (reference ThingIterator::IndexRange/UniqueRange)."""

    def __init__(self, tb: str, ix: dict, beg, end, beg_incl: bool, end_incl: bool):
        self.tb = tb
        self.ix = ix
        self.beg, self.end = beg, end
        self.beg_incl, self.end_incl = beg_incl, end_incl

    def explain(self) -> dict:
        rng: dict = {}
        if self.beg is not None:
            rng["from"] = {"inclusive": self.beg_incl, "value": self.beg}
        if self.end is not None:
            rng["to"] = {"inclusive": self.end_incl, "value": self.end}
        return {"index": self.ix["name"], "operator": "range", "range": rng}

    def iterate(self, ctx):
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        name = self.ix["name"]
        uniq = self.ix["index"]["type"] == "uniq"
        mk_pre = keys.unique_entry_prefix if uniq else keys.index_entry_prefix
        base = mk_pre(ns, db, self.tb, name)
        from surrealdb_tpu.key.encode import enc_value_key

        if self.beg is None:
            beg = base
        else:
            bk = base + enc_value_key(self.beg)
            beg = bk if self.beg_incl else prefix_end(bk)
        if self.end is None:
            end = prefix_end(base)
        else:
            ek = base + enc_value_key(self.end)
            end = prefix_end(ek) if self.end_incl else ek
        for chunk in txn.batch(beg, end, 1000):
            for k, v in chunk:
                if uniq:
                    rid = unpack(v)
                else:
                    _, rid = keys.decode_index_entry_id(k, ns, db, self.tb, name, 1)
                yield rid, None, None


class TableScanPlan:
    def __init__(self, tb: str):
        self.tb = tb

    def explain(self) -> dict:
        return {"table": self.tb}


# ------------------------------------------------------------------ analysis
def plan_sources(ctx, stm, sources: List[Any]) -> List[Any]:
    """Rewrite ITable sources into IIndex plans where the WHERE/kNN shape
    allows (reference QueryPlanner::add_iterables)."""
    from surrealdb_tpu.dbs.iterator import IIndex, ITable

    with_ = getattr(stm, "with_", None)
    if with_ is not None and with_.noindex:
        return sources

    out: List[Any] = []
    for s in sources:
        if not isinstance(s, ITable):
            out.append(s)
            continue
        plan = build_plan(ctx, stm, s.tb, with_)
        if plan is None:
            out.append(s)
        else:
            out.append(IIndex(s.tb, plan))
    return out


def build_plan(ctx, stm, tb: str, with_) -> Optional[Any]:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    indexes = txn.all_tb_indexes(ns, db, tb)
    # an index mid-build (CONCURRENTLY) must not serve reads yet
    indexes = [ix for ix in indexes if ix.get("status", "ready") == "ready"]
    if not indexes:
        return None
    if with_ is not None and with_.indexes:
        indexes = [ix for ix in indexes if ix["name"] in with_.indexes]

    cond = getattr(stm, "cond", None)

    # kNN / MATCHES operators take priority (reference executor entries)
    knn = _find_operator(cond, KnnOp)
    if knn is not None:
        plan = _plan_knn(ctx, tb, indexes, knn)
        if plan is not None:
            return plan
    matches = _find_operator(cond, MatchesOp)
    if matches is not None:
        plan = _plan_matches(ctx, tb, indexes, matches, stm)
        if plan is not None:
            return plan

    if cond is None:
        return None
    return _plan_condition(ctx, tb, indexes, cond)


def _find_operator(expr, klass):
    """Locate a kNN/MATCHES operator reachable through ANDs."""
    if expr is None:
        return None
    if isinstance(expr, klass):
        return expr
    if isinstance(expr, BinaryOp) and expr.op in ("&&", "AND"):
        return _find_operator(expr.l, klass) or _find_operator(expr.r, klass)
    return None


def _plan_knn(ctx, tb: str, indexes: List[dict], knn: KnnOp):
    if not isinstance(knn.l, Idiom):
        return None
    field_txt = repr(knn.l)
    target = knn.r.compute(ctx)
    for ix in indexes:
        if ix["index"]["type"] not in ("hnsw", "mtree"):
            continue
        if not ix["fields"] or repr(ix["fields"][0]) != field_txt:
            continue
        return KnnPlan(tb, ix, knn, target)
    # no vector index: brute-force kNN plan over the table
    from .knn import BruteForceKnnPlan

    return BruteForceKnnPlan(tb, knn, target)


def _plan_matches(ctx, tb: str, indexes: List[dict], m: MatchesOp, stm):
    if not isinstance(m.l, Idiom):
        return None
    field_txt = repr(m.l)
    for ix in indexes:
        if ix["index"]["type"] != "search":
            continue
        if not ix["fields"] or repr(ix["fields"][0]) != field_txt:
            continue
        return MatchesPlan(tb, ix, m, m.r.compute(ctx))
    return None


def _plan_condition(ctx, tb: str, indexes: List[dict], cond):
    """Match simple `field op literal` shapes against single-column indexes."""
    shape = _extract_shape(ctx, cond)
    if shape is None:
        return None
    field_txt, op, value = shape
    for ix in indexes:
        if ix["index"]["type"] not in ("idx", "uniq"):
            continue
        if len(ix["fields"]) != 1 or repr(ix["fields"][0]) != field_txt:
            continue
        if op == "=":
            return IndexEqualPlan(tb, ix, [value])
        if op == "<":
            return IndexRangePlan(tb, ix, None, value, True, False)
        if op == "<=":
            return IndexRangePlan(tb, ix, None, value, True, True)
        if op == ">":
            return IndexRangePlan(tb, ix, value, None, False, False)
        if op == ">=":
            return IndexRangePlan(tb, ix, value, None, True, False)
    return None


def _extract_shape(ctx, cond) -> Optional[Tuple[str, str, Any]]:
    """`field op constant` (either side) where the WHERE clause is exactly
    one comparison. Broader trees fall back to scans in v1."""
    if not isinstance(cond, BinaryOp):
        return None
    op = cond.op
    if op not in ("=", "<", "<=", ">", ">="):
        return None
    l, r = cond.l, cond.r
    if isinstance(l, Idiom) and _is_const(r):
        return repr(l), op, r.compute(ctx)
    if isinstance(r, Idiom) and _is_const(l):
        flip = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return repr(r), flip[op], l.compute(ctx)
    return None


def _is_const(e) -> bool:
    return isinstance(e, (Literal, Param))


# ------------------------------------------------------------------ explain
def explain(ctx, stm, sources: List[Any], full: bool = False) -> List[dict]:
    """EXPLAIN output (reference: core/src/dbs/plan.rs)."""
    from surrealdb_tpu.dbs.iterator import (
        IIndex,
        IRange,
        ITable,
        IThing,
        IValue,
    )

    planned = plan_sources(ctx, stm, sources)
    out: List[dict] = []
    for s in planned:
        if isinstance(s, IIndex):
            out.append({"detail": {"plan": s.plan.explain(), "table": s.tb}, "operation": "Iterate Index"})
        elif isinstance(s, ITable):
            out.append({"detail": {"table": s.tb}, "operation": "Iterate Table"})
        elif isinstance(s, IRange):
            out.append({"detail": {"table": s.tb}, "operation": "Iterate Range"})
        elif isinstance(s, IThing):
            out.append({"detail": {"thing": s.t}, "operation": "Iterate Thing"})
        elif isinstance(s, IValue):
            out.append({"detail": {"value": s.v}, "operation": "Iterate Value"})
    if getattr(stm, "parallel", False) and len(planned) > 1:
        from surrealdb_tpu import cnf as _cnf

        out.append(
            {
                "detail": {"workers": min(len(planned), _cnf.MAX_CONCURRENT_TASKS)},
                "operation": "Parallel",
            }
        )
    if full:
        out.append({"detail": {"type": "Memory"}, "operation": "Collector"})
    return out
