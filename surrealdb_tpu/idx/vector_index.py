"""Vector index write path (MTREE / HNSW definitions).

Role of the reference's MTreeIndex/HnswIndex index_document (reference:
core/src/idx/trees/mtree.rs:85, trees/hnsw/index.rs:89). TPU-first design:
vectors are persisted row-wise in the KV under the index's state keyspace,
and the device-resident mirror (a padded [N, D] matrix used by the batched
distance/top-k kernels in idx/knn.py) refreshes by generation, mirroring the
reference's TreeCache generation swap (trees/store/cache.rs).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import TypeError_
from surrealdb_tpu.key.encode import enc_value_key, dec_value_key, prefix_end
from surrealdb_tpu.sql.value import Thing, is_nullish
from surrealdb_tpu.utils.ser import pack, unpack

_ROW = b"v"  # per-record vector row


def pack_vector(vec) -> bytes:
    """Row storage codec: packed little-endian float32 (the dtype the device
    mirror holds anyway) — ~40% of the msgpack float-list size at 768-d."""
    return pack({"$f32": np.asarray(vec, dtype="<f4").tobytes()})


def unpack_vector(raw: bytes):
    v = unpack(raw)
    if isinstance(v, dict) and "$f32" in v:
        return np.frombuffer(v["$f32"], dtype="<f4")
    return v  # legacy float-list rows


def check_vector(ix: dict, val: Any) -> Optional[np.ndarray]:
    """Validate/coerce a field value into the index's vector shape
    (float32 row, the dtype the KV codec and device mirror hold)."""
    if is_nullish(val) or val is None:
        return None
    if not isinstance(val, (list, tuple)):
        raise TypeError_("Vector index field must be an array of numbers")
    dim = ix["index"].get("dimension", 0)
    if dim and len(val) != dim:
        raise TypeError_(
            f"Incorrect vector dimension ({len(val)}). Expected a vector of {dim} dimension."
        )
    # bulk numeric coercion: one numpy pass replaces a per-element
    # isinstance/float() loop (the hot path of every indexed vector write);
    # dtype is inferred first so strings/objects/all-bool rows are rejected,
    # and a single type() scan catches bools numpy would promote silently
    try:
        arr = np.asarray(val)
    except (TypeError, ValueError):
        raise TypeError_("Vector index field must be an array of numbers")
    if (
        arr.ndim != 1
        or arr.dtype.kind not in ("i", "u", "f")
        or any(type(x) is bool for x in val)
    ):
        raise TypeError_("Vector index field must be an array of numbers")
    return arr.astype(np.float32)


def _row_key(ns, db, tb, name, rid: Thing) -> bytes:
    return keys.index_state(ns, db, tb, name, _ROW + enc_value_key(rid))


def update_vector_index(ctx, ix: dict, rid: Thing, old_vals, new_vals) -> None:
    ns, db = ctx.ns_db()
    txn = ctx.txn()
    tb, name = ix["table"], ix["name"]
    old_vec = check_vector(ix, old_vals[0]) if old_vals else None
    new_vec = check_vector(ix, new_vals[0]) if new_vals else None
    if old_vec is None and new_vec is None:
        return
    k = _row_key(ns, db, tb, name, rid)
    if new_vec is None:
        txn.delete(k)
    else:
        txn.set(k, pack_vector(new_vec))
    # buffered mirror delta, applied on commit (idx/knn.py VectorMirror);
    # a cancelled transaction never touches the shared mirror
    txn.vector_delta(ns, db, tb, name, rid, new_vec)


def scan_vectors(txn, ns, db, tb, name):
    """Yield (rid, vector) rows from the persisted index state."""
    pre = keys.index_state(ns, db, tb, name, _ROW)
    for chunk in txn.batch(pre, prefix_end(pre), 1000):
        for k, v in chunk:
            rid, _ = dec_value_key(k, len(pre))
            yield rid, unpack_vector(v)
