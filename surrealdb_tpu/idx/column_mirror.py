"""Columnar table mirror: typed column arrays + the vectorized scan plan.

Role of the per-row `scan_table` → `cond.compute` hot loop (dbs/iterator.py)
re-designed batch-at-a-time, the same proven pattern as idx/ft_mirror.py and
idx/graph_csr.py: hot tables' scalar fields are materialized into typed
numpy columns (tag/num/str triples per dotted path) plus a row-id map, so a
simple `SELECT ... WHERE` becomes ONE vectorized mask evaluation
(ops/predicates.py) over the whole table, with `unpack` paid only for the
surviving rows and the statement deadline checked per block instead of per
row. The r07 slowest trace showed 161.8s of `execute` wrapping 16.6s of
`knn_search` — this module attacks exactly that GIL-bound per-row gap.

Staleness protocol (the part that must be airtight):

- Every committed record write bumps the table's entry in
  `ColumnMirrors.versions` BEFORE the backend commit, inside the
  datastore's commit lock (kvs/tx.py). A build atomically captures
  (version, fresh snapshot) under the same lock. A reader therefore serves
  the mirror ONLY when (a) its own transaction has no uncommitted writes to
  the table, (b) the mirror's build version still equals the table's
  current version, and (c) the reader's snapshot is at least as new as the
  build snapshot. Any commit that could make the mirror wrong for that
  reader is guaranteed to have bumped the version before the reader's
  snapshot even opened — a stale mask can never serve.
- Commits into a mirrored table also arm a debounced background rebuild
  (pattern of GraphMirrors' ingest-time prewarm) so the post-ingest first
  query finds a fresh mirror; query-time rebuilds are rate-limited by the
  same window, falling back to the row path while writes are hot.

The KV state stays authoritative; results are always identical to the row
path (rows the predicate compiler can't judge are re-checked per row).
"""

from __future__ import annotations

import threading
from surrealdb_tpu.utils import locks as _locks
import time as _time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from surrealdb_tpu import cnf
from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.ops.predicates import (
    TAG_BOOL,
    TAG_DATETIME,
    TAG_FLOAT,
    TAG_INT,
    TAG_NONE,
    TAG_NULL,
    TAG_OTHER,
    TAG_STR,
    F64_EXACT_INT,
    CompiledPredicate,
)
from surrealdb_tpu.sql.value import Datetime, Thing, is_none, is_null
from surrealdb_tpu.utils.ser import unpack


# ------------------------------------------------------------------ columns
class Column:
    """One dotted path's values over the table's row order."""

    __slots__ = ("tags", "nums", "_strs", "_nonempty", "_i64")

    def __init__(
        self,
        tags: np.ndarray,
        nums: np.ndarray,
        strs: Optional[np.ndarray],
        i64: Optional[np.ndarray] = None,
    ):
        self.tags = tags
        self.nums = nums
        self._strs = strs  # object-dtype, "" where not a string
        self._nonempty: Optional[np.ndarray] = None
        # exact integer plane: datetime nanos (epoch nanos overflow the f64
        # mantissa — ~1.7e18 vs 2^53 — so they compare on int64)
        self._i64 = i64

    def i64(self) -> np.ndarray:
        if self._i64 is None:
            self._i64 = np.zeros(len(self.tags), dtype=np.int64)
        return self._i64

    def str_eq(self, c: str) -> np.ndarray:
        if self._strs is None:
            return np.zeros(len(self.tags), dtype=bool)
        return np.asarray(self._strs == c, dtype=bool)

    def str_cmp(self, c: str) -> Tuple[np.ndarray, np.ndarray]:
        if self._strs is None:
            z = np.zeros(len(self.tags), dtype=bool)
            return z, z
        return (
            np.asarray(self._strs < c, dtype=bool),
            np.asarray(self._strs > c, dtype=bool),
        )

    def str_array(self) -> np.ndarray:
        """The string plane ("" where not a string) — the pipeline's sort /
        group-key rank source and cell reconstruction."""
        if self._strs is None:
            self._strs = np.full(len(self.tags), "", dtype=object)
        return self._strs

    def str_nonempty(self) -> np.ndarray:
        if self._nonempty is None:
            if self._strs is None:
                self._nonempty = np.zeros(len(self.tags), dtype=bool)
            else:
                self._nonempty = np.asarray(self._strs != "", dtype=bool)
        return self._nonempty

    def str_contains(self, c: str) -> np.ndarray:
        """Substring containment per STRING cell (`field CONTAINS 'sub'`).
        Object-dtype columns have no vectorized substring kernel; the
        generator pass is still one C-level loop over python strings —
        far from the row path's full per-row cond.compute machinery."""
        if self._strs is None:
            return np.zeros(len(self.tags), dtype=bool)
        return np.fromiter(
            (c in s for s in self._strs), dtype=bool, count=len(self.tags)
        )


def _all_none_column(n: int) -> Column:
    return Column(np.zeros(n, dtype=np.int8), np.zeros(n, dtype=np.float64), None)


class _ColBuilder:
    """Growable column during the build scan; rows before first sight
    backfill as NONE (missing field == NONE, get_path semantics)."""

    __slots__ = ("tags", "nums", "str_rows", "str_vals", "i64_rows", "i64_vals", "n")

    def __init__(self, cap: int, backfill: int):
        self.tags = np.zeros(cap, dtype=np.int8)
        self.nums = np.zeros(cap, dtype=np.float64)
        self.str_rows: List[int] = []
        self.str_vals: List[str] = []
        self.i64_rows: List[int] = []  # datetime cells (nanos, exact)
        self.i64_vals: List[int] = []
        self.n = backfill  # rows already covered (as NONE)

    def grow(self, cap: int) -> None:
        if len(self.tags) < cap:
            t = np.zeros(cap, dtype=np.int8)
            t[: len(self.tags)] = self.tags
            m = np.zeros(cap, dtype=np.float64)
            m[: len(self.nums)] = self.nums
            self.tags, self.nums = t, m

    def put(self, row: int, v: Any) -> None:
        tag, num, s, i64 = _classify(v)
        self.tags[row] = tag
        if num is not None:
            self.nums[row] = num
        if s is not None:
            self.str_rows.append(row)
            self.str_vals.append(s)
        if i64 is not None:
            self.i64_rows.append(row)
            self.i64_vals.append(i64)
        self.n = row + 1

    def finalize(self, n: int) -> Column:
        tags = self.tags[:n].copy()
        nums = self.nums[:n].copy()
        strs = None
        if self.str_vals:
            strs = np.full(n, "", dtype=object)
            strs[self.str_rows] = self.str_vals
        i64 = None
        if self.i64_vals:
            i64 = np.zeros(n, dtype=np.int64)
            i64[self.i64_rows] = self.i64_vals
        return Column(tags, nums, strs, i64)


def _classify(v) -> Tuple[int, Optional[float], Optional[str], Optional[int]]:
    """(tag, numeric value, string value, int64 value) for one scalar cell;
    anything the mask algebra can't reproduce exactly is OTHER (per-row
    fallback)."""
    if is_none(v):
        return TAG_NONE, None, None, None
    if is_null(v):
        return TAG_NULL, None, None, None
    if isinstance(v, bool):
        return TAG_BOOL, 1.0 if v else 0.0, None, None
    if isinstance(v, int):
        if -F64_EXACT_INT <= v <= F64_EXACT_INT:
            return TAG_INT, float(v), None, None
        return TAG_OTHER, None, None, None
    if isinstance(v, float):
        return TAG_FLOAT, v, None, None
    if isinstance(v, str) and type(v) is str:
        return TAG_STR, None, v, None
    if isinstance(v, Datetime):
        return TAG_DATETIME, None, None, v.nanos
    return TAG_OTHER, None, None, None


# ------------------------------------------------------------------ mirror
class ColumnMirror:
    """One table's columns, frozen at (built_version, build snapshot)."""

    __slots__ = (
        "ids",
        "enc_keys",
        "columns",
        "nested_unsafe",
        "overflow",
        "n",
        "built_version",
        "built_store_version",
        "build_time",
        "delta_fed",
        "_order",
        "_virtual",
        "_id_index",
        "_slot_perm",
    )

    def __init__(self):
        self.ids: List[Any] = []  # row -> record id (key-scan order)
        self.enc_keys: List[bytes] = []  # row -> enc_value_key(id)
        self.columns: Dict[str, Column] = {}
        # top-level fields holding a list/record-link in ANY row: a nested
        # path under them can't default to all-NONE (get_path distributes
        # over lists and fetches through Things)
        self.nested_unsafe: Set[str] = set()
        self.overflow = False  # field budget exceeded: unknown paths exist
        self.n = 0
        self.built_version = -1
        self.built_store_version = -1
        self.build_time = 0.0
        self.delta_fed = False  # rows appended by a bulk delta (not key order)
        # row indices in key order when delta-fed (None = already key order);
        # computed lazily on the first scan that streams rows out
        self._order: Optional[np.ndarray] = None
        self._virtual: Dict[str, Column] = {}
        self._id_index: Optional[Dict[str, int]] = None
        # (id(rids list), n_slots) -> row permutation for the kNN prefilter
        self._slot_perm: Optional[Tuple[int, int, np.ndarray]] = None

    def key_order(self) -> Optional[np.ndarray]:
        """Row indices in record-key order, or None when rows are already
        key-ordered (every fully-built mirror; delta appends break it).
        Scans stream surviving rows in this order so columnar output stays
        byte-identical to the row path's key-ordered scan."""
        if not self.delta_fed:
            return None
        if self._order is None:
            self._order = np.argsort(
                np.asarray(self.enc_keys, dtype=object), kind="stable"
            )
        return self._order

    def columns_for(self, paths: Set[str]) -> Optional[Dict[str, Column]]:
        """Resolve every path to a column; a path never seen is all-NONE
        when that default is provably exact, else None (row path)."""
        out: Dict[str, Column] = {}
        for p in paths:
            col = self.columns.get(p)
            if col is None:
                if self.overflow:
                    return None
                head = p.split(".", 1)[0]
                if "." in p and head in self.nested_unsafe:
                    return None
                col = self._virtual.get(p)
                if col is None:
                    col = self._virtual[p] = _all_none_column(self.n)
            out[p] = col
        return out

    def id_index(self) -> Dict[str, int]:
        """repr(record id) -> row, for aligning foreign slot spaces."""
        if self._id_index is None:
            self._id_index = {repr(i): r for r, i in enumerate(self.ids)}
        return self._id_index

    def slot_permutation(self, rids: List[Any], cap: int) -> np.ndarray:
        """perm[slot] = column row of the vector-mirror slot's record (or -1),
        cached per (rids identity, slot count) — rebuilding the mirror
        installs a new ColumnMirror object, so the cache can't go stale."""
        cached = self._slot_perm
        if cached is not None and cached[0] == id(rids) and cached[1] == cap:
            return cached[2]
        idx = self.id_index()
        perm = np.full(cap, -1, dtype=np.int64)
        for slot, rid in enumerate(rids[:cap]):
            rid_id = rid.id if isinstance(rid, Thing) else rid
            row = idx.get(repr(rid_id))
            if row is not None:
                perm[slot] = row
        self._slot_perm = (id(rids), cap, perm)
        return perm


class ColumnMirrors:
    """Per-datastore registry: (ns, db, tb) -> ColumnMirror + the commit
    version counters the staleness protocol hangs off."""

    def __init__(self):
        self._lock = _locks.RLock("idx.column.registry")
        self.versions: Dict[Tuple[str, str, str], int] = {}
        self._mirrors: Dict[Tuple[str, str, str], ColumnMirror] = {}
        self._build_locks: Dict[Tuple[str, str, str], threading.Lock] = {}
        self._ds = None  # weakref to the owning Datastore
        self._timers: Dict[Tuple[str, str, str], threading.Timer] = {}
        self._deadlines: Dict[Tuple[str, str, str], float] = {}
        self._running: Set[Tuple[str, str, str]] = set()
        # flight-recorder task ids of armed rebuilds (bg.py lifecycle)
        self._task_ids: Dict[Tuple[str, str, str], int] = {}
        self._owner: Optional[int] = None  # id(ds), for bg teardown scoping

    # ------------------------------------------------------------ plumbing
    def bind_ds(self, ds) -> None:
        import weakref

        self._ds = weakref.ref(ds)
        self._owner = id(ds)

    def get(self, key3) -> Optional[ColumnMirror]:
        with self._lock:
            return self._mirrors.get(key3)

    # ------------------------------------------------------------ invalidation
    def invalidate(self, tables, scopes=()) -> None:
        """Bump version counters for touched tables / dropped scopes. Called
        by the committing transaction BEFORE its backend commit, under the
        datastore commit lock — see the module docstring for why that
        ordering closes every stale-serve window."""
        with self._lock:
            for k in tables:
                self.versions[k] = self.versions.get(k, 0) + 1
            for scope in scopes:
                w = len(scope)
                for k in list(self.versions):
                    if k[:w] == tuple(scope):
                        self.versions[k] += 1
                for k in list(self._mirrors):
                    if k[:w] == tuple(scope):
                        self.versions[k] = self.versions.get(k, 0) + 1

    def drop_table(self, ns: str, db: str, tb: str) -> None:
        with self._lock:
            self._mirrors.pop((ns, db, tb), None)

    def drop_db(self, ns: str, db: str) -> None:
        with self._lock:
            for k in [k for k in self._mirrors if k[:2] == (ns, db)]:
                del self._mirrors[k]

    def drop_ns(self, ns: str) -> None:
        with self._lock:
            for k in [k for k in self._mirrors if k[0] == ns]:
                del self._mirrors[k]

    def clear(self) -> None:
        with self._lock:
            self._mirrors.clear()

    # ------------------------------------------------------------ rebuild
    def schedule_rebuild(self, tables) -> None:
        """Debounced background rebuild for committed-into mirrored tables
        (deadline-advance debounce, the GraphMirrors prewarm pattern)."""
        from surrealdb_tpu import bg

        if self._ds is None:
            return
        delay = cnf.COLUMN_REBUILD_DEBOUNCE_SECS
        now = _time.monotonic()
        with self._lock:
            armed = []
            for key3 in tables:
                if key3 not in self._mirrors:
                    continue  # never queried columnar — nothing to refresh
                self._deadlines[key3] = now + delay
                if key3 not in self._timers:
                    armed.append(key3)
                else:
                    tid = self._task_ids.get(key3)
                    if tid is not None:
                        bg.touch(tid)  # debounce deadline advanced
            for key3 in armed:
                # flight-recorder record: scheduled now, running when the
                # debounce fires, linked to the committing request's trace
                self._task_ids[key3] = bg.register(
                    "column_mirror", target=".".join(key3), owner=self._owner
                )
                self._arm_timer(key3, delay)

    def _arm_timer(self, key3, delay: float) -> None:
        from surrealdb_tpu import bg

        timer = bg.timer(
            delay, self._rebuild_cb, key3, None,
            task_id=self._task_ids.get(key3),
            name=f"bg:column_mirror:{key3[2]}", start=False,
        )
        timer.args = (key3, timer)
        self._timers[key3] = timer
        timer.start()

    def _rebuild_cb(self, key3, timer) -> None:
        from surrealdb_tpu import bg

        with self._lock:
            if self._timers.get(key3) is not timer:
                return
            remaining = self._deadlines.get(key3, 0.0) - _time.monotonic()
            if remaining > 0.001:
                self._arm_timer(key3, remaining)
                return
            del self._timers[key3]
            self._deadlines.pop(key3, None)
            self._running.add(key3)
            task_id = self._task_ids.pop(key3, None)
        if task_id is None:
            task_id = bg.register(
                "column_mirror", target=".".join(key3), owner=self._owner,
                trace_id=None,
            )
        try:
            with bg.run(task_id):
                ds = self._ds() if self._ds is not None else None
                if ds is not None:
                    from surrealdb_tpu import telemetry

                    telemetry.inc("column_mirror_rebuilds", cause="ingest_prewarm")
                    self.build(ds, *key3)
        except Exception:  # noqa: BLE001 — best-effort; query path stays intact
            from surrealdb_tpu import telemetry

            # counted, not silent: a repeatedly-failing prewarm shows up on
            # /metrics instead of vanishing (the bg task record has details)
            telemetry.inc("prewarm_errors", subsystem="column_mirror")
        finally:
            with self._lock:
                self._running.discard(key3)

    def wait_rebuild(self, timeout: float = 30.0) -> bool:
        """Block until no rebuild timer or build is pending (test/bench
        determinism helper, never used on the query path)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                if not self._timers and not self._running:
                    return True
            _time.sleep(0.01)
        return False

    def shutdown(self, timeout: float = 10.0) -> None:
        """Teardown on Datastore.close(): cancel armed timers (resolving
        their flight-recorder records) and wait out in-flight builds, so
        no rebuild thread outlives its datastore."""
        from surrealdb_tpu import bg

        with self._lock:
            timers = list(self._timers.values())
            self._timers.clear()
            self._deadlines.clear()
            task_ids = list(self._task_ids.values())
            self._task_ids.clear()
        for t in timers:
            t.cancel()
        for tid in task_ids:
            bg.cancel(tid, "cancelled: datastore closed")
        self.wait_rebuild(timeout)

    # ------------------------------------------------------------ delta feed
    def apply_bulk(self, key3, parts, n_bumps: int, commit_version) -> bool:
        """Append a bulk op's decoded rows straight onto an up-to-date
        mirror (the ingest delta-feed): `parts` is the commit-ordered list
        of (ids, enc_keys, docs) blocks this flush wrote to the table and
        `n_bumps` how many version bumps those commits performed. Applies
        ONLY when the mirror was exactly current before this flush
        (built_version == current - n_bumps) — then the merged mirror
        installs at the CURRENT version and serves immediately, and the
        100k-row re-scan rebuild never queues. Any other shape (schema
        drift past the field budget, interleaved row-level writes, no
        commit version from the backend) returns False and the caller
        falls back to the debounced rebuild. Must run under the datastore
        commit lock — the version capture is only atomic there."""
        from surrealdb_tpu import faults, telemetry

        def _decline(reason: str) -> bool:
            telemetry.inc("column_mirror_delta", outcome=reason)
            return False

        # chaos hook: an injected failure here proves the decline contract —
        # the commit stays durable, the caller falls back to the debounced
        # rebuild, and a stale mirror cannot serve (version mismatch)
        faults.fire("column.delta_apply")
        if not cnf.COLUMN_DELTA_FEED:
            return _decline("disabled")
        if commit_version is None:
            return _decline("no_commit_version")
        ds = self._ds() if self._ds is not None else None
        if ds is not None:
            _locks.assert_held(ds.commit_lock, "column_mirror.delta apply")
        with self._lock:
            m = self._mirrors.get(key3)
            cur = self.versions.get(key3, 0)
        if m is None:
            return _decline("no_mirror")
        if m.built_version != cur - n_bumps:
            return _decline("stale_base")
        if m.overflow:
            return _decline("overflow_base")
        ids: List[Any] = []
        enc_keys: List[bytes] = []
        docs: List[Any] = []
        for p_ids, p_keys, p_docs in parts:
            ids.extend(p_ids)
            enc_keys.extend(p_keys)
            docs.extend(p_docs)
        bn = len(docs)
        if bn == 0:
            return _decline("empty")
        blk, blk_unsafe = _build_block(docs)
        if blk.overflow:
            return _decline("overflow_block")
        paths = set(m.columns) | set(blk.columns)
        if len(paths) > max(cnf.COLUMN_MIRROR_MAX_FIELDS, 1):
            return _decline("overflow_union")
        nm = ColumnMirror()
        nm.n = m.n + bn
        nm.ids = m.ids + ids
        nm.enc_keys = m.enc_keys + enc_keys
        nm.delta_fed = True
        # incremental key order: the old prefix is already key-ordered (or
        # carries a computed order), so merging the B appended keys costs
        # O(N + B log N) here instead of a full O(N log N) object argsort
        # on the next scan — sustained ingest would otherwise re-sort the
        # whole table's keys after every bulk statement
        old_order = m.key_order()
        old_keys = np.asarray(m.enc_keys, dtype=object)
        if old_order is not None:
            old_rows = old_order
            old_keys = old_keys[old_order]
        else:
            old_rows = np.arange(m.n, dtype=np.int64)
        blk_keys = np.asarray(enc_keys, dtype=object)
        bidx = np.argsort(blk_keys, kind="stable")
        pos = np.searchsorted(old_keys, blk_keys[bidx])
        nm._order = np.insert(old_rows, pos, m.n + bidx)
        nm.built_version = cur
        nm.built_store_version = commit_version
        nm.build_time = m.build_time
        nm.nested_unsafe = m.nested_unsafe | blk.nested_unsafe
        cols: Dict[str, Column] = {}
        for p in paths:
            a = m.columns.get(p)
            b = blk.columns.get(p)
            tags = np.concatenate(
                [
                    a.tags if a is not None else np.zeros(m.n, dtype=np.int8),
                    b.tags if b is not None else np.zeros(bn, dtype=np.int8),
                ]
            )
            nums = np.concatenate(
                [
                    a.nums if a is not None else np.zeros(m.n, dtype=np.float64),
                    b.nums if b is not None else np.zeros(bn, dtype=np.float64),
                ]
            )
            strs = None
            if (a is not None and a._strs is not None) or (
                b is not None and b._strs is not None
            ):
                strs = np.full(nm.n, "", dtype=object)
                if a is not None and a._strs is not None:
                    strs[: m.n] = a._strs
                if b is not None and b._strs is not None:
                    strs[m.n :] = b._strs
            i64 = None
            if (a is not None and a._i64 is not None) or (
                b is not None and b._i64 is not None
            ):
                i64 = np.zeros(nm.n, dtype=np.int64)
                if a is not None and a._i64 is not None:
                    i64[: m.n] = a._i64
                if b is not None and b._i64 is not None:
                    i64[m.n :] = b._i64
            if a is None and "." in p and p.split(".", 1)[0] in m.nested_unsafe:
                # a nested path first seen in this batch, under a parent that
                # held lists/record-links in old rows: those old cells are
                # not provably NONE — re-check them per row
                tags[: m.n] = TAG_OTHER
            cols[p] = Column(tags, nums, strs, i64)
        # nested columns under a parent that held a list/record-link in a
        # BATCH row abstain there (same marking the full build applies) —
        # including columns only the old mirror materialized
        for parent, rows_u in blk_unsafe.items():
            off = np.asarray(rows_u, dtype=np.int64) + m.n
            for p, col in cols.items():
                if p.startswith(parent + "."):
                    col.tags[off] = TAG_OTHER
        nm.columns = cols
        with self._lock:
            if self.versions.get(key3, 0) != cur:
                return _decline("raced")
            self._mirrors[key3] = nm
        telemetry.inc("column_mirror_delta", outcome="applied")
        telemetry.observe_hist(
            "column_mirror_delta_rows", bn, buckets=telemetry.COUNT_BUCKETS
        )
        return True

    # ------------------------------------------------------------ serve
    def serveable(self, ctx, key3) -> Optional[ColumnMirror]:
        """The mirror, iff it is provably exact for this reader's snapshot;
        triggers a (rate-limited) synchronous rebuild when stale."""
        txn = ctx.txn()
        if key3 in getattr(txn, "touched_tables", ()):  # own uncommitted writes
            return None
        snap = getattr(txn.tr, "snapshot", None)
        if snap is None:
            return None
        with self._lock:
            m = self._mirrors.get(key3)
            cur = self.versions.get(key3, 0)
        if m is None or m.built_version != cur:
            if m is not None and (
                _time.monotonic() - m.build_time < cnf.COLUMN_REBUILD_DEBOUNCE_SECS
            ):
                return None  # writes still hot: row path; debounce will rebuild
            m = self.build(ctx.ds(), *key3)
            if m is None:
                return None
        if snap < m.built_store_version:
            return None  # reader's snapshot predates the build
        return m

    # ------------------------------------------------------------ build
    def build(self, ds, ns: str, db: str, tb: str) -> Optional[ColumnMirror]:
        key3 = (ns, db, tb)
        with self._lock:
            bl = self._build_locks.setdefault(key3, _locks.Lock("idx.column.build"))
        with bl:
            with self._lock:
                m = self._mirrors.get(key3)
                cur = self.versions.get(key3, 0)
            if m is not None and m.built_version == cur:
                return m  # a racing build already refreshed it
            from surrealdb_tpu import telemetry

            # atomically capture (version, snapshot): commits bump the
            # version and apply their backend writes as one unit under this
            # same lock, so no commit can land between the two reads
            with ds.commit_lock:
                with self._lock:
                    v0 = self.versions.get(key3, 0)
                txn = ds.transaction(False)
            t0 = _time.perf_counter()
            mirror = ColumnMirror()
            try:
                mirror.built_version = v0
                mirror.built_store_version = getattr(txn.tr, "snapshot", -1)
                self._scan(txn, ns, db, tb, mirror)
            except Exception:
                telemetry.inc("column_mirror_rebuilds", cause="build_failed")
                return None
            finally:
                txn.cancel()
            mirror.build_time = _time.monotonic()
            telemetry.observe("column_mirror_build", _time.perf_counter() - t0)
            telemetry.observe_hist(
                "column_mirror_rows", mirror.n, buckets=telemetry.COUNT_BUCKETS
            )
            with self._lock:
                self._mirrors[key3] = mirror
            return mirror

    @staticmethod
    def _scan(txn, ns: str, db: str, tb: str, mirror: ColumnMirror) -> None:
        max_fields = max(cnf.COLUMN_MIRROR_MAX_FIELDS, 1)
        nested_depth = cnf.COLUMN_MIRROR_MAX_DEPTH
        pre = keys.thing_prefix(ns, db, tb)
        builders: Dict[str, _ColBuilder] = {}
        # parent field -> rows where it held a list/record-link: nested
        # columns under it must abstain there (get_path distributes over
        # lists and fetches through Things — all-NONE would be wrong)
        unsafe_rows: Dict[str, List[int]] = {}
        ids: List[Any] = []
        enc_keys: List[bytes] = []
        npre = len(pre)
        cap = 1024
        row = 0
        for chunk in txn.batch(pre, prefix_end(pre), cnf.NORMAL_FETCH_SIZE):
            for k, raw in chunk:
                if row >= cap:
                    cap *= 2
                    for b in builders.values():
                        b.grow(cap)
                ids.append(keys.decode_thing_id(k, ns, db, tb))
                enc_keys.append(k[npre:])
                doc = unpack(raw)
                if isinstance(doc, dict):
                    for name, v in doc.items():
                        _put_cell(
                            builders, name, v, row, cap, max_fields,
                            nested_depth, mirror, unsafe_rows,
                        )
                row += 1
        mirror.ids = ids
        mirror.enc_keys = enc_keys
        mirror.n = row
        mirror.columns = {p: b.finalize(row) for p, b in builders.items()}
        for parent, rows_u in unsafe_rows.items():
            for p, col in mirror.columns.items():
                if p.startswith(parent + "."):
                    col.tags[rows_u] = TAG_OTHER


def _build_block(docs) -> Tuple[ColumnMirror, Dict[str, List[int]]]:
    """Classify one bulk batch's decoded rows into a block of columns (the
    delta-feed unit): the same `_put_cell` machinery the full build scan
    runs, minus the KV scan and unpack — the bulk path already decoded the
    rows once. Returns (block, unsafe parent -> block rows)."""
    blk = ColumnMirror()
    max_fields = max(cnf.COLUMN_MIRROR_MAX_FIELDS, 1)
    nested_depth = cnf.COLUMN_MIRROR_MAX_DEPTH
    builders: Dict[str, _ColBuilder] = {}
    unsafe_rows: Dict[str, List[int]] = {}
    cap = max(len(docs), 1)
    for row, doc in enumerate(docs):
        if isinstance(doc, dict):
            for name, v in doc.items():
                _put_cell(
                    builders, name, v, row, cap, max_fields,
                    nested_depth, blk, unsafe_rows,
                )
    blk.n = len(docs)
    blk.columns = {p: b.finalize(blk.n) for p, b in builders.items()}
    return blk, unsafe_rows


def _put_cell(builders, name, v, row, cap, max_fields, nested_depth, mirror, unsafe_rows):
    """Classify one top-level cell, descending one level into dicts."""
    b = _builder_for(builders, name, row, cap, max_fields, mirror)
    if b is not None:
        b.put(row, v)
    if isinstance(v, (list, tuple, Thing)):
        mirror.nested_unsafe.add(name)
        unsafe_rows.setdefault(name, []).append(row)
    if isinstance(v, dict) and nested_depth >= 2:
        for cn, cv in v.items():
            cb = _builder_for(
                builders, f"{name}.{cn}", row, cap, max_fields, mirror
            )
            if cb is not None:
                cb.put(row, cv)  # dicts/lists classify OTHER (exact fallback)


def _builder_for(builders, path, row, cap, max_fields, mirror):
    b = builders.get(path)
    if b is None:
        if len(builders) >= max_fields:
            mirror.overflow = True
            return None
        b = builders[path] = _ColBuilder(cap, row)
    return b


# ------------------------------------------------------------------ shared mask
def columnar_mask(ctx, tb: str, compiled: CompiledPredicate):
    """Evaluate a compiled predicate over `tb`'s mirror for THIS reader.
    Returns (mask, needs_row, mirror) or None when the mirror can't serve
    (stale, too small, unresolvable paths, txn writes...)."""
    ns, db = ctx.ns_db()
    registry = getattr(ctx.ds(), "column_mirrors", None)
    if registry is None:
        return None
    mirror = registry.serveable(ctx, (ns, db, tb))
    if mirror is None or mirror.n == 0:
        return None
    cols = mirror.columns_for(compiled.paths)
    if cols is None:
        return None
    mask, needs_row = compiled.evaluate(cols)
    return mask, needs_row, mirror


# ------------------------------------------------------------------ plan
class ColumnScanPlan:
    """Planner-selected vectorized table scan: one mask evaluation, then
    surviving rows stream out in key order, docs fetched per block. The
    iterator skips re-evaluating the WHERE (`cond_satisfied`) — rows the
    mask algebra can't judge are re-checked here, per row, before yielding,
    so output is always identical to the row path.

    With `order_specs` (the planner lowered the statement's ORDER BY onto
    mirror columns) survivors stream in the statement's ORDER instead of
    key order and the plan advertises `provides_order`: the iterator's
    LIMIT fast path then stops pulling after start+limit rows (late
    materialization — only the top rows' documents decode) and the
    postprocess skips the re-sort. If the mirror cannot serve, the promised
    order is unkeepable — OrderPushdownBailout re-runs the statement on the
    plain scan + post-sort path."""

    cond_satisfied = True

    def __init__(self, tb: str, stm, compiled: Optional[CompiledPredicate],
                 order_specs=None):
        self.tb = tb
        self.stm = stm
        self.compiled = compiled
        self.order_specs = order_specs or None
        self.provides_order = bool(order_specs)

    def explain(self) -> dict:
        out: Dict[str, Any] = {"table": self.tb}
        if self.order_specs:
            out["strategy"] = "columnar-pipeline"
            out["stages"] = ["mask", "sort", "materialize"]
            out["order"] = [
                {"key": s.path, "direction": "ASC" if s.asc else "DESC"}
                for s in self.order_specs
            ]
        else:
            out["strategy"] = "columnar-scan"
        if self.compiled is not None:
            out["predicate"] = self.compiled.source
        return out

    def iterate(self, ctx):
        from surrealdb_tpu import telemetry

        with telemetry.span("scan_columnar", table=self.tb):
            res = self._mask(ctx)
        if res is None:
            if self.order_specs:
                # the promised ORDER cannot be produced — re-plan row path
                from surrealdb_tpu.idx.planner import OrderPushdownBailout

                raise OrderPushdownBailout()
            telemetry.inc("scan_strategy", strategy="row_fallback")
            yield from self._row_scan(ctx)
            return
        mask, needs_row, mirror = res
        telemetry.inc("scan_strategy", strategy="columnar")
        # the mask evaluation examined every mirrored row — tally the same
        # rows_scanned the row path's chunked scan_table would have
        from surrealdb_tpu import accounting

        accounting.tally(rows_scanned=float(mask.size))
        n_fb = int(needs_row.sum())
        if n_fb:
            telemetry.observe_hist(
                "columnar_fallback_rows", n_fb, buckets=telemetry.COUNT_BUCKETS
            )
        ns, db = ctx.ns_db()
        txn = ctx.txn()
        ids = mirror.ids
        want = mask | needs_row
        order = mirror.key_order()
        if order is None:
            cand = np.nonzero(want)[0]
        else:
            # delta-appended rows sit past the key-ordered prefix: stream
            # survivors in record-key order so output matches the row path
            cand = order[want[order]]
        t_sort = _time.perf_counter()
        doc_cache: dict = {}
        if self.order_specs:
            from surrealdb_tpu.ops.pipeline import order_permutation

            cand = order_permutation(
                ctx, self.tb, mirror, cand, self.order_specs, doc_cache,
                value_mode=getattr(self.stm, "value_mode", False),
            )
            if cand is None:
                from surrealdb_tpu.idx.planner import OrderPushdownBailout

                raise OrderPushdownBailout()
        note = {
            "table": self.tb,
            "plan": "ColumnScanPlan",
            "strategy": "columnar-pipeline" if self.order_specs else "columnar-scan",
            "stages": {
                "mask": {"rows": int(cand.size)},
            },
        }
        if self.order_specs:
            note["stages"]["sort"] = {
                "rows": int(cand.size),
                "keys": [s.path for s in self.order_specs],
                "ms": round((_time.perf_counter() - t_sort) * 1e3, 3),
            }
        block = max(cnf.COLUMN_BLOCK_SIZE, 1)
        from surrealdb_tpu.sql.value import truthy

        cond = self.stm.cond
        yielded = 0
        t_mat = _time.perf_counter()
        try:
            for lo in range(0, cand.size, block):
                ctx.check_deadline()
                for i in cand[lo : lo + block]:
                    i = int(i)
                    rid = Thing(self.tb, ids[i])
                    doc = doc_cache.get(i)
                    if doc is None:
                        doc = txn.get_record(ns, db, self.tb, ids[i])
                    if doc is None:
                        continue
                    if needs_row[i]:
                        # mixed-type row: the mask abstained — row-path check
                        with ctx.with_doc_value(doc, rid=rid) as c:
                            if not truthy(cond.compute(c)):
                                continue
                    yielded += 1
                    yield rid, doc, None
        finally:
            note["stages"]["materialize"] = {
                "rows": yielded,
                "ms": round((_time.perf_counter() - t_mat) * 1e3, 3),
            }
            telemetry.note_plan(note)

    def _mask(self, ctx):
        """(mask, needs_row, mirror) — the cond-less variant serves an
        all-true mask so ORDER BY+LIMIT pushdown works without a WHERE."""
        if self.compiled is not None:
            return columnar_mask(ctx, self.tb, self.compiled)
        ns, db = ctx.ns_db()
        registry = getattr(ctx.ds(), "column_mirrors", None)
        if registry is None:
            return None
        mirror = registry.serveable(ctx, (ns, db, self.tb))
        if mirror is None or mirror.n == 0:
            return None
        ones = np.ones(mirror.n, dtype=bool)
        return ones, np.zeros(mirror.n, dtype=bool), mirror

    def _row_scan(self, ctx):
        """Exact row-path twin (mirror unavailable): scan + per-row WHERE,
        here because the iterator was told the cond is already satisfied."""
        from surrealdb_tpu.dbs.iterator import scan_table
        from surrealdb_tpu.sql.value import truthy

        cond = self.stm.cond
        for rid, doc in scan_table(ctx, self.tb):
            if cond is not None:
                with ctx.with_doc_value(doc, rid=rid) as c:
                    if not truthy(cond.compute(c)):
                        continue
            yield rid, doc, None


def try_columnar_count(ctx, stm, sources) -> Optional[list]:
    """`SELECT count() FROM tb WHERE ... GROUP ALL` without ever touching a
    document: the answer is the mask's popcount (plus a per-row check of the
    rows the mask abstained on). Returns None to keep the ordinary path."""
    from surrealdb_tpu.dbs.iterator import ITable
    from surrealdb_tpu.sql.ast import FunctionCall
    from surrealdb_tpu.sql.path import Idiom as _Idiom

    if len(sources) != 1 or not isinstance(sources[0], ITable):
        return None
    if not getattr(stm, "group_all", False) or getattr(stm, "group", None):
        return None
    fields = getattr(stm, "fields", None) or []
    if len(fields) != 1 or getattr(fields[0], "all", False):
        return None
    f = fields[0]
    expr = f.expr
    if not (isinstance(expr, FunctionCall) and expr.name == "count" and not expr.args):
        return None
    if f.alias is None:
        name = "count"
    elif isinstance(f.alias, _Idiom) and f.alias.simple_name() is not None:
        name = f.alias.simple_name()
    else:
        return None
    for attr in ("split", "fetch", "omit", "order", "limit", "start"):
        if getattr(stm, attr, None):
            return None
    if getattr(stm, "value_mode", False):
        return None
    plan = column_scan_plan(ctx, stm, sources[0].tb)
    if plan is None:
        return None
    tb = sources[0].tb
    from surrealdb_tpu import telemetry

    with telemetry.span("scan_columnar", table=tb):
        res = columnar_mask(ctx, tb, plan.compiled)
    if res is None:
        return None
    mask, needs_row, mirror = res
    telemetry.inc("scan_strategy", strategy="columnar_count")
    # mask popcount still examined every mirrored row (tenant meter parity
    # with the iterator path's per-chunk rows_scanned tally)
    from surrealdb_tpu import accounting

    accounting.tally(rows_scanned=float(mask.size))
    total = int((mask & ~needs_row).sum())
    fb = np.nonzero(needs_row)[0]
    if fb.size:
        from surrealdb_tpu.sql.value import truthy

        ns, db = ctx.ns_db()
        txn = ctx.txn()
        cond = stm.cond
        for i in fb:
            ctx.check_deadline()
            i = int(i)
            doc = txn.get_record(ns, db, tb, mirror.ids[i])
            if doc is None:
                continue
            with ctx.with_doc_value(doc, rid=Thing(tb, mirror.ids[i])) as c:
                if truthy(cond.compute(c)):
                    total += 1
    if total == 0:
        return []  # GROUP ALL over zero rows yields no group (row path)
    return [{name: total}]


def column_scan_plan(ctx, stm, tb: str):
    """Planner hook: a ColumnScanPlan when the WHERE lowers onto columns and
    the table is big enough to pay for mirroring; None keeps the row path.
    When the statement's ORDER BY also lowers (plain multi-key paths with
    no grouping/splitting), the plan sorts survivors columnar and
    advertises `provides_order` — the iterator's LIMIT fast path then
    composes with the pushed sort instead of re-sorting (ISSUE 13)."""
    if not cnf.COLUMN_MIRROR:
        return None
    cond = getattr(stm, "cond", None)
    from surrealdb_tpu.iam.check import perms_apply

    if perms_apply(ctx):
        return None  # per-record PERMISSIONS must see every document
    compiled = None
    if cond is not None:
        from surrealdb_tpu.ops.predicates import compile_where

        compiled = compile_where(ctx, cond)
        if compiled is None:
            return None
    order_specs = None
    if (
        getattr(stm, "order", None)
        and not getattr(stm, "group", None)
        and not getattr(stm, "group_all", False)
        and not getattr(stm, "split", None)
    ):
        from surrealdb_tpu.ops.pipeline import resolve_order_specs

        specs = resolve_order_specs(stm)
        if specs:
            order_specs = specs
    if compiled is None and not order_specs:
        return None  # nothing lowers: keep the plain scan
    registry = getattr(ctx.ds(), "column_mirrors", None)
    if registry is None:
        return None
    from surrealdb_tpu.ops.pipeline import mirror_floor_ok

    if not mirror_floor_ok(ctx, registry, tb):
        return None
    if order_specs:
        from surrealdb_tpu import telemetry

        telemetry.inc("column_pipeline", outcome="order_planned")
    return ColumnScanPlan(tb, stm, compiled, order_specs)
