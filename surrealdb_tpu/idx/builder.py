"""Background index building (DEFINE INDEX … CONCURRENTLY).

Role of the reference's async index builder (reference:
core/src/kvs/index.rs:28-41 — building statuses started/initial/updates/
ready surfaced through INFO FOR INDEX). The build scans the table in
CHUNKED transactions (one short write txn per batch) so it never holds a
long snapshot against concurrent writers; writes that land during the build
index themselves through the normal doc pipeline, and chunk application is
idempotent (index keys are deterministic), so the two paths converge.

While an index is building the planner refuses to serve reads from it
(status != ready → table scan / brute-force kNN), matching the reference.
"""

from __future__ import annotations

from surrealdb_tpu.utils import locks as _locks
import time
from typing import Dict, Optional, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.sql.value import Thing
from surrealdb_tpu.utils.ser import unpack


class IndexBuilder:
    def __init__(self, ds):
        self.ds = ds
        self._lock = _locks.Lock("idx.builder")
        self._status: Dict[Tuple[str, str, str, str], dict] = {}

    # ------------------------------------------------------------ status
    def status(self, ns: str, db: str, tb: str, name: str) -> Optional[dict]:
        with self._lock:
            st = self._status.get((ns, db, tb, name))
            return dict(st) if st else None

    def _set(self, key, **kw) -> None:
        with self._lock:
            self._status.setdefault(key, {}).update(kw)

    # ------------------------------------------------------------ build
    def build(self, ns: str, db: str, tb: str, ix: dict, session) -> None:
        """Kick a background initial build; returns immediately. Call AFTER
        the defining transaction commits (on_commit hook) so the builder's
        transactions see the index definition."""
        key = (ns, db, tb, ix["name"])
        with self._lock:
            if self._status.get(key, {}).get("status") in ("started", "indexing"):
                return  # already building
            self._status[key] = {"status": "started", "count": 0}
        from surrealdb_tpu import bg

        task_id = bg.register(
            "index_build", target=f"{tb}.{ix['name']}", owner=id(self.ds)
        )
        bg.start_thread(task_id, self._run, key, ns, db, tb, ix, session, task_id)

    def _ctx(self, session):
        """Fresh executor + write txn + context for one build chunk."""
        from surrealdb_tpu.dbs.context import Context
        from surrealdb_tpu.dbs.executor import Executor

        ex = Executor(self.ds, session, {})
        ex.txn = self.ds.transaction(write=True)
        return Context(ex, session), ex.txn

    _RETRIES = 5

    def _chunk_txn(self, key, session, fn) -> None:
        """Run one build step in its own short txn, retrying on write
        conflicts (first-committer-wins backend) with backoff."""
        from surrealdb_tpu.err import TxConflictError

        for attempt in range(self._RETRIES):
            ctx, txn = self._ctx(session)
            try:
                out = fn(ctx, txn)
                txn.commit()
                return out
            except TxConflictError:
                txn.cancel()
                if attempt == self._RETRIES - 1:
                    raise
                time.sleep(0.01 * (2**attempt))
            except BaseException:
                txn.cancel()
                raise

    def _run(self, key, ns, db, tb, ix, session, task_id=None) -> None:
        from surrealdb_tpu import bg

        if task_id is None:
            task_id = bg.register("index_build", target=f"{tb}.{ix['name']}")
        with bg.run(task_id):
            self._run_inner(key, ns, db, tb, ix, session)

    def _run_inner(self, key, ns, db, tb, ix, session) -> None:
        from surrealdb_tpu.idx.index import extract_index_values, _apply

        name = ix["name"]
        try:
            self._set(key, status="indexing")
            # wipe any previous definition's entries + mirror first (like
            # rebuild_index): a DEFINE INDEX OVERWRITE ... CONCURRENTLY must
            # not leave old-field entries under the same prefix. The planner
            # refuses reads while status != ready, so nothing serves the gap.
            pre_ix = keys.index_prefix(ns, db, tb, name)

            def wipe(ctx, txn):
                txn.delr(pre_ix, prefix_end(pre_ix))

            self._chunk_txn(key, session, wipe)
            self.ds.index_stores.remove(ns, db, tb, name)

            count = 0
            rpre = keys.thing_prefix(ns, db, tb)
            cursor = rpre
            end = prefix_end(rpre)
            batch = 1000
            while True:
                state = {"chunk": None}

                def step(ctx, txn):
                    chunk = list(txn.scan(cursor, end, batch))
                    state["chunk"] = chunk
                    for k, v in chunk:
                        doc = unpack(v)
                        rid = Thing(tb, keys.decode_thing_id(k, ns, db, tb))
                        new_vals = extract_index_values(ctx, ix, doc)
                        _apply(ctx, ix, rid, None, new_vals)

                self._chunk_txn(key, session, step)
                chunk = state["chunk"]
                if not chunk:
                    break
                count += len(chunk)
                cursor = chunk[-1][0] + b"\x00"
                self._set(key, count=count)

            self._flip_status(key, session, ns, db, tb, name, "ready")
            self._set(key, status="ready", count=count, finished=time.time())
            # the index just became servable: cached plans (and prefetched
            # index defs) that planned without it are now stale
            self.ds.plan_cache.bump_generation(ns, db)
        except Exception as e:  # surface failures through INFO — both
            # the live status and the persisted def (so a stuck 'building'
            # never lies about an aborted build)
            self._set(key, status="error", error=str(e))
            try:
                self._flip_status(key, session, ns, db, tb, name, "error")
            except Exception as e2:
                # the live status already says error; keep the secondary
                # failure visible instead of erasing it
                self._set(key, flip_error=str(e2))
        except BaseException as e:
            # shutdown-class (KeyboardInterrupt/SystemExit/injected panic):
            # record the aborted build, then PROPAGATE — bg.run marks the
            # task failed and the interpreter keeps its shutdown signal
            self._set(key, status="error", error=str(e))
            raise

    def _flip_status(self, key, session, ns, db, tb, name, status: str) -> None:
        def flip(ctx, txn):
            d = txn.get_tb_index(ns, db, tb, name)
            if d is not None:
                d["status"] = status
                txn.put_tb_index(ns, db, tb, name, d)

        self._chunk_txn(key, session, flip)
