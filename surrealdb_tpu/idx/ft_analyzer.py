"""Full-text analyzers: tokenizers + filters.

Role of the reference's analyzer machinery (reference:
core/src/idx/ft/analyzer/ — tokenizers blank/camel/class/punct in
tokenizer.rs, filters lowercase/uppercase/ascii/edgengram/ngram/snowball/
mapper in filter.rs:99-140). DEFINE ANALYZER definitions are stored by the
catalog; this module compiles one into a callable pipeline.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Iterable, List, Optional, Tuple

Token = Tuple[str, int, int]  # (text, start, end) byte offsets in chars


# ------------------------------------------------------------------ tokenizers
def _tok_blank(text: str) -> List[Token]:
    out = []
    for m in re.finditer(r"\S+", text):
        out.append((m.group(), m.start(), m.end()))
    return out


def _tok_punct(text: str) -> List[Token]:
    out = []
    for m in re.finditer(r"[^\s\W]+|\w+", text, re.UNICODE):
        out.append((m.group(), m.start(), m.end()))
    return out


def _split_further(tokens: List[Token], pattern: str) -> List[Token]:
    out: List[Token] = []
    rx = re.compile(pattern)
    for text, start, _ in tokens:
        pos = 0
        for m in rx.finditer(text):
            seg = m.group()
            out.append((seg, start + m.start(), start + m.end()))
    return out


def _tok_camel(tokens: List[Token]) -> List[Token]:
    """Split camelCase boundaries within existing tokens."""
    out: List[Token] = []
    for text, start, end in tokens:
        parts = re.finditer(r"[A-Z]+(?![a-z])|[A-Z][a-z]*|[a-z]+|\d+", text)
        found = False
        for m in parts:
            found = True
            out.append((m.group(), start + m.start(), start + m.end()))
        if not found:
            out.append((text, start, end))
    return out


def _tok_class(tokens: List[Token]) -> List[Token]:
    """Split on character-class changes (letter/digit/punct)."""
    out: List[Token] = []
    for text, start, end in tokens:
        for m in re.finditer(r"[^\W\d_]+|\d+|[^\w\s]+", text, re.UNICODE):
            out.append((m.group(), start + m.start(), start + m.end()))
    return out


# ------------------------------------------------------------------ filters
def _f_lowercase(toks: List[Token]) -> List[Token]:
    return [(t.lower(), s, e) for t, s, e in toks]


def _f_uppercase(toks: List[Token]) -> List[Token]:
    return [(t.upper(), s, e) for t, s, e in toks]


def _f_ascii(toks: List[Token]) -> List[Token]:
    out = []
    for t, s, e in toks:
        nk = unicodedata.normalize("NFKD", t)
        out.append(("".join(c for c in nk if not unicodedata.combining(c)), s, e))
    return out


def _f_ngram(min_n: int, max_n: int):
    def f(toks: List[Token]) -> List[Token]:
        out = []
        for t, s, e in toks:
            for n in range(min_n, max_n + 1):
                for i in range(0, max(len(t) - n + 1, 0)):
                    out.append((t[i : i + n], s, e))
        return out

    return f


def _f_edgengram(min_n: int, max_n: int):
    def f(toks: List[Token]) -> List[Token]:
        out = []
        for t, s, e in toks:
            for n in range(min_n, min(max_n, len(t)) + 1):
                out.append((t[:n], s, e))
        return out

    return f


# A compact Porter-style English stemmer fills the reference's snowball role
# for `snowball(english)`; other languages pass through unstemmed.
_VOWELS = "aeiou"


def _porter_stem(w: str) -> str:
    if len(w) <= 2:
        return w
    for suf, rep in (
        ("sses", "ss"), ("ies", "i"), ("ss", "ss"), ("s", ""),
    ):
        if w.endswith(suf):
            if suf == "s" and w.endswith(("us", "ss")):
                break
            w = w[: len(w) - len(suf)] + rep
            break
    for suf in ("eed", "ed", "ing"):
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if suf == "eed":
                if _measure(stem) > 0:
                    w = stem + "ee"
            elif any(c in _VOWELS for c in stem):
                w = stem
                if w.endswith(("at", "bl", "iz")):
                    w += "e"
                elif len(w) > 1 and w[-1] == w[-2] and w[-1] not in "lsz":
                    w = w[:-1]
                elif _measure(w) == 1 and _cvc(w):
                    w += "e"
            break
    for suf, rep in (
        ("ational", "ate"), ("tional", "tion"), ("izer", "ize"), ("iveness", "ive"),
        ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"), ("biliti", "ble"),
        ("ation", "ate"), ("ator", "ate"), ("alism", "al"), ("iviti", "ive"),
        ("ement", ""), ("ment", ""), ("ent", ""), ("tion", "t"), ("ence", ""),
        ("ance", ""), ("able", ""), ("ible", ""), ("ize", ""), ("ive", ""),
        ("ous", ""), ("iti", ""), ("al", ""), ("er", ""), ("ic", ""),
    ):
        if w.endswith(suf):
            stem = w[: len(w) - len(suf)]
            if _measure(stem) > 1:
                w = stem + rep
            break
    if w.endswith("e") and _measure(w[:-1]) > 1:
        w = w[:-1]
    return w


def _measure(w: str) -> int:
    m = 0
    prev_v = False
    for c in w:
        v = c in _VOWELS
        if prev_v and not v:
            m += 1
        prev_v = v
    return m


def _cvc(w: str) -> bool:
    if len(w) < 3:
        return False
    c1, v, c2 = w[-3] not in _VOWELS, w[-2] in _VOWELS, w[-1] not in _VOWELS
    return c1 and v and c2 and w[-1] not in "wxy"


def _f_snowball(lang: str):
    if str(lang).lower() in ("english", "en"):
        return lambda toks: [(_porter_stem(t), s, e) for t, s, e in toks]
    return lambda toks: toks


# ------------------------------------------------------------------ compiler
class Analyzer:
    """Compiled DEFINE ANALYZER pipeline."""

    def __init__(self, definition: Optional[dict]):
        d = definition or {}
        self.tokenizers = [t.lower() for t in d.get("tokenizers", ["blank"])] or ["blank"]
        self.filters = []
        for f in d.get("filters", []):
            name = f["name"].lower()
            args = f.get("args", [])
            if name == "lowercase":
                self.filters.append(_f_lowercase)
            elif name == "uppercase":
                self.filters.append(_f_uppercase)
            elif name == "ascii":
                self.filters.append(_f_ascii)
            elif name == "ngram":
                self.filters.append(_f_ngram(int(args[0]), int(args[1])))
            elif name == "edgengram":
                self.filters.append(_f_edgengram(int(args[0]), int(args[1])))
            elif name == "snowball":
                self.filters.append(_f_snowball(args[0] if args else "english"))
            # mapper (lemma files) accepted but inert until file loading lands

        # Offset-free fast mode: a blank tokenizer with only case filters is
        # exactly str.split() over the case-folded text. Bulk ingest (which
        # never needs highlight offsets) rides this; any other pipeline
        # falls back to the full analyzer.
        names = [f["name"].lower() for f in (d.get("filters") or [])]
        if self.tokenizers == ["blank"] and all(
            n in ("lowercase", "uppercase") for n in names
        ):
            if "lowercase" in names:
                self._fast = "lower"
            elif "uppercase" in names:
                self._fast = "upper"
            else:
                self._fast = "plain"
        else:
            self._fast = None

    def terms_fast(self, text: str) -> List[str]:
        """Term list without offsets — cheap path for bulk indexing."""
        if self._fast == "lower":
            return text.lower().split()
        if self._fast == "upper":
            return text.upper().split()
        if self._fast == "plain":
            return text.split()
        return self.terms(text)

    def analyze(self, text: str) -> List[Token]:
        toks = _tok_blank(text)
        if "punct" in self.tokenizers:
            toks = _split_further(toks, r"\w+|[^\w\s]+")
        if "class" in self.tokenizers:
            toks = _tok_class(toks)
        if "camel" in self.tokenizers:
            toks = _tok_camel(toks)
        for f in self.filters:
            toks = f(toks)
        return [t for t in toks if t[0]]

    def terms(self, text: str) -> List[str]:
        return [t for t, _, _ in self.analyze(text)]


DEFAULT_LIKE = Analyzer(
    {"tokenizers": ["blank", "class"], "filters": [{"name": "lowercase", "args": []}]}
)


def analyzer_for(ctx, name: Optional[str]) -> Analyzer:
    """Resolve an analyzer by catalog name; the built-in fallback mirrors the
    reference's default `like` behavior."""
    if not name or name == "like":
        return DEFAULT_LIKE
    ns, db = ctx.ns_db()
    d = ctx.txn().get_az(ns, db, name)
    if d is None:
        from surrealdb_tpu.err import AzNotFoundError

        raise AzNotFoundError(name)
    return Analyzer(d)
