"""Embedded (local) SDK engine: owns a Datastore in-process.

Role of the reference's engine/local (reference: sdk/src/api/engine/local/
native.rs — translates Method::* into Datastore calls, routes live
notifications to per-query channels).
"""

from __future__ import annotations

import queue
from typing import Any, List, Optional

from surrealdb_tpu.dbs.session import Session
from surrealdb_tpu.kvs.ds import Datastore
from surrealdb_tpu.rpc.method import RpcContext


class LocalEngine:
    def __init__(self, endpoint: str):
        scheme, _, rest = endpoint.partition("://")
        if scheme in ("mem", "memory"):
            path = "memory"
        else:
            path = f"{scheme}://{rest}"
        self.ds = Datastore(path)
        self.ds.enable_notifications()
        self.session = Session.owner(None, None)
        self.rpc_ctx = RpcContext(self.ds, self.session)

    def rpc(self, method: str, params: List[Any]) -> Any:
        # SDK ingress: the embedded engine mints the request trace here so
        # local calls get the same span trees as HTTP/WS ones (tracing.py)
        from surrealdb_tpu import tracing

        with tracing.request("sdk_rpc", method=method.lower()):
            return self.rpc_ctx.execute(method, params)

    def next_notification(self, live_id: str, timeout: Optional[float]):
        hub = self.ds.notifications
        if hub is None:
            return None
        q = hub.subscribe(live_id)
        try:
            n = q.get(timeout=timeout) if timeout else q.get_nowait()
            return n.to_value()
        except queue.Empty:
            return None

    def debug_bundle(self) -> dict:
        """Embedded flight-recorder bundle (the GET /debug/bundle payload)
        without standing up a server — attach it to any perf report."""
        from surrealdb_tpu.bundle import debug_bundle

        return debug_bundle(self.ds)

    def export(self) -> str:
        from surrealdb_tpu.kvs.export import export_database

        return export_database(self.ds, self.session)

    def import_(self, text: str) -> None:
        from surrealdb_tpu.kvs.export import import_database

        import_database(self.ds, self.session, text)

    def import_model(self, spec: dict) -> dict:
        from surrealdb_tpu.ml.exec import import_model

        return import_model(
            self.ds, self.session, spec.get("name", ""), spec.get("version", ""), spec
        )

    def import_surml(self, raw: bytes) -> dict:
        from surrealdb_tpu.ml.exec import import_surml

        return import_surml(self.ds, self.session, raw)

    def export_model(self, name: str, version: str) -> dict:
        from surrealdb_tpu.ml.exec import export_model

        return export_model(self.ds, self.session, name, version)

    def close(self) -> None:
        self.ds.close()
