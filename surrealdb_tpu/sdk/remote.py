"""Remote SDK engines: HTTP and WebSocket.

Role of the reference's engine/remote (reference: sdk/src/api/engine/remote/
— ws via tungstenite, http via reqwest). Wire format is msgpack (the
full-fidelity codec); the WS engine runs a reader thread routing responses
by request id and live notifications into per-query queues.
"""

from __future__ import annotations

import http.client
import itertools
import queue
import socket
from surrealdb_tpu.utils import locks as _locks
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

from surrealdb_tpu.err import SurrealError
from surrealdb_tpu.net import ws as wsproto
from surrealdb_tpu.utils.ser import wire_pack as pack, wire_unpack as unpack


class HttpEngine:
    def __init__(self, endpoint: str, **opts):
        u = urlparse(endpoint)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.tls = u.scheme == "https"
        self.headers: Dict[str, str] = {}
        self._session_params: List[Any] = []
        # wire format: msgpack (default) | cbor | json (reference SDKs
        # negotiate per-connection, core/src/rpc/format/mod.rs)
        self.format = opts.get("format", "msgpack")
        if self.format not in ("msgpack", "cbor", "json"):
            raise SurrealError(f"unknown wire format {self.format!r}")

    def rpc(self, method: str, params: List[Any]) -> Any:
        # HTTP is stateless: replay use/auth state as headers
        if method == "use":
            if params and params[0]:
                self.headers["surreal-ns"] = str(params[0])
            if len(params) > 1 and params[1]:
                self.headers["surreal-db"] = str(params[1])
            return None
        if method == "authenticate" and params:
            self.headers["Authorization"] = f"Bearer {params[0]}"
            return None
        resp = self._post("/rpc", {"id": 1, "method": method, "params": params})
        if "error" in resp and resp["error"]:
            raise SurrealError(resp["error"].get("message", "RPC error"))
        result = resp.get("result")
        if method in ("signin", "signup") and isinstance(result, str):
            self.headers["Authorization"] = f"Bearer {result}"
        return result

    def _conn(self, timeout: int = 30):
        cls = http.client.HTTPSConnection if self.tls else http.client.HTTPConnection
        return cls(self.host, self.port, timeout=timeout)

    def _encode(self, body: Any) -> bytes:
        if self.format == "cbor":
            from surrealdb_tpu.rpc import cbor as _cbor

            return _cbor.encode(body)
        if self.format == "json":
            import json as _json

            from surrealdb_tpu.sql.value import to_json_value

            return _json.dumps(to_json_value(body)).encode()
        return pack(body)

    def _decode(self, data: bytes) -> Any:
        if self.format == "cbor":
            from surrealdb_tpu.rpc import cbor as _cbor

            return _cbor.decode(data)
        if self.format == "json":
            import json as _json

            return _json.loads(data)
        return unpack(data)

    def _post(self, path: str, body: Any) -> Any:
        conn = self._conn()
        try:
            headers = {
                "Content-Type": f"application/{self.format}",
                **self.headers,
            }
            conn.request("POST", path, self._encode(body), headers)
            r = conn.getresponse()
            data = r.read()
            if r.status == 401:
                raise SurrealError("Authentication failed")
            return self._decode(data)
        finally:
            conn.close()

    def next_notification(self, live_id: str, timeout: Optional[float]):
        raise SurrealError("Live queries require a WebSocket connection")

    def export(self) -> str:
        conn = self._conn(timeout=60)
        try:
            conn.request("GET", "/export", headers=self.headers)
            return conn.getresponse().read().decode()
        finally:
            conn.close()

    def import_(self, text: str) -> None:
        conn = self._conn(timeout=120)
        try:
            conn.request("POST", "/import", text.encode(), self.headers)
            conn.getresponse().read()
        finally:
            conn.close()

    def import_surml(self, raw: bytes) -> dict:
        import json as _json

        conn = self._conn(timeout=120)
        try:
            hdrs = {**self.headers, "Content-Type": "application/octet-stream"}
            conn.request("POST", "/ml/import", raw, hdrs)
            resp = conn.getresponse()
            out = _json.loads(resp.read())
            if resp.status != 200:
                raise SurrealError(out.get("error", "model import failed"))
            return out
        finally:
            conn.close()

    def import_model(self, spec: dict) -> dict:
        import json as _json

        conn = self._conn(timeout=120)
        try:
            hdrs = {**self.headers, "Content-Type": "application/json"}
            conn.request("POST", "/ml/import", _json.dumps(spec).encode(), hdrs)
            resp = conn.getresponse()
            out = _json.loads(resp.read())
            if resp.status != 200:
                raise SurrealError(out.get("error", "model import failed"))
            return out
        finally:
            conn.close()

    def export_model(self, name: str, version: str) -> dict:
        import json as _json
        from urllib.parse import quote

        conn = self._conn(timeout=120)
        try:
            conn.request(
                "GET", f"/ml/export/{quote(name, safe='')}/{quote(version, safe='')}",
                headers=self.headers,
            )
            resp = conn.getresponse()
            out = _json.loads(resp.read())
            if resp.status != 200:
                raise SurrealError(out.get("error", "model export failed"))
            return out
        finally:
            conn.close()

    def close(self) -> None:
        pass


class WsEngine:
    def __init__(self, endpoint: str, **opts):
        u = urlparse(endpoint)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 8000
        path = u.path or "/rpc"
        self.sock = socket.create_connection((self.host, self.port), timeout=30)
        leftover = wsproto.client_handshake(self.sock, f"{self.host}:{self.port}", path)
        self.sock.settimeout(None)
        self._rsock = wsproto.BufferedSocket(self.sock, leftover)
        self._ids = itertools.count(1)
        self._pending: Dict[int, "queue.Queue[Any]"] = {}
        self._notifications: Dict[str, "queue.Queue[Any]"] = {}
        self._lock = _locks.Lock("sdk.ws_client")
        self._closed = False
        # registered service thread (graftlint GL001): the reader shows up
        # in the task registry as bg:sdk_reader:<host>:<port> instead of an
        # anonymous daemon — embedded test/SDK processes share the registry
        from surrealdb_tpu import bg

        self._reader = bg.spawn_service(
            "sdk_reader", f"{self.host}:{self.port}", self._read_loop
        )

    def _read_loop(self) -> None:
        try:
            while not self._closed:
                op, payload = wsproto.read_frame(self._rsock)
                if op == wsproto.OP_CLOSE:
                    return
                if op == wsproto.OP_PING:
                    self.sock.sendall(
                        wsproto.encode_frame(wsproto.OP_PONG, payload, mask=True)
                    )
                    continue
                if op != wsproto.OP_BINARY:
                    continue
                msg = unpack(payload)
                mid = msg.get("id")
                if mid is None:
                    # live notification push
                    n = msg.get("result") or {}
                    lid = str(n.get("id"))
                    with self._lock:
                        q = self._notifications.setdefault(lid, queue.Queue())
                    q.put(n)
                    continue
                with self._lock:
                    q = self._pending.pop(mid, None)
                if q is not None:
                    q.put(msg)
        except (ConnectionError, OSError):
            pass

    def rpc(self, method: str, params: List[Any]) -> Any:
        mid = next(self._ids)
        q: "queue.Queue[Any]" = queue.Queue()
        with self._lock:
            self._pending[mid] = q
        frame = wsproto.encode_frame(
            wsproto.OP_BINARY, pack({"id": mid, "method": method, "params": params}), mask=True
        )
        self.sock.sendall(frame)
        msg = q.get(timeout=60)
        if msg.get("error"):
            raise SurrealError(msg["error"].get("message", "RPC error"))
        return msg.get("result")

    def next_notification(self, live_id: str, timeout: Optional[float]):
        with self._lock:
            q = self._notifications.setdefault(live_id, queue.Queue())
        try:
            return q.get(timeout=timeout) if timeout else q.get_nowait()
        except queue.Empty:
            return None

    def export(self) -> str:
        raise SurrealError("export over WebSocket is not supported; use HTTP")

    def import_(self, text: str) -> None:
        raise SurrealError("import over WebSocket is not supported; use HTTP")

    def import_surml(self, raw: bytes) -> dict:
        import json as _json

        conn = self._conn(timeout=120)
        try:
            hdrs = {**self.headers, "Content-Type": "application/octet-stream"}
            conn.request("POST", "/ml/import", raw, hdrs)
            resp = conn.getresponse()
            out = _json.loads(resp.read())
            if resp.status != 200:
                raise SurrealError(out.get("error", "model import failed"))
            return out
        finally:
            conn.close()

    def import_model(self, spec: dict) -> dict:
        raise SurrealError("model import over WebSocket is not supported; use HTTP")

    def export_model(self, name: str, version: str) -> dict:
        raise SurrealError("model export over WebSocket is not supported; use HTTP")

    def close(self) -> None:
        self._closed = True
        try:
            self.sock.sendall(wsproto.encode_frame(wsproto.OP_CLOSE, b"", mask=True))
            self.sock.close()
        except OSError:
            pass
