"""Typed client SDK.

Role of the reference SDK (reference: sdk/src/api — `Surreal<C>` method
builders, `engine/local` embedding a Datastore, `engine/remote` speaking
WS/HTTP, `engine/any` picking by URL scheme). The Python surface:

    db = Surreal("mem://")                # embedded, in-memory
    db = Surreal("file:///data/db")       # embedded, persistent
    db = Surreal("http://host:8000")      # remote HTTP
    db = Surreal("ws://host:8000/rpc")    # remote WebSocket
    db.use("ns", "db")
    db.signin(user="root", password="root")
    db.query("SELECT * FROM person WHERE age > $min", {"min": 18})
    db.create("person", {"name": "x"}); db.select("person:1"); ...
    stream = db.live("person"); stream.next(timeout=1)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from surrealdb_tpu.err import SurrealError


class Surreal:
    def __init__(self, endpoint: str = "mem://", **opts):
        self.endpoint = endpoint
        scheme = endpoint.split("://", 1)[0].lower()
        if scheme in ("mem", "memory", "file", "surrealkv", "rocksdb"):
            from .local import LocalEngine

            self._engine = LocalEngine(endpoint)
        elif scheme in ("http", "https"):
            from .remote import HttpEngine

            self._engine = HttpEngine(endpoint, **opts)
        elif scheme in ("ws", "wss"):
            from .remote import WsEngine

            self._engine = WsEngine(endpoint, **opts)
        else:
            raise SurrealError(f"Unsupported endpoint scheme {scheme!r}")

    # ------------------------------------------------------------ session
    def use(self, ns: Optional[str] = None, db: Optional[str] = None) -> "Surreal":
        self._engine.rpc("use", [ns, db])
        return self

    def signin(self, **creds) -> str:
        mapped = {}
        for k, v in creds.items():
            mapped[{"user": "user", "username": "user", "password": "pass"}.get(k, k)] = v
        return self._engine.rpc("signin", [mapped])

    def signup(self, **creds) -> str:
        return self._engine.rpc("signup", [creds])

    def authenticate(self, token: str) -> None:
        self._engine.rpc("authenticate", [token])

    def invalidate(self) -> None:
        self._engine.rpc("invalidate", [])

    def let(self, name: str, value: Any) -> None:
        self._engine.rpc("let", [name, value])

    def unset(self, name: str) -> None:
        self._engine.rpc("unset", [name])

    def info(self) -> Any:
        return self._engine.rpc("info", [])

    def version(self) -> str:
        return self._engine.rpc("version", [])

    def ping(self) -> None:
        self._engine.rpc("ping", [])

    # ------------------------------------------------------------ querying
    def query(self, text: str, vars: Optional[Dict[str, Any]] = None) -> List[dict]:
        return self._engine.rpc("query", [text, vars or {}])

    def select(self, what: str) -> Any:
        return self._engine.rpc("select", [what])

    def create(self, what: str, data: Optional[dict] = None) -> Any:
        return self._engine.rpc("create", [what, data])

    def insert(self, what: str, data: Any) -> Any:
        return self._engine.rpc("insert", [what, data])

    def insert_relation(self, what: str, data: Any) -> Any:
        return self._engine.rpc("insert_relation", [what, data])

    def update(self, what: str, data: Optional[dict] = None) -> Any:
        return self._engine.rpc("update", [what, data])

    def upsert(self, what: str, data: Optional[dict] = None) -> Any:
        return self._engine.rpc("upsert", [what, data])

    def merge(self, what: str, data: dict) -> Any:
        return self._engine.rpc("merge", [what, data])

    def patch(self, what: str, ops: List[dict]) -> Any:
        return self._engine.rpc("patch", [what, ops])

    def delete(self, what: str) -> Any:
        return self._engine.rpc("delete", [what])

    def relate(self, from_: str, kind: str, to: str, data: Optional[dict] = None) -> Any:
        return self._engine.rpc("relate", [from_, kind, to, data])

    def run(self, name: str, version: Optional[str] = None, args: Optional[list] = None) -> Any:
        return self._engine.rpc("run", [name, version, args or []])

    # ------------------------------------------------------------ realtime
    def live(self, table: str, diff: bool = False) -> "LiveStream":
        live_id = self._engine.rpc("live", [table, diff])
        return LiveStream(self, live_id)

    def kill(self, live_id) -> None:
        self._engine.rpc("kill", [str(live_id)])

    # ------------------------------------------------------------ export/import
    def export(self) -> str:
        return self._engine.export()

    def import_(self, text: str) -> None:
        self._engine.import_(text)

    def import_surml(self, raw: bytes) -> dict:
        """Import a surrealml `.surml` model file."""
        return self._engine.import_surml(raw)

    def import_model(self, spec: dict) -> dict:
        """Store an ML model (spec dict with weights) for ml:: calls."""
        return self._engine.import_model(spec)

    def export_model(self, name: str, version: str = "") -> dict:
        return self._engine.export_model(name, version)

    def close(self) -> None:
        self._engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LiveStream:
    """Notifications for one LIVE query (reference sdk Stream type)."""

    def __init__(self, client: Surreal, live_id):
        self.client = client
        # normalize Uuid values to the bare hex-dash string used as hub key
        self.id = str(getattr(live_id, "value", live_id))

    def next(self, timeout: Optional[float] = 1.0):
        return self.client._engine.next_notification(str(self.id), timeout)

    def drain(self) -> list:
        out = []
        while True:
            n = self.next(timeout=0.0)
            if n is None:
                return out
            out.append(n)

    def close(self) -> None:
        self.client.kill(self.id)
