"""Changefeed garbage collection.

Role of the reference's cf GC (reference: core/src/cf/gc.rs — per-database
watermark = now minus the longest CHANGEFEED retention among the database
and its tables; change entries older than the watermark are deleted on the
node tick)."""

from __future__ import annotations

from typing import List, Tuple

from surrealdb_tpu import key as keys
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.utils.ser import unpack


def gc_all(ds) -> int:
    """One GC sweep over every database; returns entries deleted. Each
    sweep is a flight-recorder task (bg.py): the server tick loop runs it
    unsupervised, so a wedged sweep must surface as `stalled`, not as an
    unexplained commit-lock stall."""
    from surrealdb_tpu import bg

    task_id = bg.register(
        "changefeed_gc", target=ds.path, owner=id(ds), trace_id=None
    )
    deleted = 0
    with bg.run(task_id, rename_thread=False):
        from surrealdb_tpu import faults

        # chaos hook: a GC sweep that dies must surface through the task
        # registry (failed) and the tick loop's supervision — never wedge
        # the commit lock or leak its transaction
        faults.fire("cf.gc")
        txn = ds.transaction(write=True)
        try:
            now = ds.clock.now_nanos()
            for ns_def in txn.all_ns():
                ns = ns_def["name"]
                for db_def in txn.all_db(ns):
                    db = db_def["name"]
                    retention = _max_retention(txn, ns, db, db_def)
                    if retention is None:
                        continue
                    watermark = now - retention
                    deleted += _gc_db(txn, ns, db, watermark)
            if deleted:
                txn.commit()
            else:
                txn.cancel()
        except BaseException:
            txn.cancel()
            raise
    if not deleted:
        # an uneventful sweep (the overwhelmingly common case on the 10s
        # tick) must not flood the bounded finished-task ring
        bg.forget(task_id)
    return deleted


def _max_retention(txn, ns: str, db: str, db_def: dict):
    """Longest retention among the db's own CHANGEFEED and its tables'."""
    out = None
    cf = db_def.get("changefeed")
    if cf:
        out = cf.get("expiry", 0)
    for tb_def in txn.all_tb(ns, db):
        cf = tb_def.get("changefeed")
        if cf:
            e = cf.get("expiry", 0)
            out = e if out is None else max(out, e)
    return out


def _gc_db(txn, ns: str, db: str, watermark: int) -> int:
    pre = keys.change_prefix(ns, db)
    dead: List[bytes] = []
    for k, raw in txn.scan(pre, prefix_end(pre)):
        entry = unpack(raw)
        ts = entry.get("ts")
        if ts is None:
            continue  # pre-timestamp entries: never GC'd (age unknown)
        if ts >= watermark:
            break  # vs-ordered keys are time-ordered; the rest is retained
        dead.append(k)
    for k in dead:
        txn.delete(k)
    return len(dead)
