"""Changefeed reading: SHOW CHANGES FOR TABLE ... SINCE ...

Role of the reference's cf reader (reference: core/src/cf/reader.rs): scan
the versionstamped change keys of the database and surface each ChangeSet as
{versionstamp, changes: [...]}.
"""

from __future__ import annotations

from typing import Any, List

from surrealdb_tpu import key as keys
from surrealdb_tpu.err import SurrealError
from surrealdb_tpu.key.encode import prefix_end
from surrealdb_tpu.kvs.vs import vs_to_u64, u64_to_vs
from surrealdb_tpu.sql.value import Datetime
from surrealdb_tpu.utils.ser import unpack


def show_changes(ctx, stm) -> List[dict]:
    ns, db = ctx.ns_db()
    txn = ctx.txn()

    db_def = txn.get_db(ns, db)
    tb_def = txn.get_tb(ns, db, stm.table) if stm.table else None
    has_cf = (db_def or {}).get("changefeed") or (tb_def or {}).get("changefeed")
    if not has_cf:
        raise SurrealError(
            f"Change feed for table '{stm.table}' is not enabled"
            if stm.table
            else f"Change feed for database '{db}' is not enabled"
        )

    since_vs = 0
    since_ts = None
    if stm.since is not None:
        v = stm.since.compute(ctx) if hasattr(stm.since, "compute") else stm.since
        if isinstance(v, Datetime):
            # datetime SINCE: entries carry their commit timestamp; skip
            # those older than the requested instant (keys are vs-ordered =
            # time-ordered, so the retained scan stays bounded by GC)
            since_ts = v.nanos
        else:
            since_vs = int(v)

    beg = keys.change(ns, db, u64_to_vs(since_vs))
    end = prefix_end(keys.change_prefix(ns, db))
    # the LIMIT counts RETURNED change sets, so it must apply after the
    # ts filter, not to the raw key scan
    limit = stm.limit if stm.limit is not None else None

    out: List[dict] = []
    for k, raw in txn.scan(beg, end):
        if limit is not None and len(out) >= limit:
            break
        entry = unpack(raw)
        ts = entry.get("ts")
        # entries written before timestamps existed replay (never drop)
        if since_ts is not None and ts is not None and ts < since_ts:
            continue
        vs = keys.decode_change(k, ns, db)
        changes: List[Any] = []
        for tb, muts in entry.get("tables", {}).items():
            if stm.table and tb != stm.table:
                continue
            for m in muts:
                if m.get("delete"):
                    changes.append({"delete": {"id": m["id"]}})
                elif "bulk_ids" in m:
                    # batch entry (bulk ingest): the entry stores record ids
                    # only; expand each to its committed document via a
                    # versioned read pinned at the entry's own commit
                    # version, so replay shows exactly the bulk-op values
                    # even after later updates. Backends without MVCC
                    # version tracking expand with the current value.
                    changes.extend(_expand_bulk(txn, ns, db, tb, k, m["bulk_ids"]))
                else:
                    changes.append({"update": m.get("update")})
        if changes:
            out.append({"versionstamp": vs_to_u64(vs), "changes": changes})
    return out


def _expand_bulk(txn, ns: str, db: str, tb: str, entry_key: bytes, ids) -> List[dict]:
    """Reader-side expansion of a bulk changefeed entry: one `{update: doc}`
    per surviving record id. Records whose pinned version was GC'd past the
    MVCC horizon expand with the oldest retained value (same best-effort
    contract as retention GC); records deleted before their bulk entry was
    read are skipped."""
    ver = txn.tr.version_of(entry_key)
    out: List[dict] = []
    for id_ in ids:
        k = keys.thing(ns, db, tb, id_)
        raw = txn.tr.get(k, ver)
        if raw is None and ver is not None:
            # pinned version GC'd past the MVCC horizon: fall back to the
            # oldest retained value (retention-GC contract) — None there
            # too means the record is genuinely gone
            raw = txn.tr.oldest_retained(k)
        if raw is None:
            continue
        out.append({"update": unpack(raw)})
    return out
