"""RPC method dispatch.

Role of the reference's Method enum + RpcContext::execute (reference:
core/src/rpc/method.rs:3, rpc_context.rs, basic_context.rs): one
transport-agnostic entry point mapping method names + params onto the
Datastore, tracking per-connection session state (USE, LET, auth).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from surrealdb_tpu.err import InvalidAuthError, SurrealError
from surrealdb_tpu.sql.value import NONE, Table, Thing, Uuid, format_value, is_nullish

METHODS = {
    "ping",
    "info",
    "use",
    "signup",
    "signin",
    "authenticate",
    "invalidate",
    "reset",
    "kill",
    "live",
    "set",
    "let",
    "unset",
    "select",
    "insert",
    "insert_relation",
    "create",
    "upsert",
    "update",
    "merge",
    "patch",
    "delete",
    "relate",
    "run",
    "query",
    "version",
    "graphql",
}


class RpcContext:
    """One client connection's RPC state."""

    def __init__(self, ds, session):
        self.ds = ds
        self.session = session
        self.vars: Dict[str, Any] = {}
        self.live_ids: set = set()  # live queries owned by this connection

    def close(self) -> None:
        """Disconnect sweep: KILL every live query this connection still
        owns. Without this, a WS close/error path leaves the registrations
        live forever — the notification hub keeps buffering matches for a
        subscriber that will never drain them (the r19 leak). Each kill is
        independent: one failure (live id already archived by a node
        takeover) must not strand the rest."""
        from surrealdb_tpu import telemetry

        ids, self.live_ids = list(self.live_ids), set()
        for live_id in ids:
            try:
                self._query("KILL $_id", {"_id": _as_uuid(live_id)})
            except Exception:  # noqa: BLE001 — already-dead registration
                telemetry.inc("live_disconnect_kill_errors")

    # ------------------------------------------------------------ dispatch
    def execute(self, method: str, params: Optional[List[Any]] = None) -> Any:
        from surrealdb_tpu import telemetry, tracing

        params = params or []
        m = method.lower()
        if m not in METHODS:
            # bounded label: arbitrary client-supplied names must not mint
            # unbounded metric series
            telemetry.inc("rpc_errors", method="_unknown", error="MethodNotFound")
            raise SurrealError(f"Method '{method}' not found")

        # one seam covers BOTH the HTTP /rpc route and the WS actor
        # (reference: src/telemetry/metrics/ws/ rpc method instrumentation).
        # tracing.request mints the root trace for embedded SDK callers; under
        # an HTTP/WS ingress the rpc_method span below is the nested node.
        telemetry.inc("rpc_requests", method=m)
        try:
            # nest=False: under an HTTP/WS/SDK ingress root the rpc_method
            # span below IS the node; a second wrapper would only duplicate it
            with tracing.request("rpc", method=m, nest=False), telemetry.span(
                "rpc_method", method=m
            ):
                return getattr(self, f"_m_{m}")(params)
        except Exception as e:
            telemetry.inc("rpc_errors", method=m, error=telemetry.error_class(e))
            raise

    # ------------------------------------------------------------ helpers
    def _query(self, text: str, vars: Optional[Dict[str, Any]] = None) -> List[dict]:
        merged = dict(self.vars)
        if vars:
            merged.update(vars)
        return self.ds.execute(text, self.session, merged)

    def _one_result(self, responses: List[dict]) -> Any:
        resp = responses[-1]
        if resp["status"] != "OK":
            raise SurrealError(str(resp["result"]))
        return resp["result"]

    @staticmethod
    def _target(what: Any) -> str:
        if isinstance(what, Thing):
            return repr(what)
        if isinstance(what, (Table, str)):
            from surrealdb_tpu.sql.value import escape_ident

            s = str(what)
            if ":" in s:
                return repr(Thing.parse(s))
            return escape_ident(s)
        raise SurrealError(f"Invalid target {format_value(what)}")

    # ------------------------------------------------------------ methods
    def _m_ping(self, p) -> Any:
        return NONE

    def _m_version(self, p) -> Any:
        from surrealdb_tpu import __version__

        return f"surrealdb-tpu-{__version__}"

    def _m_info(self, p) -> Any:
        return self._one_result(self._query("SELECT * FROM $auth"))

    def _m_use(self, p) -> Any:
        ns = p[0] if len(p) > 0 else None
        db = p[1] if len(p) > 1 else None
        if ns and not is_nullish(ns):
            self.session.ns = str(ns)
        if db and not is_nullish(db):
            self.session.db = str(db)
        return NONE

    def _m_set(self, p) -> Any:
        if len(p) < 2:
            raise SurrealError("set expects [name, value]")
        self.vars[str(p[0]).lstrip("$")] = p[1]
        return NONE

    _m_let = _m_set

    def _m_unset(self, p) -> Any:
        if p:
            self.vars.pop(str(p[0]).lstrip("$"), None)
        return NONE

    def _m_signin(self, p) -> Any:
        from surrealdb_tpu.iam.signin import signin

        creds = p[0] if p else {}
        return signin(self.ds, self.session, creds)

    def _m_signup(self, p) -> Any:
        from surrealdb_tpu.iam.signup import signup

        creds = p[0] if p else {}
        return signup(self.ds, self.session, creds)

    def _m_authenticate(self, p) -> Any:
        from surrealdb_tpu.iam.token import authenticate

        token = p[0] if p else None
        if not isinstance(token, str):
            raise InvalidAuthError()
        authenticate(self.ds, self.session, token)
        return NONE

    def _m_invalidate(self, p) -> Any:
        from surrealdb_tpu.dbs.session import Auth

        self.session.auth = Auth()
        return NONE

    def _m_reset(self, p) -> Any:
        self.vars = {}
        return self._m_invalidate(p)

    def _m_query(self, p) -> Any:
        if not p or not isinstance(p[0], str):
            raise SurrealError("query expects [text, vars?]")
        vars = p[1] if len(p) > 1 and isinstance(p[1], dict) else None
        return self._query(p[0], vars)

    def _m_select(self, p) -> Any:
        what = self._target(p[0])
        return self._one_result(self._query(f"SELECT * FROM {what}"))

    def _m_create(self, p) -> Any:
        what = self._target(p[0])
        data = p[1] if len(p) > 1 else None
        q = f"CREATE {what}"
        vars = None
        if data is not None:
            q += " CONTENT $_data"
            vars = {"_data": data}
        return self._one_result(self._query(q, vars))

    def _m_insert(self, p) -> Any:
        what = self._target(p[0]) if p and p[0] else None
        data = p[1] if len(p) > 1 else {}
        q = "INSERT INTO " + what if what else "INSERT"
        return self._one_result(self._query(q + " $_data", {"_data": data}))

    def _m_insert_relation(self, p) -> Any:
        what = self._target(p[0]) if p and p[0] else None
        data = p[1] if len(p) > 1 else {}
        q = "INSERT RELATION INTO " + what if what else "INSERT RELATION"
        return self._one_result(self._query(q + " $_data", {"_data": data}))

    def _m_update(self, p) -> Any:
        what = self._target(p[0])
        data = p[1] if len(p) > 1 else None
        q = f"UPDATE {what}"
        vars = None
        if data is not None:
            q += " CONTENT $_data"
            vars = {"_data": data}
        return self._one_result(self._query(q, vars))

    def _m_upsert(self, p) -> Any:
        what = self._target(p[0])
        data = p[1] if len(p) > 1 else None
        q = f"UPSERT {what}"
        vars = None
        if data is not None:
            q += " CONTENT $_data"
            vars = {"_data": data}
        return self._one_result(self._query(q, vars))

    def _m_merge(self, p) -> Any:
        what = self._target(p[0])
        data = p[1] if len(p) > 1 else {}
        return self._one_result(
            self._query(f"UPDATE {what} MERGE $_data", {"_data": data})
        )

    def _m_patch(self, p) -> Any:
        what = self._target(p[0])
        data = p[1] if len(p) > 1 else []
        return self._one_result(
            self._query(f"UPDATE {what} PATCH $_data RETURN DIFF" if len(p) > 2 and p[2] else f"UPDATE {what} PATCH $_data", {"_data": data})
        )

    def _m_delete(self, p) -> Any:
        what = self._target(p[0])
        return self._one_result(self._query(f"DELETE {what} RETURN BEFORE"))

    def _m_relate(self, p) -> Any:
        if len(p) < 3:
            raise SurrealError("relate expects [from, kind, to, data?]")
        f = self._target(p[0])
        kind = self._target(p[1])
        w = self._target(p[2])
        q = f"RELATE {f}->{kind}->{w}"
        vars = None
        if len(p) > 3 and p[3] is not None:
            q += " CONTENT $_data"
            vars = {"_data": p[3]}
        return self._one_result(self._query(q, vars))

    def _m_run(self, p) -> Any:
        if not p:
            raise SurrealError("run expects [name, version?, args?]")
        name = str(p[0])
        args = p[2] if len(p) > 2 and isinstance(p[2], list) else []
        arg_params = {f"_a{i}": a for i, a in enumerate(args)}
        arg_txt = ", ".join(f"$_a{i}" for i in range(len(args)))
        return self._one_result(self._query(f"RETURN {name}({arg_txt})", arg_params))

    def _m_live(self, p) -> Any:
        what = self._target(p[0])
        diff = len(p) > 1 and bool(p[1])
        q = f"LIVE SELECT DIFF FROM {what}" if diff else f"LIVE SELECT * FROM {what}"
        out = self._one_result(self._query(q))
        self.live_ids.add(str(getattr(out, "value", out)))
        return out

    def _m_kill(self, p) -> Any:
        if not p:
            raise SurrealError("kill expects [id]")
        u = _as_uuid(p[0])
        self.live_ids.discard(str(u.value))
        return self._one_result(self._query("KILL $_id", {"_id": u}))

    def _m_graphql(self, p) -> Any:
        from surrealdb_tpu.gql import execute_graphql

        req = p[0] if p else {}
        if isinstance(req, str):
            req = {"query": req}
        return execute_graphql(self.ds, self.session, req)


def _as_uuid(v):
    import uuid as _uuid

    if isinstance(v, Uuid):
        return v
    return Uuid(_uuid.UUID(str(v)))
