"""CBOR wire format (RFC 8949) with SurrealDB's tag scheme.

Role of the reference's cbor format (reference: core/src/rpc/format/cbor/
convert.rs — the format real SurrealDB SDKs speak). Tags implemented
bidirectionally:

    0   datetime (RFC3339 text, decode)      12  datetime [secs, nanos]
    6   NONE                                 13  duration (text, decode)
    7   table                                14  duration [secs, nanos]
    8   record id (text or [tb, id])         37  uuid (bytes)
    9   uuid (text, decode)                  49  range  (50/51 bounds)
    10  decimal (text)                       88+ geometries

Self-contained encoder/decoder — no third-party cbor dependency exists in
this environment.
"""

from __future__ import annotations

import decimal as _decimal
import math
import struct
from typing import Any, List, Tuple

from surrealdb_tpu.err import SurrealError
from surrealdb_tpu.sql.value import (
    NONE,
    Datetime,
    Duration,
    Geometry,
    Null,
    Range,
    Table,
    Thing,
    Uuid,
    is_none,
    is_null,
)

TAG_SPEC_DATETIME = 0
TAG_NONE = 6
TAG_TABLE = 7
TAG_RECORDID = 8
TAG_STRING_UUID = 9
TAG_STRING_DECIMAL = 10
TAG_CUSTOM_DATETIME = 12
TAG_STRING_DURATION = 13
TAG_CUSTOM_DURATION = 14
TAG_SPEC_UUID = 37
TAG_RANGE = 49
TAG_BOUND_INCLUDED = 50
TAG_BOUND_EXCLUDED = 51
TAG_GEOMETRY_POINT = 88
TAG_GEOMETRY_LINE = 89
TAG_GEOMETRY_POLYGON = 90
TAG_GEOMETRY_MULTIPOINT = 91
TAG_GEOMETRY_MULTILINE = 92
TAG_GEOMETRY_MULTIPOLYGON = 93
TAG_GEOMETRY_COLLECTION = 94

_GEOM_TAGS = {
    "Point": TAG_GEOMETRY_POINT,
    "LineString": TAG_GEOMETRY_LINE,
    "Polygon": TAG_GEOMETRY_POLYGON,
    "MultiPoint": TAG_GEOMETRY_MULTIPOINT,
    "MultiLineString": TAG_GEOMETRY_MULTILINE,
    "MultiPolygon": TAG_GEOMETRY_MULTIPOLYGON,
    "GeometryCollection": TAG_GEOMETRY_COLLECTION,
}
_GEOM_NAMES = {v: k for k, v in _GEOM_TAGS.items()}


# ------------------------------------------------------------------ encoder
def _head(major: int, n: int) -> bytes:
    if n < 24:
        return bytes([(major << 5) | n])
    if n < 0x100:
        return bytes([(major << 5) | 24, n])
    if n < 0x10000:
        return bytes([(major << 5) | 25]) + struct.pack(">H", n)
    if n < 0x100000000:
        return bytes([(major << 5) | 26]) + struct.pack(">I", n)
    return bytes([(major << 5) | 27]) + struct.pack(">Q", n)


def _enc_tag(tag: int, payload: bytes) -> bytes:
    return _head(6, tag) + payload


def encode(v: Any) -> bytes:
    out = bytearray()
    _enc(v, out)
    return bytes(out)


def _enc(v: Any, out: bytearray) -> None:
    if is_none(v):
        out += _enc_tag(TAG_NONE, b"\xf6")  # tag 6 + null
        return
    if v is None or is_null(v):
        out += b"\xf6"
        return
    if isinstance(v, bool):
        out += b"\xf5" if v else b"\xf4"
        return
    if isinstance(v, int):
        if v >= 0:
            out += _head(0, v)
        else:
            out += _head(1, -1 - v)
        return
    if isinstance(v, float):
        out += b"\xfb" + struct.pack(">d", v)
        return
    if isinstance(v, _decimal.Decimal):
        s = format(v, "f")
        out += _enc_tag(TAG_STRING_DECIMAL, _head(3, len(s.encode())) + s.encode())
        return
    if isinstance(v, Table):  # before str — Table subclasses str
        out += _enc_tag(TAG_TABLE, encode(str(v)))
        return
    if isinstance(v, str):
        b = v.encode()
        out += _head(3, len(b)) + b
        return
    if isinstance(v, bytes):
        out += _head(2, len(v)) + v
        return
    if isinstance(v, Duration):
        secs, nanos = divmod(v.nanos, 1_000_000_000)
        if secs == 0 and nanos == 0:
            payload = encode([])
        elif nanos == 0:
            payload = encode([secs])
        else:
            payload = encode([secs, nanos])
        out += _enc_tag(TAG_CUSTOM_DURATION, payload)
        return
    if isinstance(v, Datetime):
        secs, nanos = divmod(v.nanos, 1_000_000_000)
        out += _enc_tag(TAG_CUSTOM_DATETIME, encode([secs, nanos]))
        return
    if isinstance(v, Uuid):
        out += _enc_tag(TAG_SPEC_UUID, _head(2, 16) + v.value.bytes)
        return
    if isinstance(v, Thing):
        out += _head(6, TAG_RECORDID)
        inner = bytearray()
        _enc(v.tb, inner)
        if isinstance(v.id, Range):
            inner += _enc_tag(TAG_RANGE, _enc_range_payload(v.id))
        else:
            _enc(v.id, inner)
        out += _head(4, 2) + inner
        return
    if isinstance(v, Range):
        out += _enc_tag(TAG_RANGE, _enc_range_payload(v))
        return
    if isinstance(v, Geometry):
        tag = _GEOM_TAGS.get(v.kind)
        if tag is None:
            raise SurrealError(f"cannot encode geometry {v.kind} as CBOR")
        out += _enc_tag(tag, encode(v.coords))
        return
    if isinstance(v, (list, tuple)):
        out += _head(4, len(v))
        for item in v:
            _enc(item, out)
        return
    if type(v).__name__ == "ndarray":  # packed vector -> plain CBOR array
        _enc(v.tolist(), out)
        return
    if isinstance(v, dict):
        out += _head(5, len(v))
        for k, item in v.items():
            _enc(str(k), out)
            _enc(item, out)
        return
    raise SurrealError(f"cannot encode {type(v).__name__} as CBOR")


def _enc_range_payload(r: Range) -> bytes:
    def bound(val, incl: bool) -> bytes:
        if is_none(val) or val is None:
            return b"\xf6"
        tag = TAG_BOUND_INCLUDED if incl else TAG_BOUND_EXCLUDED
        return _enc_tag(tag, encode(val))

    return _head(4, 2) + bound(r.beg, r.beg_incl) + bound(r.end, r.end_incl)


# ------------------------------------------------------------------ decoder
class _Dec:
    def __init__(self, data: bytes):
        self.b = data
        self.i = 0

    def u8(self) -> int:
        if self.i >= len(self.b):
            raise SurrealError("truncated CBOR")
        v = self.b[self.i]
        self.i += 1
        return v

    def peek(self) -> int:
        if self.i >= len(self.b):
            raise SurrealError("truncated CBOR")
        return self.b[self.i]

    def read(self, n: int) -> bytes:
        if n < 0 or self.i + n > len(self.b):
            raise SurrealError("truncated CBOR")
        v = self.b[self.i : self.i + n]
        self.i += n
        return v

    def length(self, info: int) -> int:
        if info < 24:
            return info
        if info == 24:
            return self.u8()
        if info == 25:
            return struct.unpack(">H", self.read(2))[0]
        if info == 26:
            return struct.unpack(">I", self.read(4))[0]
        if info == 27:
            return struct.unpack(">Q", self.read(8))[0]
        if info == 31:
            return -1  # indefinite
        raise SurrealError("bad CBOR length")

    def value(self) -> Any:
        ib = self.u8()
        major, info = ib >> 5, ib & 0x1F
        if major == 0:
            return self.length(info)
        if major == 1:
            return -1 - self.length(info)
        if major == 2:
            return self._chunks(info, 2)
        if major == 3:
            try:
                return self._chunks(info, 3).decode()
            except UnicodeDecodeError:
                raise SurrealError("invalid CBOR text (not UTF-8)")
        if major == 4:
            n = self.length(info)
            if n < 0:
                out: List[Any] = []
                while self.peek() != 0xFF:
                    out.append(self.value())
                self.i += 1
                return out
            return [self.value() for _ in range(n)]
        if major == 5:
            n = self.length(info)
            obj = {}
            if n < 0:
                while self.peek() != 0xFF:
                    k = self.value()
                    obj[str(k)] = self.value()
                self.i += 1
                return obj
            for _ in range(n):
                k = self.value()
                obj[str(k)] = self.value()
            return obj
        if major == 6:
            tag = self.length(info)
            payload = self.value()
            try:
                return _untag(tag, payload)
            except SurrealError:
                raise
            except (TypeError, ValueError, IndexError, KeyError, AttributeError, OverflowError):
                raise SurrealError(f"malformed CBOR tag {tag} payload")
        # major 7: simple / float
        if info == 20:
            return False
        if info == 21:
            return True
        if info == 22:
            return Null
        if info == 23:
            return NONE  # undefined ~ NONE
        if info == 25:
            return _half(struct.unpack(">H", self.read(2))[0])
        if info == 26:
            return struct.unpack(">f", self.read(4))[0]
        if info == 27:
            return struct.unpack(">d", self.read(8))[0]
        raise SurrealError(f"unsupported CBOR simple value {info}")

    def _chunks(self, info: int, major: int) -> bytes:
        n = self.length(info)
        if n >= 0:
            return self.read(n)
        out = bytearray()
        while self.peek() != 0xFF:
            ib = self.u8()
            if ib >> 5 != major:
                raise SurrealError("bad indefinite chunk")
            m = self.length(ib & 0x1F)
            if m < 0:  # nested indefinite chunk is invalid (RFC 8949 §3.2.3)
                raise SurrealError("bad indefinite chunk")
            out += self.read(m)
        self.i += 1
        return bytes(out)


def _half(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0**-24
    if exp == 31:
        return sign * (math.inf if frac == 0 else math.nan)
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


def _untag(tag: int, v: Any) -> Any:
    if tag == TAG_NONE:
        return NONE
    if tag == TAG_SPEC_DATETIME:
        return Datetime.parse(str(v))
    if tag == TAG_CUSTOM_DATETIME:
        secs = int(v[0]) if len(v) > 0 else 0
        nanos = int(v[1]) if len(v) > 1 else 0
        return Datetime(secs * 1_000_000_000 + nanos)
    if tag == TAG_STRING_UUID:
        import uuid as _uuid

        return Uuid(_uuid.UUID(str(v)))
    if tag == TAG_SPEC_UUID:
        import uuid as _uuid

        if not isinstance(v, (bytes, bytearray)) or len(v) != 16:
            raise SurrealError("Expected a 16-byte UUID payload")
        return Uuid(_uuid.UUID(bytes=bytes(v)))
    if tag == TAG_STRING_DECIMAL:
        try:
            return _decimal.Decimal(str(v))
        except _decimal.InvalidOperation:
            raise SurrealError("Expected a valid Decimal value")
    if tag == TAG_STRING_DURATION:
        return Duration.parse(str(v))
    if tag == TAG_CUSTOM_DURATION:
        secs = int(v[0]) if len(v) > 0 else 0
        nanos = int(v[1]) if len(v) > 1 else 0
        return Duration(secs * 1_000_000_000 + nanos)
    if tag == TAG_RECORDID:
        if isinstance(v, str):
            return Thing.parse(v)
        if isinstance(v, list) and len(v) == 2:
            tb = str(v[0]) if not isinstance(v[0], Table) else str(v[0])
            return Thing(tb, v[1])
        raise SurrealError("Expected a text or 2-element record id")
    if tag == TAG_TABLE:
        return Table(str(v))
    if tag == TAG_RANGE:
        return _dec_range(v)
    if tag in (TAG_BOUND_INCLUDED, TAG_BOUND_EXCLUDED):
        return (tag, v)  # resolved by _dec_range
    if tag in _GEOM_NAMES:
        return Geometry(_GEOM_NAMES[tag], v)
    return v  # unknown tags pass their payload through


def _dec_range(v: Any) -> Range:
    def bound(b):
        if b is None or is_null(b) or is_none(b):
            return NONE, True
        if isinstance(b, tuple) and len(b) == 2 and b[0] in (TAG_BOUND_INCLUDED, TAG_BOUND_EXCLUDED):
            return b[1], b[0] == TAG_BOUND_INCLUDED
        return b, True

    beg, beg_incl = bound(v[0] if len(v) > 0 else None)
    end, end_incl = bound(v[1] if len(v) > 1 else None)
    return Range(beg, end, beg_incl, end_incl)


def decode(data: bytes) -> Any:
    try:
        return _decode_inner(data)
    except RecursionError:
        raise SurrealError("CBOR value is too deeply nested") from None


def _decode_inner(data: bytes) -> Any:
    d = _Dec(data)
    v = d.value()
    return v
