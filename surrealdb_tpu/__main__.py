import sys

from surrealdb_tpu.cli import main

sys.exit(main())
