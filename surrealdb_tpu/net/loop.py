"""Selector-based event-loop ingress (the C1M network plane).

The r10 ingress was thread-per-connection (`ThreadingHTTPServer` + a
`bg:ws` pool per socket): correct, but a few thousand sockets of thread
stacks and scheduler thrash away from the north star's "heavy traffic
from millions of users". This module rebuilds ingress as
`SURREAL_NET_LOOPS` nonblocking accept/read/write loops multiplexing
HTTP parsing and RFC6455 WS framing for 100k+ sockets:

- the LOOP owns sockets: nonblocking accept, incremental HTTP header/
  body assembly, incremental WS frame assembly, and per-connection
  bounded write queues. It never parses SurrealQL and never executes a
  statement;
- fully-decoded requests hand off to a bounded executor pool
  (`SURREAL_NET_EXECUTORS` supervised `bg:net_exec` workers) through the
  per-tenant weighted-fair admission plane (net/qos.py). Responses come
  back as atomic byte-chunk appends to the connection's write queue;
- every overload path is a BOUNDED buffer and a clean counted close,
  never unbounded memory: accepts past `SURREAL_NET_MAX_CONNS` shed
  immediately, header dribblers (slowloris) die at
  `SURREAL_NET_HEADER_TIMEOUT`, and a reader that never drains its
  write queue is closed once `SURREAL_NET_WRITE_BUF_MAX` queued bytes
  accumulate (`net.backpressure_close`).

Route logic is NOT duplicated: a decoded HTTP request replays through
the existing `SurrealHandler` routes via an in-memory rfile/wfile
adapter, so both ingresses serve byte-identical responses. WS framing
is loop-native (the threaded upgrade path runs a blocking per-socket
loop that cannot ride a selector) but dispatches into the same
RpcContext, and one shared `bg:net_notify` pump drains live-query
notifications for EVERY connection on the server — not a thread per
socket.

Scale beyond the fd rlimit: connections are transport-agnostic. A
`VirtualConn` (attach_virtual) runs the same state machine — HTTP
parse, QoS admission, executor dispatch, bounded write queue — with
byte buffers fed/drained by the caller instead of a kernel socket, so
the connection-scale bench can hold 20k+ concurrent connections on a
container whose hard RLIMIT_NOFILE is 20000.

This module is event-loop-marked (graftlint GL016): blocking socket
calls (`recv`/`sendall`/`accept` outside the `_nb_*` nonblocking
wrappers) and `time.sleep` are lint findings here — one blocking call
on the loop thread stalls every socket it owns.
"""

from __future__ import annotations

import heapq
import io
import itertools
import json
import queue as _queue
import selectors
import socket
import struct
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from surrealdb_tpu import cnf
from surrealdb_tpu.utils import locks as _locks

from . import qos
from . import ws as wsproto

# graftlint GL016 marker: the rules below apply to this whole module
EVENT_LOOP_MODULE = True

_CONN_SEQ = itertools.count(1)
_MAX_HEADER = 64 * 1024  # request line + headers assembly cap
_READ_CHUNK = 65536

# session-mutating RPC methods run ALONE on their connection (drain the
# concurrent-request window first) — same contract as the threaded ingress
_WS_SESSION_METHODS = frozenset(
    {"use", "signin", "signup", "authenticate", "invalidate",
     "let", "set", "unset", "reset"}
)


# ------------------------------------------------------------------ nb wrappers
def _nb_accept(listener: socket.socket):
    """Nonblocking accept: (sock, addr) or None when no connection is
    pending. The ONLY sanctioned accept call in an event-loop module."""
    try:
        return listener.accept()
    except (BlockingIOError, InterruptedError):
        return None
    except OSError:
        return None


def _nb_recv(sock, n: int) -> Optional[bytes]:
    """Nonblocking read: bytes, b'' on EOF, None when no data is ready.
    The ONLY sanctioned recv call in an event-loop module."""
    try:
        return sock.recv(n)
    except (BlockingIOError, InterruptedError):
        return None
    except OSError:
        return b""


def _nb_send_some(sock, view) -> int:
    """Nonblocking partial send: bytes written (0 = try later, -1 = dead
    socket). The ONLY sanctioned send call in an event-loop module."""
    try:
        return sock.send(view)
    except (BlockingIOError, InterruptedError):
        return 0
    except OSError:
        return -1


# ------------------------------------------------------------------ conn state
class _Conn:
    """One connection's state machine — real socket or virtual transport."""

    __slots__ = (
        "cid", "loop", "sock", "sink", "peer", "inbuf", "outq", "out_bytes",
        "state", "accepted_t", "first_byte_t", "header_deadline",
        "body_total", "http_busy", "close_after_flush", "closed", "ws",
        "want_write", "__weakref__",
    )

    def __init__(self, loop: "_Loop", sock: Optional[socket.socket], sink):
        self.cid = next(_CONN_SEQ)
        self.loop = loop
        self.sock = sock
        self.sink = sink  # virtual-conn output callable (None = accumulate)
        try:
            self.peer = sock.getpeername() if sock is not None else ("virtual", self.cid)
        except OSError:
            self.peer = ("?", 0)
        self.inbuf = bytearray()
        self.outq: Deque[memoryview] = deque()
        self.out_bytes = 0
        self.state = "headers"  # headers -> body -> (headers | ws)
        self.accepted_t = time.monotonic()
        self.first_byte_t: Optional[float] = None
        self.header_deadline = self.accepted_t + max(
            cnf.NET_HEADER_TIMEOUT_SECS, 0.05
        )
        self.body_total = 0  # header_end + content-length while reading a body
        self.http_busy = False  # a request is executing; don't parse the next
        self.close_after_flush = False
        self.closed = False
        self.ws: Optional[dict] = None  # set on upgrade
        self.want_write = False

    @property
    def virtual(self) -> bool:
        return self.sock is None


class VirtualConn:
    """Caller-facing handle for a loop-attached in-memory connection: the
    full ingress state machine without a kernel socket. `feed()` injects
    client->server bytes; output either streams into `collect` or
    accumulates in the bounded write queue (pass collect=None to model a
    reader that never drains — the backpressure-close test shape)."""

    def __init__(self, loop: "_Loop", conn: _Conn, collected: Optional[List[bytes]]):
        self._loop = loop
        self._conn = conn
        self._collected = collected

    def feed(self, data: bytes) -> None:
        self._loop._cmd(("feed", self._conn, bytes(data)))

    def take_output(self) -> bytes:
        if self._collected is None:
            return b""
        out = b"".join(self._collected)
        del self._collected[: len(self._collected)]
        return out

    def close(self) -> None:
        self._loop._cmd(("close", self._conn, "client"))

    @property
    def closed(self) -> bool:
        return self._conn.closed


# ------------------------------------------------------------------ executor
class _ExecPool:
    """Bounded worker pool for decoded requests. Workers are supervised
    bg services (`bg:net_exec:<i>`) — visible in the task registry, and a
    crash restarts with backoff instead of silently shrinking the pool."""

    def __init__(self, workers: int, owner=None):
        from surrealdb_tpu import bg

        self._q: "_queue.Queue" = _queue.Queue()
        self._threads = [
            # detached service workers: each submit() copies the submitter's
            # context (see _worker) — the spawn itself has no arming trace
            # graftflow: disable=GF002
            bg.spawn_service("net_exec", str(i), self._worker, owner=owner, restart=True)
            for i in range(max(workers, 1))
        ]

    def _worker(self) -> None:
        import contextvars as _cv  # noqa: F401 — submit side copies context

        from surrealdb_tpu import telemetry

        while True:
            item = self._q.get()
            if item is None:
                return
            fn, cvctx = item
            try:
                cvctx.run(fn)
            except Exception:  # noqa: BLE001 — tasks answer their own errors
                # through response bytes; count the escape regardless
                telemetry.inc("net_exec_task_errors")

    def submit(self, fn: Callable[[], None]) -> None:
        import contextvars as _cv

        self._q.put((fn, _cv.copy_context()))

    def shutdown(self) -> None:
        for _ in self._threads:
            self._q.put(None)


# ------------------------------------------------------------------ the loop
class _Loop:
    """One selector thread owning a shard of the server's sockets."""

    def __init__(self, server: "EventLoopServer", idx: int):
        self.server = server
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        self._lock = _locks.Lock("net.loop")
        self._cmds: Deque[tuple] = deque()
        self._stop = threading.Event()
        self.conns: set = set()
        self.ws_conns: set = set()
        self._dirty_virtual: set = set()
        self._deadlines: list = []  # heap of (deadline, cid, conn)
        # wakeup channel: any thread appends a cmd and pokes this pipe
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ, None)
        self.listener: Optional[socket.socket] = None

    # ------------------------------------------------------ cross-thread API
    def _cmd(self, cmd: tuple) -> None:
        with self._lock:
            self._cmds.append(cmd)
        self._wake()

    def _wake(self) -> None:
        _nb_send_some(self._wake_w, b"\x00")

    def enqueue_write(self, conn: _Conn, data: bytes) -> None:
        """Append one atomic chunk (a full response / frame) to a
        connection's bounded write queue; any thread may call this."""
        from surrealdb_tpu import telemetry

        overflow = False
        with self._lock:
            if conn.closed:
                return
            conn.outq.append(memoryview(bytes(data)))
            conn.out_bytes += len(data)
            if conn.out_bytes > max(cnf.NET_WRITE_BUF_MAX, 4096):
                overflow = True
            self._cmds.append(("drain", conn, None))
        telemetry.observe_hist("net_write_queue_bytes", conn.out_bytes)
        if overflow:
            self._cmd(("close", conn, "backpressure"))
        else:
            self._wake()

    def attach_virtual(self, collect: bool = True) -> VirtualConn:
        """Attach an in-memory connection (see VirtualConn). collect=False
        models a reader that never drains its write queue."""
        from surrealdb_tpu import telemetry

        conn = _Conn(self, None, None)
        collected: Optional[List[bytes]] = [] if collect else None
        if collect:
            conn.sink = collected.append
        with self._lock:
            self.conns.add(conn)
        heapq.heappush(self._deadlines, (conn.header_deadline, conn.cid, conn))
        telemetry.gauge_add("net_connections", 1)
        self._wake()
        return VirtualConn(self, conn, collected)

    # ------------------------------------------------------------ main loop
    def run(self) -> None:
        try:
            while not self._stop.is_set():
                self._tick()
        finally:
            self._close_all()

    def _tick(self) -> None:
        timeout = 0.05
        if self._dirty_virtual or self._cmds:
            timeout = 0.0
        elif self._deadlines:
            timeout = min(timeout, max(self._deadlines[0][0] - time.monotonic(), 0.0))
        for key, mask in self.sel.select(timeout):
            if key.data is None:  # wakeup pipe
                while _nb_recv(self._wake_r, 4096):
                    pass
                continue
            if key.data == "listener":
                self._accept_ready()
                continue
            conn = key.data
            if mask & selectors.EVENT_READ:
                self._read_ready(conn)
            if mask & selectors.EVENT_WRITE and not conn.closed:
                self._write_ready(conn)
        self._run_cmds()
        self._drain_virtual()
        qos.poll()
        self._expire_deadlines()

    def _run_cmds(self) -> None:
        while True:
            with self._lock:
                if not self._cmds:
                    return
                cmd, conn, arg = self._cmds.popleft()
            if cmd == "feed":
                if conn is not None and not conn.closed:
                    conn.inbuf += arg
                    self._process(conn)
                    self._dirty_virtual.add(conn)
            elif cmd == "drain":
                if not conn.closed:
                    if conn.virtual:
                        self._dirty_virtual.add(conn)
                    else:
                        self._write_ready(conn)
            elif cmd == "close":
                self._close(conn, arg or "server")
            elif cmd == "http_done":
                if not conn.closed:
                    conn.http_busy = False
                    if conn.close_after_flush:
                        self._flush_interest(conn)
                    else:
                        self._process(conn)  # a pipelined next request may wait
            elif cmd == "ws_done":
                self._ws_next(conn)
            elif cmd == "stop":
                self._stop.set()

    # ------------------------------------------------------------ accepting
    def _accept_ready(self) -> None:
        from surrealdb_tpu import events, telemetry

        shed = 0
        while True:
            pair = _nb_accept(self.listener)
            if pair is None:
                break
            sock, _addr = pair
            if self.server.total_conns() >= max(cnf.NET_MAX_CONNS, 8):
                # accept storm past the cap: shed with an immediate close —
                # a counted refusal, not an unbounded accept queue
                try:
                    sock.close()
                except OSError:
                    pass
                shed += 1
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(self, sock, None)
            with self._lock:
                self.conns.add(conn)
            self.sel.register(sock, selectors.EVENT_READ, conn)
            heapq.heappush(self._deadlines, (conn.header_deadline, conn.cid, conn))
            telemetry.gauge_add("net_connections", 1)
        if shed:
            telemetry.inc("net_overload_close", reason="conn_cap", by=float(shed))
            events.emit("net.overload_close", reason="conn_cap", count=shed)

    # ------------------------------------------------------------ reading
    def _read_ready(self, conn: _Conn) -> None:
        budget = 4 * _READ_CHUNK  # per-conn per-tick read fairness
        while budget > 0 and not conn.closed:
            data = _nb_recv(conn.sock, _READ_CHUNK)
            if data is None:
                break
            if data == b"":
                self._close(conn, "eof")
                return
            conn.inbuf += data
            budget -= len(data)
            self._process(conn)

    def _process(self, conn: _Conn) -> None:
        """Advance the connection state machine over whatever is buffered."""
        while not conn.closed:
            if conn.state == "ws":
                if not self._ws_frames(conn):
                    return
                continue
            if conn.http_busy:
                # responses are strictly ordered: buffer (bounded) until
                # the in-flight request finishes
                if len(conn.inbuf) > cnf.HTTP_MAX_BODY_SIZE + _MAX_HEADER:
                    self._close(conn, "pipeline_overflow")
                return
            if conn.state == "headers":
                end = conn.inbuf.find(b"\r\n\r\n")
                if end < 0:
                    if len(conn.inbuf) > _MAX_HEADER:
                        self._close(conn, "header_overflow")
                    return
                if not self._begin_request(conn, end + 4):
                    return
                continue
            if conn.state == "body":
                if len(conn.inbuf) < conn.body_total:
                    return
                self._dispatch_http(conn)
                continue
            return

    def _begin_request(self, conn: _Conn, header_end: int) -> bool:
        """Parse the buffered header block far enough to route: body
        length, tenant headers, websocket upgrade. Returns False when the
        connection changed state terminally (closed/ws)."""
        head = bytes(conn.inbuf[:header_end])
        lines = head.split(b"\r\n")
        headers: Dict[bytes, bytes] = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            if k:
                headers[k.strip().lower()] = v.strip()
        conn.header_deadline = 0.0  # full header block arrived: disarm
        if (headers.get(b"upgrade") or b"").lower() == b"websocket":
            del conn.inbuf[:header_end]
            self._ws_handshake(conn, lines[0], headers)
            return conn.state == "ws" and not conn.closed
        try:
            clen = int(headers.get(b"content-length") or 0)
        except ValueError:
            clen = 0
        if clen < 0 or clen > cnf.HTTP_MAX_BODY_SIZE:
            self._respond_simple(
                conn, 413, {"error": "request body too large"}, close=True
            )
            return False
        conn.body_total = header_end + clen
        conn.state = "body"
        return True

    def _dispatch_http(self, conn: _Conn) -> None:
        """A full request is buffered: admit through per-tenant QoS and
        hand the raw bytes to the executor pool."""
        raw = bytes(conn.inbuf[: conn.body_total])
        del conn.inbuf[: conn.body_total]
        conn.state = "headers"
        conn.http_busy = True
        head, _, body = raw.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        headers: Dict[bytes, bytes] = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            if k:
                headers[k.strip().lower()] = v.strip()
        try:
            path = lines[0].split(b" ")[1].split(b"?")[0].decode("latin-1")
        except (IndexError, UnicodeDecodeError):
            path = "/"
        ns = (headers.get(b"surreal-ns") or headers.get(b"ns") or b"").decode(
            "latin-1"
        ) or None
        db = (headers.get(b"surreal-db") or headers.get(b"db") or b"").decode(
            "latin-1"
        ) or None
        cls = qos.INTERNAL if path == "/cluster" else "tenant"
        fp = None
        if path == "/sql" and 0 < len(body) <= 4096:
            try:
                from surrealdb_tpu import stats

                fp = stats.fingerprint(body.decode())[0]
            except Exception:  # noqa: BLE001 — an unfingerprintable body
                fp = None  # just loses its cost estimate, not its request

        server = self.server

        def run():
            try:
                server.run_http(conn, raw)
            finally:
                qos.release(ns, db, cls=cls)
                self._cmd(("http_done", conn, None))

        try:
            qos.submit(
                ns, db, lambda: server.pool.submit(run), fingerprint=fp, cls=cls
            )
        except qos.Shed:
            self._respond_simple(
                conn, 503,
                {"error": "server overloaded: admission control shed this request"},
            )
            conn.http_busy = False

    def _respond_simple(
        self, conn: _Conn, code: int, payload: dict, close: bool = False
    ) -> None:
        body = json.dumps(payload).encode()
        reason = {413: "Payload Too Large", 503: "Service Unavailable"}.get(code, "")
        head = (
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: application/json\r\nContent-Length: {len(body)}\r\n"
            + ("Connection: close\r\n" if close else "")
            + "\r\n"
        ).encode()
        if close:
            conn.close_after_flush = True
        self.enqueue_write(conn, head + body)

    # ------------------------------------------------------------ websocket
    def _ws_handshake(self, conn: _Conn, reqline: bytes, headers: Dict[bytes, bytes]) -> None:
        from surrealdb_tpu import telemetry
        from surrealdb_tpu.dbs.session import Session
        from surrealdb_tpu.rpc.method import RpcContext

        server = self.server
        path = b"/"
        parts = reqline.split(b" ")
        if len(parts) > 1:
            path = parts[1].split(b"?")[0]
        if path != b"/rpc" or not server.ds.capabilities.allows_http_route("rpc"):
            self._respond_simple(conn, 403, {"error": "rpc route not allowed"}, close=True)
            return
        key = (headers.get(b"sec-websocket-key") or b"").decode("latin-1")
        if not key:
            self._respond_simple(conn, 400, {"error": "bad websocket request"}, close=True)
            return
        offered = [
            p.strip()
            for p in (headers.get(b"sec-websocket-protocol") or b"")
            .decode("latin-1").split(",")
            if p.strip()
        ]
        proto = next((p for p in offered if p in ("json", "cbor", "msgpack")), None)
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {wsproto.accept_key(key)}\r\n"
            + (f"Sec-WebSocket-Protocol: {proto}\r\n" if proto else "")
            + "\r\n"
        ).encode()
        sess = Session.anonymous()
        sess.rt = True
        if not server.auth_enabled:
            sess = Session.owner(None, None)
            sess.ns = sess.db = None
        shim = server.handler_shim()
        shim._ws_proto = proto
        conn.ws = {
            "ctx": RpcContext(server.ds, sess),
            "shim": shim,
            "binary": False,
            "frag_op": None,
            "frag": bytearray(),
            "inflight": 0,
            "exclusive": False,  # a session-mutating method is running alone
            "pending": deque(),
        }
        conn.state = "ws"
        server.ds.enable_notifications()
        with self._lock:
            self.ws_conns.add(conn)
        telemetry.gauge_add("ws_connections", 1)
        self.enqueue_write(conn, resp)

    def _ws_frames(self, conn: _Conn) -> bool:
        """Assemble frames from inbuf; returns False when more bytes are
        needed (or the conn died)."""
        buf = conn.inbuf
        if len(buf) < 2:
            return False
        b1, b2 = buf[0], buf[1]
        fin, op = b1 & 0x80, b1 & 0x0F
        masked = b2 & 0x80
        n = b2 & 0x7F
        off = 2
        if n == 126:
            if len(buf) < off + 2:
                return False
            n = struct.unpack(">H", bytes(buf[off:off + 2]))[0]
            off += 2
        elif n == 127:
            if len(buf) < off + 8:
                return False
            n = struct.unpack(">Q", bytes(buf[off:off + 8]))[0]
            off += 8
        if n > cnf.HTTP_MAX_BODY_SIZE:
            self._close(conn, "frame_too_large")
            return False
        key = None
        if masked:
            if len(buf) < off + 4:
                return False
            key = bytes(buf[off:off + 4])
            off += 4
        if len(buf) < off + n:
            return False
        payload = bytes(buf[off:off + n])
        del buf[: off + n]
        if key:
            payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        ws = conn.ws
        if op == wsproto.OP_CLOSE:
            self.enqueue_write(conn, wsproto.encode_frame(wsproto.OP_CLOSE, b""))
            conn.close_after_flush = True
            self._flush_interest(conn)
            return False
        if op == wsproto.OP_PING:
            self.enqueue_write(conn, wsproto.encode_frame(wsproto.OP_PONG, payload))
            return True
        if op == wsproto.OP_PONG:
            return True
        # continuation assembly
        if op == wsproto.OP_CONT:
            ws["frag"] += payload
            if not fin:
                return True
            op = ws["frag_op"] or wsproto.OP_BINARY
            payload = bytes(ws["frag"])
            ws["frag"] = bytearray()
            ws["frag_op"] = None
        elif not fin:
            ws["frag_op"] = op
            ws["frag"] = bytearray(payload)
            return True
        if op not in (wsproto.OP_TEXT, wsproto.OP_BINARY):
            return True
        self._ws_message(conn, op == wsproto.OP_BINARY, payload)
        return True

    def _ws_message(self, conn: _Conn, binary: bool, payload: bytes) -> None:
        ws = conn.ws
        ws["binary"] = binary
        try:
            if not binary:
                req = json.loads(payload)
            elif getattr(ws["shim"], "_ws_proto", None) == "cbor":
                from surrealdb_tpu.rpc import cbor as _cbor

                req = _cbor.decode(payload)
            else:
                from surrealdb_tpu.utils.ser import wire_unpack

                req = wire_unpack(payload)
        except Exception:  # noqa: BLE001 — mirror the threaded ingress:
            return  # an undecodable frame is ignored, not fatal
        if not isinstance(req, dict):
            return
        sess = ws["ctx"].session
        fp = None
        method = str(req.get("method", "")).lower()
        if method == "query":
            params = req.get("params") or []
            if params and isinstance(params[0], str) and len(params[0]) <= 4096:
                try:
                    from surrealdb_tpu import stats

                    fp = stats.fingerprint(params[0])[0]
                except Exception:  # noqa: BLE001 — cost estimate only
                    fp = None
        server = self.server

        is_session = method in _WS_SESSION_METHODS

        def on_admit():
            with self._lock:
                if conn.closed or conn.ws is None:
                    released = True
                else:
                    conn.ws["pending"].append(
                        (req, binary, sess.ns, sess.db, is_session)
                    )
                    released = False
            if released:
                qos.release(sess.ns, sess.db)
                return
            self._ws_start_ready(conn)

        try:
            qos.submit(sess.ns, sess.db, on_admit, fingerprint=fp)
        except qos.Shed as e:
            resp = {
                "id": req.get("id"),
                "error": {"code": -32000, "message": str(e)},
            }
            self._ws_send_obj(conn, resp, binary)

    def _ws_start_ready(self, conn: _Conn) -> None:
        """Mirror the threaded ingress's per-socket request window: up to
        WEBSOCKET_MAX_CONCURRENT_REQUESTS frames of one connection execute
        concurrently (so its queries can coalesce into shared kernel
        launches), while a session-mutating method (`use`/`signin`/...)
        drains the window first and runs alone — it can never race a
        concurrently-executing query."""
        limit = max(cnf.WEBSOCKET_MAX_CONCURRENT_REQUESTS, 1)
        starts: List[tuple] = []
        with self._lock:
            ws = conn.ws
            if ws is None or conn.closed:
                return
            while ws["pending"] and not ws["exclusive"]:
                if ws["pending"][0][4]:  # session-mutating head
                    if ws["inflight"] > 0:
                        break  # drain the window first
                    ws["exclusive"] = True
                    ws["inflight"] += 1
                    starts.append(ws["pending"].popleft())
                    break
                if ws["inflight"] >= limit:
                    break
                ws["inflight"] += 1
                starts.append(ws["pending"].popleft())
        for item in starts:
            self.server.pool.submit(lambda it=item: self._ws_run_one(conn, it))

    def _ws_run_one(self, conn: _Conn, item: tuple) -> None:
        req, binary, ns, db, is_session = item
        try:
            if conn.ws is not None and not conn.closed:
                self.server.run_ws_frame(conn, req, binary)
        finally:
            qos.release(ns, db)
            with self._lock:
                ws = conn.ws
                if ws is not None:
                    ws["inflight"] -= 1
                    if is_session:
                        ws["exclusive"] = False
            self._cmd(("ws_done", conn, None))

    def _ws_next(self, conn: _Conn) -> None:
        self._ws_start_ready(conn)

    def _ws_send_obj(self, conn: _Conn, obj: Any, binary: bool) -> None:
        from surrealdb_tpu.sql.value import to_json_value

        if binary:
            frame = wsproto.encode_frame(
                wsproto.OP_BINARY, conn.ws["shim"]._ws_encode(obj)
            )
        else:
            frame = wsproto.encode_frame(
                wsproto.OP_TEXT, json.dumps(to_json_value(obj)).encode()
            )
        self.enqueue_write(conn, frame)

    # ------------------------------------------------------------ writing
    def _flush_interest(self, conn: _Conn) -> None:
        if conn.virtual:
            self._dirty_virtual.add(conn)
        else:
            self._write_ready(conn)

    def _note_first_byte(self, conn: _Conn) -> None:
        if conn.first_byte_t is None:
            from surrealdb_tpu import telemetry

            conn.first_byte_t = time.monotonic()
            dt = conn.first_byte_t - conn.accepted_t
            telemetry.observe("net_accept_to_first_byte", dt)
            self.server.note_ttfb(dt)

    def _write_ready(self, conn: _Conn) -> None:
        """Drain as much of the write queue as the socket accepts; manage
        EVENT_WRITE interest."""
        while conn.outq:
            view = conn.outq[0]
            n = _nb_send_some(conn.sock, view)
            if n < 0:
                self._close(conn, "eof")
                return
            if n == 0:
                break
            self._note_first_byte(conn)
            with self._lock:
                conn.out_bytes -= n
            if n == len(view):
                conn.outq.popleft()
            else:
                conn.outq[0] = view[n:]
        want = bool(conn.outq)
        if want != conn.want_write:
            conn.want_write = want
            mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
            try:
                self.sel.modify(conn.sock, mask, conn)
            except (KeyError, ValueError, OSError):
                pass
        if not conn.outq and conn.close_after_flush:
            self._close(conn, "server")

    def _drain_virtual(self) -> None:
        while self._dirty_virtual:
            conn = self._dirty_virtual.pop()
            if conn.closed:
                continue
            if conn.sink is not None and conn.outq:
                self._note_first_byte(conn)
                with self._lock:
                    chunks = list(conn.outq)
                    conn.outq.clear()
                    conn.out_bytes = 0
                for view in chunks:
                    conn.sink(bytes(view))
            if not conn.outq and conn.close_after_flush:
                self._close(conn, "server")

    # ------------------------------------------------------------ closing
    def _expire_deadlines(self) -> None:
        from surrealdb_tpu import events, telemetry

        now = time.monotonic()
        expired = 0
        while self._deadlines and self._deadlines[0][0] <= now:
            _, _, conn = heapq.heappop(self._deadlines)
            if (
                not conn.closed
                and conn.state == "headers"
                and conn.header_deadline
                and conn.header_deadline <= now
                and not conn.http_busy
                and conn.inbuf  # an idle keep-alive socket is fine;
                # a PARTIAL header block past deadline is a slowloris
            ):
                self._close(conn, "header_timeout", quiet=True)
                expired += 1
        if expired:
            telemetry.inc(
                "net_overload_close", reason="header_timeout", by=float(expired)
            )
            events.emit("net.overload_close", reason="header_timeout", count=expired)

    def _close(self, conn: _Conn, reason: str, quiet: bool = False) -> None:
        from surrealdb_tpu import events, telemetry

        with self._lock:
            if conn.closed:
                return
            conn.closed = True
            self.conns.discard(conn)
            was_ws = conn in self.ws_conns
            self.ws_conns.discard(conn)
            conn.outq.clear()
            conn.out_bytes = 0
        self._dirty_virtual.discard(conn)
        if conn.sock is not None:
            try:
                self.sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        telemetry.gauge_add("net_connections", -1)
        if reason == "backpressure":
            telemetry.inc("net_backpressure_close")
            ws = conn.ws
            sess = ws["ctx"].session if ws else None
            events.emit(
                "net.backpressure_close",
                ns=(sess.ns if sess else None) or "",
                db=(sess.db if sess else None) or "",
                queued_bytes=cnf.NET_WRITE_BUF_MAX,
            )
        if was_ws and conn.ws is not None:
            telemetry.gauge_add("ws_connections", -1)
            ctx = conn.ws["ctx"]
            conn.ws = None
            # disconnect sweep (the live-query leak fix): KILL every live
            # query this connection still owns, off the loop thread
            self.server.pool.submit(ctx.close)

    def _close_all(self) -> None:
        for conn in list(self.conns):
            self._close(conn, "shutdown", quiet=True)
        try:
            self.sel.unregister(self._wake_r)
        except (KeyError, ValueError):
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        if self.listener is not None:
            try:
                self.sel.unregister(self.listener)
            except (KeyError, ValueError):
                pass
        self.sel.close()


# ------------------------------------------------------------------ the server
_SERVERS: "weakref.WeakSet[EventLoopServer]" = weakref.WeakSet()


class EventLoopServer:
    """The event-loop ingress: a listener + NET_LOOPS selector loops + one
    bounded executor pool, serving the SAME SurrealHandler routes as the
    threaded ingress through an in-memory adapter."""

    def __init__(
        self,
        handler_cls,
        host: str = "127.0.0.1",
        port: int = 8000,
    ):
        self.handler_cls = handler_cls
        self.ds = handler_cls.ds
        self.auth_enabled = handler_cls.auth_enabled
        self.listener = socket.create_server(
            (host, port), backlog=1024, reuse_port=False
        )
        self.listener.setblocking(False)
        self.host, self.port = self.listener.getsockname()[:2]
        self.loops = [_Loop(self, i) for i in range(max(cnf.NET_LOOPS, 1))]
        self.loops[0].listener = self.listener
        self.loops[0].sel.register(self.listener, selectors.EVENT_READ, "listener")
        self.pool = _ExecPool(cnf.NET_EXECUTORS, owner=id(self.ds))
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._adapter_cls = _make_adapter(handler_cls)
        self._ttfb_lock = _locks.Lock("net.loop")  # same family: leaf usage
        self._ttfb: Deque[float] = deque(maxlen=16384)
        self._started = False
        _SERVERS.add(self)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "EventLoopServer":
        from surrealdb_tpu import bg

        if self._started:
            return self
        self._started = True
        self._threads = [
            # detached selector loops own every connection's tracing scope
            # per-request; there is no single arming trace to propagate
            # graftflow: disable=GF002
            bg.spawn_service(
                "net_loop", str(i), lp.run, owner=id(self.ds), restart=True
            )
            for i, lp in enumerate(self.loops)
        ]
        self._threads.append(
            bg.spawn_service(
                "net_notify", "all", self._notify_pump, owner=id(self.ds), restart=True
            )
        )
        return self

    def serve_forever(self) -> None:
        self.start()
        self._stop.wait()

    def shutdown(self) -> None:
        self._stop.set()
        for lp in self.loops:
            lp._stop.set()
            lp._wake()
        self.pool.shutdown()
        for t in self._threads:
            t.join(timeout=5)
        try:
            self.listener.close()
        except OSError:
            pass

    def server_close(self) -> None:
        self.shutdown()

    def total_conns(self) -> int:
        return sum(len(lp.conns) for lp in self.loops)

    def note_ttfb(self, dt: float) -> None:
        with self._ttfb_lock:
            self._ttfb.append(dt)

    def handler_shim(self):
        """A routeless SurrealHandler instance: _rpc_denied/_ws_encode
        without a socket behind it."""
        return self.handler_cls.__new__(self.handler_cls)

    # ------------------------------------------------------------ execution
    def run_http(self, conn: _Conn, raw: bytes) -> None:
        """Executor side: replay the decoded request through the real
        SurrealHandler routes against in-memory files."""
        self._adapter_cls(conn, raw)

    def run_ws_frame(self, conn: _Conn, req: dict, binary: bool) -> None:
        """Executor side: one WS RPC frame — the same trace/deny/execute/
        encode contract as the threaded ingress's per-frame handler."""
        from surrealdb_tpu import tracing
        from surrealdb_tpu.err import InvalidAuthError, SurrealError
        from surrealdb_tpu.sql.value import to_json_value

        ws = conn.ws
        if ws is None:
            return
        ctx, shim = ws["ctx"], ws["shim"]
        rid = req.get("id")
        method = req.get("method", "")
        t_field = req.get("trace")
        tid, t_parent = None, None
        if isinstance(t_field, str) and t_field:
            parsed = tracing.parse_traceparent(t_field)
            if parsed is not None:
                tid, t_parent = parsed
            else:
                tid = t_field
        tr = None
        try:
            with tracing.request(
                "ws_rpc", trace_id=tid, parent_id=t_parent, method=str(method)
            ) as tr:
                denied = shim._rpc_denied(method, ctx.session)
                if denied is not None:
                    raise InvalidAuthError(denied)
                result = ctx.execute(method, req.get("params") or [])
            resp: Dict[str, Any] = {"id": rid, "result": result}
            if tr is not None and tid is not None:
                resp["trace"] = tr.trace_id
        except Exception as e:  # noqa: BLE001 — a worker must not die silently
            msg = str(e) if isinstance(e, SurrealError) else f"Internal error: {e}"
            resp = {"id": rid, "error": {"code": -32000, "message": msg}}
            if tid is not None and tr is not None:
                resp["trace"] = tr.trace_id
        if binary:
            frame = wsproto.encode_frame(wsproto.OP_BINARY, shim._ws_encode(resp))
        else:
            frame = wsproto.encode_frame(
                wsproto.OP_TEXT, json.dumps(to_json_value(resp)).encode()
            )
        conn.loop.enqueue_write(conn, frame)

    # ------------------------------------------------------------ notifications
    def _notify_pump(self) -> None:
        """ONE shared live-query pump for every WS connection on this
        server (the threaded ingress burns a thread per socket on this).
        Event.wait paces it — never time.sleep on a loop-plane thread."""
        from surrealdb_tpu import telemetry  # noqa: F401
        from surrealdb_tpu.sql.value import to_json_value

        while not self._stop.wait(0.02):
            hub = self.ds.notifications
            if hub is None:
                continue
            for lp in self.loops:
                for conn in list(lp.ws_conns):
                    ws = conn.ws
                    if ws is None or conn.closed:
                        continue
                    ctx = ws["ctx"]
                    for live_id in list(ctx.live_ids):
                        try:
                            n = hub.subscribe(live_id).get_nowait()
                        except (_queue.Empty, KeyError):
                            continue
                        note = {"result": n.to_value()}
                        if ws["binary"]:
                            frame = wsproto.encode_frame(
                                wsproto.OP_BINARY, ws["shim"]._ws_encode(note)
                            )
                        else:
                            frame = wsproto.encode_frame(
                                wsproto.OP_TEXT,
                                json.dumps(to_json_value(note)).encode(),
                            )
                        lp.enqueue_write(conn, frame)

    # ------------------------------------------------------------ views
    def ttfb_quantiles(self) -> Dict[str, Optional[float]]:
        with self._ttfb_lock:
            xs = sorted(self._ttfb)
        if not xs:
            return {"p50_ms": None, "p99_ms": None, "samples": 0}
        def q(p: float) -> float:
            return xs[min(int(p * len(xs)), len(xs) - 1)] * 1e3
        return {
            "p50_ms": round(q(0.50), 3),
            "p99_ms": round(q(0.99), 3),
            "samples": len(xs),
        }

    def stats(self) -> dict:
        return {
            "host": self.host,
            "port": self.port,
            "loops": len(self.loops),
            "conns": self.total_conns(),
            "ws_conns": sum(len(lp.ws_conns) for lp in self.loops),
            "virtual_conns": sum(
                1 for lp in self.loops for c in lp.conns if c.virtual
            ),
            "accept_to_first_byte": self.ttfb_quantiles(),
        }


# ------------------------------------------------------------------ adapter
def _make_adapter(handler_cls):
    """Subclass the bound SurrealHandler so a loop-decoded request replays
    through the REAL route logic against in-memory rfile/wfile."""

    class _ConnWriter:
        """wfile shim: buffer the whole response, enqueue ONE atomic chunk
        on flush (so loop-interleaved writers can never shear a response)."""

        def __init__(self, conn: _Conn):
            self._conn = conn
            self._buf = bytearray()

        def write(self, data: bytes) -> int:
            self._buf += data
            return len(data)

        def flush(self) -> None:
            if self._buf:
                self._conn.loop.enqueue_write(self._conn, bytes(self._buf))
                self._buf = bytearray()

    class _LoopAdapter(handler_cls):
        def __init__(self, conn: _Conn, raw: bytes):  # noqa: D401
            # deliberately NOT calling BaseHTTPRequestHandler.__init__:
            # there is no socket to set up — the loop already framed the
            # request; this object only replays routes
            self.rfile = io.BufferedReader(io.BytesIO(raw))
            self.wfile = _ConnWriter(conn)
            self.client_address = conn.peer
            self.connection = None
            self.close_connection = True
            try:
                self.handle_one_request()
            except Exception:  # noqa: BLE001 — a route crash must close
                # the connection, never kill the executor worker
                from surrealdb_tpu import telemetry

                telemetry.inc("net_adapter_errors")
            try:
                self.wfile.flush()
            except Exception:  # noqa: BLE001 — conn raced closed
                from surrealdb_tpu import telemetry

                telemetry.inc("net_adapter_errors")
            if self.close_connection:
                conn.close_after_flush = True
                conn.loop._cmd(("drain", conn, None))

    return _LoopAdapter


# ------------------------------------------------------------------ plane views
def snapshot() -> dict:
    """The bundle `net` section: every live event-loop server + the QoS
    plane's admission state."""
    servers = [s.stats() for s in list(_SERVERS) if s._started and not s._stop.is_set()]
    return {
        "enabled": bool(cnf.NET_LOOP),
        "servers": servers,
        "qos": qos.snapshot(),
    }


def queue_depths() -> Dict[str, float]:
    """Scrape-time gauges: summed write-queue bytes + open conns across
    live servers (telemetry.collect_node_metrics calls this)."""
    conns = 0
    queued = 0
    for s in list(_SERVERS):
        if not s._started or s._stop.is_set():
            continue
        for lp in s.loops:
            for c in list(lp.conns):
                conns += 1
                queued += c.out_bytes
    return {"conns": float(conns), "write_queued_bytes": float(queued)}
