"""Minimal RFC6455 WebSocket framing (server + client sides).

The reference uses tokio-tungstenite (reference: src/rpc/connection.rs); the
stdlib has no WebSocket support, so the handshake and frame codec live here.
Only the features the RPC protocol needs: text/binary frames, ping/pong,
close, client-side masking.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
from typing import Optional, Tuple

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY, OP_CLOSE, OP_PING, OP_PONG = 0x0, 0x1, 0x2, 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    head = bytearray([0x80 | opcode])
    n = len(payload)
    mbit = 0x80 if mask else 0
    if n < 126:
        head.append(mbit | n)
    elif n < 65536:
        head.append(mbit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mbit | 127)
        head += struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        head += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


def _read_exact(sock, n: int) -> bytes:
    """Read exactly n bytes from a socket OR a buffered file-like reader.

    Server handlers must read via their buffered rfile — the HTTP header
    parser may already have consumed the first frame bytes into its buffer;
    reading the raw socket afterwards would desynchronize the stream.
    """
    buf = b""
    reader = sock.recv if hasattr(sock, "recv") else sock.read
    while len(buf) < n:
        chunk = reader(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket closed")
        buf += chunk
    return buf


def read_frame(sock) -> Tuple[int, bytes]:
    """-> (opcode, payload); handles continuation assembly."""
    opcode = None
    payload = b""
    while True:
        b1, b2 = _read_exact(sock, 2)
        fin = b1 & 0x80
        op = b1 & 0x0F
        masked = b2 & 0x80
        n = b2 & 0x7F
        if n == 126:
            n = struct.unpack(">H", _read_exact(sock, 2))[0]
        elif n == 127:
            n = struct.unpack(">Q", _read_exact(sock, 8))[0]
        key = _read_exact(sock, 4) if masked else None
        data = _read_exact(sock, n) if n else b""
        if key:
            data = bytes(b ^ key[i % 4] for i, b in enumerate(data))
        if op != OP_CONT:
            opcode = op
        payload += data
        if fin:
            return opcode if opcode is not None else OP_BINARY, payload


def client_handshake(sock: socket.socket, host: str, path: str) -> bytes:
    """Perform the client upgrade. Returns any frame bytes that arrived in
    the same recv() as the response headers — the caller MUST feed them to
    the frame reader before reading the socket again."""
    key = base64.b64encode(os.urandom(16)).decode()
    req = (
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n\r\n"
    )
    sock.sendall(req.encode())
    # read response headers
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("handshake failed")
        buf += chunk
    headers, _, leftover = buf.partition(b"\r\n\r\n")
    status = headers.split(b"\r\n", 1)[0]
    if b"101" not in status:
        raise ConnectionError(f"handshake rejected: {status.decode(errors='replace')}")
    expect = accept_key(key)
    for line in headers.split(b"\r\n"):
        if line.lower().startswith(b"sec-websocket-accept:"):
            got = line.split(b":", 1)[1].strip().decode()
            if got != expect:
                raise ConnectionError("bad accept key")
            return leftover
    raise ConnectionError("missing accept key")


class BufferedSocket:
    """recv() shim serving handshake-leftover bytes before the socket."""

    def __init__(self, sock: socket.socket, leftover: bytes = b""):
        self.sock = sock
        self._buf = leftover

    def recv(self, n: int) -> bytes:
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            return out
        return self.sock.recv(n)

    def sendall(self, data: bytes) -> None:
        self.sock.sendall(data)


class DaemonPool:
    """Tiny fixed-size worker pool on DAEMON threads (a stuck query must
    not block interpreter exit the way concurrent.futures' atexit-joined
    workers would — the surrounding HTTP handler threads are daemonized
    for the same reason). submit() returns a threading.Event that sets
    when the task finishes (exceptions included — tasks handle their own
    errors)."""

    def __init__(self, workers: int, target: str = "", owner=None):
        import queue as _queue

        from surrealdb_tpu import bg

        self._q: "_queue.Queue" = _queue.Queue()
        # flight-recorder registration (graftlint GL001): each worker is a
        # bg SERVICE task — deterministic bg:ws_worker:<conn>.<i> names,
        # visible in the task registry, resolved when shutdown() drains
        self._threads = [
            bg.spawn_service(
                "ws_worker",
                f"{target}.{i}" if target else str(i),
                self._worker,
                owner=owner,
                # supervised: a worker that dies on an uncaught exception
                # (panic-class faults included) restarts with backoff
                # instead of silently shrinking the pool
                restart=True,
            )
            for i in range(max(workers, 1))
        ]

    def _worker(self) -> None:
        import time as _time

        from surrealdb_tpu import telemetry

        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args, done, t_submit, cvctx = item
            telemetry.observe("ws_pool_queue_wait", _time.perf_counter() - t_submit)
            try:
                # run under the submitter's contextvars snapshot so trace
                # context (tracing.py) survives the thread hand-off
                cvctx.run(fn, *args)
            except Exception:  # noqa: BLE001 — tasks report their own errors
                # through their response frames; count the escape so a
                # crashing pool task is visible on /metrics regardless
                telemetry.inc("ws_pool_task_errors")
            finally:
                done.set()
                telemetry.gauge_add("ws_inflight", -1)

    def submit(self, fn, *args):
        import contextvars as _contextvars
        import threading as _threading
        import time as _time

        from surrealdb_tpu import telemetry

        telemetry.gauge_add("ws_inflight", 1)
        done = _threading.Event()
        self._q.put(
            (fn, args, done, _time.perf_counter(), _contextvars.copy_context())
        )
        return done

    def shutdown(self) -> None:
        for _ in self._threads:
            self._q.put(None)
