"""HTTP + WebSocket server.

Role of the reference's axum net layer + WS RPC actor (reference:
src/net/mod.rs:162-183 routes, src/rpc/connection.rs:80-417): routes /sql,
/rpc (HTTP msgpack POST and WS upgrade), /key/{tb}[/{id}] REST CRUD,
/signin, /signup, /health, /version, /export, /import. Sessions: WS
connections hold a stateful RpcContext; HTTP requests authenticate per
request from headers.

Wire formats: JSON (default, values via to_json_value) and msgpack (the
storage codec doubling as full-fidelity wire format) — content negotiation
via Content-Type/Accept (reference has 5 formats, core/src/rpc/format/).
"""

from __future__ import annotations

import json
import queue
import threading
from surrealdb_tpu.utils import locks as _locks
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import urlparse

from surrealdb_tpu import __version__
from surrealdb_tpu.dbs.session import Auth, Session
from surrealdb_tpu.err import InvalidAuthError, SurrealError
from surrealdb_tpu.rpc.method import RpcContext
from surrealdb_tpu.sql.value import to_json_value
from surrealdb_tpu.utils.ser import wire_pack as pack, wire_unpack

from . import ws as wsproto

# deterministic per-connection labels for the WS service threads
# (bg:ws_pump:connN / bg:ws_worker:connN.i in stack dumps + task registry)
import itertools as _itertools

_WS_CONN_SEQ = _itertools.count(1)


class BodyTooLarge(Exception):
    """Request body exceeds cnf.HTTP_MAX_BODY_SIZE; connection is dropped."""


def _capped(fn):
    """Route wrapper (the per-request middleware seam, reference
    src/net/mod.rs:68-183 + net/tracer.rs): request-id assignment, client-ip
    extraction, trace-context extraction (W3C `traceparent` or
    `surreal-trace-id`), duration telemetry, and the oversized-body 413
    guard. The root span of the request's trace opens here; `_send` echoes
    the trace id so clients can fetch the tree via GET /trace/:id."""

    def inner(self):
        import time as _time

        from surrealdb_tpu import telemetry, tracing
        from surrealdb_tpu.dbs.capabilities import HTTP_ROUTES

        seg = urlparse(self.path).path.split("/")[1] or "root"
        route = seg if seg in HTTP_ROUTES or seg == "root" else "_other"
        tid, parent = None, None
        tp = self.headers.get("traceparent")
        if tp:
            parsed = tracing.parse_traceparent(tp)
            if parsed is not None:
                tid, parent = parsed
        if tid is None and self.headers.get("surreal-trace-id"):
            tid = self.headers.get("surreal-trace-id")
        # a WS upgrade never gets a request-scoped trace: the handler runs
        # the connection loop for the socket's whole lifetime, and each RPC
        # frame mints its own trace — nesting those under one
        # connection-long root would mis-scope (and never finalize) them
        is_ws = (self.headers.get("Upgrade") or "").lower() == "websocket"
        t0 = _time.perf_counter()
        try:
            if is_ws:
                return fn(self)
            with tracing.request(
                "http_request",
                trace_id=tid,
                parent_id=parent,
                method=self.command or "?",
                route=route,
            ) as tr:
                self._trace_id = tr.trace_id if tr is not None else None
                return fn(self)
        except BodyTooLarge:
            return self._send(413, {"error": "request body too large"})
        finally:
            if is_ws:
                # fn() ran the connection loop until disconnect — that is a
                # connection lifetime, not an HTTP request latency, and
                # would blow out the request histogram's tail
                telemetry.observe(
                    "ws_connection_duration", _time.perf_counter() - t0
                )
            else:
                telemetry.observe(
                    "http_request_duration",
                    _time.perf_counter() - t0,
                    method=self.command or "?",
                    route=route,
                )

    return inner


class SurrealHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = f"surrealdb-tpu/{__version__}"
    ds = None  # set by serve()
    auth_enabled = True
    cors_origins = "*"  # None disables CORS headers entirely

    # ------------------------------------------------------------ plumbing
    def log_message(self, fmt, *args):  # quiet by default
        pass

    def parse_request(self):
        # one handler instance serves many keep-alive requests
        self.__dict__.pop("_cached_body", None)
        self.__dict__.pop("_req_id", None)
        self.__dict__.pop("_trace_id", None)
        return super().parse_request()

    def request_id(self) -> str:
        """Per-request id: the client's x-request-id when given (so traces
        compose across services), else a fresh UUID — echoed on every
        response (reference: src/net/mod.rs request-id layer)."""
        rid = self.__dict__.get("_req_id")
        if rid is None:
            import uuid as _uuid

            rid = self.headers.get("x-request-id") or str(_uuid.uuid4())
            self._req_id = rid[:128]
        return self._req_id

    def client_ip(self) -> str:
        """Originating client ip: first X-Forwarded-For hop, X-Real-IP, or
        the socket peer (reference: src/net/client_ip.rs)."""
        fwd = self.headers.get("x-forwarded-for")
        if fwd:
            return fwd.split(",")[0].strip()
        real = self.headers.get("x-real-ip")
        if real:
            return real.strip()
        return self.client_address[0]

    def _cors_headers(self) -> list:
        origins = self.cors_origins
        if origins is None:
            return []
        origin = self.headers.get("Origin")
        if origins == "*":
            allow = "*"
        elif isinstance(origins, str):
            # a single allowed origin — EXACT match (substring matching
            # would reflect attacker origins)
            if origin != origins:
                return []
            allow = origin
        elif origin and origin in origins:  # list/set membership
            allow = origin
        else:
            return []
        out = [("Access-Control-Allow-Origin", allow)]
        if allow != "*":
            out.append(("Vary", "Origin"))
        return out

    def do_OPTIONS(self):
        """CORS preflight (reference: src/net/mod.rs CorsLayer)."""
        self.send_response(204)
        for k, v in self._cors_headers():
            self.send_header(k, v)
        self.send_header("Access-Control-Allow-Methods", "GET, POST, PUT, PATCH, DELETE, OPTIONS")
        self.send_header(
            "Access-Control-Allow-Headers",
            "Authorization, Content-Type, Accept, NS, DB, surreal-ns, surreal-db, x-request-id",
        )
        self.send_header("Access-Control-Max-Age", "86400")
        self.send_header("x-request-id", self.request_id())
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _body(self) -> bytes:
        if not hasattr(self, "_cached_body"):
            from surrealdb_tpu import cnf

            try:
                n = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                n = -1
            if n < 0 or n > cnf.HTTP_MAX_BODY_SIZE:
                # never read an oversized body — respond 413 and drop the
                # connection (draining would block on bytes that may never
                # arrive)
                self._cached_body = b""
                self.close_connection = True
                raise BodyTooLarge()
            self._cached_body = self.rfile.read(n) if n else b""
        return self._cached_body

    def _send(
        self, code: int, payload: Any, content_type: str = "application/json"
    ) -> int:
        # returns the response body size so data routes (/sql) can charge
        # bytes_out to the session's tenant
        # drain any unread request body first, or the next keep-alive request
        # parses mid-stream
        self._body()
        if content_type == "application/json":
            body = json.dumps(to_json_value(payload)).encode()
        elif content_type == "application/msgpack":
            body = pack(payload)
        elif content_type == "application/cbor":
            from surrealdb_tpu.rpc import cbor as _cbor

            body = _cbor.encode(payload)
        else:
            body = payload if isinstance(payload, bytes) else str(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in self._cors_headers():
            self.send_header(k, v)
        self.send_header("x-request-id", self.request_id())
        tid = self.__dict__.get("_trace_id")
        if tid is not None:
            # echo the request's trace context (inbound id honored, fresh
            # ids discoverable); surreal-trace-id is ALWAYS the resolvable
            # /trace/:id key — traceparent only accompanies it when the id
            # is W3C-shaped (deriving one for an opaque id would name a
            # second, unresolvable trace). Root span id is always 1.
            from surrealdb_tpu import tracing

            self.send_header("surreal-trace-id", tid)
            if tracing.is_hex_trace_id(tid):
                self.send_header("traceparent", tracing.format_traceparent(tid, 1))
        self.end_headers()
        self.wfile.write(body)
        return len(body)

    def _session(self) -> Session:
        """Per-request session from headers (HTTP is stateless)."""
        ns = self.headers.get("surreal-ns") or self.headers.get("NS")
        db = self.headers.get("surreal-db") or self.headers.get("DB")
        sess = Session.anonymous(ns, db)
        auth_header = self.headers.get("Authorization") or ""
        if auth_header.startswith("Basic "):
            import base64

            try:
                user, _, pwd = base64.b64decode(auth_header[6:]).decode().partition(":")
            except Exception as e:
                raise InvalidAuthError() from e
            from surrealdb_tpu.iam.signin import basic_signin

            basic_signin(self.ds, sess, user, pwd, ns, db)
        elif auth_header.startswith("Bearer "):
            from surrealdb_tpu.iam.token import authenticate

            authenticate(self.ds, sess, auth_header[7:])
        elif not self.auth_enabled:
            sess = Session.owner(ns, db)
        sess.ns = sess.ns or ns
        sess.db = sess.db or db
        return sess

    def _authorized_session(self) -> Session:
        """Session for a data-access route: anonymous is rejected when auth
        is enabled unless the operator granted the guest-access capability
        (reference: capabilities.rs allows_guest_access, default deny)."""
        sess = self._session()
        if (
            self.auth_enabled
            and sess.auth.is_anon()
            and not self.ds.capabilities.allows_guest_access()
        ):
            raise InvalidAuthError()
        return sess

    def _system_gate(self):
        """Auth gate for debug surfaces that expose raw statement text
        (/slow, /traces, /trace/:id): require a system user when auth is
        enabled. Returns the session, or None after sending the 401."""
        try:
            sess = self._authorized_session()
            if self.auth_enabled and sess.auth.level not in ("db", "ns", "root"):
                raise InvalidAuthError()
            return sess
        except SurrealError as e:
            self._send(401, {"error": str(e)})
            return None

    def _cluster_query(self) -> bool:
        """True when the request asks for the cluster-federated variant of
        an observability surface (`?cluster=1`) AND this node can serve it
        (attached to a cluster)."""
        from urllib.parse import parse_qs

        q = parse_qs(urlparse(self.path).query)
        return (
            q.get("cluster", [""])[0] in ("1", "true")
            and self.ds.cluster is not None
        )

    def _route_allowed(self, route: str) -> bool:
        """HTTP-route capability gate (reference: RouteTarget allow/deny).
        Sends the 403 itself when denied."""
        if self.ds.capabilities.allows_http_route(route):
            return True
        from surrealdb_tpu.err import RouteNotAllowedError

        self._send(403, {"error": str(RouteNotAllowedError(route))})
        return False

    # ------------------------------------------------------------ routes
    @_capped
    def do_GET(self):
        path = urlparse(self.path).path
        from surrealdb_tpu import telemetry

        seg = path.split("/")[1] or "root"
        # bounded label: arbitrary client paths must not mint unbounded series
        from surrealdb_tpu.dbs.capabilities import HTTP_ROUTES

        telemetry.inc(
            "http_requests",
            method="GET",
            route=seg if seg in HTTP_ROUTES or seg == "root" else "_other",
        )
        if path == "/metrics":
            if not self._route_allowed("metrics"):
                return
            from surrealdb_tpu import telemetry

            if self._cluster_query():
                # federated scrape: every member's registry re-labeled
                # node=<id>, dead members as cluster_scrape_up 0. Unlike
                # the plain (local, cheap) render this fans RPCs out to
                # the whole membership on the scatter pool — debug-class
                # work, so system-gated like the other federation routes
                if self._system_gate() is None:
                    return
                from surrealdb_tpu.cluster.federation import federated_metrics

                return self._send(200, federated_metrics(self.ds).encode(), "text/plain")
            # refresh node runtime gauges (RSS, live queries, jit cache,
            # device memory) so the scrape sees current values
            telemetry.collect_node_metrics(self.ds)
            return self._send(
                200, telemetry.render_prometheus().encode(), "text/plain"
            )
        if path == "/traces" or path.startswith("/trace/"):
            # span trees carry statement text in labels, so like /slow the
            # endpoints need a system user, not just the route capability
            if not self._route_allowed("traces" if path == "/traces" else "trace"):
                return
            if self._system_gate() is None:
                return
            from urllib.parse import parse_qs, unquote

            from surrealdb_tpu import tracing

            if path == "/traces":
                return self._send(200, tracing.list_traces())
            doc = tracing.get_trace(unquote(path.split("/", 2)[2]))
            if doc is None:
                return self._send(404, {"error": "trace not found"})
            fmt = parse_qs(urlparse(self.path).query).get("format", [""])[0]
            if fmt == "chrome":
                return self._send(200, tracing.to_chrome(doc))
            return self._send(200, dict(doc, tree=tracing.span_tree(doc)))
        if path == "/debug/bundle":
            # one-shot flight-recorder bundle (bundle.py): traces + slow/
            # error rings + task registry + compile log + dispatch/mirror
            # state. Carries raw statement text, so system-user-gated like
            # /slow and /traces.
            if not self._route_allowed("debug"):
                return
            if self._system_gate() is None:
                return
            from surrealdb_tpu.bundle import debug_bundle

            if self._cluster_query():
                # the federated bundle: per-node sections merged under this
                # coordinator, dead members marked unreachable — still 200
                from surrealdb_tpu.cluster.federation import federated_bundle

                return self._send(200, federated_bundle(self.ds))
            return self._send(200, debug_bundle(self.ds))
        if path == "/events":
            # the structured event timeline (events.py): trace-linked
            # operational transitions. Carries trace ids + node/session
            # context, so system-gated like the other debug surfaces.
            if not self._route_allowed("events"):
                return
            if self._system_gate() is None:
                return
            from urllib.parse import parse_qs

            from surrealdb_tpu import events as _events

            q = parse_qs(urlparse(self.path).query)
            kind = q.get("kind", [None])[0]
            try:
                limit = int(q.get("limit", [None])[0]) if q.get("limit") else None
            except (TypeError, ValueError):
                limit = None
            if self._cluster_query():
                from surrealdb_tpu.cluster.federation import federated_events

                return self._send(
                    200, federated_events(self.ds, kind_prefix=kind, limit=limit)
                )
            return self._send(
                200, _events.snapshot(kind_prefix=kind, limit=limit)
            )
        if path == "/statements":
            # workload statistics plane (stats.py): cumulative per-
            # statement-shape stats + plan-mix vectors. Normalized SQL
            # shapes are statement text (literals erased, but identifiers
            # and structure intact), so system-gated like /slow and /traces.
            if not self._route_allowed("statements"):
                return
            if self._system_gate() is None:
                return
            from urllib.parse import parse_qs

            from surrealdb_tpu import stats as _stats

            q = parse_qs(urlparse(self.path).query)
            fp = q.get("fingerprint", [None])[0]
            try:
                limit = int(q.get("limit", [None])[0]) if q.get("limit") else 50
            except (TypeError, ValueError):
                limit = 50
            sort = q.get("sort", ["total_s"])[0]
            if self._cluster_query():
                from surrealdb_tpu.cluster.federation import federated_statements

                return self._send(
                    200,
                    federated_statements(
                        self.ds, limit=limit, fingerprint=fp, sort=sort
                    ),
                )
            rows = _stats.statements(limit=limit, fingerprint=fp, sort=sort)
            # plan-cache plane: annotate each shape with its cache state
            # (cached? variants? which dispatch fronts serve warm?)
            return self._send(200, self.ds.plan_cache.annotate(rows))
        if path == "/tenants":
            # tenant cost-attribution plane (accounting.py): per-(ns, db)
            # resource meters with per-fingerprint drill-down. Fingerprints
            # name statement shapes and namespaces name customers, so
            # system-gated like /statements.
            if not self._route_allowed("tenants"):
                return
            if self._system_gate() is None:
                return
            from urllib.parse import parse_qs

            from surrealdb_tpu import accounting as _accounting

            q = parse_qs(urlparse(self.path).query)
            try:
                limit = int(q.get("limit", [None])[0]) if q.get("limit") else 50
            except (TypeError, ValueError):
                limit = 50
            sort = q.get("sort", ["exec_s"])[0]
            if self._cluster_query():
                from surrealdb_tpu.cluster.federation import federated_tenants

                return self._send(
                    200, federated_tenants(self.ds, limit=limit, sort=sort)
                )
            return self._send(200, _accounting.top(limit=limit, sort=sort))
        if path == "/advisor":
            # advisor plane (advisor.py): evidence-chained tuning proposals
            # (observe-only; nothing is ever applied). Proposals cite
            # statement fingerprints and tenant namespaces, so system-gated
            # like /statements and /tenants.
            if not self._route_allowed("advisor"):
                return
            if self._system_gate() is None:
                return
            from urllib.parse import parse_qs

            from surrealdb_tpu import advisor as _advisor

            q = parse_qs(urlparse(self.path).query)
            kind = q.get("kind", [None])[0]
            try:
                limit = int(q.get("limit", [None])[0]) if q.get("limit") else 50
            except (TypeError, ValueError):
                limit = 50
            if self._cluster_query():
                from surrealdb_tpu.cluster.federation import federated_advisor

                return self._send(
                    200, federated_advisor(self.ds, limit=limit)
                )
            if kind:
                return self._send(
                    200, {"proposals": _advisor.proposals(limit=limit, kind=kind)}
                )
            return self._send(200, _advisor.snapshot(limit=limit))
        if path == "/slow":
            # structured slow-query log (ring buffer; dbs/executor.py) — the
            # /metrics-adjacent debug endpoint. Entries carry raw statement
            # text which may embed data literals, so like /export it needs a
            # system user, not just the route capability
            if not self._route_allowed("slow"):
                return
            if self._system_gate() is None:
                return
            from surrealdb_tpu import telemetry

            return self._send(200, telemetry.slow_queries())
        if path == "/health":
            if not self._route_allowed("health"):
                return
            return self._send(200, {"status": "ok"})
        if path == "/version":
            if not self._route_allowed("version"):
                return
            return self._send(200, f"surrealdb-tpu-{__version__}", "text/plain")
        if path == "/rpc" and (self.headers.get("Upgrade") or "").lower() == "websocket":
            if not self._route_allowed("rpc"):
                return
            return self._ws_upgrade()
        if path == "/export":
            if not self._route_allowed("export"):
                return
            try:
                sess = self._authorized_session()
                # export dumps raw KV state, bypassing table/field PERMISSIONS,
                # so it requires a *system* user covering this db — record-access
                # users (public /signup) must not reach it (reference:
                # src/net/export.rs db.check(View, Any.on_db(..)))
                if self.auth_enabled:
                    a = sess.auth
                    if a.level not in ("db", "ns", "root") or not a.has_db_access(
                        sess.ns, sess.db
                    ):
                        raise InvalidAuthError()
                from surrealdb_tpu.kvs.export import export_database

                return self._send(200, export_database(self.ds, sess), "text/plain")
            except SurrealError as e:
                return self._send(401, {"error": str(e)})
        if path.startswith("/ml/export/"):
            if not self._route_allowed("ml"):
                return
            return self._ml_export(path)
        if path.startswith("/key/"):
            if not self._route_allowed("key"):
                return
            return self._key_route("GET")
        return self._send(404, {"error": "not found"})

    @_capped
    def do_POST(self):
        from surrealdb_tpu import telemetry

        telemetry.inc(
            "http_requests",
            method="POST",
            route=urlparse(self.path).path.split("/")[1] or "root",
        )
        path = urlparse(self.path).path
        if path == "/sql":
            if not self._route_allowed("sql"):
                return
            return self._sql()
        if path == "/rpc":
            if not self._route_allowed("rpc"):
                return
            return self._rpc_http()
        if path == "/signin":
            if not self._route_allowed("signin"):
                return
            return self._auth_route("signin")
        if path == "/signup":
            if not self._route_allowed("signup"):
                return
            return self._auth_route("signup")
        if path == "/cluster":
            # internal shard-to-shard channel (surrealdb_tpu/cluster/):
            # CBOR ops authenticated by the shared cluster secret, NOT by
            # user auth — the coordinator's public ingress enforced that.
            # 404 (not 403) when this node is not in a cluster, so a
            # misrouted public client learns nothing about the topology.
            if self.ds.cluster is None:
                return self._send(404, {"error": "not found"})
            if not self._route_allowed("cluster"):
                return
            secret = self.ds.cluster.config.secret
            if secret:
                import hmac as _hmac

                from surrealdb_tpu import events, telemetry
                from surrealdb_tpu.cluster.config import derive_node_key

                # per-node derived credential: recompute HMAC(secret,
                # node:epoch) from the request's own derivation inputs and
                # constant-time compare — the shared secret never rides the
                # wire, so a captured header is one node's one-epoch
                # credential, not cluster-wide system privilege
                given = self.headers.get("x-surreal-cluster-key") or ""
                node = self.headers.get("x-surreal-cluster-node") or ""
                epoch = self.headers.get("x-surreal-cluster-epoch") or "0"
                expect = derive_node_key(secret, node, epoch)
                if not given or not _hmac.compare_digest(given, expect):
                    telemetry.inc("cluster_auth_rejects")
                    events.emit("cluster.auth_reject", node=node)
                    return self._send(401, {"error": "bad cluster key"})
            from surrealdb_tpu.cluster import rpc as _cluster_rpc
            from surrealdb_tpu.rpc import cbor as _cbor

            try:
                req = _cbor.decode(self._body())
            except SurrealError:
                return self._send(400, {"error": "invalid CBOR body"})
            if not isinstance(req, dict):
                return self._send(400, {"error": "cluster request must be a map"})
            return self._send(
                200, _cluster_rpc.handle(self.ds, req), "application/cbor"
            )
        if path == "/ml/import":
            if not self._route_allowed("ml"):
                return
            return self._ml_import()
        if path == "/graphql":
            if not self._route_allowed("graphql"):
                return
            return self._graphql()
        if path == "/import":
            if not self._route_allowed("import"):
                return
            try:
                sess = self._authorized_session()
                out = self.ds.execute(self._body().decode(), sess)
                return self._send(200, out)
            except InvalidAuthError as e:
                return self._send(401, {"error": str(e)})
            except SurrealError as e:
                return self._send(400, {"error": str(e)})
        if path.startswith("/key/"):
            return self._key_route("POST")
        return self._send(404, {"error": "not found"})

    @_capped
    def do_PUT(self):
        if urlparse(self.path).path.startswith("/key/"):
            if not self._route_allowed("key"):
                return
            return self._key_route("PUT")
        return self._send(404, {"error": "not found"})

    @_capped
    def do_PATCH(self):
        if urlparse(self.path).path.startswith("/key/"):
            if not self._route_allowed("key"):
                return
            return self._key_route("PATCH")
        return self._send(404, {"error": "not found"})

    @_capped
    def do_DELETE(self):
        if urlparse(self.path).path.startswith("/key/"):
            if not self._route_allowed("key"):
                return
            return self._key_route("DELETE")
        return self._send(404, {"error": "not found"})

    # ------------------------------------------------------------ handlers
    def _sql(self):
        try:
            sess = self._authorized_session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        body = self._body()
        text = body.decode()
        try:
            out = self.ds.execute(text, sess)
        except SurrealError as e:
            return self._send(400, {"error": str(e)})
        sent = self._send(200, out)
        # wire cost: charged here, at the protocol edge, because only the
        # edge knows the serialized sizes (the executor sees row counts)
        from surrealdb_tpu import accounting

        accounting.charge(
            sess.ns, sess.db, bytes_in=float(len(body)), bytes_out=float(sent)
        )
        return None

    def _auth_route(self, kind: str):
        try:
            creds = json.loads(self._body() or b"{}")
        except json.JSONDecodeError:
            return self._send(400, {"error": "invalid JSON"})
        sess = Session.anonymous()
        try:
            if kind == "signin":
                from surrealdb_tpu.iam.signin import signin

                token = signin(self.ds, sess, creds)
            else:
                from surrealdb_tpu.iam.signup import signup

                token = signup(self.ds, sess, creds)
            return self._send(200, {"code": 200, "details": "Authentication succeeded", "token": token})
        except SurrealError as e:
            return self._send(401, {"code": 401, "details": str(e)})

    def _key_route(self, verb: str):
        """REST /key/{tb}[/{id}] (reference: src/net/key.rs)."""
        from urllib.parse import unquote

        from surrealdb_tpu.sql.value import Thing, escape_ident

        parts = urlparse(self.path).path.split("/")[2:]
        tb = unquote(parts[0]) if parts else None
        rid = unquote(parts[1]) if len(parts) > 1 else None
        if not tb:
            return self._send(400, {"error": "missing table"})
        try:
            sess = self._authorized_session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        # escape path segments — they are identifiers, not SurrealQL
        if rid is not None and rid.lstrip("-").isdigit():
            rid = int(rid)
        target = repr(Thing(tb, rid)) if rid is not None else escape_ident(tb)
        body = self._body()
        try:
            data = json.loads(body) if body else None
        except json.JSONDecodeError:
            return self._send(400, {"error": "invalid JSON body"})
        vars = {"_data": data}
        q = {
            "GET": f"SELECT * FROM {target}",
            "POST": f"CREATE {target} CONTENT $_data",
            "PUT": f"UPSERT {target} CONTENT $_data",
            "PATCH": f"UPSERT {target} MERGE $_data",
            "DELETE": f"DELETE {target} RETURN BEFORE",
        }[verb]
        try:
            out = self.ds.execute(q, sess, vars if data is not None else None)
        except SurrealError as e:
            return self._send(400, {"error": str(e)})
        return self._send(200, out)

    # RPC methods an unauthenticated client may always call (the
    # authentication bootstrap itself plus connection management); whether
    # anonymous clients may call anything ELSE is the operator-controlled
    # guest-access capability (reference: rpc layer + allows_guest_access)
    _RPC_ANON_METHODS = frozenset(
        {"ping", "version", "use", "signin", "signup", "authenticate", "invalidate"}
    )

    def _rpc_denied(self, method: str, sess) -> str | None:
        """Capability policy for one RPC call; returns a denial message or
        None. Method allow/deny applies to every caller; anonymous callers
        additionally need guest access for non-bootstrap methods."""
        if not self.ds.capabilities.allows_rpc_method(method):
            from surrealdb_tpu.err import MethodNotAllowedError

            return str(MethodNotAllowedError(method))
        if (
            self.auth_enabled
            and sess.auth.is_anon()
            and method not in self._RPC_ANON_METHODS
            and not self.ds.capabilities.allows_guest_access()
        ):
            return "Not authenticated"
        return None

    def _system_session(self):
        """Session for model import/export: system user covering the db
        (reference: src/net/ml.rs check on Edit/View)."""
        sess = self._authorized_session()
        if self.auth_enabled:
            a = sess.auth
            if a.level not in ("db", "ns", "root") or not a.has_db_access(sess.ns, sess.db):
                raise InvalidAuthError()
        return sess

    def _ml_import(self):
        try:
            sess = self._system_session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        body = self._body()
        ct = (self.headers.get("Content-Type") or "").split(";")[0]
        if ct == "application/octet-stream" or (body[:1] not in (b"{", b"[")):
            # binary .surml upload (reference src/net/ml.rs import route)
            from surrealdb_tpu.ml.exec import import_surml

            try:
                entry = import_surml(self.ds, sess, body)
            except SurrealError as e:
                return self._send(400, {"error": str(e)})
            return self._send(
                200,
                {"name": entry["name"], "version": entry["version"], "blob": entry["blob"]},
            )
        try:
            spec = json.loads(body)
        except json.JSONDecodeError:
            return self._send(400, {"error": "invalid JSON model spec"})
        from surrealdb_tpu.ml.exec import import_model

        try:
            entry = import_model(
                self.ds, sess, spec.get("name", ""), spec.get("version", ""), spec
            )
        except SurrealError as e:
            return self._send(400, {"error": str(e)})
        except (ValueError, TypeError, AttributeError, KeyError) as e:
            # validate_spec raises these on malformed specs (ragged weight
            # lists, non-dict layers, …) — a bad spec is a client error,
            # never a handler crash; anything else is a genuine 500
            return self._send(400, {"error": f"invalid model spec: {e}"})
        return self._send(200, {"name": entry["name"], "version": entry["version"], "blob": entry["blob"]})

    def _ml_export(self, path: str):
        try:
            sess = self._system_session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        parts = path.split("/")[3:]  # /ml/export/{name}/{version}
        if len(parts) != 2:
            return self._send(400, {"error": "expected /ml/export/{name}/{version}"})
        from urllib.parse import unquote

        from surrealdb_tpu.ml.exec import export_model

        try:
            return self._send(200, export_model(self.ds, sess, unquote(parts[0]), unquote(parts[1])))
        except SurrealError as e:
            return self._send(404, {"error": str(e)})

    def _graphql(self):
        """POST /graphql: {"query": ..., "variables": {...}} (reference:
        src/net/gql.rs; gated by SURREAL_EXPERIMENTAL_GRAPHQL)."""
        try:
            sess = self._authorized_session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        try:
            req = json.loads(self._body())
        except json.JSONDecodeError:
            return self._send(400, {"error": "invalid JSON body"})
        if not isinstance(req, dict):
            return self._send(400, {"error": "GraphQL request must be a JSON object"})
        from surrealdb_tpu.gql import execute_graphql

        try:
            return self._send(200, execute_graphql(self.ds, sess, req))
        except SurrealError as e:
            return self._send(400, {"error": str(e)})
        except Exception as e:  # malformed inputs must never kill the handler
            return self._send(400, {"error": f"{type(e).__name__}: {e}"})

    def _rpc_http(self):
        ct = (self.headers.get("Content-Type") or "application/json").split(";")[0]
        body = self._body()
        try:
            if ct == "application/msgpack":
                req = wire_unpack(body)
            elif ct == "application/cbor":
                from surrealdb_tpu.rpc import cbor as _cbor

                req = _cbor.decode(body)
            else:
                req = json.loads(body)
        except Exception:
            return self._send(400, {"error": "invalid request body"})
        try:
            sess = self._session()
        except SurrealError as e:
            return self._send(401, {"error": str(e)})
        rid = req.get("id")
        method = req.get("method", "")
        denied = self._rpc_denied(method, sess)
        if denied is not None:
            return self._send(
                401, {"id": rid, "error": {"code": -32000, "message": denied}}, ct
            )
        ctx = RpcContext(self.ds, sess)
        try:
            result = ctx.execute(method, req.get("params") or [])
            resp = {"id": rid, "result": result}
        except SurrealError as e:
            resp = {"id": rid, "error": {"code": -32000, "message": str(e)}}
        return self._send(200, resp, ct)

    def _ws_encode(self, payload) -> bytes:
        if getattr(self, "_ws_proto", None) == "cbor":
            from surrealdb_tpu.rpc import cbor as _cbor

            return _cbor.encode(payload)
        return pack(payload)

    # ------------------------------------------------------------ websocket
    def _ws_upgrade(self):
        key = self.headers.get("Sec-WebSocket-Key")
        if not key:
            return self._send(400, {"error": "bad websocket request"})
        # format negotiation via subprotocol (reference rpc/format/mod.rs:
        # json | cbor | msgpack; binary frames use the negotiated codec)
        offered = [
            p.strip()
            for p in (self.headers.get("Sec-WebSocket-Protocol") or "").split(",")
            if p.strip()
        ]
        proto = next((p for p in offered if p in ("json", "cbor", "msgpack")), None)
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", wsproto.accept_key(key))
        if proto:
            self.send_header("Sec-WebSocket-Protocol", proto)
        self.end_headers()
        self.wfile.flush()
        self._ws_proto = proto

        sock = self.connection
        sess = Session.anonymous()
        sess.rt = True
        if not self.auth_enabled:
            sess = Session.owner(None, None)
            sess.ns = sess.db = None
        ctx = RpcContext(self.ds, sess)
        send_lock = _locks.Lock("net.ws_send")
        alive = {"v": True}
        # wire format follows the client's most recent request frame so JSON
        # (text) clients receive notifications they can actually decode
        fmt = {"binary": False}

        # live-notification pump: drain ONLY this connection's live queries
        def pump():
            import time as _t

            hub = self.ds.notifications
            while alive["v"]:
                sent = False
                if hub is not None:
                    for live_id in list(ctx.live_ids):
                        try:
                            n = hub.subscribe(live_id).get_nowait()
                        except (queue.Empty, KeyError):
                            continue
                        note = {"result": n.to_value()}
                        if fmt["binary"]:
                            frame = wsproto.encode_frame(
                                wsproto.OP_BINARY, self._ws_encode(note)
                            )
                        else:
                            frame = wsproto.encode_frame(
                                wsproto.OP_TEXT, json.dumps(to_json_value(note)).encode()
                            )
                        with send_lock:
                            try:
                                sock.sendall(frame)
                            except OSError:
                                return
                        sent = True
                if not sent:
                    _t.sleep(0.02)

        self.ds.enable_notifications()
        # flight-recorder registration: the pump used to be an anonymous
        # daemon thread — a blind spot in every stack dump and task-registry
        # view (graftlint GL001). conn label makes the name deterministic.
        from surrealdb_tpu import bg

        conn = f"conn{next(_WS_CONN_SEQ)}"
        bg.spawn_service("ws_pump", conn, pump, owner=id(self.ds), restart=True)

        # per-socket concurrent request pool (reference: the WS actor's
        # concurrent-request semaphore, src/rpc/connection.rs:80-147).
        # Concurrency here is what lets one connection's queries coalesce
        # into shared kernel launches (dbs/dispatch.py); session-mutating
        # methods drain in-flight work first and run inline so `use`/
        # `signin` can't race a concurrently-executing query.
        from surrealdb_tpu import cnf, telemetry
        from surrealdb_tpu.net.ws import DaemonPool

        telemetry.gauge_add("ws_connections", 1)
        pool = DaemonPool(
            max(cnf.WEBSOCKET_MAX_CONCURRENT_REQUESTS, 1),
            target=conn, owner=id(self.ds),
        )
        inflight: list = []
        _SESSION_METHODS = {
            "use", "signin", "signup", "authenticate", "invalidate",
            "let", "set", "unset", "reset",
        }

        def handle(req: dict, binary: bool) -> None:
            from surrealdb_tpu import tracing

            rid = req.get("id")
            method = req.get("method", "")
            # per-frame trace context: a client-supplied `trace` field (a
            # 32-hex trace id or a full W3C traceparent) is honored and
            # echoed; every statement of a multi-statement `query` frame
            # shares this one trace
            t_field = req.get("trace")
            tid, t_parent = None, None
            if isinstance(t_field, str) and t_field:
                parsed = tracing.parse_traceparent(t_field)
                if parsed is not None:
                    tid, t_parent = parsed
                else:
                    tid = t_field
            frame = None
            tr = None
            try:
                # the trace opens BEFORE the capability check so a denied
                # request still yields a retrievable (errored, pinned)
                # trace under the id the client supplied
                with tracing.request(
                    "ws_rpc", trace_id=tid, parent_id=t_parent, method=str(method)
                ) as tr:
                    # same capability policy as HTTP /rpc; checked per
                    # message because signin/authenticate upgrade the
                    # session mid-stream
                    denied = self._rpc_denied(method, ctx.session)
                    if denied is not None:
                        raise InvalidAuthError(denied)
                    result = ctx.execute(method, req.get("params") or [])
                resp: Dict[str, Any] = {"id": rid, "result": result}
                if tr is not None and tid is not None:
                    resp["trace"] = tr.trace_id
                # encode INSIDE the guard: an unserializable result must
                # still produce an error frame, never a silent dropped id
                if binary:
                    frame = wsproto.encode_frame(wsproto.OP_BINARY, self._ws_encode(resp))
                else:
                    frame = wsproto.encode_frame(
                        wsproto.OP_TEXT, json.dumps(to_json_value(resp)).encode()
                    )
            except Exception as e:  # noqa: BLE001 — a worker must not die silently
                msg = str(e) if isinstance(e, SurrealError) else f"Internal error: {e}"
                resp = {"id": rid, "error": {"code": -32000, "message": msg}}
                # echo the id the trace is actually STORED under (an opaque
                # client id may have been sanitized) — never a derived one
                if tid is not None and tr is not None:
                    resp["trace"] = tr.trace_id
                if binary:
                    frame = wsproto.encode_frame(wsproto.OP_BINARY, self._ws_encode(resp))
                else:
                    frame = wsproto.encode_frame(
                        wsproto.OP_TEXT, json.dumps(to_json_value(resp)).encode()
                    )
            try:
                with send_lock:
                    sock.sendall(frame)
            except OSError:
                pass

        try:
            while True:
                # read via the buffered rfile (it may hold early frame bytes)
                op, payload = wsproto.read_frame(self.rfile)
                if op == wsproto.OP_CLOSE:
                    with send_lock:
                        sock.sendall(wsproto.encode_frame(wsproto.OP_CLOSE, b""))
                    break
                if op == wsproto.OP_PING:
                    with send_lock:
                        sock.sendall(wsproto.encode_frame(wsproto.OP_PONG, payload))
                    continue
                if op not in (wsproto.OP_TEXT, wsproto.OP_BINARY):
                    continue
                fmt["binary"] = op == wsproto.OP_BINARY
                try:
                    if op != wsproto.OP_BINARY:
                        req = json.loads(payload)
                    elif getattr(self, "_ws_proto", None) == "cbor":
                        from surrealdb_tpu.rpc import cbor as _cbor

                        req = _cbor.decode(payload)
                    else:
                        req = wire_unpack(payload)
                except Exception:
                    continue
                if not isinstance(req, dict):
                    continue
                inflight = [ev for ev in inflight if not ev.is_set()]
                # width of the per-socket concurrent-request window — how
                # many requests ride this socket's pool simultaneously (the
                # population that can coalesce into shared kernel launches)
                telemetry.observe_hist("ws_inflight_width", len(inflight) + 1)
                if str(req.get("method", "")).lower() in _SESSION_METHODS:
                    for ev in inflight:
                        ev.wait()
                    inflight.clear()
                    handle(req, op == wsproto.OP_BINARY)
                else:
                    inflight.append(pool.submit(handle, req, op == wsproto.OP_BINARY))
        except (ConnectionError, OSError):
            pass
        finally:
            alive["v"] = False
            pool.shutdown()
            telemetry.gauge_add("ws_connections", -1)
            # disconnect sweep: KILL this connection's remaining live
            # queries — every close/error path used to leak them into the
            # notification hub forever
            ctx.close()
        self.close_connection = True


class _LoopHttpd:
    """`httpd`-shaped facade over the event-loop ingress. Embedders (and
    a decade of tests) reach through `server.httpd` for the bound handler
    class (`.RequestHandlerClass.ds`) and abrupt teardown
    (`.server_close()`); loop mode keeps both spellings working."""

    def __init__(self, handler_cls, netloop):
        self.RequestHandlerClass = handler_cls
        self._netloop = netloop
        self.server_address = (netloop.host, netloop.port)

    def serve_forever(self) -> None:
        self._netloop.serve_forever()

    def shutdown(self) -> None:
        self._netloop.shutdown()

    def server_close(self) -> None:
        self._netloop.server_close()


class Server:
    """Embedded server handle (reference: `surreal start`).

    Ingress is the selector event loop (net/loop.py) unless
    `SURREAL_NET_LOOP=0` or TLS is configured — TLS handshakes are
    blocking per-socket work, so certificates keep the thread-per-
    connection ingress (documented fallback, not a silent downgrade)."""

    def __init__(
        self,
        ds,
        host: str = "127.0.0.1",
        port: int = 8000,
        auth_enabled: bool = True,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        cors_origins="*",
    ):
        from surrealdb_tpu import cnf

        handler = type(
            "BoundHandler",
            (SurrealHandler,),
            {"ds": ds, "auth_enabled": auth_enabled, "cors_origins": cors_origins},
        )
        self.tls = bool(tls_cert)
        self.loop_mode = bool(cnf.NET_LOOP) and not tls_cert
        if self.loop_mode:
            from surrealdb_tpu.net.loop import EventLoopServer

            self.netloop = EventLoopServer(handler, host, port)
            self.httpd = _LoopHttpd(handler, self.netloop)
        else:
            self.netloop = None
            self.httpd = ThreadingHTTPServer((host, port), handler)
            if tls_cert:
                # TLS termination (reference: surreal start --web-crt/--web-key)
                import ssl

                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(tls_cert, tls_key or tls_cert)
                self.httpd.socket = ctx.wrap_socket(
                    self.httpd.socket, server_side=True
                )
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        # node membership bootstrap (reference ds.rs:623): register this
        # node and archive dead nodes' live queries
        try:
            ds.bootstrap()
        except Exception:  # noqa: BLE001 — single-node boot must not die
            from surrealdb_tpu import telemetry

            # counted, not silent: a boot that skipped node registration
            # serves fine single-node but is a membership-protocol gap
            telemetry.inc("bootstrap_errors")
        # periodic maintenance (heartbeat + membership + changefeed GC —
        # reference engine/tasks.rs)
        self._tick_stop = threading.Event()

        def tick_loop():
            from surrealdb_tpu import cnf

            # no inner swallow: an uncaught tick failure (a wedged GC
            # sweep, an injected bg.changefeed_gc panic) propagates to the
            # service supervisor, which restarts the loop with capped
            # backoff and counts bg_service_restarts{kind="tick"} — a
            # crash is a metric, not a silent death of all maintenance
            while not self._tick_stop.wait(cnf.CHANGEFEED_GC_INTERVAL_SECS):
                ds.tick()

        from surrealdb_tpu import bg

        self._ticker = bg.spawn_service(
            "tick", "server", tick_loop, owner=id(ds), restart=True
        )

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def start_background(self) -> "Server":
        if self.netloop is not None:
            # the loops ARE the background threads (bg:net_loop:N services)
            self.netloop.start()
            return self
        from surrealdb_tpu import bg

        # detached accept loop: requests mint their own traces inside
        # graftflow: disable=GF002
        self._thread = bg.spawn_service(
            "http_serve", f"{self.host}:{self.port}", self.httpd.serve_forever
        )
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self._tick_stop.set()
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def serve(
    path: str = "memory",
    host: str = "127.0.0.1",
    port: int = 8000,
    auth_enabled: bool = True,
    capabilities=None,
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
    cors_origins="*",
    cluster_config=None,
) -> Server:
    from surrealdb_tpu.kvs.ds import Datastore

    ds = Datastore(path)
    ds.enable_notifications()
    if capabilities is not None:
        ds.capabilities = capabilities
    if cluster_config is not None:
        # sharded serving: this node owns its consistent-hash slice and
        # coordinates scatter/gather for queries that arrive here
        from surrealdb_tpu import cluster as _cluster

        _cluster.attach(ds, cluster_config)
    return Server(
        ds, host, port, auth_enabled,
        tls_cert=tls_cert, tls_key=tls_key, cors_origins=cors_origins,
    )
