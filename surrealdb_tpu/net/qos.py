"""Per-tenant weighted-fair admission control (the C1M QoS plane).

The r10 admission story was one global semaphore on the cluster
coordinator (cluster/executor.py): overload degraded to bounded latency,
but one tenant's pathological statement shape throttled the NODE, not
the tenant. This plane promotes admission to the ingress and keys it by
tenant `(ns, db)`:

- every tenant gets a **token bucket** (`SURREAL_NET_TENANT_RATE`
  tokens/s refill into a `SURREAL_NET_TENANT_BURST` bucket; rate 0
  disables rate limiting) and an **in-flight quota**
  (`SURREAL_NET_TENANT_INFLIGHT` concurrently-executing requests);
- past either bound a request is QUEUED (`net.throttle`, counted) up to
  `SURREAL_NET_ADMIT_QUEUE` entries per tenant, then SHED
  (`net.admission_shed`, counted) — overload is a bounded queue and a
  clean refusal, never collapse;
- queued work drains **weighted-fair** (start-time fair queueing): each
  tenant carries a virtual clock; dispatching a request advances it by
  `cost / weight`, and the scheduler always serves the eligible tenant
  with the SMALLEST virtual time. `cost` is the r16 per-fingerprint p99
  estimate (stats.py); `weight` derives from the r17 accounting meters
  (accounting.py) — a tenant consuming more than its fair share of
  `exec_s` earns a proportionally smaller weight (clamped to
  [0.25, 4.0]), so an expensive statement shape throttles ITS tenant
  while cheap tenants sail past it in the same queue structure.

Internal cluster RPCs ride a DEDICATED class (`cls="internal"`) with its
own in-flight bound (`SURREAL_NET_INTERNAL_INFLIGHT`) and FIFO queue:
scatter traffic can never be starved by tenant queues, and tenants can
never consume internal slots.

Lock discipline: `net.qos` is leaf-style — decisions happen under the
lock; admitted callbacks, events and counters fire AFTER release (events
and telemetry are lower hierarchy levels and must never nest inside).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from surrealdb_tpu import cnf
from surrealdb_tpu.utils import locks as _locks

INTERNAL = "internal"  # the cluster-channel QoS class


class Shed(Exception):
    """Request refused by admission control (bounded-queue overflow or a
    closed server); the transport answers 503 and the client may retry."""

    def __init__(self, reason: str, ns: str = "", db: str = ""):
        super().__init__(
            f"admission control shed request ({reason}) for tenant "
            f"({ns or '-'}, {db or '-'}) — server overloaded, retry later"
        )
        self.reason = reason
        self.ns, self.db = ns, db


class _Tenant:
    __slots__ = (
        "key", "tokens", "last_refill", "inflight", "queue", "vtime",
        "last_start", "admitted", "shed", "throttled",
    )

    def __init__(self, key: Tuple[str, str], now: float):
        self.key = key
        self.tokens = max(cnf.NET_TENANT_BURST, 1.0)
        self.last_refill = now
        self.inflight = 0
        # (fingerprint, cost_ms, on_admit, enqueue_t)
        self.queue: Deque[tuple] = deque()
        self.vtime = 0.0
        self.last_start = 0.0
        self.admitted = 0
        self.shed = 0
        self.throttled = 0


_lock = _locks.Lock("net.qos")
_tenants: Dict[Tuple[str, str], _Tenant] = {}
_internal_inflight = 0
_internal_queue: Deque[tuple] = deque()
_vclock = 0.0  # floor for new/idle tenants so they can't replay the past
_totals = {"admitted": 0, "shed": 0, "throttled": 0}


def _key(ns: Optional[str], db: Optional[str]) -> Tuple[str, str]:
    return (ns or "", db or "")


# ------------------------------------------------------------------ inputs
def cost_estimate_ms(fingerprint: Optional[str]) -> float:
    """The r16 plane's per-shape cost estimate: the fingerprint's p99 (its
    tail is what a scheduler must budget for), falling back to the mean
    and then to one quantum for never-seen shapes."""
    floor = max(cnf.NET_QOS_QUANTUM_MS, 0.1)
    if not fingerprint:
        return floor
    from surrealdb_tpu import stats

    d = stats.get(fingerprint)
    if not d:
        return floor
    est = d.get("p99_ms") or d.get("mean_ms")
    return max(float(est), floor) if est else floor


def tenant_weight(ns: Optional[str], db: Optional[str]) -> float:
    """The r17 plane's fairness input: `fair_share / tenant_exec_s`,
    clamped to [0.25, 4.0]. A tenant burning 4x the per-tenant fair share
    of engine seconds earns a quarter-weight queue; an idle one at most
    4x. Tenants with no history (or an empty store) weigh 1.0."""
    from surrealdb_tpu import accounting

    e = accounting.get(ns, db)
    if e is None:
        return 1.0
    t_exec = float(e.get("exec_s") or 0.0)
    if t_exec <= 0.0:
        return 1.0
    total = float(accounting.global_totals().get("exec_s") or 0.0)
    n = max(accounting.size(), 1)
    fair = total / n
    if fair <= 0.0:
        return 1.0
    return min(max(fair / t_exec, 0.25), 4.0)


# ------------------------------------------------------------------ engine
def _refill(t: _Tenant, now: float) -> None:
    rate = cnf.NET_TENANT_RATE
    if rate <= 0:
        return
    burst = max(cnf.NET_TENANT_BURST, 1.0)
    t.tokens = min(burst, t.tokens + (now - t.last_refill) * rate)
    t.last_refill = now


def _eligible(t: _Tenant, now: float) -> bool:
    if not t.queue:
        return False
    if t.inflight >= max(cnf.NET_TENANT_INFLIGHT, 1):
        return False
    _refill(t, now)
    return cnf.NET_TENANT_RATE <= 0 or t.tokens >= 1.0


def _drain_locked(now: float) -> List[tuple]:
    """Dispatch everything admittable; returns [(on_admit, wait_s), ...]
    to invoke after the lock is released."""
    global _internal_inflight, _vclock
    out: List[tuple] = []
    # internal class first: dedicated slots, plain FIFO, never starved
    while (
        _internal_queue
        and _internal_inflight < max(cnf.NET_INTERNAL_INFLIGHT, 1)
    ):
        _fp, _cost, on_admit, t0 = _internal_queue.popleft()
        _internal_inflight += 1
        _totals["admitted"] += 1
        out.append((on_admit, now - t0))
    # tenant classes: start-time fair queueing over the eligible set
    while True:
        best: Optional[_Tenant] = None
        for t in _tenants.values():
            if _eligible(t, now) and (best is None or t.vtime < best.vtime):
                best = t
        if best is None:
            break
        fp, cost_ms, on_admit, t0, weight = best.queue.popleft()
        best.inflight += 1
        if cnf.NET_TENANT_RATE > 0:
            best.tokens -= 1.0
        # the virtual clock advance IS the weighting: cost from the r16
        # stats plane, weight from the r17 accounting plane
        best.last_start = max(best.vtime, _vclock)
        best.vtime = best.last_start + cost_ms / max(weight, 1e-6)
        best.admitted += 1
        _totals["admitted"] += 1
        out.append((on_admit, now - t0))
    # advance the floor to the smallest busy START tag (not finish tag: a
    # heavy admit's finish is far in the future, and a floor taken from it
    # would charge newly-arriving tenants for work they never submitted)
    busy = [t.last_start for t in _tenants.values() if t.queue or t.inflight]
    if busy:
        _vclock = max(_vclock, min(busy))
    return out


def _fire(admitted: List[tuple]) -> None:
    from surrealdb_tpu import telemetry

    for on_admit, wait_s in admitted:
        if wait_s > 1e-4:
            telemetry.observe("net_admission_wait", wait_s)
        on_admit()


def submit(
    ns: Optional[str],
    db: Optional[str],
    on_admit: Callable[[], None],
    *,
    fingerprint: Optional[str] = None,
    cls: str = "tenant",
) -> None:
    """Admit-or-queue `on_admit` for tenant `(ns, db)`. The callback runs
    synchronously when a slot is free NOW, else later from whichever
    thread releases the unblocking slot (or from poll()). Raises Shed
    when the tenant's bounded queue is full; the caller answers 503."""
    from surrealdb_tpu import events, telemetry

    if not cnf.NET_QOS:
        on_admit()
        return
    now = time.monotonic()
    key = _key(ns, db)
    throttled = False
    with _lock:
        if cls == INTERNAL:
            if len(_internal_queue) >= 4 * max(cnf.NET_ADMIT_QUEUE, 1):
                _totals["shed"] += 1
                shed = Shed("internal queue full", *key)
            else:
                _internal_queue.append((fingerprint, 0.0, on_admit, now))
                shed = None
        else:
            t = _tenants.get(key)
            if t is None:
                t = _tenants[key] = _Tenant(key, now)
                t.vtime = t.last_start = _vclock
            if len(t.queue) >= max(cnf.NET_ADMIT_QUEUE, 1):
                t.shed += 1
                _totals["shed"] += 1
                shed = Shed("tenant queue full", *key)
            else:
                shed = None
                cost = cost_estimate_ms(fingerprint)
                weight = tenant_weight(ns, db)
                busy = (
                    t.inflight >= max(cnf.NET_TENANT_INFLIGHT, 1)
                    or (cnf.NET_TENANT_RATE > 0 and t.tokens < 1.0)
                )
                t.queue.append((fingerprint, cost, on_admit, now, weight))
                if busy:
                    t.throttled += 1
                    _totals["throttled"] += 1
                    throttled = True
        admitted = [] if shed else _drain_locked(now)
    # lock released: now the observability (events/telemetry are LOWER
    # hierarchy levels) and the admitted callbacks
    if shed is not None:
        telemetry.inc("net_admission_shed", ns=key[0] or "-", cls=cls)
        events.emit(
            "net.admission_shed",
            ns=key[0], db=key[1], fingerprint=fingerprint or "",
            cls=cls, reason=shed.reason,
        )
        raise shed
    if throttled:
        telemetry.inc("net_throttled", ns=key[0] or "-")
        events.emit(
            "net.throttle",
            ns=key[0], db=key[1], fingerprint=fingerprint or "",
            reason="quota",
        )
    _fire(admitted)


def release(ns: Optional[str], db: Optional[str], *, cls: str = "tenant") -> None:
    """A request finished: free its slot and drain whatever that unblocks."""
    global _internal_inflight
    if not cnf.NET_QOS:
        return
    now = time.monotonic()
    with _lock:
        if cls == INTERNAL:
            _internal_inflight = max(_internal_inflight - 1, 0)
        else:
            t = _tenants.get(_key(ns, db))
            if t is not None:
                t.inflight = max(t.inflight - 1, 0)
        admitted = _drain_locked(now)
    _fire(admitted)


def poll() -> None:
    """Time-based drain: token buckets refill on the clock, not on
    completions — the event loop (and blocking waiters) call this so
    rate-limited queues drain without needing a release() edge."""
    if not cnf.NET_QOS:
        return
    with _lock:
        admitted = _drain_locked(time.monotonic())
    _fire(admitted)


def acquire(
    ns: Optional[str],
    db: Optional[str],
    *,
    fingerprint: Optional[str] = None,
    cls: str = "tenant",
    timeout: Optional[float] = None,
) -> bool:
    """Blocking admission for thread-per-connection ingress: returns True
    once admitted (caller MUST release()), raises Shed on queue overflow,
    returns False on timeout (the entry is abandoned — its on_admit
    no-ops)."""
    if not cnf.NET_QOS:
        return True
    got = threading.Event()
    state = {"abandoned": False}

    def on_admit():
        if state["abandoned"]:
            # timed-out waiter: hand the slot straight back
            release(ns, db, cls=cls)
            return
        got.set()

    submit(ns, db, on_admit, fingerprint=fingerprint, cls=cls)
    deadline = None if timeout is None else time.monotonic() + timeout
    while not got.is_set():
        poll()
        wait = 0.02
        if deadline is not None:
            left = deadline - time.monotonic()
            if left <= 0:
                state["abandoned"] = True
                # re-check: admission may have raced the flag
                if got.is_set():
                    return True
                return False
            wait = min(wait, left)
        got.wait(wait)
    return True


# ------------------------------------------------------------------ views
def snapshot(limit: int = 20) -> dict:
    """The bundle `net.qos` half: totals, internal class, worst tenants."""
    with _lock:
        tenants = [
            {
                "ns": t.key[0], "db": t.key[1],
                "inflight": t.inflight, "queued": len(t.queue),
                "admitted": t.admitted, "shed": t.shed,
                "throttled": t.throttled,
                "vtime_ms": round(t.vtime, 3),
                "tokens": round(t.tokens, 2),
            }
            for t in _tenants.values()
        ]
        internal = {
            "inflight": _internal_inflight, "queued": len(_internal_queue),
        }
        totals = dict(_totals)
    tenants.sort(key=lambda e: (-(e["shed"] + e["throttled"]), e["ns"], e["db"]))
    return {
        "enabled": bool(cnf.NET_QOS),
        "totals": totals,
        "internal": internal,
        "tenants": len(tenants),
        "top": tenants[: max(int(limit), 1)],
    }


def queue_depths() -> Dict[str, int]:
    """Scrape-time gauges (telemetry.collect_node_metrics)."""
    with _lock:
        queued = sum(len(t.queue) for t in _tenants.values())
        inflight = sum(t.inflight for t in _tenants.values())
        return {
            "queued": queued + len(_internal_queue),
            "inflight": inflight + _internal_inflight,
        }


def reset() -> None:
    """Drop all admission state (tests / bench windows)."""
    global _internal_inflight, _vclock
    with _lock:
        _tenants.clear()
        _internal_queue.clear()
        _internal_inflight = 0
        _vclock = 0.0
        for k in _totals:
            _totals[k] = 0
