"""Signin flows: root / namespace / database users + record access.

Role of the reference's signin module (reference: core/src/iam/signin.rs):
credential shape decides the level; success mutates the session and returns
a JWT.
"""

from __future__ import annotations

import time
from typing import Any, Dict

from surrealdb_tpu.err import InvalidAuthError, InvalidSigninError
from surrealdb_tpu.sql.value import Thing

from .password import verify_password
from .token import issue_token

_DEFAULT_USER_KEY_LIFETIME = 3600  # 1h token unless DURATION overrides


def signin(ds, session, creds: Dict[str, Any]) -> str:
    ns = creds.get("NS") or creds.get("ns")
    db = creds.get("DB") or creds.get("db")
    ac = creds.get("AC") or creds.get("ac") or creds.get("access")
    user = creds.get("user") or creds.get("username")
    pwd = creds.get("pass") or creds.get("password")

    if ac and creds.get("key") is not None:
        # dispatch on the access method's TYPE, not the key's shape: a
        # RECORD method whose SIGNIN reads $key must not be shadowed by a
        # bearer-looking key (reference signin.rs matches on access kind)
        from .access import access_level, bearer_signin

        txn = ds.transaction(False)
        try:
            acd = txn.get_access(access_level(ns, db), ac)
        finally:
            txn.cancel()
        if acd is not None and acd.get("access_type") == "bearer":
            return bearer_signin(ds, session, creds, ac_def=acd)
    if ac and ns and db:
        return _record_signin(ds, session, ns, db, ac, creds)
    if user is None or pwd is None:
        raise InvalidAuthError("No signin target to a root, namespace, database or record user")
    if ns and db:
        return _user_signin(ds, session, ("db", ns, db), user, pwd)
    if ns:
        return _user_signin(ds, session, ("ns", ns, None), user, pwd)
    return _user_signin(ds, session, ("root", None, None), user, pwd)


def basic_signin(ds, session, user: str, pwd: str, ns=None, db=None) -> str:
    """HTTP Basic auth: try the most specific level first, then fall back
    (reference: iam/verify.rs basic — db → ns → root)."""
    attempts = []
    if ns and db:
        attempts.append(("db", ns, db))
    if ns:
        attempts.append(("ns", ns, None))
    attempts.append(("root", None, None))
    last: Exception = InvalidAuthError()
    for level in attempts:
        try:
            return _user_signin(ds, session, level, user, pwd)
        except InvalidAuthError as e:
            last = e
    raise last


def _user_signin(ds, session, level, user: str, pwd: str) -> str:
    from surrealdb_tpu.dbs.session import Auth

    kind, ns, db = level
    txn = ds.transaction(False)
    try:
        if kind == "root":
            u = txn.get_root_user(user)
        elif kind == "ns":
            u = txn.get_ns_user(ns, user)
        else:
            u = txn.get_db_user(ns, db, user)
    finally:
        txn.cancel()
    if u is None or not u.get("hash") or not verify_password(pwd, u["hash"]):
        raise InvalidAuthError("There was a problem with authentication")

    session.ns = ns or session.ns
    session.db = db or session.db
    session.auth = Auth(kind, ns=ns, db=db, user=user, roles=u.get("roles", []))
    dur = u.get("token_duration")
    exp = time.time() + (dur / 10**9 if dur else _DEFAULT_USER_KEY_LIFETIME)
    claims = {"ID": user, "NS": ns, "DB": db, "exp": int(exp), "iss": "surrealdb-tpu"}
    return issue_token(claims, u["hash"] or "")


def _record_signin(ds, session, ns: str, db: str, ac: str, creds: Dict[str, Any]) -> str:
    from surrealdb_tpu.dbs.session import Auth, Session

    txn = ds.transaction(False)
    try:
        acc = txn.get_access((ns, db), ac)
    finally:
        txn.cancel()
    if acc is None or acc.get("access_type") != "record":
        raise InvalidAuthError("Unknown access method")
    signin_expr = acc.get("signin")
    if signin_expr is None:
        raise InvalidAuthError("This access method has no SIGNIN clause")

    # evaluate the SIGNIN expression with the credential params bound
    sess = Session.owner(ns, db)
    vars = {k: v for k, v in creds.items() if k not in ("NS", "DB", "AC", "ns", "db", "ac")}
    from surrealdb_tpu.dbs.executor import Executor

    ex = Executor(ds, sess, vars)
    rid = ex.compute_expression(signin_expr)
    if isinstance(rid, list):
        rid = rid[0] if rid else None
    if isinstance(rid, dict):
        rid = rid.get("id")
    if not isinstance(rid, Thing):
        raise InvalidSigninError()

    session.ns, session.db = ns, db
    session.auth = Auth("record", ns=ns, db=db, access=ac, rid=rid)
    dur = acc.get("token_duration")
    exp = time.time() + (dur / 10**9 if dur else _DEFAULT_USER_KEY_LIFETIME)
    claims = {
        "ID": repr(rid), "NS": ns, "DB": db, "AC": ac,
        "exp": int(exp), "iss": "surrealdb-tpu",
    }
    return issue_token(claims, acc.get("jwt_key") or "", acc.get("jwt_alg", "HS512"))
